"""First-match rule containment kernel (reference C12's hot loop,
AssociationRules.scala:88-102) as chunked matmuls + a running argmin.

The reference scans the confidence-sorted rule list per user basket until
the first rule whose antecedent is a subset of the basket fires (:95-102).
On TPU, for a batch of (deduplicated) baskets U ∈ {0,1}^{Nb×F} and rule
antecedents A ∈ {0,1}^{R×F} sorted by priority:

- containment:  ``U · Aᵀ == |antecedent|``  (int8 matmul, int32 acc);
- eligibility:  ``|antecedent| <= |basket|`` and consequent ∉ basket
  (:90 — the reference pre-filters, we mask);
- first match:  argmin over rule index with ineligible rows mapped to R.

Baskets are sharded over the mesh axis (data parallelism over users —
each device answers its own slice; no reduction needed); the rule tables
are replicated, the analog of the reference's rule broadcast (:76-78).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fastapriori_tpu import compat

AXIS = "txn"


# "No rule yet" sentinel in `best`.  A plain Python int, cast inside the
# traced kernels — a module-scope jnp scalar would initialize the JAX
# backend at import time (imports must stay backend-free so the CLI can
# fail gracefully when the accelerator tunnel is down).
NO_MATCH = 2**31 - 1


def local_first_match_chunk(
    baskets: jnp.ndarray,  # [Nb_local, F] int8
    basket_len: jnp.ndarray,  # [Nb_local] int32
    ant_cols: jnp.ndarray,  # [Rc, K] int32 — ONE priority chunk's
    #   antecedent item ranks; padding positions point at the guaranteed
    #   all-zero bitmap column (F_pad - 1), padding ROWS are all-padding
    ant_size: jnp.ndarray,  # [Rc] int32
    consequent: jnp.ndarray,  # [Rc] int32
    base: jnp.ndarray,  # () int32 — global RANK of this chunk's first rule
    best: jnp.ndarray,  # [Nb_local] int32 — running best global rule rank
    step: int = 1,  # static — global rank stride between adjacent rows
) -> jnp.ndarray:
    """Fold one rule chunk into the running first-match.

    The reference's per-user scan stops at the first hit (:95-102); the
    batch analog processes rules in priority-ordered chunks and keeps a
    running minimum, so the caller can stop dispatching chunks once every
    basket has matched — and the [Nb, R] eligibility matrix never exists
    at full R, only [Nb, Rc] per step.

    Antecedents arrive COMPACT ([Rc, K] column indexes, like the level
    engine's prefix_cols) and expand to the one-hot [Rc, F] form on
    device: the dense form was ~13 MB per chunk over the host link at
    movielens scale (f_pad ~1.7K) vs ~400 KB compact — chunk uploads,
    not compute, dominated the scan on tunneled chips.  The expansion
    is a broadcast compare-and-sum, NOT a scatter: TPU scatters cost
    ~200 ns per index (40 s across a webdocs-scale 16M-rule no-match
    scan), while the [Rc, K, F] compare tree is plain VPU work that
    XLA fuses into the matmul's operand.

    ``step``: the RANK-STRIDED table layout of the sharded scan (local
    row ``i`` holds global rank ``i·step + s``); the caller folds the
    shard offset into ``base``, so local row ``base/step + j`` maps to
    global rank ``base + j·step``.  ``step=1`` is the replicated-table
    scan (rank == row index)."""
    rc = ant_cols.shape[0]
    f = baskets.shape[1]
    # [Rc, F]; pad positions all point at the guaranteed all-zero bitmap
    # column, whose duplicate count contributes 0 to every overlap.
    antecedents = jnp.sum(
        (
            ant_cols[:, :, None]
            == jnp.arange(f, dtype=ant_cols.dtype)[None, None, :]
        ).astype(jnp.int8),
        axis=1,
        dtype=jnp.int8,
    )
    overlap = lax.dot_general(
        baskets,
        antecedents,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [Nb, Rc]
    contained = overlap == ant_size[None, :]
    size_ok = ant_size[None, :] <= basket_len[:, None]
    cons_in_basket = jnp.take(baskets, consequent, axis=1) > 0
    eligible = contained & size_ok & ~cons_in_basket
    ranks = jnp.arange(rc, dtype=jnp.int32) * jnp.int32(step) + base
    idx = jnp.where(eligible, ranks[None, :], jnp.int32(NO_MATCH))
    return jnp.minimum(best, jnp.min(idx, axis=1))


def local_first_match_scan(
    baskets: jnp.ndarray,  # [Nb_local, F] int8
    basket_len: jnp.ndarray,  # [Nb_local] int32 (0 on padding rows)
    ant_cols: jnp.ndarray,  # [R_pad, K] int32 — the FULL resident table
    ant_size: jnp.ndarray,  # [R_pad] int32 (padding rows: > F, never hit)
    consequent: jnp.ndarray,  # [R_pad] int32
    *,
    chunk: int,
    axis_name=None,
):
    """The whole priority scan as ONE device program: a ``lax.while_loop``
    over rule chunks with the early exit ON DEVICE (stop as soon as every
    real local basket has a match — padding rows, ``basket_len == 0``,
    are excluded or they would pin the loop to full length).

    Replaces the host-driven chunk loop whose per-chunk uploads and
    lagged early-exit fetches were link-bound on tunneled chips
    (VERDICT weak #4): the rule table is resident (uploaded once per
    recommender instance), each dispatch costs only the basket upload +
    one [Nb_local] result fetch.  Exactness: later chunks hold only
    larger rule indices, so stopping once every real row is below
    NO_MATCH cannot change the running minimum.

    Returns ``(best [Nb_local] int32, chunks_run () int32)`` —
    ``chunks_run`` (the max across shards when meshed) feeds the MAC
    accounting that the mining phases already have."""
    r_pad = ant_cols.shape[0]
    n_chunks = r_pad // chunk
    real = basket_len > 0

    def cond(state):
        c, best = state
        return (c < n_chunks) & jnp.any(real & (best == jnp.int32(NO_MATCH)))

    def body(state):
        c, best = state
        base = c * chunk
        best = local_first_match_chunk(
            baskets,
            basket_len,
            lax.dynamic_slice_in_dim(ant_cols, base, chunk, 0),
            lax.dynamic_slice_in_dim(ant_size, base, chunk, 0),
            lax.dynamic_slice_in_dim(consequent, base, chunk, 0),
            base,
            best,
        )
        return c + 1, best

    best0 = jnp.full(baskets.shape[0], NO_MATCH, dtype=jnp.int32)
    if axis_name is not None:
        # The carry varies over the mesh axis (it is derived from the
        # sharded baskets); mark the initial value to match.
        best0 = compat.pcast(best0, (axis_name,), to="varying")
    c, best = lax.while_loop(cond, body, (jnp.int32(0), best0))
    if axis_name is not None:
        # Shards may exit at different chunks (no collectives inside the
        # loop); report the deepest scan for the cost model.
        c = lax.pmax(c, axis_name)
    return best, c


def make_sharded_first_match_scan(mesh: Mesh, chunk: int):
    """shard_map-wrapped, jitted resident-table scan: baskets and the
    result sharded over the mesh axis, rule tables replicated (the
    reference's rule broadcast, AssociationRules.scala:76-78)."""
    import functools

    return jax.jit(
        compat.shard_map(
            functools.partial(
                local_first_match_scan, chunk=chunk, axis_name=AXIS
            ),
            mesh=mesh,
            in_specs=(
                P(AXIS, None),
                P(AXIS),
                P(None, None),
                P(None),
                P(None),
            ),
            out_specs=(P(AXIS), P()),
        )
    )


# ---------------------------------------------------------------------------
# Device-resident rule generation (reference C11's level-wise subset joins,
# AssociationRules.scala:122-188, reformulated as packed-key layouts and
# batched sorted-key gathers — the transposition "A New Data Layout For Set
# Intersection on GPUs" applies to set containment, PAPERS.md).
#
# The host formulation (rules/gen.py) joins each k-itemset's k deleted-column
# antecedents against the sorted (k-1)-itemset key table with numpy
# searchsorted — 13.6-19.3 s of host wall for 16.34M rules at webdocs scale
# (VERDICT r5 weak #8).  Here the same join runs on device: row keys pack
# into uint32 LANES (no 64-bit device dtypes — jax_enable_x64 stays off, the
# repo-wide G004 contract), the parent table is sorted once per level with
# `lax.sort` (multi-operand lexicographic), all k column deletions of a level
# batch into ONE dispatch, and the dominance prune's confidence comparisons
# run as exact 48-bit rational compares (see `frac_less24`).


def rule_key_bits(f: int) -> int:
    """Bits per item rank in the packed row keys (rules/gen.py `_row_keys`
    uses the same widths for its uint64 host keys)."""
    return 8 if f <= 256 else (16 if f <= 65536 else 32)


def pack_rank_keys(mat: jnp.ndarray, bits: int) -> list:
    """Pack int32 [N, w] sorted-row ranks into ``ceil(w*bits/32)`` uint32
    key columns, left-aligned so lexicographic order over the column tuple
    equals lexicographic row order (the host packs the same fields into
    one uint64; the device splits them across 32-bit lanes because 64-bit
    dtypes silently downcast while jax_enable_x64 is off)."""
    n, w = mat.shape
    per = 32 // bits
    m = mat.astype(jnp.uint32)
    cols = []
    for ci in range(-(-w // per)):
        acc = None
        for j in range(per):
            pos = ci * per + j
            if pos >= w:
                break
            part = m[:, pos] << ((per - 1 - j) * bits)
            acc = part if acc is None else acc | part
        cols.append(acc)
    return cols


def lex_searchsorted(
    sorted_cols, n_real: jnp.ndarray, query_cols, n_iters: int
) -> jnp.ndarray:
    """Left insertion point of each query row in a lexicographically
    sorted multi-column uint32 key table — a vectorized binary search
    (``n_iters`` static gather/compare rounds over all queries at once),
    bounded by the TRACED real row count so pow2-padded tables need no
    sentinel discipline."""
    m = query_cols[0].shape[0]
    lo0 = jnp.zeros(m, jnp.int32)
    hi0 = jnp.broadcast_to(n_real.astype(jnp.int32), (m,))

    def body(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        lt = jnp.zeros(m, bool)
        eq = jnp.ones(m, bool)
        for sc, qc in zip(sorted_cols, query_cols):
            v = jnp.take(sc, mid)
            lt = lt | (eq & (v < qc))
            eq = eq & (v == qc)
        active = lo < hi
        lo = jnp.where(active & lt, mid + 1, lo)
        hi = jnp.where(active & ~lt, mid, hi)
        return lo, hi

    lo, _ = lax.fori_loop(0, n_iters, body, (lo0, hi0))
    return lo


def _mul24_wide(a: jnp.ndarray, b: jnp.ndarray):
    """Exact 48-bit product of two uint32 values < 2^24 as a (hi, lo)
    uint32 pair — 16-bit-limb schoolbook multiply (no 64-bit dtypes on
    device).  Bounds: a0,b0 < 2^16 and a1,b1 < 2^8, so every partial
    product and the limb sum fit uint32 exactly; only the final lo add
    can wrap, and its carry is recovered by comparison."""
    a0, a1 = a & 0xFFFF, a >> 16
    b0, b1 = b & 0xFFFF, b >> 16
    p00 = a0 * b0
    mid = a0 * b1 + a1 * b0  # < 2^25: no wrap
    t = (mid & 0xFFFF) << 16
    lo = p00 + t
    carry = (lo < p00).astype(jnp.uint32)
    hi = a1 * b1 + (mid >> 16) + carry
    return hi, lo


def frac_less24(pn, pd, cn, cd) -> jnp.ndarray:
    """``pn/pd < cn/cd`` for positive int counts < 2^24, EXACTLY matching
    the host's IEEE-double comparison (rules/gen.py compares f64
    confidences, like the reference's JVM doubles).  Equivalence: with
    denominators < 2^24 two distinct rationals in (0, 1] differ by at
    least 1/(pd·cd) > 2^-48, while doubles at or below 1.0 are spaced at
    most 2^-53 — distinct rationals therefore round to distinct doubles
    and the double order IS the rational order, so the exact cross
    product compare (48-bit, `_mul24_wide`) reproduces it bit-for-bit.
    Callers gate the device path on counts < 2^24 (rules/gen.py)."""
    h1, l1 = _mul24_wide(pn.astype(jnp.uint32), cd.astype(jnp.uint32))
    h2, l2 = _mul24_wide(cn.astype(jnp.uint32), pd.astype(jnp.uint32))
    return (h1 < h2) | ((h1 == h2) & (l1 < l2))


def rule_level_kernel(
    mat: jnp.ndarray,  # [N_pad, k] int32 lex-sorted k-itemset rows
    cnts: jnp.ndarray,  # [N_pad] int32 itemset counts (< 2^24, gated)
    n_real: jnp.ndarray,  # () int32 — real row count (pow2 row padding)
    psorted,  # tuple of [Np_pad] uint32 — parent sorted key columns
    porder: jnp.ndarray,  # [Np_pad] int32 — parent sort order (row ids)
    pcnts: jnp.ndarray,  # [Np_pad] int32 — (k-1)-itemset counts
    np_real: jnp.ndarray,  # () int32 — real parent rows
    prev_surv: jnp.ndarray,  # [(k-1)*Np_pad] bool — parent-RULE survival
    prev_d: jnp.ndarray,  # [(k-1)*Np_pad] int32 — parent-rule denominators
    *,
    k: int,
    bits: int,
    first: bool,
):
    """One level's raw rule generation + dominance prune in ONE dispatch
    (all k column deletions batched): the k→(k-1) antecedent lookups as
    packed-key binary searches over the resident sorted parent table,
    then the reference's "cut leaves" prune (AssociationRules.scala:
    147-182) as flat gathers into the previous level's device-resident
    survival/denominator arrays — rule (S-{e}→S[j]) survives iff each
    parent rule (S-{e,x}→S[j]) survived with strictly lower confidence,
    compared exactly (`frac_less24`).

    ``first`` statically marks the k=2 base level: its parents are the
    1-itemsets (an identity table — the deleted single-column rows ARE
    the parent row indexes, no search), and every found rule survives
    (the reference's base case, :173).

    Returns ``(packed, skeys, order, d_flat, surv_flat)``: ``packed`` is
    the ONE host-bound array — the j-major survivor bitmask plus a
    4-byte little-endian count of unmatched antecedents (downward-
    closure violations; the host raises InputError) — while ``skeys``/
    ``order`` (this table's sorted keys, the next level's parent) and
    ``d_flat``/``surv_flat`` (this level's rule denominators/survival,
    the next level's prune inputs) stay device-resident."""
    from fastapriori_tpu.ops.count import pack_bits_msb

    n_pad = mat.shape[0]
    valid = jnp.arange(n_pad, dtype=jnp.int32) < n_real.astype(jnp.int32)
    if first:
        # k == 2: parent table is the 1-itemset arange — delete column j
        # and the remaining rank IS the parent row index.
        rows = jnp.stack([mat[:, 1], mat[:, 0]])
        found = jnp.broadcast_to(valid[None, :], (k, n_pad))
    else:
        np_pad = porder.shape[0]
        dels = [
            jnp.concatenate([mat[:, :j], mat[:, j + 1 :]], axis=1)
            for j in range(k)
        ]
        packed_q = [pack_rank_keys(d, bits) for d in dels]
        n_cols = len(packed_q[0])
        flat_q = [
            jnp.stack([packed_q[j][ci] for j in range(k)]).reshape(-1)
            for ci in range(n_cols)
        ]
        # np_pad is a static Python shape int, so the iteration count is
        # compile-time constant.
        pos = lex_searchsorted(
            psorted, np_real, flat_q, np_pad.bit_length() + 1
        )
        safe = jnp.clip(pos, 0, jnp.maximum(np_real - 1, 0))
        eq = pos < np_real
        for sc, qc in zip(psorted, flat_q):
            eq = eq & (jnp.take(sc, safe) == qc)
        found = eq.reshape(k, n_pad) & valid[None, :]
        rows = jnp.take(porder, safe).reshape(k, n_pad)
    # Denominators: count(S - {e}) per deleted column — ALSO each parent
    # rule's numerator (the prune below reuses the same gather).
    d = jnp.take(pcnts, rows.reshape(-1)).reshape(k, n_pad)
    miss = jnp.sum(valid[None, :] & ~found, dtype=jnp.int32)
    if first:
        ok = found  # base case: every min-size rule survives (:173)
    else:
        np_pad = porder.shape[0]
        oks = []
        for j in range(k):
            ok_j = found[j]
            for e in range(k):
                if e == j:
                    continue
                # Parent rule (S-{e}) -> S[j]: the consequent position
                # shifts down when the deleted column precedes it
                # (rules/gen.py uses the same flat addressing).
                jp = j - (e < j)
                pidx = jp * np_pad + rows[e]
                ok_j = (
                    ok_j
                    & jnp.take(prev_surv, pidx)
                    & frac_less24(d[e], jnp.take(prev_d, pidx), cnts, d[j])
                )
            oks.append(ok_j)
        ok = jnp.stack(oks)
    surv_flat = ok.reshape(-1)
    d_flat = d.reshape(-1)
    miss_u = miss.astype(jnp.uint32)
    packed = jnp.concatenate(
        [
            pack_bits_msb(surv_flat),
            jnp.stack(
                [(miss_u >> (8 * i)) & 0xFF for i in range(4)]
            ).astype(jnp.uint8),
        ]
    )
    # This table's sorted keys feed the NEXT level's search; pow2 row
    # padding sorts to the tail via the all-ones sentinel (real keys can
    # never be all-ones: ranks within a row strictly increase, and
    # left-aligned packing zero-fills any unused low bits).
    scols = [
        jnp.where(valid, c, jnp.uint32(0xFFFFFFFF))
        for c in pack_rank_keys(mat, bits)
    ]
    srt = lax.sort(
        tuple(scols) + (jnp.arange(n_pad, dtype=jnp.int32),),
        num_keys=len(scols),
    )
    return packed, tuple(srt[:-1]), srt[-1], d_flat, surv_flat


# ---------------------------------------------------------------------------
# Multi-chip rule generation (ISSUE 8 tentpole): the per-level join/prune
# above, sharded over the txn mesh axis.  Query rows (the k-itemset table)
# are SHARDED — each shard searches only its N/S rows' k column deletions
# against the REPLICATED sorted parent table — and the per-shard survivor
# bitmask / denominator blocks are merged with the packed-mask exchange
# (the byte layout of ops/count.py local_sparse_psum's union gather; here
# the blocks are disjoint, so the merge is a tiled concatenation rather
# than an OR).  The SNIPPETS custom_partitioning/sharding-constraint
# pattern: replicated inputs, sharded compute, replicated outputs, so the
# kernel is mesh-polymorphic (S=1 reproduces the single-chip engine).


def _tiled_all_gather(
    x: jnp.ndarray, axis_name: str, axis: int, groups=None
):
    """``all_gather`` of per-shard blocks, concatenated along ``axis`` in
    shard order — the layout inverse of a P(AXIS)-sharded placement.
    Spelled as stack+reshape (the 0.4.x-safe form under shard_map).
    ``groups``: a ``(groups, per_group)`` grid routes the reassembly
    through the two-level hierarchy (parallel/hier.py
    hier_tiled_all_gather — intra-group chunk assembly, then one
    inter-group exchange of whole group chunks): identical shard-order
    layout, bit for bit, with the slow tier moving ``groups-1`` large
    contiguous chunks per level instead of ``S-per_group`` small
    blocks."""
    if groups is not None:
        from fastapriori_tpu.parallel.hier import hier_tiled_all_gather

        return hier_tiled_all_gather(x, axis_name, axis, groups)
    g = lax.all_gather(x, axis_name)  # [S, ...]
    if axis == 0:
        return g.reshape((-1,) + x.shape[1:])
    assert axis == 1, axis
    g = jnp.moveaxis(g, 0, 1)  # [d0, S, d1, ...]
    return g.reshape(x.shape[0], -1, *x.shape[2:])


def rule_level_shard_kernel(
    mat: jnp.ndarray,  # [N_loc, k] int32 — THIS shard's lex-sorted rows
    cnts: jnp.ndarray,  # [N_loc] int32 itemset counts (< 2^24, gated)
    n_real: jnp.ndarray,  # () int32 — real row count (pow2·8S padding)
    psorted,  # tuple of [Np_pad] uint32 — parent sorted keys (replicated)
    porder: jnp.ndarray,  # [Np_pad] int32 — parent sort order (replicated)
    pcnts: jnp.ndarray,  # [Np_pad] int32 — parent counts (replicated)
    np_real: jnp.ndarray,  # () int32
    prev_surv: jnp.ndarray,  # [(k-1)*Np_pad] bool — parent-rule survival
    prev_d: jnp.ndarray,  # [(k-1)*Np_pad] int32 — parent-rule denominators
    *,
    k: int,
    bits: int,
    first: bool,
    axis_name: str,
    n_shards: int,
    groups=None,
):
    """Sharded twin of :func:`rule_level_kernel`, still ONE dispatch per
    level: each shard runs the k→(k-1) packed-key binary searches and the
    dominance prune for ITS row block only (the O(N·k·log Np) join cost —
    phase 2's dominant term — divides by S), then one packed-mask + one
    denominator exchange reassemble the full j-major survivor state on
    every shard, and the (cheap, O(N log N)) lex sort of the full table
    runs replicated so the next level's parent keys need no further
    exchange.  Returns the :func:`rule_level_kernel` tuple extended with
    the gathered ``(mat_full, cnts_full)`` — device-resident inputs to
    the next level's search and to the recommender's scan-table build
    (rules/gen.py DeviceRuleState)."""
    from fastapriori_tpu.ops.count import _unpack_bits_msb, pack_bits_msb

    n_loc = mat.shape[0]
    n_pad = n_loc * n_shards
    s = lax.axis_index(axis_name)
    row0 = s.astype(jnp.int32) * jnp.int32(n_loc)
    valid_loc = (
        jnp.arange(n_loc, dtype=jnp.int32) + row0
    ) < n_real.astype(jnp.int32)
    if first:
        # k == 2: parents are the 1-itemset arange — the deleted single
        # column IS the parent row index, no search.
        rows = jnp.stack([mat[:, 1], mat[:, 0]])
        found = jnp.broadcast_to(valid_loc[None, :], (k, n_loc))
    else:
        np_pad = porder.shape[0]
        dels = [
            jnp.concatenate([mat[:, :j], mat[:, j + 1 :]], axis=1)
            for j in range(k)
        ]
        packed_q = [pack_rank_keys(d, bits) for d in dels]
        n_cols = len(packed_q[0])
        flat_q = [
            jnp.stack([packed_q[j][ci] for j in range(k)]).reshape(-1)
            for ci in range(n_cols)
        ]
        pos = lex_searchsorted(
            psorted, np_real, flat_q, np_pad.bit_length() + 1
        )
        safe = jnp.clip(pos, 0, jnp.maximum(np_real - 1, 0))
        eq = pos < np_real
        for sc, qc in zip(psorted, flat_q):
            eq = eq & (jnp.take(sc, safe) == qc)
        found = eq.reshape(k, n_loc) & valid_loc[None, :]
        rows = jnp.take(porder, safe).reshape(k, n_loc)
    d = jnp.take(pcnts, rows.reshape(-1)).reshape(k, n_loc)
    miss = jnp.sum(valid_loc[None, :] & ~found, dtype=jnp.int32)
    if first:
        ok = found
    else:
        np_pad = porder.shape[0]
        oks = []
        for j in range(k):
            ok_j = found[j]
            for e in range(k):
                if e == j:
                    continue
                jp = j - (e < j)
                pidx = jp * np_pad + rows[e]
                ok_j = (
                    ok_j
                    & jnp.take(prev_surv, pidx)
                    & frac_less24(d[e], jnp.take(prev_d, pidx), cnts, d[j])
                )
            oks.append(ok_j)
        ok = jnp.stack(oks)
    # Merge: per-shard [k, N/S] survivor blocks cross the axis bit-packed
    # (N/S is a multiple of 8 by the dispatch layer's 8·S row padding, so
    # per-block MSB-first packing concatenates into exactly the j-major
    # bitmask the single-chip kernel emits); denominators go as int32.
    ok_full = _unpack_bits_msb(
        _tiled_all_gather(pack_bits_msb(ok), axis_name, 1, groups=groups)
    )
    d_full = _tiled_all_gather(d, axis_name, 1, groups=groups)  # [k, N_pad]
    miss = lax.psum(miss, axis_name)
    miss_u = miss.astype(jnp.uint32)
    packed = jnp.concatenate(
        [
            pack_bits_msb(ok_full.reshape(-1)),
            jnp.stack(
                [(miss_u >> (8 * i)) & 0xFF for i in range(4)]
            ).astype(jnp.uint8),
        ]
    )
    # The one table exchange ("parent keys replicated via one all_gather
    # at upload"): rows arrive sharded over the link, the full table is
    # reassembled once over ICI, and the lex sort for the NEXT level's
    # search runs replicated on it — identical on every shard.
    mat_full = _tiled_all_gather(mat, axis_name, 0, groups=groups)
    cnts_full = _tiled_all_gather(cnts, axis_name, 0, groups=groups)
    valid_full = jnp.arange(n_pad, dtype=jnp.int32) < n_real.astype(
        jnp.int32
    )
    scols = [
        jnp.where(valid_full, c, jnp.uint32(0xFFFFFFFF))
        for c in pack_rank_keys(mat_full, bits)
    ]
    srt = lax.sort(
        tuple(scols) + (jnp.arange(n_pad, dtype=jnp.int32),),
        num_keys=len(scols),
    )
    return (
        packed,
        tuple(srt[:-1]),
        srt[-1],
        d_full.reshape(-1),
        ok_full.reshape(-1),
        mat_full,
        cnts_full,
    )


def rule_shard_bytes(
    k: int, n_pad: int, n_shards: int, groups=None
) -> tuple:
    """(gather_bytes, psum_bytes) payload model of one sharded rule-level
    dispatch — the per-level comms accounting rules/gen.py records next
    to the mining collectives: the packed survivor-mask + denominator
    block exchanges and the table reassembly land ``S×`` their payload
    (every shard receives every block), the miss counter is one int32
    psum.  Reassembly totals are topology-invariant (every shard must
    end holding every block; the hierarchy restages, it cannot shrink a
    concatenation) — ``groups`` changes the intra/inter attribution and
    the slow-tier message count, not this total; see
    :func:`rule_shard_stage_bytes`."""
    mask_b = k * (n_pad // 8)
    den_b = 4 * k * n_pad
    table_b = 4 * n_pad * k + 4 * n_pad  # mat_full + cnts_full
    return n_shards * (mask_b + den_b + table_b), 4 * n_shards


def rule_shard_stage_bytes(
    k: int, n_pad: int, n_shards: int, groups=None
) -> tuple:
    """Per-shard ``(intra_bytes, inter_bytes, inter_msgs)`` attribution
    of :func:`rule_shard_bytes`' gather total: flat puts every block on
    the single slow tier in ``3·(S-1)`` messages (mask + denominator +
    table exchanges); the hierarchical reassembly assembles group
    chunks intra-group first, so the slow tier moves ``groups`` whole
    chunks in ``3·(groups-1)`` messages — the staging win bench's rule
    scaling records per level."""
    per_shard = (
        k * (n_pad // 8) + 4 * k * n_pad + 4 * n_pad * k + 4 * n_pad
    ) // n_shards
    from fastapriori_tpu.parallel.hier import gather_stage_bytes

    intra, inter = gather_stage_bytes(per_shard, n_shards, groups)
    msgs = 3 * ((groups[0] if groups else n_shards) - 1)
    return intra, inter, msgs


# ---------------------------------------------------------------------------
# Device-resident priority scan (ISSUE 8 tentpole, part b): the sorted
# rule table is BUILT on device from the join kernels' resident state —
# the 16M-rule table never round-trips the host after the level-table
# upload — with the confidence-descending order reproduced exactly by a
# 49-bit rational sort key (the frac_less24 spacing argument, turned from
# a comparator into an order-embedding integer), and the first-match scan
# sharded over the mesh: rules rank-strided across shards, baskets
# micro-batched and replicated, one pmin/pmax exchange merges the
# per-shard argmin-over-rank.


def conf_sort_keys(num: jnp.ndarray, den: jnp.ndarray) -> tuple:
    """Exact 49-bit order embedding of the confidence ``num/den``
    (positive int counts < 2^24, ``num <= den`` — support monotonicity
    guarantees it for every rule): ``key = floor(num · 2^48 / den)``,
    computed by 8-bit long division (six steps; every intermediate
    ``r << 8`` fits uint32 because ``r < den < 2^24``), returned as
    ``(hi, lo)`` uint32 holding bits [48..24] and [23..0].

    Exactness (the frac_less24 spacing argument, reused as a KEY instead
    of a comparator): two distinct rationals in (0, 1] with denominators
    < 2^24 differ by more than 2^-48, so their keys differ by more than
    1 and floor preserves strict order; equal rationals share the key.
    The host's f64 sort order is the rational order (distinct rationals
    round to distinct doubles at this spacing), so sorting by this key
    descending IS the host ``np.lexsort((pr, -conf))`` confidence
    component, bit-for-bit."""
    n = num.astype(jnp.uint32)
    d = jnp.maximum(den.astype(jnp.uint32), jnp.uint32(1))
    q0 = n // d  # integer part: 1 iff num == den (conf <= 1 by gate)
    r = n - q0 * d
    frac_hi = jnp.zeros_like(n)
    for _ in range(3):
        r = r << 8
        qi = r // d
        r = r - qi * d
        frac_hi = (frac_hi << 8) | qi
    frac_lo = jnp.zeros_like(n)
    for _ in range(3):
        r = r << 8
        qi = r // d
        r = r - qi * d
        frac_lo = (frac_lo << 8) | qi
    return (q0 << 24) | frac_hi, frac_lo


def rule_scan_build(
    level_arrays,  # per level: (mat_full, cnts_full, d_flat, surv_flat)
    offsets: jnp.ndarray,  # [L] int32 — emission offset per level (traced)
    pr: jnp.ndarray,  # [F] int32 — consequent tie-priority per rank
    *,
    ks,  # static tuple of level sizes k
    r_pad: int,
    k_max: int,
    zcol: int,
    n_shards: int,
):
    """Build the priority-sorted compact scan table ON DEVICE from the
    rule-join kernels' resident per-level state (one dispatch, once per
    recommender instance): compact each level's j-major survivors to
    their emission slots (a cumsum over the resident survivor flags —
    the slot index IS the host pipeline's emission ordinal, which is
    exactly np.lexsort's stability tie-break), derive each rule's
    antecedent columns / size / consequent / (numerator, denominator)
    from the resident tables, sort once by ``(padding, conf desc via
    conf_sort_keys, consequent priority, emission ordinal)`` — the
    host sort_rule_arrays order, key for key — and emit the table in
    SHARD-MAJOR rank-strided layout (out row ``s·R/S + i`` = sorted
    rank ``i·S + s``) so a P(AXIS) placement gives every shard the
    rank-interleaved slice the strided scan kernel expects.

    Returns ``(ant_cols [R_pad, k_max], ant_size [R_pad],
    consequent [R_pad])`` — padding rows never match (size > any
    basket); antecedent padding points at the zero column ``zcol``."""
    ant = jnp.full((r_pad, k_max), jnp.int32(zcol))
    size = jnp.full((r_pad,), jnp.int32(zcol + 2))  # > any basket length
    cons = jnp.zeros((r_pad,), jnp.int32)
    num = jnp.zeros((r_pad,), jnp.uint32)
    den = jnp.ones((r_pad,), jnp.uint32)  # pad key = 0/1 -> sorts last
    for li, (k, (mat, cnts, d_flat, surv)) in enumerate(
        zip(ks, level_arrays)
    ):
        n_pad_l = mat.shape[0]
        t = k * n_pad_l
        sv = surv.astype(jnp.int32)
        slot = jnp.where(
            surv, offsets[li] + jnp.cumsum(sv) - 1, jnp.int32(r_pad)
        )  # r_pad = out of bounds, dropped by the scatter
        j = jnp.arange(t, dtype=jnp.int32) // n_pad_l
        rr = jnp.arange(t, dtype=jnp.int32) % n_pad_l
        ccols = jnp.arange(k - 1, dtype=jnp.int32)
        gcols = ccols[None, :] + (ccols[None, :] >= j[:, None]).astype(
            jnp.int32
        )
        ant_rows = mat[rr[:, None], gcols]  # [t, k-1] col-j-deleted rows
        ant = ant.at[slot, : k - 1].set(ant_rows, mode="drop")
        size = size.at[slot].set(jnp.int32(k - 1), mode="drop")
        cons = cons.at[slot].set(mat[rr, j], mode="drop")
        num = num.at[slot].set(
            jnp.take(cnts, rr).astype(jnp.uint32), mode="drop"
        )
        den = den.at[slot].set(d_flat.astype(jnp.uint32), mode="drop")
    khi, klo = conf_sort_keys(num, den)
    # Descending confidence = ascending bitwise complement; padding rows
    # (num=0 -> key 0 -> complement max) sort to the tail behind every
    # real rule (real keys are >= 2^24: floor(n·2^48/d) with d < 2^24).
    pr_cons = jnp.take(pr, jnp.clip(cons, 0, pr.shape[0] - 1))
    idx = jnp.arange(r_pad, dtype=jnp.int32)
    srt = lax.sort((~khi, ~klo, pr_cons, idx), num_keys=4)
    perm = srt[-1]

    def strided(x):
        # Shard-major rank interleave: out[s·R/S + i] = sorted[i·S + s].
        resh = x.reshape((r_pad // n_shards, n_shards) + x.shape[1:])
        return jnp.swapaxes(resh, 0, 1).reshape(x.shape)

    return (
        strided(jnp.take(ant, perm, axis=0)),
        strided(jnp.take(size, perm)),
        strided(jnp.take(cons, perm)),
    )


def local_strided_match_scan(
    baskets: jnp.ndarray,  # [mb, F] int8 — one micro-batch, REPLICATED
    basket_len: jnp.ndarray,  # [mb] int32 (0 on padding rows)
    ant_cols: jnp.ndarray,  # [R_loc, K] int32 — THIS shard's strided slice
    ant_size: jnp.ndarray,  # [R_loc] int32
    consequent: jnp.ndarray,  # [R_loc] int32
    *,
    chunk: int,
    n_shards: int,
    axis_name: str,
    pallas: Optional[tuple] = None,  # (rule_tile, interpret)
):
    """Sharded first-match over the rank-strided resident table: each
    shard scans its R/S rule slice (local row i = global rank
    ``i·S + s``, so every shard participates in the top-confidence
    chunks and the early exit fires at the same table depth as the
    replicated scan), keeping a per-basket argmin over GLOBAL rank;
    one ``pmin`` merges the shard minima — later local chunks hold only
    larger ranks, so a shard may stop as soon as every real basket has
    some local match without affecting the merged minimum — and one
    ``pmax`` selects the winning shard's consequent (global ranks are
    unique across shards: rank mod S identifies the owner).  Returns
    ``(best_rank [mb], consequent-or-minus-1 [mb], chunks_run ())``,
    identical across shards.

    Padding contract (the serving tier depends on it — ISSUE 10): rows
    with ``basket_len == 0`` are padding, excluded from the early-exit
    census, and scan to NO_MATCH/-1.  The serving micro-batcher
    (serve/state.py) therefore dispatches every batch at ONE fixed
    [mb, F_pad] shape — a partial batch rides as zero-length rows
    instead of compiling a fresh program per observed batch size, which
    is what makes the linger/batch-size knobs a latency trade-off
    rather than a compile-cache hazard."""
    r_loc = ant_cols.shape[0]
    n_chunks = r_loc // chunk
    s = lax.axis_index(axis_name).astype(jnp.int32)
    real = basket_len > 0

    if pallas is not None:
        # Pallas tier: one fused launch sweeping EVERY rule tile with a
        # running min (ops/pallas_vertical.py) — no early exit, but
        # exact: later tiles hold only larger global ranks, so the min
        # over all rules equals the early-exit result.  chunks_run
        # reports the full sweep.  The pmin/pmax merge below is shared
        # with the XLA while_loop path verbatim.
        from fastapriori_tpu.ops.pallas_vertical import (
            strided_best_rank_pallas,
        )

        rule_tile, interp = pallas
        best = strided_best_rank_pallas(
            baskets, basket_len, ant_cols, ant_size, consequent,
            s, n_shards, rule_tile, NO_MATCH, interp,
        )
        c = jnp.int32(n_chunks)
        return _strided_merge(
            best, consequent, s, c, r_loc, n_shards, axis_name
        )

    def cond(state):
        c, best = state
        return (c < n_chunks) & jnp.any(real & (best == jnp.int32(NO_MATCH)))

    def body(state):
        c, best = state
        base = c * chunk
        best = local_first_match_chunk(
            baskets,
            basket_len,
            lax.dynamic_slice_in_dim(ant_cols, base, chunk, 0),
            lax.dynamic_slice_in_dim(ant_size, base, chunk, 0),
            lax.dynamic_slice_in_dim(consequent, base, chunk, 0),
            base * jnp.int32(n_shards) + s,
            best,
            step=n_shards,
        )
        return c + 1, best

    best0 = compat.pcast(
        jnp.full(baskets.shape[0], NO_MATCH, dtype=jnp.int32),
        (axis_name,),
        to="varying",
    )
    c, best = lax.while_loop(cond, body, (jnp.int32(0), best0))
    return _strided_merge(best, consequent, s, c, r_loc, n_shards, axis_name)


def _strided_merge(best, consequent, s, c, r_loc, n_shards, axis_name):
    """Cross-shard merge of the per-shard strided minima (shared by the
    while_loop and Pallas local bodies).  The winner's consequent: only
    the owning shard's local best equals the global minimum (ranks are
    unique mod S), so a masked pmax is an exact one-collective select."""
    best_g = lax.pmin(best, axis_name)
    local_row = jnp.clip(
        (best - s) // jnp.int32(n_shards), 0, jnp.int32(r_loc - 1)
    )
    mine = (best == best_g) & (best < jnp.int32(NO_MATCH))
    cons_l = jnp.where(mine, jnp.take(consequent, local_row), jnp.int32(-1))
    cons_g = lax.pmax(cons_l, axis_name)
    return best_g, cons_g, lax.pmax(c, axis_name)


def make_strided_first_match_scan(
    mesh: Mesh, chunk: int, n_shards: int, pallas: Optional[tuple] = None
):
    """shard_map-wrapped, jitted strided-table scan: the rule table
    sharded over the mesh axis (R/S rows per shard — the table's HBM
    footprint no longer replicates), basket micro-batches replicated,
    outputs replicated after the pmin/pmax exchange.  ``pallas``
    (rule_tile, interpret) mounts the fused first-match kernel as the
    local body (serve_scan chain stage "pallas")."""
    import functools

    return jax.jit(
        compat.shard_map(
            functools.partial(
                local_strided_match_scan,
                chunk=chunk,
                n_shards=n_shards,
                axis_name=AXIS,
                pallas=pallas,
            ),
            mesh=mesh,
            in_specs=(
                P(None, None),
                P(None),
                P(AXIS, None),
                P(AXIS),
                P(AXIS),
            ),
            out_specs=(P(), P(), P()),
        )
    )
