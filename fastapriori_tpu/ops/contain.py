"""First-match rule containment kernel (reference C12's hot loop,
AssociationRules.scala:88-102) as one matmul + argmin.

The reference scans the confidence-sorted rule list per user basket until
the first rule whose antecedent is a subset of the basket fires (:95-102).
On TPU, for a batch of (deduplicated) baskets U ∈ {0,1}^{Nb×F} and rule
antecedents A ∈ {0,1}^{R×F} sorted by priority:

- containment:  ``U · Aᵀ == |antecedent|``  (int8 matmul, int32 acc);
- eligibility:  ``|antecedent| <= |basket|`` and consequent ∉ basket
  (:90 — the reference pre-filters, we mask);
- first match:  argmin over rule index with ineligible rows mapped to R.

Baskets are sharded over the mesh axis (data parallelism over users —
each device answers its own slice; no reduction needed); the rule tables
are replicated, the analog of the reference's rule broadcast (:76-78).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

AXIS = "txn"


def local_first_match(
    baskets: jnp.ndarray,  # [Nb_local, F] int8
    basket_len: jnp.ndarray,  # [Nb_local] int32  (distinct frequent items)
    antecedents: jnp.ndarray,  # [R, F] int8, priority-sorted
    ant_size: jnp.ndarray,  # [R] int32 (padded rules: F+1 => never eligible)
    consequent: jnp.ndarray,  # [R] int32 rank of the consequent
) -> jnp.ndarray:
    """Per basket: rank of the recommended item, or -1 for no match."""
    r = antecedents.shape[0]
    overlap = lax.dot_general(
        baskets,
        antecedents,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [Nb, R]
    contained = overlap == ant_size[None, :]
    size_ok = ant_size[None, :] <= basket_len[:, None]
    # consequent ∉ basket: gather each basket's bit at the consequent's rank.
    cons_in_basket = jnp.take(baskets, consequent, axis=1) > 0  # [Nb, R]
    eligible = contained & size_ok & ~cons_in_basket
    idx = jnp.where(eligible, jnp.arange(r, dtype=jnp.int32)[None, :], r)
    first = jnp.min(idx, axis=1)  # [Nb]
    found = first < r
    rec = jnp.take(consequent, jnp.where(found, first, 0))
    return jnp.where(found, rec, -1)


def make_sharded_first_match(mesh: Mesh):
    """shard_map-wrapped, jitted first-match kernel: baskets sharded over
    the mesh axis, rule tables replicated."""
    return jax.jit(
        jax.shard_map(
            local_first_match,
            mesh=mesh,
            in_specs=(P(AXIS, None), P(AXIS), P(None, None), P(None), P(None)),
            out_specs=P(AXIS),
        )
    )
