"""First-match rule containment kernel (reference C12's hot loop,
AssociationRules.scala:88-102) as chunked matmuls + a running argmin.

The reference scans the confidence-sorted rule list per user basket until
the first rule whose antecedent is a subset of the basket fires (:95-102).
On TPU, for a batch of (deduplicated) baskets U ∈ {0,1}^{Nb×F} and rule
antecedents A ∈ {0,1}^{R×F} sorted by priority:

- containment:  ``U · Aᵀ == |antecedent|``  (int8 matmul, int32 acc);
- eligibility:  ``|antecedent| <= |basket|`` and consequent ∉ basket
  (:90 — the reference pre-filters, we mask);
- first match:  argmin over rule index with ineligible rows mapped to R.

Baskets are sharded over the mesh axis (data parallelism over users —
each device answers its own slice; no reduction needed); the rule tables
are replicated, the analog of the reference's rule broadcast (:76-78).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

AXIS = "txn"


# "No rule yet" sentinel in `best`.  A plain Python int, cast inside the
# traced kernels — a module-scope jnp scalar would initialize the JAX
# backend at import time (imports must stay backend-free so the CLI can
# fail gracefully when the accelerator tunnel is down).
NO_MATCH = 2**31 - 1


def local_first_match_chunk(
    baskets: jnp.ndarray,  # [Nb_local, F] int8
    basket_len: jnp.ndarray,  # [Nb_local] int32
    ant_cols: jnp.ndarray,  # [Rc, K] int32 — ONE priority chunk's
    #   antecedent item ranks; padding positions point at the guaranteed
    #   all-zero bitmap column (F_pad - 1), padding ROWS are all-padding
    ant_size: jnp.ndarray,  # [Rc] int32
    consequent: jnp.ndarray,  # [Rc] int32
    base: jnp.ndarray,  # () int32 — global index of this chunk's first rule
    best: jnp.ndarray,  # [Nb_local] int32 — running best global rule index
) -> jnp.ndarray:
    """Fold one rule chunk into the running first-match.

    The reference's per-user scan stops at the first hit (:95-102); the
    batch analog processes rules in priority-ordered chunks and keeps a
    running minimum, so the caller can stop dispatching chunks once every
    basket has matched — and the [Nb, R] eligibility matrix never exists
    at full R, only [Nb, Rc] per step.

    Antecedents arrive COMPACT ([Rc, K] column indexes, like the level
    engine's prefix_cols) and scatter to the one-hot [Rc, F] form on
    device: the dense form was ~13 MB per chunk over the host link at
    movielens scale (f_pad ~1.7K) vs ~400 KB compact — chunk uploads,
    not compute, dominated the scan on tunneled chips."""
    from fastapriori_tpu.ops.bitmap import scatter_one_hot

    rc = ant_cols.shape[0]
    antecedents = scatter_one_hot(ant_cols, baskets.shape[1])
    overlap = lax.dot_general(
        baskets,
        antecedents,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [Nb, Rc]
    contained = overlap == ant_size[None, :]
    size_ok = ant_size[None, :] <= basket_len[:, None]
    cons_in_basket = jnp.take(baskets, consequent, axis=1) > 0
    eligible = contained & size_ok & ~cons_in_basket
    idx = jnp.where(
        eligible,
        jnp.arange(rc, dtype=jnp.int32)[None, :] + base,
        jnp.int32(NO_MATCH),
    )
    return jnp.minimum(best, jnp.min(idx, axis=1))


def make_sharded_first_match_chunk(mesh: Mesh):
    """shard_map-wrapped, jitted chunk kernel: baskets and
    the running ``best`` vector sharded over the mesh axis, the rule
    chunk replicated."""
    return jax.jit(
        jax.shard_map(
            local_first_match_chunk,
            mesh=mesh,
            in_specs=(
                P(AXIS, None),
                P(AXIS),
                P(None, None),
                P(None),
                P(None),
                P(),
                P(AXIS),
            ),
            out_specs=P(AXIS),
        )
    )
