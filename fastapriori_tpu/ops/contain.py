"""First-match rule containment kernel (reference C12's hot loop,
AssociationRules.scala:88-102) as chunked matmuls + a running argmin.

The reference scans the confidence-sorted rule list per user basket until
the first rule whose antecedent is a subset of the basket fires (:95-102).
On TPU, for a batch of (deduplicated) baskets U ∈ {0,1}^{Nb×F} and rule
antecedents A ∈ {0,1}^{R×F} sorted by priority:

- containment:  ``U · Aᵀ == |antecedent|``  (int8 matmul, int32 acc);
- eligibility:  ``|antecedent| <= |basket|`` and consequent ∉ basket
  (:90 — the reference pre-filters, we mask);
- first match:  argmin over rule index with ineligible rows mapped to R.

Baskets are sharded over the mesh axis (data parallelism over users —
each device answers its own slice; no reduction needed); the rule tables
are replicated, the analog of the reference's rule broadcast (:76-78).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fastapriori_tpu import compat

AXIS = "txn"


# "No rule yet" sentinel in `best`.  A plain Python int, cast inside the
# traced kernels — a module-scope jnp scalar would initialize the JAX
# backend at import time (imports must stay backend-free so the CLI can
# fail gracefully when the accelerator tunnel is down).
NO_MATCH = 2**31 - 1


def local_first_match_chunk(
    baskets: jnp.ndarray,  # [Nb_local, F] int8
    basket_len: jnp.ndarray,  # [Nb_local] int32
    ant_cols: jnp.ndarray,  # [Rc, K] int32 — ONE priority chunk's
    #   antecedent item ranks; padding positions point at the guaranteed
    #   all-zero bitmap column (F_pad - 1), padding ROWS are all-padding
    ant_size: jnp.ndarray,  # [Rc] int32
    consequent: jnp.ndarray,  # [Rc] int32
    base: jnp.ndarray,  # () int32 — global index of this chunk's first rule
    best: jnp.ndarray,  # [Nb_local] int32 — running best global rule index
) -> jnp.ndarray:
    """Fold one rule chunk into the running first-match.

    The reference's per-user scan stops at the first hit (:95-102); the
    batch analog processes rules in priority-ordered chunks and keeps a
    running minimum, so the caller can stop dispatching chunks once every
    basket has matched — and the [Nb, R] eligibility matrix never exists
    at full R, only [Nb, Rc] per step.

    Antecedents arrive COMPACT ([Rc, K] column indexes, like the level
    engine's prefix_cols) and expand to the one-hot [Rc, F] form on
    device: the dense form was ~13 MB per chunk over the host link at
    movielens scale (f_pad ~1.7K) vs ~400 KB compact — chunk uploads,
    not compute, dominated the scan on tunneled chips.  The expansion
    is a broadcast compare-and-sum, NOT a scatter: TPU scatters cost
    ~200 ns per index (40 s across a webdocs-scale 16M-rule no-match
    scan), while the [Rc, K, F] compare tree is plain VPU work that
    XLA fuses into the matmul's operand."""
    rc = ant_cols.shape[0]
    f = baskets.shape[1]
    # [Rc, F]; pad positions all point at the guaranteed all-zero bitmap
    # column, whose duplicate count contributes 0 to every overlap.
    antecedents = jnp.sum(
        (
            ant_cols[:, :, None]
            == jnp.arange(f, dtype=ant_cols.dtype)[None, None, :]
        ).astype(jnp.int8),
        axis=1,
        dtype=jnp.int8,
    )
    overlap = lax.dot_general(
        baskets,
        antecedents,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [Nb, Rc]
    contained = overlap == ant_size[None, :]
    size_ok = ant_size[None, :] <= basket_len[:, None]
    cons_in_basket = jnp.take(baskets, consequent, axis=1) > 0
    eligible = contained & size_ok & ~cons_in_basket
    idx = jnp.where(
        eligible,
        jnp.arange(rc, dtype=jnp.int32)[None, :] + base,
        jnp.int32(NO_MATCH),
    )
    return jnp.minimum(best, jnp.min(idx, axis=1))


def local_first_match_scan(
    baskets: jnp.ndarray,  # [Nb_local, F] int8
    basket_len: jnp.ndarray,  # [Nb_local] int32 (0 on padding rows)
    ant_cols: jnp.ndarray,  # [R_pad, K] int32 — the FULL resident table
    ant_size: jnp.ndarray,  # [R_pad] int32 (padding rows: > F, never hit)
    consequent: jnp.ndarray,  # [R_pad] int32
    *,
    chunk: int,
    axis_name=None,
):
    """The whole priority scan as ONE device program: a ``lax.while_loop``
    over rule chunks with the early exit ON DEVICE (stop as soon as every
    real local basket has a match — padding rows, ``basket_len == 0``,
    are excluded or they would pin the loop to full length).

    Replaces the host-driven chunk loop whose per-chunk uploads and
    lagged early-exit fetches were link-bound on tunneled chips
    (VERDICT weak #4): the rule table is resident (uploaded once per
    recommender instance), each dispatch costs only the basket upload +
    one [Nb_local] result fetch.  Exactness: later chunks hold only
    larger rule indices, so stopping once every real row is below
    NO_MATCH cannot change the running minimum.

    Returns ``(best [Nb_local] int32, chunks_run () int32)`` —
    ``chunks_run`` (the max across shards when meshed) feeds the MAC
    accounting that the mining phases already have."""
    r_pad = ant_cols.shape[0]
    n_chunks = r_pad // chunk
    real = basket_len > 0

    def cond(state):
        c, best = state
        return (c < n_chunks) & jnp.any(real & (best == jnp.int32(NO_MATCH)))

    def body(state):
        c, best = state
        base = c * chunk
        best = local_first_match_chunk(
            baskets,
            basket_len,
            lax.dynamic_slice_in_dim(ant_cols, base, chunk, 0),
            lax.dynamic_slice_in_dim(ant_size, base, chunk, 0),
            lax.dynamic_slice_in_dim(consequent, base, chunk, 0),
            base,
            best,
        )
        return c + 1, best

    best0 = jnp.full(baskets.shape[0], NO_MATCH, dtype=jnp.int32)
    if axis_name is not None:
        # The carry varies over the mesh axis (it is derived from the
        # sharded baskets); mark the initial value to match.
        best0 = compat.pcast(best0, (axis_name,), to="varying")
    c, best = lax.while_loop(cond, body, (jnp.int32(0), best0))
    if axis_name is not None:
        # Shards may exit at different chunks (no collectives inside the
        # loop); report the deepest scan for the cost model.
        c = lax.pmax(c, axis_name)
    return best, c


def make_sharded_first_match_scan(mesh: Mesh, chunk: int):
    """shard_map-wrapped, jitted resident-table scan: baskets and the
    result sharded over the mesh axis, rule tables replicated (the
    reference's rule broadcast, AssociationRules.scala:76-78)."""
    import functools

    return jax.jit(
        compat.shard_map(
            functools.partial(
                local_first_match_scan, chunk=chunk, axis_name=AXIS
            ),
            mesh=mesh,
            in_specs=(
                P(AXIS, None),
                P(AXIS),
                P(None, None),
                P(None),
                P(None),
            ),
            out_specs=(P(AXIS), P()),
        )
    )
