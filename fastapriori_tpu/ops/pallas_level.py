"""Pallas TPU kernel for the hot op: fused prefix-containment + weighted
extension counting (reference C8's hot loops, FastApriori.scala:143-152).

The XLA formulation (ops/count.py local_level_gather) materializes
``member = (B Sᵀ == k-1)`` — a [tc, P] intermediate — in HBM and reads
it back for the counting matmul; measured on v5e that write+read traffic
(not the MXU) bounds the whole level phase (~57-120 TOPS effective at
webdocs shapes).  This kernel keeps each ``member`` tile in VMEM: one
grid step loads a transaction tile of the bitmap B and of the
pre-scaled ``WB = w ⊙ B``, computes B's overlap with the block's prefix
rows on the MXU, thresholds in-register, and immediately feeds the
``common`` tile into the counting matmul against WB, accumulating the
output block in place — HBM traffic for ``member`` drops from 2·T·P
bytes to zero.

Design notes from the measured variants (chain-delta timed on v5e at
[T=426K, P=8192, F=256]):

- **WB as an input** (w folded into the F-wide operand on the XLA side,
  one [T, F] int8 elementwise per level) instead of the earlier
  in-kernel ``where(common, w, 0)`` select: the select ran in int32
  (Mosaic has no int8 vector multiply on v5e) and serialized against
  both matmuls; the WB form measures **~378 TOPS-equiv — 96% of the
  int8 MXU peak** vs ~120 for the best XLA formulation.
- Digit count is NOT a kernel concern: the caller passes one WB per
  weight digit (production corpora are all single-digit after the
  weight split, models/apriori.py _split_weights; the engine falls back
  to the XLA path for the rare multi-digit profile).
- ``k-1`` rides scalar prefetch (SMEM), so one compilation serves every
  level depth at a given shape.

Grid: (P tiles, T tiles); T is the innermost (fastest) axis so each
output block [M_TILE, F] is initialized at its first T step and
accumulated in place across the sweep (the standard Pallas accumulation
pattern).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fastapriori_tpu import compat

# Default VMEM-friendly tile sizes (int8 min tile is (32, 128)).  The
# in-VMEM [M_TILE, T_TILE] membership tile is the budget driver:
# 1024 x 4096 x 4 B (int32 overlap) = 16 MB.
T_TILE = 4096
M_TILE = 1024


def _kernel(km1_ref, b_ref, wb_ref, s_ref, out_ref):
    """One (m_tile, t_tile) grid step.

    km1_ref: SMEM (1,) int32 — [k-1]
    b_ref:   VMEM [T_TILE, F] int8 bitmap tile
    wb_ref:  VMEM [T_TILE, F] int8 pre-scaled (w ⊙ B) tile
    s_ref:   VMEM [M_TILE, F] int8 prefix-set tile
    out_ref: VMEM [M_TILE, F] int32 accumulated counts
    """
    t_idx = pl.program_id(1)

    @pl.when(t_idx == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    overlap = lax.dot_general(
        s_ref[:],
        b_ref[:],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [M_TILE, T_TILE]
    common = (overlap == km1_ref[0]).astype(jnp.int8)
    out_ref[:] += lax.dot_general(
        common,
        wb_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [M_TILE, F]


def pick_tile(n: int, candidates=(4096, 2048, 1024, 512, 256)) -> int:
    """Largest candidate tile evenly dividing ``n`` (0 = none fits)."""
    for c in candidates:
        if n % c == 0:
            return c
    return 0


@functools.partial(
    jax.jit,
    static_argnames=("t_tile", "m_tile", "interpret"),
)
def level_counts_pallas(
    bitmap: jnp.ndarray,  # [T, F] int8
    wb: jnp.ndarray,  # [T, F] int8 — w ⊙ B (single weight digit)
    s_mat: jnp.ndarray,  # [M, F] int8
    km1: jnp.ndarray,  # scalar int32 (k-1)
    t_tile: int = T_TILE,
    m_tile: int = M_TILE,
    interpret: bool = False,
) -> jnp.ndarray:
    """counts[m, f] = Σ_t w_t · [basket t ⊇ prefix m] · B[t, f] (int32),
    with the weights pre-folded into ``wb = w[:, None] * bitmap``."""
    t, f = bitmap.shape
    m = s_mat.shape[0]
    assert t % t_tile == 0, (t, t_tile)
    assert m % m_tile == 0, (m, m_tile)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m // m_tile, t // t_tile),
        in_specs=[
            pl.BlockSpec(
                (t_tile, f), lambda i, j, _s: (j, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (t_tile, f), lambda i, j, _s: (j, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (m_tile, f), lambda i, j, _s: (i, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (m_tile, f), lambda i, j, _s: (i, 0), memory_space=pltpu.VMEM
        ),
    )
    # Under shard_map (check_vma=True) the output must declare how it
    # varies over mesh axes: exactly as the union of the inputs.
    vma = frozenset()
    for arr in (bitmap, wb, s_mat):
        vma = vma | getattr(compat.typeof(arr), "vma", frozenset())
    return pl.pallas_call(
        _kernel,
        out_shape=compat.shape_dtype_struct((m, f), jnp.int32, vma=vma),
        grid_spec=grid_spec,
        interpret=interpret,
    )(km1.reshape(1).astype(jnp.int32), bitmap, wb, s_mat)
