"""Pallas TPU kernel for the hot op: fused prefix-containment + weighted
extension counting (reference C8's hot loops, FastApriori.scala:143-152).

STATUS: reference kernel, not wired into the mining engine.  Proven
Mosaic-compiled and bit-exact on real v5e (tests_tpu/test_pallas_hw.py),
but at production webdocs shapes it measured device-time parity with the
XLA formulation (both ~35 ms at [T=1.66M, P=4096, F=256, D=2] — round 3,
dependency-chained timing), so the engine keeps the single XLA path
(ops/count.py local_level_gather) and this stays as the VMEM-resident
formulation for future wider-item workloads where XLA's [tc, P]
intermediates would dominate.

The XLA version (ops/fused.py) materializes ``common = (B Sᵀ == k-1)`` —
a [T, M] int8 intermediate — in HBM and reads it back for the counting
matmul.  This kernel keeps each ``common`` tile in VMEM: one grid step
loads a transaction tile of the bitmap, computes its overlap with every
candidate prefix on the MXU, thresholds in-register, applies the weight
digit, and accumulates the extension-count matmul into the output block —
HBM traffic for ``common`` drops from 2·T·M bytes to zero.

Grid: (M tiles, T tiles); T is the innermost (fastest) axis so each output
block [M_TILE, F] is initialized at its first T step and accumulated in
place across the sweep (the standard Pallas accumulation pattern).

Inputs are the same device arrays the fused engine already holds: the
int8 bitmap [T, F], per-transaction weight digits [D, T] int8 (base-128,
ops/bitmap.py), and the frequent-set matrix S [M, F] int8.  ``k-1`` and
the digit count are scalars prefetched to SMEM, so one compilation serves
every level and weight profile.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# VMEM-friendly tile sizes (int8 min tile is (32, 128)).
T_TILE = 512
M_TILE = 512
MAX_DIGITS = 4  # static unroll bound for base-128 weight digits


def _kernel(km1_ref, b_ref, wd_ref, s_ref, out_ref):
    """One (m_tile, t_tile) grid step.

    km1_ref: SMEM (2,) int32 — [k-1, n_digits]
    b_ref:   VMEM [T_TILE, F] int8 bitmap tile
    wd_ref:  VMEM [D, T_TILE] int8 weight digits
    s_ref:   VMEM [M_TILE, F] int8 prefix-set tile
    out_ref: VMEM [M_TILE, F] int32 accumulated counts
    """
    t_idx = pl.program_id(1)

    @pl.when(t_idx == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    km1 = km1_ref[0]
    n_digits = km1_ref[1]

    overlap = lax.dot_general(
        s_ref[:],
        b_ref[:],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [M_TILE, T_TILE]
    common = overlap == km1  # bool mask

    # Unrolled digit loop with static bound; digits beyond n_digits are
    # masked to zero scale so they contribute nothing.  The masked weight
    # is a select, not an int8 multiply — Mosaic has no int8 vector
    # `muli` lowering on v5e (fails to legalize).  The select runs in
    # int32 (same (8,128) tiling as the i1 mask from the int32 compare;
    # mixing the mask with (32,128)-tiled int8 operands is an invalid
    # relayout), then truncates to int8 to feed the MXU.
    total = jnp.zeros_like(out_ref)
    for d in range(MAX_DIGITS):
        w_d = wd_ref[d, :].astype(jnp.int32)  # [T_TILE]
        scaled = jnp.where(common, w_d[None, :], 0).astype(jnp.int8)
        part = lax.dot_general(
            scaled,
            b_ref[:],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [M_TILE, F]
        scale = jnp.where(d < n_digits, jnp.int32(128) ** d, 0)
        total = total + part * scale
    out_ref[:] += total


def level_counts_pallas(
    bitmap: jnp.ndarray,  # [T, F] int8
    w_digits: jnp.ndarray,  # [D, T] int8 (D <= MAX_DIGITS)
    s_mat: jnp.ndarray,  # [M, F] int8
    km1: jnp.ndarray,  # scalar int32 (k-1)
    interpret: bool = False,
) -> jnp.ndarray:
    """counts[m, f] = Σ_t w_t · [basket t ⊇ prefix m] · B[t, f] (int32)."""
    t, f = bitmap.shape
    m = s_mat.shape[0]
    d = w_digits.shape[0]
    assert t % T_TILE == 0, (t, T_TILE)
    assert m % M_TILE == 0, (m, M_TILE)
    assert d <= MAX_DIGITS

    wd_pad = jnp.zeros((MAX_DIGITS, t), dtype=jnp.int8).at[:d].set(w_digits)
    scalars = jnp.stack(
        [km1.astype(jnp.int32), jnp.int32(d)]
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m // M_TILE, t // T_TILE),
        in_specs=[
            pl.BlockSpec(
                (T_TILE, f), lambda i, j, _s: (j, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (MAX_DIGITS, T_TILE),
                lambda i, j, _s: (0, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (M_TILE, f), lambda i, j, _s: (i, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (M_TILE, f), lambda i, j, _s: (i, 0), memory_space=pltpu.VMEM
        ),
    )
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((m, f), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(scalars, bitmap, wd_pad, s_mat)
