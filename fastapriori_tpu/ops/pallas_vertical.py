"""Pallas TPU kernels for the vertical engine's AND+popcount hot loop
and the serving scan's strided first-match (ROADMAP direction 3; the
vertical twin of ops/pallas_level.py's HBM-traffic-to-zero move).

**Vertical popcount kernel.**  The XLA formulation
(ops/vertical.py vertical_level_local) materializes the prefix AND
``pref = AND_k arena[prefix_cols[:, k]]`` — a ``[P_cap, NL]`` uint32
intermediate — in HBM and gathers it back per candidate chunk; at big T
that write+read traffic bounds the level phase exactly like the bitmap
engine's ``member`` tile did before pallas_level.  This kernel keeps the
prefix intersections VMEM-resident across the candidate chunk: grid
``(lane tiles, candidate tiles)`` with the candidate axis innermost, so
one lane tile of the arena and the weight bit-planes is loaded per outer
step, the prefix rows are ANDed in-register ONCE per lane tile (at the
first candidate step, into a VMEM scratch that stays resident for the
whole candidate sweep), and each candidate tile accumulates
``Σ_b 2^b·popcount(inter & plane_b)`` into a VMEM-resident [1, C] output
— the ``[P_cap, NL]`` intermediate is never written to HBM
(``member_bytes_saved = 2·4·P_cap·NL`` per launch, the bench
engine-compare HBM-traffic model).

**Strided first-match kernel.**  The serving scan's per-shard while_loop
(ops/contain.py local_strided_match_scan) early-exits on chunks; this
kernel instead sweeps EVERY rule tile with a running min — bit-exact
because later chunks hold only strictly larger global ranks, so the min
over all rules equals the early-exit result (the trade: no data-
dependent exit, but one fused launch with the rank-argmin in-register).
The cross-shard pmin/pmax merge stays in contain.py, shared with the
XLA path verbatim.

**Correctness vs performance split.**  Interpreter mode
(``interpret=True``) is the correctness contract — tests pin both
kernels bit-exact against the XLA vertical path and the bitmap
differential oracle on CPU.  Real-chip compilation (gather + popcount
lowering on the VPU) is only exercised on TPU runs; the runtime gate in
parallel/mesh.py walks to the exact-by-construction XLA path on any
failure (CHAINS ``vertical_kernel``/``serve_scan``), so a Mosaic
lowering gap degrades throughput, never correctness.

Tile planning: the VMEM budget driver is the resident set
``(arena rows + planes + prefix scratch + candidate tile) · lane_tile``
words; :func:`plan_vertical_tiles` walks the pow2 lane-tile ladder until
it fits (None = fall back to XLA).  Tile shape constants are pow2
multiples of 128 lanes (G005).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fastapriori_tpu import compat
from fastapriori_tpu.ops.pallas_level import pick_tile

# Candidate/lane tile ladders (pow2, lane-dim multiples of 128 for the
# VPU lane width).  VMEM_BUDGET leaves headroom under the ~16 MB/core
# v5e budget for Mosaic's own double-buffering.
CAND_TILE_CANDIDATES = (512, 256, 128)
LANE_TILE_CANDIDATES = (4096, 2048, 1024, 512, 256, 128)
VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def plan_vertical_tiles(
    p_cap: int, f_pad: int, n_planes: int, c_cap: int, lane_cap: int
):
    """Pick ``(cand_tile, lane_tile)`` for :func:`vertical_counts_pallas`
    fitting the VMEM budget, or None when no shape fits (the caller then
    stays on the XLA vertical path).  ``lane_cap`` is the strict
    FA_VERTICAL_LANE_TILE-bucketed ceiling — the same knob that bounds
    the XLA path's lane streaming, so both tiers stream identically."""
    ct = pick_tile(c_cap, CAND_TILE_CANDIDATES)
    if not ct:
        return None
    for lt in LANE_TILE_CANDIDATES:
        if lt > max(int(lane_cap), 128):
            continue
        resident = (f_pad + 1 + n_planes + p_cap + ct) * lt
        if resident * 4 + 8 * c_cap <= VMEM_BUDGET_BYTES:
            return (ct, lt)
    return None


def _vertical_kernel(
    pc_ref,  # SMEM [P, K] int32 prefix cols (identity-remapped)
    a_ref,  # VMEM [f_pad+1, LT] uint32 arena lane tile
    w_ref,  # VMEM [B, LT] uint32 weight bit-plane lane tile
    cand_ref,  # VMEM [1, C] int32 flat candidate indices (whole)
    out_ref,  # VMEM [1, C] int32 accumulated counts (whole, resident)
    pref_ref,  # VMEM scratch [P, LT] uint32 prefix AND for this lane tile
    *,
    scales,
    f_pad,
    cand_tile,
):
    t = pl.program_id(0)
    c = pl.program_id(1)

    @pl.when((t == 0) & (c == 0))
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # One prefix AND per lane tile, at the first candidate step; the
    # scratch stays VMEM-resident across the whole candidate sweep —
    # the [P_cap, NL] HBM intermediate of the XLA path never exists.
    @pl.when(c == 0)
    def _prefix():
        a = a_ref[...]
        cols = pc_ref[...]
        acc = jnp.take(a, cols[:, 0], axis=0)
        for i in range(1, cols.shape[1]):
            acc = acc & jnp.take(a, cols[:, i], axis=0)
        pref_ref[...] = acc

    ix = cand_ref[0, pl.ds(c * cand_tile, cand_tile)]
    row = ix // jnp.int32(f_pad)
    y = ix % jnp.int32(f_pad)
    a = a_ref[...]
    inter = jnp.take(pref_ref[...], row, axis=0) & jnp.take(a, y, axis=0)
    total = None
    for b, scale in enumerate(scales):
        pc = lax.population_count(inter & w_ref[b, :][None, :])
        part = jnp.sum(pc.astype(jnp.int32), axis=1)
        part = part if scale == 1 else part * jnp.int32(scale)
        total = part if total is None else total + part
    cur = out_ref[0, pl.ds(c * cand_tile, cand_tile)]
    out_ref[0, pl.ds(c * cand_tile, cand_tile)] = cur + total


@functools.partial(
    jax.jit,
    static_argnames=("scales", "cand_tile", "lane_tile", "interpret"),
)
def vertical_counts_pallas(
    arena: jnp.ndarray,  # [f_pad+1, NL] uint32 (row f_pad = AND identity)
    w_planes: jnp.ndarray,  # [B, NL] uint32
    prefix_cols: jnp.ndarray,  # [P, K] int (padding -> zero column)
    cand_idx: jnp.ndarray,  # [C] int32 flat row·f_pad + y
    scales: tuple,  # static, len B
    cand_tile: int,
    lane_tile: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """LOCAL per-candidate weighted intersection counts (int32[C]) —
    the drop-in body of ops/vertical.py ``vertical_level_local``; the
    sparse/psum cross-shard reduction stays outside, shared with the
    XLA path.  Lanes are zero-padded to the tile multiple (zero bits
    contribute 0 to every popcount — the vertical_pair_local padding
    argument), so any NL streams exactly."""
    f_pad = arena.shape[0] - 1
    nl = arena.shape[1]
    c = cand_idx.shape[0]
    assert c % cand_tile == 0, (c, cand_tile)
    p = prefix_cols.shape[0]
    # Padded prefix positions carry the horizontal engine's zero column
    # f_pad-1; for the AND they must be the identity row f_pad (the
    # _prefix_and remap, hoisted to the host side of the kernel).
    cols = prefix_cols.astype(jnp.int32)
    cols = jnp.where(cols == f_pad - 1, jnp.int32(f_pad), cols)
    nlt = -(-nl // lane_tile) * lane_tile
    if nlt > nl:
        arena = jnp.pad(arena, ((0, 0), (0, nlt - nl)))
        w_planes = jnp.pad(w_planes, ((0, 0), (0, nlt - nl)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nlt // lane_tile, c // cand_tile),
        in_specs=[
            pl.BlockSpec(
                (f_pad + 1, lane_tile),
                lambda t, cc, _pc: (0, t),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (w_planes.shape[0], lane_tile),
                lambda t, cc, _pc: (0, t),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                # lint: waive G005 -- single-row candidate-index vector: sublane pads 1->8 (7 wasted rows of one int32 vector, bounded); the lane dim is cand-tile-aligned
                (1, c), lambda t, cc, _pc: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            # lint: waive G005 -- single-row count accumulator, same sublane 1->8 padding trade as the candidate vector above
            (1, c), lambda t, cc, _pc: (0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.VMEM((p, lane_tile), jnp.uint32)],
    )
    vma = frozenset()
    for arr in (arena, w_planes, prefix_cols, cand_idx):
        vma = vma | getattr(compat.typeof(arr), "vma", frozenset())
    out = pl.pallas_call(
        functools.partial(
            _vertical_kernel,
            scales=tuple(scales),
            f_pad=f_pad,
            cand_tile=cand_tile,
        ),
        out_shape=compat.shape_dtype_struct((1, c), jnp.int32, vma=vma),
        grid_spec=grid_spec,
        interpret=interpret,
    )(cols, arena, w_planes, cand_idx.reshape(1, c).astype(jnp.int32))
    return out.reshape(-1)


def _match_kernel(
    s_ref,  # SMEM (1,) int32 — this shard's mesh index
    b_ref,  # VMEM [MB, F] int8 basket one-hot (whole, resident)
    blen_ref,  # VMEM [MB, 1] int32 basket sizes
    ant_ref,  # VMEM [RT, K] int32 antecedent cols (padding -> zero col)
    size_ref,  # VMEM [RT, 1] int32 antecedent sizes
    cons_ref,  # VMEM [RT, 1] int32 consequent cols
    out_ref,  # VMEM [MB, 1] int32 running best global rank
    *,
    n_shards,
    rule_tile,
    no_match,
):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        out_ref[:] = jnp.full_like(out_ref, no_match)

    b = b_ref[...]
    ant = ant_ref[...]
    # One-pass antecedent gather (ISSUE 19 satellite): a single flat
    # take over all RT*K columns replaces K separate [MB, RT] sweeps —
    # one gather instead of K per rule tile.  Bit-exact vs the K-pass
    # form: the same int32 membership bits sum per (row, rule), and
    # padding slots still gather column 0 (a zero column).
    rt, k_width = ant.shape
    gathered = jnp.take(b, ant.reshape(-1), axis=1).astype(jnp.int32)
    overlap = gathered.reshape(b.shape[0], rt, k_width).sum(axis=2)
    size = size_ref[...].reshape(-1)  # [RT]
    cons = cons_ref[...].reshape(-1)
    blen = blen_ref[...]  # [MB, 1]
    cons_in = jnp.take(b, cons, axis=1).astype(jnp.int32)  # [MB, RT]
    eligible = (
        (overlap == size[None, :])
        & (size[None, :] <= blen)
        & (cons_in == 0)
    )
    local = r * rule_tile + lax.broadcasted_iota(
        jnp.int32, (1, rule_tile), 1
    )
    ranks = local * jnp.int32(n_shards) + s_ref[0]
    best = jnp.min(
        jnp.where(eligible, ranks, jnp.int32(no_match)), axis=1
    )
    out_ref[...] = jnp.minimum(out_ref[...], best[:, None])


@functools.partial(
    jax.jit,
    static_argnames=("n_shards", "rule_tile", "no_match", "interpret"),
)
def strided_best_rank_pallas(
    baskets: jnp.ndarray,  # [MB, F] int8 one-hot (dup counts ok)
    basket_len: jnp.ndarray,  # [MB] int32
    ant_cols: jnp.ndarray,  # [R_loc, K] int32 (padding -> zero col)
    ant_size: jnp.ndarray,  # [R_loc] int32 (padding > F)
    consequent: jnp.ndarray,  # [R_loc] int32 (padding -> zero col)
    shard: jnp.ndarray,  # () int32 this shard's mesh index
    n_shards: int,
    rule_tile: int,
    no_match: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Per-shard best GLOBAL rank (int32[MB]; ``no_match`` where no
    local rule fires) — the Pallas body of ops/contain.py
    ``local_strided_match_scan``: every rule tile swept with a running
    min (no early exit; exact because later tiles hold only larger
    ranks).  The pmin/pmax shard merge stays in contain.py."""
    mb, f = baskets.shape
    r_loc, _k = ant_cols.shape
    assert r_loc % rule_tile == 0, (r_loc, rule_tile)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r_loc // rule_tile,),
        in_specs=[
            pl.BlockSpec(
                (mb, f), lambda r, _s: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                # lint: waive G005 -- per-basket length column: one int32 per basket row, kept column-shaped so it broadcasts against the [mb, rule_tile] eligibility mask; lane pads 1->128 on mb<=batch-cap rows, bounded
                (mb, 1), lambda r, _s: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (rule_tile, ant_cols.shape[1]),
                lambda r, _s: (r, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                # lint: waive G005 -- per-rule antecedent-size column (one int32 per rule of the tile); lane pads 1->128, bounded
                (rule_tile, 1), lambda r, _s: (r, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                # lint: waive G005 -- per-rule consequent column, same 1->128 lane padding trade as the size column above
                (rule_tile, 1), lambda r, _s: (r, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            # lint: waive G005 -- per-basket best-rank column accumulator; lane pads 1->128, bounded (mb rows only)
            (mb, 1), lambda r, _s: (0, 0), memory_space=pltpu.VMEM
        ),
    )
    vma = frozenset()
    for arr in (baskets, basket_len, ant_cols, ant_size, consequent, shard):
        vma = vma | getattr(compat.typeof(arr), "vma", frozenset())
    out = pl.pallas_call(
        functools.partial(
            _match_kernel,
            n_shards=n_shards,
            rule_tile=rule_tile,
            no_match=no_match,
        ),
        out_shape=compat.shape_dtype_struct((mb, 1), jnp.int32, vma=vma),
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        shard.reshape(1).astype(jnp.int32),
        baskets,
        basket_len.reshape(mb, 1).astype(jnp.int32),
        ant_cols.astype(jnp.int32),
        ant_size.reshape(r_loc, 1).astype(jnp.int32),
        consequent.reshape(r_loc, 1).astype(jnp.int32),
    )
    return out.reshape(-1)
