"""Reliability layer: deterministic fault injection, bounded retries,
and the degradation ledger.

The reference inherited all of this from Spark (RDD lineage re-execution,
task retries, loud executor loss); the JAX port replaced that substrate
with raw ``shard_map``/``psum`` and had nothing — a crash anywhere in a
multi-hour mine lost everything, and every graceful fallback degraded
silently.  Three cooperating parts (each module documents its own
contract):

- :mod:`~fastapriori_tpu.reliability.failpoints` — named injection sites
  (``FA_FAILPOINTS``) so every failure path is testable on CPU;
- :mod:`~fastapriori_tpu.reliability.retry` — transient/fatal/user error
  classification + bounded backoff around device fetches and fs ops;
- :mod:`~fastapriori_tpu.reliability.ledger` — structured, warn-once
  degradation events into the metrics/bench record.

Crash-safe *persistence* (atomic writes, the per-run ``MANIFEST.json``,
mid-mine checkpoints) lives with the artifact formats in
``fastapriori_tpu/io/``; it consumes this package's failpoints and
ledger."""

from fastapriori_tpu.reliability import failpoints, ledger, retry  # noqa: F401
