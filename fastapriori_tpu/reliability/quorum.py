"""Cross-process fault domain: cascade consensus, peer-death detection
and fenced checkpoints (ISSUE 12 tentpole).

PR 9 proved the chaos invariant (byte-identical-or-classified-or-
degraded, never a hang) on a single-host virtual mesh; the CHAINS
cascade it built is exactly the mechanism that can *deadlock* a real
multi-process mesh.  Every chain walk that changes collective shape or
count — engine fused→level, mine_engine vertical→bitmap, count_reduce
sparse→dense, rule_engine sharded→host — is a PER-PROCESS decision
(transient exhaustion is local: one rank's flaky link, one rank's
injected failpoint).  If rank r degrades and its peers do not, the two
sides issue collectives with different shapes/counts and the mesh hangs
forever — the classic failure mode of the exchange layouts the sparse
allreduce construction (arxiv 1312.3020) relies on.  This module makes
that divergence impossible by construction:

**Cascade consensus.**  Every shape-changing downgrade becomes an
epoch-stamped *proposal*: :func:`~fastapriori_tpu.reliability.watchdog.
downgrade` folds the new position into this process's published state
the moment it happens (before the next dispatch), and every sync point
(mine start, level boundaries, phase-2 start, run end) exchanges the
tiny fixed-shape position vector across processes.  All processes adopt
the elementwise MOST-DEGRADED position — a peer's transient exhaustion
degrades everyone in lockstep, ledger-recorded with the originating
rank (``quorum_adopt`` + the standard ``cascade`` event), so divergent
collectives cannot be issued.  Positions are forward-only, exactly like
the cascade itself.

**Peer-death detection.**  The consensus exchange and the phase
rendezvous are wall-bounded (``FA_QUORUM_TIMEOUT_S``), and every
process publishes a heartbeat (``FA_HEARTBEAT_MS``, a background
daemon thread on the file transport).  A killed or wedged peer
surfaces as a classified :class:`PeerLost` error NAMING THE RANK within
``attempts × FA_QUORUM_TIMEOUT_S`` (the exchange runs under the
standard bounded retry), instead of an indefinite collective hang.
PeerLost carries the ``UNAVAILABLE`` status so retry.classify sees a
transient — a flapping peer gets its retry; a dead one exhausts the
budget and the run dies classified.

**Divergence demonstration (consensus off).**  A domain built with
``consensus=False`` models the RAW mesh: sync points become collective
rendezvous comparing a digest of each rank's collective-shaping state
(positions + site).  A divergence-injected chain walk then does what a
real mesh would — the mismatched collective "hangs", bounded by the
quorum timeout into a classified :class:`MeshDivergence` naming both
ranks and digests.  tests/test_reliability.py pins both halves: hang
(bounded) without consensus, lockstep degradation with it.

**Fenced checkpoints.**  The domain owns a monotonic FENCE epoch
(``<dir>/FENCE``, atomically incremented under an exclusive lock).  The
checkpoint writer (quorum rank 0) acquires a fence once per run and
stamps it into the checkpoint meta AND ``MANIFEST.json``; a writer
whose fence has been superseded (split-brain: an old coordinator coming
back after a flap) is REJECTED at commit time (:class:`StaleFenceError`,
classified), and peers validate fence+signature at resume — a
mixed-epoch artifact can neither be committed nor resumed from.

**Transports.**  Single-process (the default): no domain, every hook is
a memoized no-op costing one attribute read.  The FILE transport
(``FA_QUORUM_DIR`` + ``FA_QUORUM_RANK`` + ``FA_QUORUM_PROCS``) backs
the simulated-multiprocess harness (``tools/chaos.py --procs N`` and
the test suites) — the same role PR 9's monkeypatched
``jax.process_index`` played, made real with actual subprocesses,
because the pinned jax 0.4.37 CPU backend refuses multiprocess
computations.  The JAX transport (real ``jax.distributed`` meshes,
``jax.process_count() > 1``) exchanges the same vector through
``process_allgather`` under the dispatch watchdog; its two-process
cases version-gate on jax >= 0.5 alongside tests/test_distributed.py.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from fastapriori_tpu.errors import InputError
from fastapriori_tpu.reliability import failpoints, ledger

# The consensus chains: the CHAINS entries whose position changes the
# SHAPE or COUNT of mesh collectives (watchdog.CHAINS keeps the full
# set; serving/rule_scan are host-local and never cross the mesh).
# Order is the wire format of the exchanged position vector — pinned by
# tests; reordering is a protocol change.
CONSENSUS_CHAINS: Tuple[str, ...] = (
    "engine",
    "mine_engine",
    "count_reduce",
    "rule_engine",
    # ISSUE 15: the hierarchical exchange issues DIFFERENT collectives
    # (two grouped stages) than the flat one, so a hier→flat walk is
    # collective-shaping and must clamp the whole domain.  Appended at
    # the END of the wire vector — existing position indices are
    # unchanged (the order stays a pinned protocol).
    "exchange",
    # ISSUE 17: whether a PeerLost aborts the in-flight level and
    # re-rendezvouses the survivors (continue) or classifies the run
    # dead (abort).  Consensus-registered so one rank's retry-budget
    # exhaustion clamps every survivor's next rejoin decision
    # identically.  Appended at the END (pinned wire order).
    "elastic",
    # ISSUE 18: the vertical Pallas kernel tier compiles a DIFFERENT
    # local program per shard than the XLA vertical path, so one rank's
    # pallas→xla walk must clamp every peer's next dispatch to the same
    # tier (the plan consult in parallel/mesh.py _vertical_pallas_plan
    # reads this floor).  serve_scan stays host-local — the serving
    # merge collectives are shape-identical across tiers.  Appended at
    # the END (pinned wire order).
    "vertical_kernel",
)

FENCE_NAME = "FENCE"


class PeerLost(RuntimeError):
    """A quorum peer died or wedged: no heartbeat / no rendezvous
    arrival within the bound.  The message leads with ``UNAVAILABLE``
    so retry.classify sees a transient — the exchange's bounded retry
    absorbs a flap, and exhaustion surfaces as this classified error
    naming the rank (never an indefinite collective hang)."""

    def __init__(self, rank: int, site: str, detail: str):
        self.rank = rank
        self.site = site
        super().__init__(
            f"UNAVAILABLE: quorum peer rank {rank} lost at {site!r} — "
            f"{detail}"
        )


class MeshDivergence(RuntimeError):
    """Collective-shape divergence detected at a rendezvous (consensus
    disabled — the raw-mesh failure mode this module exists to kill).
    Carries ``ABORTED`` so classification sees a transient: the bounded
    retry re-checks (a peer may still converge), and exhaustion is a
    classified error naming both sides instead of a hang."""


class MeshEpochAbort(RuntimeError):
    """A peer advanced the mesh epoch past this rank's (an elastic
    abort is in progress): the in-flight level must abort and
    re-rendezvous under the advertised epoch.  Deliberately carries NO
    transient status word — retry.classify sees "fatal", so the abort
    ESCAPES the bounded retry immediately (it is a control-flow signal
    for the elastic rejoin arm, not a failure to be retried)."""

    def __init__(self, target_epoch: int, dead, site: str, detail: str):
        self.target_epoch = int(target_epoch)
        self.dead = sorted(int(d) for d in dead)
        self.site = site
        super().__init__(
            f"mesh epoch superseded at {site!r}: {detail} — abort the "
            f"in-flight level and re-rendezvous at epoch {target_epoch}"
        )


class StaleFenceError(InputError):
    """A checkpoint commit or resume with a superseded fence epoch
    (split-brain writer).  InputError: the run cannot proceed against a
    newer coordinator's artifacts; the message names the checkpoint
    fence so the chaos invariant classifies it."""


def quorum_timeout_s() -> float:
    """``FA_QUORUM_TIMEOUT_S``: wall bound (seconds) on one consensus
    exchange / rendezvous wait (strict; default 30).  Total worst-case
    stall on a dead peer is ``retry attempts × this bound``."""
    from fastapriori_tpu.utils.env import env_float

    return env_float("FA_QUORUM_TIMEOUT_S", 30.0, minimum=0.1)


def heartbeat_ms() -> float:
    """``FA_HEARTBEAT_MS``: heartbeat publish interval (milliseconds,
    strict; default 200).  Must be well under the quorum timeout —
    liveness is judged by heartbeat age against the timeout."""
    from fastapriori_tpu.utils.env import env_float

    return env_float("FA_HEARTBEAT_MS", 200.0, minimum=1.0)


def epoch_retry_max() -> int:
    """``FA_EPOCH_RETRY_MAX``: elastic-mesh retry budget — the highest
    mesh epoch a run may reach by aborting in-flight levels and
    re-rendezvousing the survivors around lost peers (strict; default
    0 = elastic continuation DISABLED, a peer death stays a classified
    PeerLost).  Each survivor-set shrink consumes one epoch; exhaustion
    re-classifies as PeerLost — the bound is strict, never
    best-effort."""
    from fastapriori_tpu.utils.env import env_int

    return env_int("FA_EPOCH_RETRY_MAX", 0, minimum=0)


# ---------------------------------------------------------------------------
# transports


class FileTransport:
    """Shared-directory transport for the simulated-multiprocess mesh:
    one atomically-replaced state file per rank (positions + seq +
    publish time), marker files for rendezvous sites, a background
    daemon heartbeat, and best-effort exit markers so a cleanly-failed
    peer is detected immediately instead of after the staleness bound.
    All writes are tmp+rename (a reader never sees a torn file)."""

    def __init__(self, root: str, rank: int, nprocs: int):
        self.root = root
        self.rank = rank
        self.nprocs = nprocs
        os.makedirs(root, exist_ok=True)
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    # -- atomic helpers -------------------------------------------------
    def _write_json(self, name: str, doc: dict) -> None:
        path = os.path.join(self.root, name)
        tmp = path + f".tmp{self.rank}"
        # lint: waive G009 -- quorum control-plane state files, not run artifacts (atomic tmp+rename below)
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def _read_json(self, name: str) -> Optional[dict]:
        try:
            with open(os.path.join(self.root, name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            # Absent, or mid-replace on a non-atomic filesystem: the
            # caller polls; a persistent parse failure surfaces as a
            # missing peer (bounded → PeerLost), never a crash.
            return None

    # -- heartbeat ------------------------------------------------------
    def start_heartbeat(self) -> None:
        if self._hb_thread is not None:
            return
        interval = heartbeat_ms() / 1e3

        def beat() -> None:
            while not self._hb_stop.wait(interval):
                failpoints.fire("quorum.heartbeat")
                self._write_json(
                    f"hb.{self.rank}", {"t": time.time()}
                )

        self._write_json(f"hb.{self.rank}", {"t": time.time()})
        t = threading.Thread(
            target=beat, name=f"fa-quorum-hb:{self.rank}", daemon=True
        )
        t.start()
        self._hb_thread = t

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()

    def heartbeat_age(self, rank: int) -> Optional[float]:
        """Seconds since ``rank`` last published a heartbeat; None when
        it never has (not started yet, or the process died pre-start)."""
        doc = self._read_json(f"hb.{rank}")
        if doc is None:
            return None
        return max(0.0, time.time() - float(doc.get("t", 0.0)))

    # -- state / markers ------------------------------------------------
    def publish_state(self, doc: dict) -> None:
        self._write_json(f"state.{self.rank}", doc)

    def peer_state(self, rank: int) -> Optional[dict]:
        return self._read_json(f"state.{rank}")

    def post_marker(self, site: str, doc: dict) -> None:
        self._write_json(f"mark.{_site_slug(site)}.{self.rank}", doc)

    def peer_marker(self, site: str, rank: int) -> Optional[dict]:
        return self._read_json(f"mark.{_site_slug(site)}.{rank}")

    def post_exit(self, status: str) -> None:
        self._write_json(
            f"exit.{self.rank}", {"status": status, "t": time.time()}
        )

    def peer_exit(self, rank: int) -> Optional[dict]:
        return self._read_json(f"exit.{rank}")

    # -- fence ----------------------------------------------------------
    def _fence_lock(self, bound_s: float):
        """Exclusive-create lock file with a staleness bound: a lock
        older than the bound belongs to a dead writer and is broken
        (the new coordinator must be able to fence it out).  Breaking
        is ATOMIC — the stale lock is renamed aside, and exactly one
        breaker wins the rename — so two coordinators can never both
        conclude they broke the same lock and hold it concurrently
        (both would then stamp the same fence: the split-brain the
        fence exists to prevent)."""
        path = os.path.join(self.root, FENCE_NAME + ".lock")
        t0 = time.monotonic()
        while True:
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                return path
            except FileExistsError:
                try:
                    stale = (
                        time.time() - os.path.getmtime(path) > bound_s
                    )
                except OSError:
                    continue  # holder released mid-check — retry create
                if stale:
                    # One winner: os.rename is atomic, the loser's
                    # rename raises.  Either way, loop back to the
                    # exclusive create — the O_EXCL race stays the one
                    # and only lock arbiter.
                    aside = path + f".broken.{self.rank}.{os.getpid()}"
                    try:
                        os.rename(path, aside)
                        os.unlink(aside)
                    except OSError:
                        pass
                    continue
                if time.monotonic() - t0 > bound_s:
                    raise PeerLost(
                        -1,
                        "fence.lock",
                        f"fence lock held past {bound_s}s",
                    ) from None
                time.sleep(0.005)

    def current_fence(self) -> int:
        doc = self._read_json(FENCE_NAME)
        return int(doc["fence"]) if doc else 0

    def acquire_fence(self) -> int:
        """Atomically increment and return the fence epoch (monotonic
        across every writer that ever touches this domain dir)."""
        bound = quorum_timeout_s()
        lock = self._fence_lock(bound)
        try:
            fence = self.current_fence() + 1
            self._write_json(FENCE_NAME, {"fence": fence})
            return fence
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass


def _site_slug(site: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else "_" for c in site)


class JaxTransport:
    """Real-mesh transport: the position vector exchanges through
    ``multihost_utils.process_allgather`` (the same tiny-global-table
    channel sharded ingest already uses), each call bounded by the
    dispatch watchdog at the quorum timeout — a dead peer turns the
    collective into a classified DEADLINE_EXCEEDED instead of a hang,
    and exhaustion surfaces as :class:`PeerLost` naming the first
    non-responding rank the runtime reports (or -1 when it cannot).
    Heartbeats/fences ride a shared filesystem only when
    ``FA_QUORUM_DIR`` is ALSO set; otherwise fencing is inactive (the
    single-writer discipline still holds via process_index)."""

    def __init__(self, rank: int, nprocs: int):
        self.rank = rank
        self.nprocs = nprocs

    def exchange(self, vec, site: str, dtype=None):
        import numpy as np

        from fastapriori_tpu.reliability import watchdog

        from jax.experimental import multihost_utils

        if dtype is None:
            dtype = np.int32

        def thunk():
            # dtype follows the payload: position vectors are tiny
            # int32, but the W_s weight-total exchange carries int64
            # sums that a silent int32 cast would WRAP — corrupting
            # every rank's sparse prune thresholds identically (the
            # one corruption the divergence machinery cannot see).
            return multihost_utils.process_allgather(
                np.asarray(vec, dtype=dtype)
            )

        try:
            return watchdog.guard(
                thunk, f"quorum.{site}", timeout_s=quorum_timeout_s()
            )
        except watchdog.DispatchTimeout as exc:
            raise PeerLost(
                -1, site, f"consensus allgather timed out ({exc})"
            ) from exc


# ---------------------------------------------------------------------------
# the domain


class QuorumDomain:
    """One process's membership in a multi-process fault domain
    (module docstring).  Thread-safe; one instance per process (see
    :func:`active`), or constructed directly by tests/harnesses."""

    def __init__(
        self,
        transport,
        rank: int,
        nprocs: int,
        consensus: bool = True,
    ):
        if nprocs < 1 or not (0 <= rank < nprocs):
            raise InputError(
                f"quorum domain needs 0 <= rank < nprocs, got rank="
                f"{rank} nprocs={nprocs}"
            )
        self.transport = transport
        self.rank = rank
        self.nprocs = nprocs
        self.consensus = consensus
        # Elastic mesh (ISSUE 17): the monotonic mesh epoch every
        # quorum round is stamped with, and the CURRENT member set
        # (initially all ranks; shrinks at each elastic rejoin — dead
        # ranks are never waited on again).
        self.mesh_epoch = 0
        self.members: List[int] = list(range(nprocs))
        self._lock = threading.Lock()
        self._seq = 0
        # Per-site payload-exchange round counters (see exchange()).
        self._xseq: Dict[str, int] = {}
        # Per-chain agreed position (index into watchdog.CHAINS[chain];
        # 0 = most capable).  Forward-only, like the cascade.
        self._pos: Dict[str, int] = {c: 0 for c in CONSENSUS_CHAINS}
        self._fence: Optional[int] = None
        self._epoch_trail: List[Dict[str, Any]] = []
        # Post-rejoin hooks (ISSUE 17): artifact owners (the miner's
        # checkpoint writer) register a callback that re-commits their
        # last durable state under the re-derived fence, so a rejoin
        # absorbed OUTSIDE the level loop (e.g. at the post-mine
        # rendezvous) cannot strand an on-disk artifact at the old
        # fence while the end-of-run manifest advances.
        self._rejoin_hooks: List[Any] = []
        if isinstance(transport, FileTransport):
            transport.start_heartbeat()
            self._publish("init")

    # -- positions ------------------------------------------------------
    def _chain_order(self, chain: str) -> Tuple[str, ...]:
        from fastapriori_tpu.reliability import watchdog

        return watchdog.CHAINS[chain]

    def position(self, chain: str) -> int:
        with self._lock:
            return self._pos[chain]

    def floor_stage(self, chain: str) -> str:
        """The agreed most-degraded stage name for ``chain`` — engine
        resolution clamps its choice at least this far down."""
        return self._chain_order(chain)[self.position(chain)]

    def stage_allowed(self, chain: str, stage: str) -> bool:
        """True when ``stage`` is at or below (more degraded than) the
        agreed floor — i.e. this process may still run it."""
        order = self._chain_order(chain)
        return order.index(stage) >= self.position(chain)

    def propose(self, chain: str, stage: str, reason: str = "") -> None:
        """Raise this process's position for ``chain`` to ``stage`` and
        PUBLISH immediately (the epoch-stamped proposal: peers see it
        before their next exchange, which runs before their next
        dispatch).  Forward-only: a proposal below the current position
        is a no-op, never a backward walk."""
        if chain not in self._pos:
            return
        idx = self._chain_order(chain).index(stage)
        with self._lock:
            if idx <= self._pos[chain]:
                return
            self._pos[chain] = idx
        self._publish(f"propose:{chain}:{reason}")

    def _vector(self) -> List[int]:
        with self._lock:
            return [self._pos[c] for c in CONSENSUS_CHAINS]

    def _publish(self, site: str) -> None:
        if not isinstance(self.transport, FileTransport):
            return
        with self._lock:
            self._seq += 1
            seq = self._seq
            vec = [self._pos[c] for c in CONSENSUS_CHAINS]
            epoch = self.mesh_epoch
        self.transport.publish_state(
            {
                "seq": seq,
                "site": site,
                "pos": vec,
                "t": time.time(),
                # The elastic abort broadcast: peers polling this state
                # see the advanced epoch and abort their own in-flight
                # level (MeshEpochAbort) instead of waiting out the
                # bound against markers that will never pair.
                "mesh_epoch": epoch,
            }
        )

    def _esite(self, site: str) -> str:
        """Epoch-namespaced marker site: every rendezvous / exchange
        marker is scoped to the CURRENT mesh epoch, so a post-abort
        re-rendezvous can never pair with a pre-abort round's payload
        (the satellite fix — per-round counters alone only separate
        rounds within one epoch's lifetime)."""
        return f"e{self.mesh_epoch}.{site}"

    def _peer_epoch_check(self, rank: int, site: str) -> None:
        """Raise :class:`MeshEpochAbort` when ``rank``'s published
        state advertises a mesh epoch beyond ours — the peer aborted
        and is re-rendezvousing; waiting for its markers at OUR epoch
        would only time out."""
        st = self.transport.peer_state(rank)
        if st is None:
            return
        pe = int(st.get("mesh_epoch", 0))
        if pe > self.mesh_epoch:
            raise MeshEpochAbort(
                pe, (), site,
                f"peer rank {rank} published mesh epoch {pe} while "
                f"this rank is at {self.mesh_epoch}",
            )

    def _adopt(self, peer_vecs: Dict[int, List[int]], site: str) -> None:
        """Elementwise most-degraded-wins merge; each adoption that a
        PEER forced lands on the ledger as the standard cascade event
        (via watchdog.downgrade, reason="quorum") plus a
        ``quorum_adopt`` event naming the originating rank."""
        from fastapriori_tpu.reliability import watchdog

        for ci, chain in enumerate(CONSENSUS_CHAINS):
            best_rank, best = None, self.position(chain)
            for r, vec in peer_vecs.items():
                if len(vec) == len(CONSENSUS_CHAINS) and vec[ci] > best:
                    best, best_rank = vec[ci], r
            if best_rank is None:
                continue
            order = self._chain_order(chain)
            frm = order[self.position(chain)]
            to = order[min(best, len(order) - 1)]
            with self._lock:
                self._pos[chain] = min(best, len(order) - 1)
            ledger.record(
                "quorum_adopt",
                once_key=f"{chain}:{to}",
                chain=chain,
                frm=frm,
                to=to,
                rank=best_rank,
                site=site,
                epoch=self._seq,
            )
            watchdog.downgrade(
                chain,
                frm,
                to,
                reason="quorum",
                once_key=f"quorum:{chain}:{to}",
                # "rank" is the cascade event's own position field;
                # the originating process rides as src_rank.
                src_rank=best_rank,
                site=site,
            )
        self._publish(f"adopt:{site}")

    # -- liveness -------------------------------------------------------
    def _check_peer_alive(
        self, rank: int, site: str, waited_s: float, bound_s: float
    ) -> None:
        """Raise PeerLost when ``rank`` is demonstrably gone: an exit
        marker without the awaited arrival, or a heartbeat stale past
        the bound.  A peer that has not STARTED yet is given the full
        wait bound (subprocess startup skew is not death)."""
        t = self.transport
        ex = t.peer_exit(rank)
        if ex is not None:
            raise PeerLost(
                rank, site,
                f"peer exited ({ex.get('status', '?')}) before reaching "
                f"this point",
            )
        age = t.heartbeat_age(rank)
        if age is not None and age > bound_s:
            raise PeerLost(
                rank, site,
                f"no heartbeat for {age:.1f}s (bound {bound_s}s, "
                f"FA_QUORUM_TIMEOUT_S)",
            )
        if age is None and waited_s > bound_s:
            raise PeerLost(
                rank, site,
                f"never published a heartbeat within {bound_s}s",
            )

    # -- sync / rendezvous ----------------------------------------------
    def sync(self, site: str, wait: bool = False) -> None:
        """The consensus exchange at ``site``.  Non-blocking form
        (default): publish my positions, read every peer's CURRENT
        state, adopt most-degraded, and check heartbeats — one poll, no
        rendezvous.  ``wait=True``: a true rendezvous — block (bounded)
        until every peer has posted THIS site's marker, detecting a
        killed peer within the bound; with ``consensus=False`` the
        rendezvous additionally compares collective digests and raises
        :class:`MeshDivergence` on mismatch (the raw-mesh demo).

        The whole exchange runs under the standard bounded retry
        (site ``quorum.<site>``), so a transient flap — including an
        armed failpoint — is absorbed and exhaustion is classified;
        worst case stall is attempts × FA_QUORUM_TIMEOUT_S."""
        if self.nprocs == 1 or len(self.members) == 1:
            # A mesh elastically shrunk to one survivor keeps mining
            # alone: nothing left to rendezvous with.
            return
        if isinstance(self.transport, JaxTransport) and not wait:
            # The real-mesh exchange is itself a collective: every rank
            # must call it the same number of times, but the
            # non-blocking boundary syncs fire a DIFFERENT number of
            # times once a rank walks an engine chain (that is the
            # whole point).  Real meshes therefore exchange only at the
            # rendezvous points every rank passes exactly once
            # (run.start / mine.end / rules.start / run.end); mid-mine
            # adoption granularity is a file-transport property.
            return
        from fastapriori_tpu.obs import flight
        from fastapriori_tpu.reliability import retry

        def attempt():
            if isinstance(self.transport, JaxTransport):
                self._sync_jax(site)
            else:
                self._sync_file(site, wait)

        try:
            retry.call_with_retries(attempt, f"quorum.{_site_slug(site)}")
        except (PeerLost, MeshDivergence) as exc:
            # The post-mortem: the consensus epoch trail (every sync
            # this domain ran, with positions) rides the flight dump.
            kind = type(exc).__name__
            ledger.record(
                "peer_lost" if isinstance(exc, PeerLost) else
                "mesh_divergence",
                site=site,
                rank=getattr(exc, "rank", -1),
                error=str(exc)[:200],
            )
            flight.auto_dump(
                kind,
                extra={
                    "site": site,
                    "rank": self.rank,
                    "epoch_trail": self.epoch_trail(),
                },
            )
            raise
        with self._lock:
            self._epoch_trail.append(
                {
                    "epoch": self._seq,
                    "site": site,
                    "pos": [self._pos[c] for c in CONSENSUS_CHAINS],
                }
            )
            trail = self._epoch_trail[-1]
        flight.note("quorum", **trail)

    def _sync_jax(self, site: str) -> None:
        import numpy as np

        vec = np.asarray([self.rank] + self._vector(), dtype=np.int32)
        gathered = self.transport.exchange(vec, _site_slug(site))
        peer_vecs = {
            int(row[0]): [int(x) for x in row[1:]]
            for row in np.asarray(gathered)
            if int(row[0]) != self.rank
        }
        if self.consensus:
            self._adopt(peer_vecs, site)

    def _sync_file(self, site: str, wait: bool) -> None:
        t = self.transport
        bound = quorum_timeout_s()
        my_vec = self._vector()
        esite = self._esite(site)
        digest = f"{_site_slug(esite)}|" + ",".join(map(str, my_vec))
        self._publish(f"sync:{site}")
        if wait or not self.consensus:
            t.post_marker(esite, {"pos": my_vec, "digest": digest})
        peers = [r for r in self.members if r != self.rank]
        peer_vecs: Dict[int, List[int]] = {}
        t0 = time.monotonic()
        pending = list(peers)
        while True:
            still: List[int] = []
            for r in pending:
                if wait or not self.consensus:
                    doc = t.peer_marker(esite, r)
                else:
                    doc = t.peer_state(r)
                    if doc is not None and (
                        int(doc.get("mesh_epoch", 0)) > self.mesh_epoch
                    ):
                        raise MeshEpochAbort(
                            int(doc["mesh_epoch"]), (), site,
                            f"peer rank {r} published mesh epoch "
                            f"{int(doc['mesh_epoch'])} while this rank "
                            f"is at {self.mesh_epoch}",
                        )
                if doc is None:
                    # A marker missing at OUR epoch may mean the peer
                    # already aborted to a newer one — its markers
                    # live under a different namespace and will never
                    # pair with ours.
                    if wait or not self.consensus:
                        self._peer_epoch_check(r, site)
                    still.append(r)
                    continue
                peer_vecs[r] = list(doc.get("pos", []))
                if not self.consensus and "digest" in doc and (
                    doc["digest"] != digest
                ):
                    raise MeshDivergence(
                        f"ABORTED: mesh divergence at {site!r}: rank "
                        f"{self.rank} would issue {digest!r} while rank "
                        f"{r} issues {doc['digest']!r} — without cascade "
                        "consensus these collectives can never match "
                        "(the raw mesh hangs here; this bound is the "
                        "watchdog)"
                    )
                # Adopting from the peer's last PUBLISHED state also
                # covers the non-blocking path: proposals publish
                # immediately at downgrade time.
            waited = time.monotonic() - t0
            if not still and (wait or not self.consensus):
                break
            if not (wait or not self.consensus):
                # Non-blocking poll: whoever has published, we saw.
                break
            for r in still:
                self._check_peer_alive(r, site, waited, bound)
            if waited > bound:
                raise PeerLost(
                    still[0] if still else -1,
                    site,
                    f"rendezvous incomplete after {bound}s "
                    f"(waiting on ranks {still})",
                )
            pending = still
            time.sleep(min(0.005, bound / 10))
        # Liveness check even on the non-blocking path: a peer whose
        # STATE file is present but whose heartbeat has gone stale is
        # dead, and must surface at the next level boundary, not only
        # at the next rendezvous.  A peer already collected THIS round
        # is only judged by heartbeat age (it may legitimately exit
        # right after a final rendezvous); one never seen gets the full
        # exit-marker/staleness check, with the full bound for startup
        # skew.
        waited = time.monotonic() - t0
        for r in peers:
            if r in peer_vecs:
                age = t.heartbeat_age(r)
                if age is not None and age > bound:
                    raise PeerLost(
                        r, site,
                        f"no heartbeat for {age:.1f}s (bound {bound}s, "
                        "FA_QUORUM_TIMEOUT_S)",
                    )
            else:
                self._check_peer_alive(r, site, waited, bound)
        if self.consensus:
            self._adopt(peer_vecs, site)

    # -- fixed-shape payload exchange -----------------------------------
    def exchange(
        self, site: str, payload: List[int]
    ) -> Dict[int, List[int]]:
        """Rendezvous exchange of one fixed-shape integer vector per
        rank at ``site`` (ISSUE 15: the one-time W_s shard-weight-total
        exchange at mine start rides this) — every rank posts its
        payload and blocks (bounded) until every peer's arrives, under
        the same liveness rules as :meth:`sync` ``wait=True``: a killed
        peer surfaces as classified :class:`PeerLost` naming the rank
        within the retry budget, never a hang.  Returns ``{rank:
        payload}`` including this rank's own.  Payload shapes must be
        uniform across ranks on the JAX transport (process_allgather —
        SPMD static shapes); the file transport takes any JSON ints."""
        if self.nprocs == 1 or len(self.members) == 1:
            return {self.rank: list(payload)}
        from fastapriori_tpu.obs import flight
        from fastapriori_tpu.reliability import retry

        box: Dict[int, List[int]] = {}

        # Each repeated exchange at a site gets its OWN marker round
        # (a per-domain monotonic sequence): payloads are DATA, not
        # monotonic positions, so a second mine under a persistent
        # domain dir must never pair with a peer's stale round-1
        # marker — with per-round sites a count mismatch surfaces as a
        # bounded PeerLost instead of silently mixed payloads.  Rounds
        # are ADDITIONALLY namespaced by mesh epoch (and restart at r1
        # per epoch): a post-abort re-exchange among the survivors
        # must never pair with a round a now-dead peer posted before
        # the abort.  The JAX transport needs no round tag
        # (process_allgather is ordered by collective-call discipline).
        with self._lock:
            ekey = f"e{self.mesh_epoch}.{site}"
            self._xseq[ekey] = self._xseq.get(ekey, 0) + 1
            round_site = f"{ekey}.r{self._xseq[ekey]}"

        def attempt():
            box.clear()
            if isinstance(self.transport, JaxTransport):
                import numpy as np

                vec = np.asarray(
                    [self.rank] + [int(v) for v in payload],
                    dtype=np.int64,
                )
                gathered = self.transport.exchange(
                    vec, _site_slug(site), dtype=np.int64
                )
                for row in np.asarray(gathered):
                    box[int(row[0])] = [int(x) for x in row[1:]]
            else:
                box.update(self._exchange_file(round_site, payload))

        try:
            retry.call_with_retries(
                attempt, f"quorum.{_site_slug(site)}"
            )
        except PeerLost as exc:
            ledger.record(
                "peer_lost", site=site, rank=exc.rank,
                error=str(exc)[:200],
            )
            flight.auto_dump(
                "PeerLost",
                extra={
                    "site": site,
                    "rank": self.rank,
                    "epoch_trail": self.epoch_trail(),
                },
            )
            raise
        return box

    def _exchange_file(
        self, site: str, payload: List[int]
    ) -> Dict[int, List[int]]:
        t = self.transport
        bound = quorum_timeout_s()
        t.post_marker(
            site, {"payload": [int(v) for v in payload]}
        )
        out: Dict[int, List[int]] = {self.rank: list(payload)}
        pending = [r for r in self.members if r != self.rank]
        t0 = time.monotonic()
        while pending:
            still: List[int] = []
            for r in pending:
                doc = t.peer_marker(site, r)
                if doc is None:
                    self._peer_epoch_check(r, site)
                    still.append(r)
                    continue
                out[r] = [int(v) for v in doc.get("payload", [])]
            waited = time.monotonic() - t0
            for r in still:
                self._check_peer_alive(r, site, waited, bound)
            if still and waited > bound:
                raise PeerLost(
                    still[0], site,
                    f"exchange incomplete after {bound}s (waiting on "
                    f"ranks {still})",
                )
            pending = still
            if pending:
                time.sleep(min(0.005, bound / 10))
        return out

    def epoch_trail(self) -> List[Dict[str, Any]]:
        """Every sync this domain ran (epoch, site, positions) — the
        consensus history a PeerLost/chaos-FAIL flight dump ships."""
        with self._lock:
            return [dict(e) for e in self._epoch_trail]

    # -- elastic mesh (ISSUE 17) ----------------------------------------
    def elastic_rejoin(self, exc: BaseException) -> None:
        """The elastic abort/retry arm: absorb a :class:`PeerLost` /
        :class:`MeshEpochAbort` by re-rendezvousing the survivors under
        an incremented mesh epoch, or re-raise classified when elastic
        continuation is disabled (``FA_EPOCH_RETRY_MAX=0``, the
        default), the budget is exhausted, or the consensus ``elastic``
        chain has been clamped to ``abort``.  On return the member set
        has shrunk, the fence is re-derived (the surviving writer
        eagerly re-acquires, fencing out every pre-abort artifact and
        any superseded straggler-writer), and the caller re-seeds its
        level loop from the last checkpoint boundary."""
        from fastapriori_tpu.reliability import watchdog

        if not isinstance(exc, (PeerLost, MeshEpochAbort)):
            raise exc
        if not isinstance(self.transport, FileTransport) or (
            self.nprocs == 1
        ):
            # The JAX transport cannot shrink its mesh in-process: a
            # real-mesh PeerLost stays classified.
            raise exc
        budget = epoch_retry_max()
        dead: set = set()
        original = exc
        while True:
            if isinstance(exc, MeshEpochAbort):
                target = max(self.mesh_epoch + 1, exc.target_epoch)
                dead.update(exc.dead)
            else:
                target = self.mesh_epoch + 1
                r = getattr(exc, "rank", -1)
                if isinstance(r, int) and r >= 0:
                    dead.add(r)
            if (
                budget <= 0
                or target > budget
                # lint: waive G017 -- lockstep: exhaustion is decided by budget (env, identical on all ranks) and target (converges to the same epoch via the rendezvous); this clamp read only keeps a SECOND exhaustion local — an exhausted rank raises and issues no further collectives, and the downgrade itself is a consensus proposal peers adopt at their next exchange
                or not self.stage_allowed("elastic", "continue")
            ):
                # lint: waive G017 -- lockstep: guard against re-walking the already-clamped elastic chain (forward-only cascade); no collective is issued on either side of this branch
                if budget > 0 and self.stage_allowed(
                    "elastic", "continue"
                ):
                    # The registered cascade walk: exhaustion clamps
                    # continue → abort for the whole domain (consensus
                    # chain — peers adopt at their next exchange, so
                    # no survivor keeps retrying a dead quorum; the
                    # stage guard keeps a SECOND exhaustion from
                    # re-walking the already-clamped chain).
                    watchdog.downgrade(
                        "elastic",
                        "continue",
                        "abort",
                        reason="epoch_retry_exhausted",
                        once_key="elastic:abort",
                        epoch=target,
                    )
                if isinstance(original, PeerLost):
                    raise original
                raise PeerLost(
                    min(dead) if dead else -1,
                    getattr(original, "site", "elastic.join"),
                    "mesh-epoch retry budget exhausted "
                    f"(FA_EPOCH_RETRY_MAX={budget}, target epoch "
                    f"{target})",
                ) from original
            try:
                self._abort_and_rendezvous(
                    target, dead, type(exc).__name__
                )
                return
            except (PeerLost, MeshEpochAbort) as nxt:
                # Another death (or a further abort) during the
                # rejoin: loop — each iteration raises the target
                # epoch, so the strict budget still bounds the total.
                exc = nxt

    def _abort_and_rendezvous(
        self, target: int, dead: set, reason: str
    ) -> None:
        """Abort the in-flight level and re-rendezvous the survivors
        at mesh epoch ``target``.  ``dead`` (mutated in place) is the
        union of every joiner's view of the lost ranks: joiners post
        their dead-set in the join marker and fold in every peer's —
        a rank whose death only ONE survivor observed is excluded by
        all, and a rank observed dying DURING the rejoin is folded in
        rather than failing the rendezvous (an epoch bump here would
        skew survivors across epochs and burn the retry budget on a
        single death)."""
        from fastapriori_tpu.obs import flight

        t = self.transport
        bound = quorum_timeout_s()
        with self._lock:
            from_epoch = self.mesh_epoch
            self.mesh_epoch = target
            members = list(self.members)
        # Publishing the advanced epoch IS the abort broadcast: every
        # peer's pending-rank poll checks published epochs and aborts
        # its own in-flight level (MeshEpochAbort escapes the bounded
        # retry) the moment it sees this.
        self._publish(f"elastic.abort:{target}")
        site = "elastic.join"
        esite = self._esite(site)

        def post_join() -> None:
            t.post_marker(
                esite,
                {"dead": sorted(dead), "from_epoch": from_epoch},
            )

        post_join()
        collected: set = set()
        t0 = time.monotonic()
        while True:
            grew = False
            still: List[int] = []
            for r in members:
                if r == self.rank or r in dead or r in collected:
                    continue
                doc = t.peer_marker(esite, r)
                if doc is None:
                    st = t.peer_state(r)
                    pe = int(st.get("mesh_epoch", 0)) if st else 0
                    if pe > target:
                        raise MeshEpochAbort(
                            pe, sorted(dead), site,
                            f"peer rank {r} aborted again past epoch "
                            f"{target} mid-rejoin",
                        )
                    still.append(r)
                    continue
                collected.add(r)
                for d in doc.get("dead", ()):
                    if int(d) not in dead:
                        dead.add(int(d))
                        grew = True
            if self.rank in dead:
                raise StaleFenceError(
                    f"mesh epoch {target} fenced this rank out: the "
                    "survivors re-rendezvoused declaring rank "
                    f"{self.rank} dead — this straggler's view of the "
                    "domain is superseded; refusing to rejoin or "
                    "commit"
                )
            waited = time.monotonic() - t0
            if grew:
                post_join()
                continue
            if not still:
                break
            for r in still:
                try:
                    self._check_peer_alive(r, site, waited, bound)
                except PeerLost:
                    dead.add(r)
                    grew = True
            if grew:
                post_join()
                continue
            if waited > bound:
                raise PeerLost(
                    still[0], site,
                    f"elastic re-rendezvous at epoch {target} "
                    f"incomplete after {bound}s (waiting on ranks "
                    f"{still})",
                )
            time.sleep(min(0.005, bound / 10))
        survivors = [r for r in members if r not in dead]
        removed = sorted(dead)
        with self._lock:
            self.members = survivors
            # Fence re-derivation for the survivor set: writership may
            # have moved (lowest surviving rank), and the OLD writer
            # must never be able to commit a pre-abort artifact.
            self._fence = None
        ledger.record(
            "mesh_epoch",
            once_key=f"epoch:{target}",
            epoch=target,
            from_epoch=from_epoch,
            dead=removed,
            members=survivors,
            reason=reason,
        )
        self._publish(f"elastic.join:{target}")
        with self._lock:
            self._epoch_trail.append(
                {
                    "epoch": self._seq,
                    "site": site,
                    "pos": [self._pos[c] for c in CONSENSUS_CHAINS],
                    "mesh_epoch": target,
                    "dead": removed,
                }
            )
        flight.note(
            "mesh_epoch",
            mesh_epoch=target,
            from_epoch=from_epoch,
            dead=removed,
            members=survivors,
            reason=reason,
        )
        if self.is_writer():
            # EAGER fence re-acquire: advancing the domain FENCE here
            # is what turns every pre-abort checkpoint stale (resume
            # validation) and makes a superseded straggler-writer's
            # next commit raise StaleFenceError.
            self.checkpoint_fence()
        for fn in list(self._rejoin_hooks):
            fn()

    def add_rejoin_hook(self, fn: Any) -> None:
        """Register a callback to run after every completed elastic
        rejoin, once the survivor set and fence are re-derived.  Used
        by checkpoint writers to re-commit their last durable levels
        under the NEW fence — without it, a rejoin absorbed after the
        mine finished would leave the npz at the old fence and the
        final manifest at the new one (exactly the mixed-epoch
        artifact the chaos invariant forbids)."""
        with self._lock:
            if fn not in self._rejoin_hooks:
                self._rejoin_hooks.append(fn)

    # -- fenced checkpoints ---------------------------------------------
    def is_writer(self) -> bool:
        # The lowest SURVIVING rank: identical to "rank 0" until an
        # elastic rejoin removes rank 0, at which point writership
        # moves (and the new writer eagerly re-acquires the fence,
        # turning every pre-abort artifact stale).
        return self.rank == min(self.members)

    def checkpoint_fence(self) -> int:
        """The fence epoch this process's checkpoint commits carry:
        acquired ONCE per run (monotonic across writers sharing the
        domain dir), then validated against the authoritative FENCE at
        every commit — a superseded writer is rejected, never allowed
        to publish a mixed-epoch artifact."""
        if not isinstance(self.transport, FileTransport):
            return 0
        with self._lock:
            if self._fence is None:
                self._fence = self.transport.acquire_fence()
            fence = self._fence
        current = self.transport.current_fence()
        if current > fence:
            raise StaleFenceError(
                f"stale checkpoint fence: this writer holds fence "
                f"{fence} but the domain has advanced to {current} — a "
                "newer coordinator owns the checkpoint; refusing the "
                "split-brain commit"
            )
        return fence

    def validate_resume_fence(self, fence: Optional[int]) -> None:
        """Resume-side fence validation: a checkpoint stamped with a
        fence older than the domain's current FENCE was written by a
        superseded coordinator and must not seed a resume."""
        if not isinstance(self.transport, FileTransport):
            return
        if fence is None:
            return
        current = self.transport.current_fence()
        if current and fence < current:
            raise StaleFenceError(
                f"stale checkpoint fence {fence}: the domain's fence "
                f"has advanced to {current} — this checkpoint was "
                "written by a superseded coordinator (split-brain); "
                "resume from the current writer's checkpoint"
            )

    # -- lifecycle ------------------------------------------------------
    def close(self, status: str = "done") -> None:
        if isinstance(self.transport, FileTransport):
            self.transport.post_exit(status)
            self.transport.stop_heartbeat()


# ---------------------------------------------------------------------------
# process-wide domain resolution (memoized; every engine hook costs one
# attribute read on the inactive single-process path)

_domain: Optional[QuorumDomain] = None
_resolved = False
_resolve_lock = threading.Lock()


def _resolve() -> Optional[QuorumDomain]:
    # lint: env-ok -- free-form path knob: every string is a valid directory (rank/procs below parse strictly)
    root = os.environ.get("FA_QUORUM_DIR", "").strip()
    if root:
        from fastapriori_tpu.utils.env import env_int

        nprocs = env_int("FA_QUORUM_PROCS", 1, minimum=1)
        rank = env_int("FA_QUORUM_RANK", 0, minimum=0)
        if rank >= nprocs:
            raise InputError(
                f"FA_QUORUM_RANK={rank} is out of range for "
                f"FA_QUORUM_PROCS={nprocs} (ranks are 0-based)"
            )
        dom = QuorumDomain(
            FileTransport(root, rank, nprocs), rank, nprocs
        )
        atexit.register(dom.close, "atexit")
        return dom
    try:
        import jax

        nprocs = jax.process_count()
        if nprocs > 1:
            return QuorumDomain(
                JaxTransport(jax.process_index(), nprocs),
                jax.process_index(),
                nprocs,
            )
    # lint: waive G006 -- no backend yet: single-process domain resolution must not force one
    except Exception:  # pragma: no cover - backend not initialized
        pass
    return None


def active() -> Optional[QuorumDomain]:
    """The process-wide domain, or None (single process — the fast
    path: one memoized read)."""
    global _domain, _resolved
    if _resolved:
        return _domain
    with _resolve_lock:
        if not _resolved:
            _domain = _resolve()
            _resolved = True
    return _domain


def set_domain(domain: Optional[QuorumDomain]) -> None:
    """Install a domain explicitly (tests/harnesses)."""
    global _domain, _resolved
    _domain = domain
    _resolved = True


def reload_from_env() -> None:
    """Drop the memoized domain so FA_QUORUM_* is re-read (tests)."""
    global _domain, _resolved
    if _domain is not None:
        _domain.close("reload")
    _domain = None
    _resolved = False


# -- thin module-level hooks (all no-ops without a domain) ---------------


def propose(chain: str, stage: str, reason: str = "") -> None:
    dom = active()
    if dom is not None:
        dom.propose(chain, stage, reason)


def sync(site: str, wait: bool = False) -> None:
    dom = active()
    if dom is not None:
        dom.sync(site, wait=wait)


def stage_allowed(chain: str, stage: str) -> bool:
    dom = active()
    return dom is None or dom.stage_allowed(chain, stage)


def elastic_enabled() -> bool:
    """True when the active domain can absorb a peer death by elastic
    re-rendezvous: a multi-process FILE domain with a positive
    ``FA_EPOCH_RETRY_MAX``.  (The JAX transport cannot shrink its mesh
    in-process — a real-mesh PeerLost stays classified.)"""
    dom = active()
    return (
        dom is not None
        and isinstance(dom.transport, FileTransport)
        and dom.nprocs > 1
        and epoch_retry_max() > 0
    )


def elastic_rejoin(exc: BaseException) -> None:
    """Absorb a PeerLost/MeshEpochAbort via the active domain's
    elastic rejoin (see :meth:`QuorumDomain.elastic_rejoin`), or
    re-raise ``exc`` when no domain is active or continuation is
    disabled/exhausted — the caller's except-arm stays a single
    call either way."""
    dom = active()
    if dom is None:
        raise exc
    dom.elastic_rejoin(exc)


def sync_or_rejoin(site: str, wait: bool = False) -> None:
    """:func:`sync` wrapped in the elastic rejoin arm, for the phase
    rendezvous sites OUTSIDE the level loop (run.start / mine.end /
    rules.start / run.end): a rank blocked here while a peer aborts
    the mesh must rejoin under the new epoch rather than misclassify
    the (alive, but epoch-advanced) peer as lost.  With elastic
    continuation disabled this is exactly ``sync`` — the rejoin arm
    re-raises."""
    while True:
        try:
            sync(site, wait=wait)
            return
        except (PeerLost, MeshEpochAbort) as exc:
            elastic_rejoin(exc)


def mesh_epoch() -> int:
    """The active domain's current mesh epoch (0 without a domain —
    also the epoch of every run that never aborts)."""
    dom = active()
    return 0 if dom is None else dom.mesh_epoch


def mesh_members() -> Optional[List[int]]:
    """The surviving member ranks, or None without a domain."""
    dom = active()
    return None if dom is None else list(dom.members)


def exchange(site: str, payload) -> Optional[Dict[int, List[int]]]:
    """Domain-wide fixed-shape vector exchange (see
    :meth:`QuorumDomain.exchange`); None without a domain — the caller
    falls back to its single-process/jax-native path."""
    dom = active()
    if dom is None:
        return None
    return dom.exchange(site, list(payload))


def floor_stage(chain: str) -> Optional[str]:
    dom = active()
    return None if dom is None else dom.floor_stage(chain)


def is_writer() -> bool:
    """True when this process owns artifact/checkpoint writes (quorum
    rank 0; every process when no domain is active — jax.process_index
    gating stays with the callers)."""
    dom = active()
    return dom is None or dom.is_writer()


def checkpoint_fence() -> int:
    dom = active()
    return 0 if dom is None else dom.checkpoint_fence()


def writer_fence() -> Optional[int]:
    """The fence epoch to stamp into an artifact manifest, or None on
    every path that must not touch the domain fence: no active domain
    (single-process, unfenced), or a non-writer rank — acquiring from
    rank != 0 would advance the shared epoch and fence out the real
    coordinator mid-run.  The writer's own acquire/validate semantics
    (StaleFenceError on a superseded coordinator) are unchanged."""
    dom = active()
    if dom is None or not dom.is_writer():
        return None
    return dom.checkpoint_fence() or None


def validate_resume_fence(fence: Optional[int]) -> None:
    dom = active()
    if dom is not None:
        dom.validate_resume_fence(fence)


def rank_suffix() -> str:
    """``".rank<r>"`` on multi-process domains (per-process trace /
    flight artifacts must not clobber each other), else ""."""
    dom = active()
    if dom is None or dom.nprocs == 1:
        return ""
    return f".rank{dom.rank}"


def rank_path(path: str) -> str:
    """Insert the rank suffix before ``path``'s final extension (or
    append when there is none): ``out.trace.json`` →
    ``out.trace.rank1.json``."""
    suffix = rank_suffix()
    if not suffix:
        return path
    base, dot, ext = path.rpartition(".")
    if dot and "/" not in ext and os.sep not in ext:
        return f"{base}{suffix}.{ext}"
    return path + suffix
