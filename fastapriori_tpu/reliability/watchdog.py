"""Dispatch watchdog + the unified degradation cascade (ISSUE 9).

Two failure-handling pieces the per-subsystem fallbacks never had:

**Watchdog.**  A device dispatch (or its blocking fetch) can hang
forever — a wedged tunnel link, a deadlocked collective, a runtime bug
— and nothing in the retry layer fires, because retries only see
*raised* errors.  :func:`guard` bounds every audited fetch with a wall
clock: past ``FA_DISPATCH_TIMEOUT_S`` (strictly parsed; unset/0 =
disabled, the default) the blocked call is abandoned and a
:class:`DispatchTimeout` is raised whose message carries the
``DEADLINE_EXCEEDED`` status — so :func:`~fastapriori_tpu.reliability.
retry.classify` sees a *transient*, the bounded retry policy gets its
shot (the thunks are pure re-runnable host materializations), and
exhaustion surfaces as a classified error naming the site instead of a
silent multi-hour hang.  Every trip lands on the degradation ledger as
a ``watchdog_timeout`` event.  The abandoned worker thread is daemonic:
it cannot be killed (Python offers no safe cross-thread abort of a
blocked C call), but it no longer blocks the pipeline — the closest
in-process analog of Spark's speculative-task abandon.  Abandonment is
ACCOUNTED (ISSUE 10 satellite / PR 9 residue): every trip carries the
live abandoned-thread count on its ledger event, and a trip past
``FA_DISPATCH_MAX_ABANDONED`` raises the fatal
:class:`AbandonedThreadCap` instead of leaking one more daemon thread
per retry — a runtime wedged that hard is down, not flapping.

**Cascade.**  The engines already degrade in half a dozen places —
fused→level salvage, vertical→bitmap, sparse→dense redo, device
rules→host — but each fallback grew its own ad-hoc ledger kind, so "how
degraded is this run" required knowing every kind.  :data:`CHAINS` is
the ONE escalation policy: each subsystem's explicit downgrade order,
and :func:`downgrade` the one event shape every fallback now ALSO
emits (kind ``cascade`` with chain/from/to/rank fields), forward-only
by construction — a downgrade can never silently "upgrade" back up a
chain mid-mine.  The chain decisions are therefore uniformly visible in
``--metrics`` streams, bench's ``degraded`` summary, and the chaos
harness's invariant check (tools/chaos.py): a run that walked any chain
can never masquerade as a clean one.

Repeated *transient* failures walk these chains instead of killing the
mine: the engine layers (models/apriori.py, rules/gen.py) catch a
transient-classified error that survived its retry budget at a
downgradeable site and step the chain — fused→level, vertical→bitmap,
sparse→dense — re-running the exact-by-construction fallback engine.
:func:`transient` is the shared classification predicate for those
catch sites.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple, TypeVar

from fastapriori_tpu.reliability import ledger

T = TypeVar("T")

# The unified escalation policy: per subsystem, the explicit downgrade
# order (most capable first, the always-defined oracle last).  Every
# stage name matches the vocabulary the engine-selection ledger events
# already use; tests pin this ordering (a reordering is a semantic
# change to every fallback site).
CHAINS: Dict[str, Tuple[str, ...]] = {
    # Mining control flow: whole-lattice fused program -> seeded tail
    # fold -> one dispatch per level.
    "engine": ("fused", "tail", "level"),
    # Mining layout: Eclat tid-lanes -> horizontal bitmap matmuls.
    "mine_engine": ("vertical", "bitmap"),
    # Mesh count reduction: threshold-sparse exchange -> dense psum.
    "count_reduce": ("sparse", "dense"),
    # Exchange topology (parallel/hier.py): two-level hierarchical
    # (intra-group ring, then inter-group) -> flat single-level.  A
    # transient-exhausted sparse dispatch walks THIS chain before
    # count_reduce (the flat exchange is the cheaper exact fallback;
    # dense is the last resort), and a quorum peer's walk clamps the
    # whole domain — the two-level collectives differ in shape/count
    # from the flat ones, so divergence here hangs a real mesh.
    "exchange": ("hier", "flat"),
    # Phase-2 rule generation: sharded device join -> device-0 join ->
    # host numpy oracle.
    "rule_engine": ("sharded", "device", "host"),
    # Recommender first-match scan: resident device table -> host scan.
    # (The v3 linter needed a G016 waiver here: its module-granularity
    # fallback attributed ANY chain walk in a collective-dispatching
    # module to the collective path.  v4's function-granular attribution
    # proves the serving-tier walk happens in a non-bearing helper, so
    # the waiver is gone — pinned by test_lint's regression case.)
    "rule_scan": ("device", "host"),
    # Serving admission control (serve/server.py): accepting requests ->
    # shedding them ("0" answers) under overload.  Each overload episode
    # records one forward walk; recovery is internal server state, not a
    # (forbidden) backward cascade event.
    "serving": ("accept", "shed"),
    # Elastic mesh (ISSUE 17): whether a PeerLost mid-mine aborts the
    # in-flight level and re-rendezvouses the survivors under a new
    # mesh epoch ("continue") or classifies the run dead ("abort").
    # Walked forward when the FA_EPOCH_RETRY_MAX budget exhausts —
    # consensus-registered so one rank's exhaustion clamps every
    # survivor's next rejoin decision identically.
    "elastic": ("continue", "abort"),
    # Vertical level kernel tier (ISSUE 18): VMEM-resident Pallas
    # popcount kernel (ops/pallas_vertical.py) -> the exact-by-
    # construction XLA vertical path.  Consensus-registered: the tier
    # choice changes every shard's compiled local program, so one
    # rank's kernel failure must clamp the whole domain to XLA before
    # its next dispatch.
    "vertical_kernel": ("pallas", "xla"),
    # Serving first-match scan body: fused Pallas rank-argmin kernel ->
    # XLA while_loop scan.  Host-local like rule_scan (the pmin/pmax
    # merge is shape-identical either way, so the tier never shapes a
    # collective); walked BEFORE rule_scan device→host — the XLA scan
    # retry is cheaper than abandoning the device table.
    "serve_scan": ("pallas", "xla"),
    # Serving mesh health (ISSUE 19): full complement -> degraded (one
    # or more hosts lost; survivors absorb the dead host's share, its
    # in-flight requests answer "0" as recorded sheds).  Walked by the
    # request router (serve/router.py) once per lost host.  HOST-LOCAL
    # by design — the router is a single process observing heartbeat
    # files; no collective shape changes, so NOT consensus-registered
    # (G016: no collective-bearing function walks it).
    "serve_mesh": ("full", "degraded"),
}


def chain_rank(chain: str, stage: str) -> int:
    """Position of ``stage`` in its chain (0 = most capable)."""
    return CHAINS[chain].index(stage)


def downgrade(
    chain: str,
    frm: str,
    to: str,
    reason: str,
    once_key: Optional[str] = None,
    **fields: Any,
) -> None:
    """Record one walk down an escalation chain.  ``frm``/``to`` must
    both belong to ``chain`` and the walk must be FORWARD (toward the
    oracle end) — a backward call is a wiring bug and raises
    immediately rather than logging an impossible trail.  The event
    lands on the degradation ledger (kind ``cascade``) and therefore in
    ``--metrics`` and bench's ``degraded``/cascade-trail fields."""
    order = CHAINS.get(chain)
    if order is None:
        raise ValueError(f"unknown cascade chain {chain!r}")
    i, j = order.index(frm), order.index(to)
    if j <= i:
        raise ValueError(
            f"cascade {chain}: {frm!r} -> {to!r} walks backward "
            f"(chain order {order})"
        )
    if reason != "quorum":
        # Cross-process cascade consensus (ISSUE 12): a LOCAL walk of a
        # collective-shaping chain becomes an epoch-stamped proposal
        # published to the fault domain the moment it happens — peers
        # adopt the most-degraded position at their next exchange,
        # BEFORE their next dispatch, so divergent collectives are
        # impossible by construction.  Adoptions arrive back through
        # this same function with reason="quorum" (the guard above
        # keeps them from re-proposing in a loop).  No-op without a
        # multi-process domain.
        from fastapriori_tpu.reliability import quorum

        if chain in quorum.CONSENSUS_CHAINS:
            quorum.propose(chain, to, reason)
    ledger.record(
        "cascade",
        once_key=once_key or f"{chain}:{frm}>{to}:{reason}",
        chain=chain,
        frm=frm,
        to=to,
        rank=j,
        reason=reason,
        **fields,
    )


def transient(exc: BaseException) -> bool:
    """True when ``exc`` is transient-classified (retry.classify) — the
    shared predicate for the chain-walking catch sites.  Deliberately
    narrow: user errors and fatal errors must keep propagating (walking
    a chain cannot fix a malformed input or a shape bug), and
    BaseExceptions (InjectedAbort, KeyboardInterrupt) never reach these
    ``except Exception`` sites at all."""
    from fastapriori_tpu.reliability import retry

    return retry.classify(exc) == "transient"


class DispatchTimeout(RuntimeError):
    """A watchdog-abandoned dispatch/fetch.  The message leads with the
    ``DEADLINE_EXCEEDED`` status so retry.classify sees a transient —
    the same contract a real XLA deadline error carries."""


class AbandonedThreadCap(RuntimeError):
    """A watchdog trip past the abandoned-thread cap
    (``FA_DISPATCH_MAX_ABANDONED``).  Each abandoned fetch leaks one
    daemon thread (Python cannot abort a blocked C call); a runtime
    wedged hard enough to strand the cap's worth of threads is not
    flapping, it is down — so this error deliberately carries NO
    transient status: retry.classify sees a fatal and the run dies
    naming the leak instead of abandoning threads unboundedly."""


_timeout_memo: Optional[float] = None
_max_abandoned_memo: Optional[int] = None

# Watchdog-abandoned worker threads still alive (pruned on every trip).
# Module-level like the ledger: the guard sites have no config in scope.
_abandoned_lock = threading.Lock()
_abandoned: list = []


def dispatch_timeout_s() -> float:
    """The process-wide watchdog bound (seconds): ``FA_DISPATCH_TIMEOUT_S``,
    strictly parsed (a typo'd value raises InputError — the FA_NO_PALLAS
    contract); 0/unset disables.  Parsed once per process; tests use
    :func:`reload_from_env`."""
    global _timeout_memo
    if _timeout_memo is None:
        from fastapriori_tpu.utils.env import env_float

        _timeout_memo = env_float(
            "FA_DISPATCH_TIMEOUT_S", 0.0, minimum=0.0
        )
    return _timeout_memo


def max_abandoned() -> int:
    """Cap on concurrently-abandoned fetch threads:
    ``FA_DISPATCH_MAX_ABANDONED``, strictly parsed; 0 disables the cap
    (unbounded abandonment, the pre-ISSUE-10 behavior).  Default 8 — a
    genuinely flapping link frees its threads as fetches eventually
    land, so only a hard-wedged runtime accumulates toward the cap."""
    global _max_abandoned_memo
    if _max_abandoned_memo is None:
        from fastapriori_tpu.utils.env import env_int

        _max_abandoned_memo = env_int(
            "FA_DISPATCH_MAX_ABANDONED", 8, minimum=0
        )
    return _max_abandoned_memo


def abandoned_live() -> int:
    """Abandoned worker threads still alive right now (dead ones are
    pruned on every trip and on every read)."""
    with _abandoned_lock:
        _abandoned[:] = [t for t in _abandoned if t.is_alive()]
        return len(_abandoned)


def _register_abandoned(worker: threading.Thread) -> int:
    """Record a freshly-abandoned worker; returns the live count
    including it."""
    with _abandoned_lock:
        _abandoned[:] = [t for t in _abandoned if t.is_alive()]
        _abandoned.append(worker)
        return len(_abandoned)


def reload_from_env() -> None:
    """Re-read the FA_DISPATCH_* knobs (tests; otherwise read once)."""
    global _timeout_memo, _max_abandoned_memo
    _timeout_memo = None
    _max_abandoned_memo = None


def reset_abandoned() -> None:
    """Forget the abandoned-thread registry (tests: earlier tests'
    deliberately-hung workers must not count against this test's cap).
    The threads themselves, being daemonic, die with the process."""
    with _abandoned_lock:
        _abandoned.clear()


def guard(
    thunk: Callable[[], T],
    site: str,
    timeout_s: Optional[float] = None,
) -> T:
    """Run ``thunk`` under the watchdog bound.  Disabled (the default)
    this is a plain call — zero threads, zero overhead beyond one memo
    read.  Enabled, the thunk runs on a fresh daemon thread and the
    caller waits at most ``timeout_s``; past it the thread is abandoned
    and :class:`DispatchTimeout` raises (classified transient, ledger
    ``watchdog_timeout`` event).  Exceptions from the thunk — including
    BaseExceptions like an injected abort — re-raise on the caller."""
    bound = dispatch_timeout_s() if timeout_s is None else timeout_s
    if not bound:
        return thunk()
    box: list = []

    def run() -> None:
        try:
            box.append(("ok", thunk()))
        # lint: waive G006 -- captured into the box and re-raised verbatim on the caller thread below
        except BaseException as exc:
            box.append(("err", exc))

    worker = threading.Thread(
        target=run, name=f"fa-watchdog:{site}", daemon=True
    )
    worker.start()
    worker.join(bound)
    if not box:
        live = _register_abandoned(worker)
        cap = max_abandoned()
        ledger.record(
            "watchdog_timeout", once_key=site, site=site,
            timeout_s=bound, abandoned_live=live,
        )
        if cap and live > cap:
            # Past the cap the leak itself is the failure: a retry would
            # strand thread cap+2 against the same wedged runtime.  No
            # transient status in the message — classify() must see a
            # fatal (test-pinned).  This is a process-is-down verdict,
            # so ship the post-mortem: the flight recorder dumps its
            # ring (the trips/retries that led here) against the
            # CLI-registered prefix before the fatal raises.
            from fastapriori_tpu.obs import flight

            flight.auto_dump(
                "abandoned_thread_cap",
                extra={"site": site, "abandoned_live": live, "cap": cap},
            )
            raise AbandonedThreadCap(
                f"dispatch watchdog: {live} abandoned fetch threads "
                f"still live after abandoning {site!r} — past the "
                f"FA_DISPATCH_MAX_ABANDONED cap of {cap}; the runtime "
                "is wedged, not flapping, so this trip is fatal instead "
                "of leaking another thread per retry"
            )
        raise DispatchTimeout(
            f"DEADLINE_EXCEEDED: dispatch watchdog abandoned {site!r} "
            f"after {bound}s (FA_DISPATCH_TIMEOUT_S) — the in-flight "
            "device work may still complete; the retried thunk is a "
            "pure re-runnable materialization"
        )
    kind, payload = box[0]
    if kind == "err":
        raise payload
    return payload
