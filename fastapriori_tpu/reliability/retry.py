"""Bounded retry with transient/fatal/user error classification.

The reference got retries from Spark's task scheduler (a failed task is
re-executed up to ``spark.task.maxFailures`` times on another executor);
the JAX port's device fetches and filesystem ops had no second chance —
VERDICT r5 measured a device link collapsing to 3.7 MB/s mid-run, the
kind of flap that surfaces as a one-off ``RESOURCE_EXHAUSTED`` or
``UNAVAILABLE`` and deserves a retry, not a dead multi-hour mine.

Classification contract:

- **user**: :class:`~fastapriori_tpu.errors.InputError` and
  FileNotFoundError — the user can fix it; retrying is noise.
- **transient**: XLA runtime errors whose status says the resource may
  come back (``RESOURCE_EXHAUSTED``, ``UNAVAILABLE``, ``DEADLINE_EXCEEDED``,
  ``ABORTED``, ``CANCELLED``, ``INTERNAL``) and the OSError errnos a flaky
  link/filesystem produces (EIO, EAGAIN, EBUSY, ETIMEDOUT, ECONNRESET).
- **fatal**: everything else (shape errors, INVALID_ARGUMENT, TypeError)
  — retrying cannot change the outcome; re-raise immediately.

Backoff is deterministic (exponential, no jitter): reproducibility is
worth more here than thundering-herd protection — there is exactly one
host per device link.

The policy is tunable per process via ``FA_RETRY_MAX`` /
``FA_RETRY_BACKOFF_MS`` (strictly parsed — :func:`policy_from_env`);
explicit ``policy=`` arguments still win at individual call sites.
"""

from __future__ import annotations

import dataclasses
import errno
import os
import time
from typing import Callable, Optional, Tuple, TypeVar

from fastapriori_tpu.errors import InputError
from fastapriori_tpu.reliability import failpoints

T = TypeVar("T")

# Canonical absl/XLA status codes that justify a retry; matched against
# the exception MESSAGE because XlaRuntimeError carries its status only
# as a text prefix ("RESOURCE_EXHAUSTED: ...").
TRANSIENT_STATUS = (
    "RESOURCE_EXHAUSTED",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "INTERNAL",
)

_TRANSIENT_ERRNOS = frozenset(
    {errno.EIO, errno.EAGAIN, errno.EBUSY, errno.ETIMEDOUT, errno.ECONNRESET}
)

_xla_types: Optional[Tuple[type, ...]] = None


def xla_runtime_error_types() -> Tuple[type, ...]:
    """The concrete error types the XLA runtime (and the fused-OOM probe)
    can raise — importable lazily so stdlib-only callers never pay a jax
    import.  Falls back to ``(RuntimeError,)``: XlaRuntimeError subclasses
    it in every pinned jaxlib."""
    global _xla_types
    if _xla_types is None:
        types: list = []
        try:
            from jax.errors import JaxRuntimeError

            types.append(JaxRuntimeError)
        except (ImportError, AttributeError):
            pass
        try:
            from jax._src.lib import xla_client

            types.append(xla_client.XlaRuntimeError)
        except (ImportError, AttributeError):
            pass
        if not types:
            types.append(RuntimeError)
        # Dedup while preserving order (JaxRuntimeError aliases
        # XlaRuntimeError on some versions).
        seen: list = []
        for t in types:
            if t not in seen:
                seen.append(t)
        _xla_types = tuple(seen)
    return _xla_types


def classify(exc: BaseException) -> str:
    """``"user"`` | ``"transient"`` | ``"fatal"`` (module docstring)."""
    if isinstance(exc, (InputError, FileNotFoundError)):
        return "user"
    if isinstance(exc, OSError):
        return (
            "transient" if exc.errno in _TRANSIENT_ERRNOS else "fatal"
        )
    if isinstance(exc, RuntimeError):
        msg = str(exc)
        if any(code in msg for code in TRANSIENT_STATUS):
            return "transient"
    return "fatal"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff.  ``max_attempts`` counts the first
    try; delays are ``base_delay_s * factor**i`` capped at
    ``max_delay_s``."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    factor: float = 4.0
    max_delay_s: float = 2.0

    def delay(self, attempt: int) -> float:
        return min(
            self.base_delay_s * (self.factor ** attempt), self.max_delay_s
        )


DEFAULT_POLICY = RetryPolicy()

_env_policy: Optional[RetryPolicy] = None


def policy_from_env() -> RetryPolicy:
    """The process-wide retry policy, with the ops knobs applied:
    ``FA_RETRY_MAX`` (attempt bound incl. the first try, >= 1) and
    ``FA_RETRY_BACKOFF_MS`` (base backoff in milliseconds, >= 0) —
    surfaced as environment variables instead of module constants
    (ROADMAP reliability follow-up) and STRICTLY parsed like
    ``FA_NO_PALLAS``: a typo'd value silently running the default policy
    on a flaky link is exactly the invisible-degradation class the
    ledger exists to kill, so malformed values raise
    :class:`~fastapriori_tpu.errors.InputError` at the first retryable
    call.  Parsed once per process; tests use
    :func:`reload_policy_from_env`."""
    global _env_policy
    if _env_policy is not None:
        return _env_policy
    kw = {}
    raw = os.environ.get("FA_RETRY_MAX", "").strip()
    if raw:
        try:
            val = int(raw)
        except ValueError:
            raise InputError(
                f"unrecognized FA_RETRY_MAX value {raw!r}: expected an "
                "integer >= 1 (attempts including the first try)"
            ) from None
        if val < 1:
            raise InputError(
                f"FA_RETRY_MAX={val} is out of range: at least 1 attempt "
                "(the first try) is required"
            )
        kw["max_attempts"] = val
    raw = os.environ.get("FA_RETRY_BACKOFF_MS", "").strip()
    if raw:
        try:
            ms = float(raw)
        except ValueError:
            raise InputError(
                f"unrecognized FA_RETRY_BACKOFF_MS value {raw!r}: "
                "expected a number of milliseconds >= 0"
            ) from None
        if ms < 0:
            raise InputError(
                f"FA_RETRY_BACKOFF_MS={ms} is out of range: backoff "
                "cannot be negative"
            )
        kw["base_delay_s"] = ms / 1e3
    _env_policy = RetryPolicy(**kw) if kw else DEFAULT_POLICY
    return _env_policy


def reload_policy_from_env() -> None:
    """Re-read the FA_RETRY_* knobs (tests; otherwise read once)."""
    global _env_policy
    _env_policy = None


def call_with_retries(
    thunk: Callable[[], T],
    site: str,
    policy: Optional[RetryPolicy] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Run ``thunk`` with the site's failpoint armed inside the retried
    body (so an ``oom*1`` spec is a genuine transient: fails once, passes
    on retry).  Transient errors back off and retry up to the policy
    bound, recording each retry in the degradation ledger; user/fatal
    errors — and exhaustion — re-raise unchanged.  Each attempt runs
    under the dispatch watchdog (reliability/watchdog.py): with
    ``FA_DISPATCH_TIMEOUT_S`` set, a hung fetch is abandoned after the
    bound and surfaces as a transient ``DEADLINE_EXCEEDED`` — retried
    like any other flap, so a wedged link can stall the pipeline for at
    most attempts × timeout instead of forever."""
    from fastapriori_tpu.obs import metrics as obs_metrics
    from fastapriori_tpu.obs import trace
    from fastapriori_tpu.reliability import ledger, watchdog

    policy = policy or policy_from_env()
    attempt = 0
    # Every audited call is a span (ISSUE 11): the site label names it,
    # retries/timeouts land as annotations + instant events under it,
    # and fetch sites feed the per-site latency histograms the serving
    # registry snapshot exposes.  Disabled tracing costs one branch.
    with trace.span(site) as sp:
        t0 = time.perf_counter()
        while True:
            try:
                # lint: waive G013 -- central instrumentation: `site` is the caller's audited label, censused at its fetch/definition site (this is the ONE shared fire point every label routes through)
                failpoints.fire(site)
                result = watchdog.guard(thunk, site)
                if site.startswith("fetch."):
                    obs_metrics.fetch_latency_observe(
                        site[6:], (time.perf_counter() - t0) * 1e3
                    )
                return result
            except Exception as exc:
                kind = classify(exc)
                if kind != "transient" or attempt >= policy.max_attempts - 1:
                    sp.update(
                        failed=f"{type(exc).__name__}", attempts=attempt + 1
                    )
                    raise
                ledger.record(
                    "retry",
                    site=site,
                    attempt=attempt + 1,
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )
                sp.update(retries=attempt + 1)
                sleep(policy.delay(attempt))
                attempt += 1


def fetch(
    thunk: Callable[[], T],
    site: str,
    policy: Optional[RetryPolicy] = None,
) -> T:
    """Audited device->host fetch wrapper: failpoint-instrumented and
    retry-wrapped under ``fetch.<site>``.  The thunk must be re-runnable
    (a pure host materialization of an already-computed device array)."""
    return call_with_retries(thunk, "fetch." + site, policy)


class AsyncFetch:
    """An in-flight audited device→host fetch (the latency-hiding form of
    :func:`fetch`): construction starts the non-blocking copy, so the
    transfer rides the link while the host does other work; ``result()``
    blocks, with the same ``fetch.<site>`` failpoint + retry discipline
    as the synchronous wrapper.  graftlint's G001 audit recognizes
    :func:`fetch_async` calls as audited fetch sites, so call sites need
    no inline waiver."""

    def __init__(self, arr, site: str, policy: Optional[RetryPolicy] = None):
        self._arr = arr
        self._site = site
        self._policy = policy
        self._result = None
        self._done = False
        try:
            arr.copy_to_host_async()
        # The copy is a HINT: result() re-materializes through the
        # retried np.asarray, so any real failure (including a transient
        # link error at issue time) surfaces there, classified.
        # lint: waive G006 -- hint only; result() re-raises real failures
        except Exception:
            pass

    def result(self):
        """Host numpy array (blocks until the copy lands; memoized)."""
        if not self._done:
            import numpy as np

            self._result = call_with_retries(
                lambda: np.asarray(self._arr),
                "fetch." + self._site,
                self._policy,
            )
            self._done = True
            self._arr = None  # drop the device reference promptly
        return self._result


def fetch_async(
    arr, site: str, policy: Optional[RetryPolicy] = None
) -> AsyncFetch:
    """Issue an audited device→host fetch WITHOUT blocking: returns an
    :class:`AsyncFetch` whose ``result()`` is consumed one host phase
    later (models/apriori.py's per-level survivor fetches and the
    pending-count drain — VERDICT r5 next #6: the work was hidden, the
    fetch was not)."""
    return AsyncFetch(arr, site, policy)
