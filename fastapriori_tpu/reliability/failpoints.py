"""Deterministic fault injection (stdlib-only).

The reference inherited fault tolerance from Spark for free — RDD lineage
re-executes a lost partition, the driver survives executor failure
(SURVEY §0) — and therefore never needed a way to *test* failure paths:
Spark's own test rig injects the failures.  The JAX port replaced Spark
with raw ``shard_map``/``psum`` and kept none of that substrate, so every
degradation path (device OOM mid-fetch, a truncated artifact, a flaky
tunnel link) would otherwise be testable only by breaking real hardware.
This registry gives each such path a *name* — an injection site threaded
through the audited fetch sites, the native loader, ``allgather_bytes``
and every artifact write — and lets tests (or ``FA_FAILPOINTS``) arm it
with a deterministic failure spec, so the retry/ledger/manifest machinery
in this package is exercised on CPU in milliseconds.

Spec grammar (comma-separated sites)::

    FA_FAILPOINTS="<site>:<kind>[@<arg>][*<count>][,<site>:<kind>...]"

kinds:

- ``oom``          raise a ``RESOURCE_EXHAUSTED``-shaped XlaRuntimeError
                   (what a device allocator / transfer failure raises);
- ``io``           raise ``OSError`` (filesystem failure);
- ``abort``        raise :class:`InjectedAbort` — a stand-in for a hard
                   crash (SIGKILL) that nothing downstream may catch as
                   transient;
- ``delay@MS``     sleep MS milliseconds (slow-link simulation);
- ``truncate@N``   artifact-write sites only: physically truncate the
                   written file at byte N (the manifest still records the
                   full intended content, so validation must reject it).

``*count`` arms the site for the first ``count`` hits only — ``oom*1``
fails once and then passes, which is exactly the shape of a transient
fault the retry policy must absorb.  Without ``*count`` the site fires on
every hit.

Sites are plain dotted names (``fetch.pair``, ``write.freqItems``,
``level.4``); :func:`fire` is a no-op for unarmed sites (one dict lookup
— safe on hot paths).  Unknown kinds or malformed specs raise
:class:`fastapriori_tpu.errors.InputError` at parse time, not silently at
the hundredth hit.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from fastapriori_tpu.errors import InputError

_KINDS = ("oom", "io", "abort", "delay", "truncate")

# Kinds that take / require an integer argument.
_ARG_REQUIRED = ("delay", "truncate")


class InjectedAbort(BaseException):
    """A failpoint-injected hard crash.  Deliberately a BaseException
    subclass so no ``except Exception`` recovery path (retry, fallback)
    can absorb it — the closest in-process analog of SIGKILL."""


class _Spec:
    __slots__ = ("kind", "arg", "remaining")

    def __init__(self, kind: str, arg: Optional[int], count: Optional[int]):
        self.kind = kind
        self.arg = arg
        self.remaining = count  # None = unlimited

    def take(self) -> bool:
        """Consume one hit; False once the armed count is exhausted."""
        if self.remaining is None:
            return True
        if self.remaining <= 0:
            return False
        self.remaining -= 1
        return True


_lock = threading.Lock()
_active: Dict[str, _Spec] = {}
_env_loaded = False


def _xla_resource_exhausted(site: str) -> Exception:
    """A ``RESOURCE_EXHAUSTED``-shaped error of the same *type* the XLA
    runtime raises, so classification code sees exactly what a real
    device OOM/transfer failure would produce.  jax is imported lazily —
    this module stays stdlib-only for every caller that never injects."""
    msg = (
        "RESOURCE_EXHAUSTED: injected failpoint "
        f"{site!r} (FA_FAILPOINTS): out of memory while simulating a "
        "device allocation/transfer failure"
    )
    try:
        from jax.errors import JaxRuntimeError

        return JaxRuntimeError(msg)
    except (ImportError, AttributeError):
        # No jax on this host: a RuntimeError carrying the status prefix
        # classifies identically (retry.classify matches the message).
        return RuntimeError(msg)


def parse_spec(text: str) -> Dict[str, _Spec]:
    """Parse a ``FA_FAILPOINTS`` value; InputError on malformed input."""
    out: Dict[str, _Spec] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        site, sep, rest = part.partition(":")
        if not sep or not site:
            raise InputError(
                f"malformed FA_FAILPOINTS entry {part!r}: expected "
                "'<site>:<kind>[@arg][*count]'"
            )
        count: Optional[int] = None
        if "*" in rest:
            rest, _, cnt = rest.rpartition("*")
            try:
                count = int(cnt)
            except ValueError:
                raise InputError(
                    f"malformed FA_FAILPOINTS count in {part!r}: "
                    f"{cnt!r} is not an integer"
                ) from None
        kind, _, arg_s = rest.partition("@")
        if kind not in _KINDS:
            raise InputError(
                f"unknown FA_FAILPOINTS kind {kind!r} in {part!r} "
                f"(known: {', '.join(_KINDS)})"
            )
        arg: Optional[int] = None
        if arg_s:
            try:
                arg = int(arg_s)
            except ValueError:
                raise InputError(
                    f"malformed FA_FAILPOINTS argument in {part!r}: "
                    f"{arg_s!r} is not an integer"
                ) from None
        if arg is None and kind in _ARG_REQUIRED:
            raise InputError(
                f"FA_FAILPOINTS kind {kind!r} requires '@<int>' "
                f"(e.g. '{site}:{kind}@100') in {part!r}"
            )
        out[site] = _Spec(kind, arg, count)
    return out


def _ensure_env_loaded() -> None:
    global _env_loaded
    if _env_loaded:
        return
    with _lock:
        if _env_loaded:
            return
        text = os.environ.get("FA_FAILPOINTS", "")
        if text:
            _active.update(parse_spec(text))
        _env_loaded = True


def reload_from_env() -> None:
    """Re-read ``FA_FAILPOINTS`` (tests; the env is otherwise read once)."""
    global _env_loaded
    with _lock:
        _active.clear()
        _env_loaded = False
    _ensure_env_loaded()


def arm(site: str, spec: str) -> None:
    """Programmatic arming: ``arm("fetch.pair", "oom*1")``."""
    _ensure_env_loaded()
    with _lock:
        _active.update(parse_spec(f"{site}:{spec}"))


def disarm_all() -> None:
    """Clear every armed site (tests)."""
    global _env_loaded
    with _lock:
        _active.clear()
        _env_loaded = True  # explicit state; reload_from_env re-reads


def active() -> Dict[str, str]:
    """Armed sites -> kind (diagnostics)."""
    _ensure_env_loaded()
    with _lock:
        return {s: sp.kind for s, sp in _active.items()}


def fire(site: str) -> None:
    """Injection point.  No-op unless ``site`` is armed; otherwise raise
    or delay per the armed spec.  ``truncate`` specs do NOT fire here —
    they are consumed by the writing layer via :func:`truncation`."""
    _ensure_env_loaded()
    with _lock:
        spec = _active.get(site)
        if spec is None or spec.kind == "truncate" or not spec.take():
            return
        kind, arg = spec.kind, spec.arg
    if kind == "oom":
        raise _xla_resource_exhausted(site)
    if kind == "io":
        raise OSError(
            f"injected failpoint {site!r} (FA_FAILPOINTS): simulated "
            "filesystem failure"
        )
    if kind == "abort":
        raise InjectedAbort(f"injected failpoint {site!r} (FA_FAILPOINTS)")
    if kind == "delay":
        time.sleep((arg or 0) / 1e3)


def truncation(site: str) -> Optional[int]:
    """For artifact-write sites: byte count to truncate the physical
    write at, or None when unarmed.  Consumes one hit."""
    _ensure_env_loaded()
    with _lock:
        spec = _active.get(site)
        if spec is None or spec.kind != "truncate" or not spec.take():
            return None
        return spec.arg
