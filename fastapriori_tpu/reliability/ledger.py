"""Degradation ledger: every silent fallback becomes a structured event.

ADVICE r5 catalogued the failure mode this fixes: the engine already
degrades gracefully in half a dozen places — fused→level-by-level
fallback, the Pallas kernel disabled by ``FA_NO_PALLAS``, pair-cap
overflow retries, int8→int32 accumulation widening — but gracefully AND
silently, so a degraded run is indistinguishable from a slow one in
``BENCH_*.json``.  Every such fallback now calls :func:`record`; the
event lands in three places:

- the in-memory ledger (``snapshot()``/``summary()`` — bench.py attaches
  it to the round record);
- the active :class:`~fastapriori_tpu.utils.logging.MetricsLogger` as an
  ``event="degraded"`` JSON line (so ``--metrics`` streams show the
  degradation inline with the phase it degraded);
- stderr, once per ``(kind, once_key)`` — a human skimming a log sees
  each distinct degradation exactly once, not 400 widening lines.

The module-level ledger is deliberately a process singleton: the sites
that degrade (``parallel/mesh.py``, ``ops`` dispatch points) have no
config or logger in scope, and threading one through every kernel-cache
layer for an observability side channel would be the tail wagging the
dog.  Tests ``reset()`` around assertions.
"""

from __future__ import annotations

import sys
import threading
from collections import Counter
from typing import Any, Dict, List, Optional


class DegradationLedger:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._warned: set = set()
        self._metrics = None  # active MetricsLogger (latest attach wins)

    def attach_metrics(self, metrics) -> None:
        """Forward future events to ``metrics.emit("degraded", ...)``."""
        self._metrics = metrics

    def record(
        self, kind: str, once_key: Optional[str] = None, **fields: Any
    ) -> None:
        event = {"kind": kind, **fields}
        warn_key = (kind, once_key if once_key is not None else kind)
        with self._lock:
            self._events.append(event)
            first = warn_key not in self._warned
            if first:
                self._warned.add(warn_key)
            metrics = self._metrics
        # Every ledger event also enters the crash flight recorder's
        # bounded ring (obs/flight.py) — watchdog trips, cascade walks
        # and retries are exactly the "what happened right before"
        # evidence a post-mortem dump needs.
        from fastapriori_tpu.obs import flight, trace

        flight.note("ledger", **{"event": kind, **fields})
        # And into the span tracer as an instant event under whatever
        # span is active — a retry or cascade walk shows up ON the
        # timeline it degraded, not just in the aggregate summary.
        trace.instant("degraded", **{"kind": kind, **fields})
        if metrics is not None:
            metrics.emit("degraded", **event)
        if first:
            detail = " ".join(f"{k}={v}" for k, v in fields.items())
            print(
                f"fastapriori: degraded: {kind}"
                + (f" ({detail})" if detail else ""),
                file=sys.stderr,
            )

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def summary(self) -> Dict[str, int]:
        """Event counts by kind — the compact form bench records carry."""
        with self._lock:
            return dict(Counter(e["kind"] for e in self._events))

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._warned.clear()


LEDGER = DegradationLedger()


def record(kind: str, once_key: Optional[str] = None, **fields: Any) -> None:
    LEDGER.record(kind, once_key=once_key, **fields)


def attach_metrics(metrics) -> None:
    LEDGER.attach_metrics(metrics)


def snapshot() -> List[Dict[str, Any]]:
    return LEDGER.snapshot()


def summary() -> Dict[str, int]:
    return LEDGER.summary()


def reset() -> None:
    LEDGER.reset()
