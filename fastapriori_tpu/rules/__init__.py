from fastapriori_tpu.rules.gen import Rule, gen_rules, sort_rules  # noqa: F401
