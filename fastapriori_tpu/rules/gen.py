"""Association-rule generation with the reference's dominance prune
(component C11, AssociationRules.scala:122-188).

Host-side: the rule table is tiny next to counting (SURVEY.md §2 C11).
Semantics — the part that defines output parity — reproduced exactly:

1. For every frequent itemset S with |S| >= 2 and every item i in S, a raw
   rule ``(S - {i}) → i`` with confidence ``count(S)/count(S - {i})``
   (:129-145).  Note the denominator for size-1 antecedents is the raw
   *occurrence* count from phase C3, not a basket support — the reference
   feeds its 1-itemset table straight into the lookup (:130).
2. Level-wise "cut leaves" prune (:147-182): every rule at the minimum
   antecedent size survives; a rule at antecedent size i survives iff for
   EACH element e of its antecedent A, the rule ``(A - {e}) → consequent``
   survived level i-1 (:173 via targets.nonEmpty, and the consequent-group
   lookup :159) AND has strictly lower confidence (:168 — any
   ``subset.conf >= conf`` kills the rule).  Net: only rules on strictly
   confidence-increasing chains survive.

Confidence is an IEEE double division of two ints, identical in Python and
on the JVM, so the >=-comparisons agree bit-for-bit with the reference.
"""

from __future__ import annotations

import os
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from fastapriori_tpu.errors import InputError
from fastapriori_tpu.obs import trace
from fastapriori_tpu.ops.bitmap import next_pow2 as _next_pow2
from fastapriori_tpu.reliability import ledger, quorum, retry, watchdog

Rule = Tuple[FrozenSet[int], int, float]  # (antecedent, consequent, confidence)

_RULE_ENGINES = ("auto", "host", "device")


def rule_engine_from_env() -> Optional[str]:
    """Strictly parsed ``FA_RULE_ENGINE`` override (host/device/auto) —
    a typo'd value silently running the wrong engine would be invisible
    in a record, so unknown spellings raise
    :class:`~fastapriori_tpu.errors.InputError` (the FA_NO_PALLAS
    contract).  None = unset, use the config."""
    raw = os.environ.get("FA_RULE_ENGINE", "")
    val = raw.strip().lower()
    if not val:
        return None
    if val in _RULE_ENGINES:
        return val
    raise InputError(
        f"unrecognized FA_RULE_ENGINE value {raw!r}: use one of "
        f"{'/'.join(_RULE_ENGINES)} (or unset to follow "
        "MinerConfig.rule_engine)"
    )


# Exact-compare gate for the device path: the dominance prune compares
# IEEE-double confidences on the host; the device reproduces that order
# with exact 48-bit rational compares, which is bit-equivalent ONLY while
# every count is < 2^24 (ops/contain.py frac_less24's spacing argument).
_DEVICE_COUNT_CAP = 1 << 24


def resolve_rule_shards(context, config) -> int:
    """Phase-2 shard count over the txn mesh axis (ISSUE 8):
    ``FA_RULE_SHARDS`` (strict, utils/env.py) over
    ``config.rule_shards``, both with 0 = auto.  Auto uses the FULL txn
    axis when the mesh is eligible (single process, no cand axis — the
    sharded kernel's exchanges are 1-D-txn collectives); 1 pins phase 2
    to device 0 (the PR-4 engine); any other explicit value must equal
    the mesh's txn axis (a silent partial-mesh run would make the
    recorded shard count a lie), and an explicit multi-shard request on
    an ineligible mesh is an InputError rather than a silent pin."""
    from fastapriori_tpu.utils.env import env_int

    req = env_int("FA_RULE_SHARDS", 0, minimum=0)
    src = "FA_RULE_SHARDS"
    if req == 0:
        src = "MinerConfig.rule_shards"
        req = int(getattr(config, "rule_shards", 0) or 0) if config else 0
        if req < 0:
            raise InputError(
                f"MinerConfig.rule_shards={req} is out of range: use 0 "
                "(auto), 1 (single-device), or the mesh's txn shard count"
            )
    import jax

    eligible = (
        context is not None
        and jax.process_count() == 1
        and context.cand_shards == 1
    )
    # Consensus floor (ISSUE 12): once any peer degraded the rule chain
    # past "sharded", the multi-shard join's exchanges are off the
    # table domain-wide; the device-0 engine still runs.  Judged
    # SEPARATELY from mesh eligibility: an explicit multi-shard request
    # on an ineligible MESH is a config error, but the same request
    # under a peer's degradation must degrade in lockstep (ledger
    # event), not blame the user's valid config for a flake.
    quorum_ok = quorum.stage_allowed("rule_engine", "sharded")
    if req == 0:
        return context.txn_shards if (eligible and quorum_ok) else 1
    if req == 1:
        return 1
    if not eligible:
        raise InputError(
            f"{src}={req} needs a single-process 1-D txn mesh "
            "(multi-process rule generation and cand meshes run the "
            "device-0 engine; use 0/1 or unset)"
        )
    if req != context.txn_shards:
        raise InputError(
            f"{src}={req} does not match the mesh's txn axis "
            f"({context.txn_shards} shards): phase 2 shards over the "
            "existing mesh, it cannot carve a sub-mesh"
        )
    if not quorum_ok:
        # The cascade event for this walk was already recorded when the
        # domain adopted the peer's position; this records the local
        # consequence (the pinned shard count) without double-walking.
        ledger.record(
            "rule_gen_fallback", once_key="quorum_shards",
            reason="quorum", requested_shards=req,
        )
        return 1
    return req


class DeviceRuleState:
    """Device-resident phase-2 state the sharded rule engine leaves
    behind for the recommender's scan-table build (ISSUE 8 part b):
    per-level replicated ``(mat_full, cnts_full, d_flat, surv_flat)``
    arrays plus the host-side survivor census — everything
    ``ops/contain.py rule_scan_build`` needs to assemble the
    priority-sorted compact table ON DEVICE, so the rule table never
    crosses the host link again after the level-table upload."""

    def __init__(self):
        self.ready = False
        self.shards = 1
        self.ks: list = []  # level sizes k
        self.n_pads: list = []
        self.arrays: list = []  # (mat_full, cnts_full, d_flat, surv_flat)
        self.offsets: list = []  # emission offset per level
        self.total = 0  # surviving rule count R
        self.gather_bytes = 0
        self.psum_bytes = 0

    def populate(self, shards, levels, offsets, total, gather_bytes,
                 psum_bytes):
        self.shards = shards
        self.ks = [lv[0] for lv in levels]
        self.n_pads = [lv[1] for lv in levels]
        self.arrays = [lv[2] for lv in levels]
        self.offsets = list(offsets)
        self.total = int(total)
        self.gather_bytes = int(gather_bytes)
        self.psum_bytes = int(psum_bytes)
        self.ready = True

    def device_bytes(self) -> int:
        """HBM footprint of the resident per-level join state (summed
        over the level arrays) — the serving tier reports it next to the
        compact scan table's bytes so a hot-swap's transient double
        residency is a visible number, not a surprise OOM."""
        total = 0
        for arrs in self.arrays:
            for a in arrs:
                total += int(getattr(a, "nbytes", 0))
        return total

    def release(self):
        """Drop the device references (the scan table, once built, is
        the only resident consumer)."""
        self.arrays = []
        self.ready = False


def _raw_rule_count(mats: Dict[int, Tuple[np.ndarray, np.ndarray]]) -> int:
    """Raw (pre-prune) rule count: every k-itemset emits k rules."""
    return sum(
        k * mat.shape[0] for k, (mat, _) in mats.items() if k >= 2
    )


def _max_count(mats: Dict[int, Tuple[np.ndarray, np.ndarray]]) -> int:
    return max(
        (int(c.max()) for _, c in mats.values() if c.size), default=0
    )


def _pick_rule_engine(mats, context, config) -> str:
    """Resolve the phase-2 engine (config.rule_engine / FA_RULE_ENGINE):
    the device path needs a context and exact-compare-safe counts, and
    under "auto" must also clear the size floor (device wins only on big
    levels — per-level dispatches and table uploads carry fixed cost)
    and a real accelerator.  The choice — and every forced-device
    fallback — is recorded in the degradation ledger so a record shows
    WHICH engine produced its rules (ISSUE 4)."""
    engine = rule_engine_from_env()
    if engine is None:
        engine = getattr(config, "rule_engine", "auto") if config else "auto"
        if engine not in _RULE_ENGINES:
            # The config field gets the same strictness as the env var —
            # a typo silently forcing the device engine is the exact
            # failure mode the FA_NO_PALLAS contract exists to kill.
            raise InputError(
                f"unrecognized MinerConfig.rule_engine value {engine!r}: "
                f"use one of {'/'.join(_RULE_ENGINES)}"
            )
    if engine == "host":
        return "host"
    raw = _raw_rule_count(mats)
    if not quorum.stage_allowed("rule_engine", "device"):
        # Consensus floor (ISSUE 12): a peer already walked phase 2 to
        # the host oracle — device/sharded joins would issue collectives
        # it will never match.
        if engine == "device":
            ledger.record(
                "rule_gen_fallback", reason="quorum", raw_rules=raw
            )
            watchdog.downgrade(
                "rule_engine", "device", "host", reason="quorum"
            )
        return "host"
    if context is None:
        if engine == "device":
            ledger.record(
                "rule_gen_fallback", reason="no_device_context", raw_rules=raw
            )
            watchdog.downgrade(
                "rule_engine", "device", "host",
                reason="no_device_context",
            )
        return "host"
    if _max_count(mats) >= _DEVICE_COUNT_CAP:
        if engine == "device":
            ledger.record(
                "rule_gen_fallback",
                reason="counts_exceed_2^24",
                raw_rules=raw,
            )
            watchdog.downgrade(
                "rule_engine", "device", "host",
                reason="counts_exceed_2^24",
            )
        return "host"
    if engine == "auto":
        floor = (
            getattr(config, "rule_device_min_rules", 1 << 21)
            if config
            else 1 << 21
        )
        if raw < floor or context.platform == "cpu":
            return "host"
    ledger.record(
        "rule_gen_engine", once_key="device", engine="device", raw_rules=raw
    )
    return "device"


def _rows_view(m: np.ndarray) -> np.ndarray:
    """View an int32 [N, K] matrix as N comparable composite scalars so
    whole rows sort/search as single keys."""
    m = np.ascontiguousarray(m)
    return m.view([("", m.dtype)] * m.shape[1]).ravel()


def _row_keys(m: np.ndarray, f: int) -> np.ndarray:
    """Sortable scalar key per row, ordered like lexicographic row
    order.  When the row fits 8 bytes at the item-axis byte width
    (F <= 256 → 1 byte/rank, etc.), rows pack into native uint64 —
    numpy sorts/searches native ints ~20x faster than the structured
    (void, memcmp-compared) fallback, which at webdocs scale (16M raw
    rules) was the difference between ~5 minutes and seconds of rule
    pruning.  Falls back to :func:`_rows_view` for wide rows."""
    n, w = m.shape
    bits = 8 if f <= 256 else (16 if f <= 65536 else 32)
    if w * bits > 64:
        return _rows_view(m)
    shifts = ((w - 1 - np.arange(w, dtype=np.uint64)) * np.uint64(bits))
    return np.bitwise_or.reduce(
        m.astype(np.uint64) << shifts[None, :], axis=1
    )


def _deleted_row_keys(m: np.ndarray, f: int) -> Optional[np.ndarray]:
    """``out[:, e] == _row_keys(np.delete(m, e, axis=1), f)`` for every
    column e, computed incrementally: with ranks packed into
    ``bits``-wide fields, deleting column e shifts the fields before it
    down one slot and keeps the fields after it — so every deleted-row
    key is one prefix-cumsum plus one suffix-cumsum, O(k) array passes
    instead of the O(k²) repack the per-column ``_row_keys`` calls cost
    (raw rule generation touches every key of every level's every
    column: ~100M packs at webdocs/minSupport=0.092 scale).  None when
    the (k-1)-wide rows don't fit uint64 (callers fall back)."""
    n, k = m.shape
    bits = 8 if f <= 256 else (16 if f <= 65536 else 32)
    if (k - 1) * bits > 64 or k < 2:
        return None
    b = np.uint64(bits)
    mu = m.astype(np.uint64)
    j = np.arange(k, dtype=np.uint64)
    # Prefix part: columns j < e land at deleted-row shift bits*(k-2-j).
    # (Temporaries are [N, k] uint64 — ~1 GB each at 16M-rule levels —
    # so accumulate in place and free eagerly.)
    a = mu[:, : k - 1] << ((np.uint64(k - 2) - j[: k - 1]) * b)[None, :]
    out = np.zeros((n, k), dtype=np.uint64)
    np.cumsum(a, axis=1, out=out[:, 1:])
    # Suffix part: columns j > e keep their full-row shift bits*(k-1-j);
    # fields are disjoint, so += never carries.
    np.multiply(
        mu[:, 1:],
        np.uint64(1) << (((np.uint64(k - 1) - j[1:]) * b))[None, :],
        out=a,
    )
    del mu
    out[:, : k - 1] += np.cumsum(a[:, ::-1], axis=1)[:, ::-1]
    return out


def _lookup_rows(
    sorted_keys: np.ndarray, order: np.ndarray, keys: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """(position-in-original-order, found) for each key row."""
    pos = np.searchsorted(sorted_keys, keys)
    found = np.zeros(len(keys), dtype=bool)
    inb = pos < len(sorted_keys)
    found[inb] = sorted_keys[pos[inb]] == keys[inb]
    safe = np.minimum(pos, max(len(sorted_keys) - 1, 0))
    return (order[safe] if len(order) else safe), found


def gen_rules(
    freq_itemsets: Sequence[Tuple[FrozenSet[int], int]]
) -> List[Rule]:
    # Group itemsets by size into sorted-row matrices; all raw-rule
    # generation and the level-wise prune are then vectorized row joins
    # (the pure-Python dict/frozenset formulation was the cold-start
    # bottleneck at 10^5-itemset scale).
    by_len: Dict[int, List[Tuple[FrozenSet[int], int]]] = {}
    for s, c in freq_itemsets:
        by_len.setdefault(len(s), []).append((s, c))
    mats: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    for k, entries in by_len.items():
        if k == 0:
            continue
        mat = np.fromiter(
            (r for s, _ in entries for r in sorted(s)),
            np.int32,
            len(entries) * k,
        ).reshape(-1, k)
        cnts = np.fromiter((c for _, c in entries), np.int64, len(entries))
        mats[k] = (mat, cnts)
    return _rules_from_tables(mats)


def _level_tables(
    levels, item_counts
) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
    """Size-grouped itemset tables from the matrix-form mining result —
    ONE builder for the object and array rule pipelines (their exact
    parity is load-bearing: the device first-match table is built from
    the arrays, the host fallback from the objects)."""
    mats: Dict[int, Tuple[np.ndarray, np.ndarray]] = {
        1: (
            np.arange(len(item_counts), dtype=np.int32)[:, None],
            # lint: host-data -- item counts are host numpy/lists
            np.asarray(item_counts, dtype=np.int64),
        )
    }
    for mat, cnts in levels:
        if mat.shape[0]:
            # lint: host-data -- level matrices are host numpy by here
            mats[mat.shape[1]] = (mat, np.asarray(cnts, dtype=np.int64))
    return mats


def gen_rules_levels(levels, item_counts) -> List[Rule]:
    """Matrix-form twin of :func:`gen_rules`: consumes the raw mining
    path's level matrices directly (FastApriori.run_file_raw) instead of
    rebuilding them from frozensets — the size-grouped tables ARE the
    levels.  ``item_counts`` are the per-rank raw occurrence counts (C3),
    the size-1 rule denominators."""
    return _rules_from_tables(_level_tables(levels, item_counts))


RuleArrays = Tuple[np.ndarray, np.ndarray, np.ndarray]  # ant [N,w], cons, conf


def rule_arrays_from_tables(
    mats: Dict[int, Tuple[np.ndarray, np.ndarray]],
    context=None,
    config=None,
    metrics=None,
    scan_state: Optional[DeviceRuleState] = None,
) -> List[RuleArrays]:
    """Matrix-form rule generation + dominance prune: surviving rules as
    ``(antecedent int32 [N, w], consequent int32 [N], confidence f64
    [N])`` per antecedent size, in the same order the object form emits
    — NO per-rule Python objects (materializing 16M frozensets at
    webdocs/minSupport=0.092 scale cost ~140 s by itself).

    ``context``/``config`` opt the call into the DEVICE engine
    (:func:`_pick_rule_engine`; config.rule_engine / FA_RULE_ENGINE):
    the level-wise subset joins and the dominance prune run as packed-
    key sorted gathers on the accelerator (ops/contain.py
    rule_level_kernel, one dispatch per level), bit-identical to this
    host path — which remains the differential oracle and the automatic
    fallback below the size threshold.  On an eligible multi-device
    mesh the joins shard over the txn axis (:func:`resolve_rule_shards`
    / FA_RULE_SHARDS, ops/contain.py rule_level_shard_kernel);
    ``scan_state`` (a :class:`DeviceRuleState`) additionally keeps the
    per-level device state resident for the recommender's on-device
    scan-table build."""
    # Phase-2 consensus exchange (ISSUE 12): adopt any cascade position
    # a peer walked during mining BEFORE resolving the rule engine, so
    # phase 2's first dispatch is already lockstep.  A rendezvous, not
    # a poll: every rank enters phase 2 exactly once, and the real-mesh
    # (JaxTransport) exchange only runs at rendezvous points — its
    # allgather must be called collectively.  No-op without a domain.
    # Rejoin-armed (ISSUE 17): survivors of an elastic abort pair here
    # under the advanced mesh epoch with any rank that finished mining
    # before the abort.
    quorum.sync_or_rejoin("rules.start", wait=True)
    engine = _pick_rule_engine(mats, context, config)
    if engine == "device":
        shards = resolve_rule_shards(context, config)
        if shards > 1:
            # Exchange topology for the sharded join's mask/denominator
            # merges and next-level table reassembly (ISSUE 15,
            # parallel/hier.py): same knob resolution + quorum floor as
            # the mining collectives, installed on the context so the
            # join kernel compiles (and its cache keys) carry it.
            from fastapriori_tpu.parallel.hier import resolve_active_spec

            context.set_exchange_spec(
                resolve_active_spec(shards, config)
            )
        # The sharded kernel always splits rows over the FULL txn axis
        # (shard_map owns the placement), so the resident-scan state is
        # only kept when the resolved shard count covers the mesh — a
        # rule_shards=1 pin on a multi-device mesh runs the device-0
        # engine and the recommender's host-built-table scan instead
        # (the 8·S row-padding layout would not match otherwise).
        if shards != context.txn_shards:
            scan_state = None
        try:
            return _rule_arrays_device(
                mats, context, metrics, shards=shards, state=scan_state
            )
        except Exception as exc:
            # Repeated transients at the device joins' fetch sites walk
            # the cascade to the host oracle (bit-identical by the
            # differential contract) instead of killing phase 2.
            if not watchdog.transient(exc):
                raise
            watchdog.downgrade(
                "rule_engine",
                "sharded" if shards > 1 else "device",
                "host",
                reason="transient_exhausted",
                error=f"{type(exc).__name__}: {exc}"[:200],
            )
            if scan_state is not None:
                scan_state.release()
    return _rule_arrays_host(mats)


def _rule_arrays_host(
    mats: Dict[int, Tuple[np.ndarray, np.ndarray]]
) -> List[RuleArrays]:
    """The numpy engine (see :func:`rule_arrays_from_tables`)."""
    # Raw rules (S - {i}) -> i with confidence count(S)/count(S - {i})
    # (:129-145); the size-1 denominator is the raw occurrence count, via
    # the 1-itemset table.  Downward closure guarantees every antecedent
    # is present (InputError otherwise — reachable only via corrupted
    # --resume-from artifacts; the reference would throw a bare
    # NoSuchElementException from its table lookup).
    f = 1 + max(
        (int(mat.max()) for mat, _ in mats.values() if mat.size), default=0
    )
    # Raw generation keeps, per k-itemset and deleted column e, the ROW
    # INDEX of S - {e} in the (k-1)-itemset table (computed anyway for
    # the confidence denominators).  The dominance prune then addresses
    # each parent RULE in O(1): raw rules of antecedent size k-2 are
    # concatenated consequent-position-major, so rule (S-{e} -> j) lives
    # at flat index j_pos_in(S-{e}) * N_{k-1} + row(S-{e}) — no key
    # rebuild, no argsort, no searchsorted over 16M-row tables (the
    # level-wise subset joins were phase 2's dominant cost at
    # webdocs/minSupport=0.092 scale, VERDICT r4 next #5).
    raw: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
    parent_rows: Dict[int, np.ndarray] = {}  # k -> int32 [k, N_k]
    n_sets: Dict[int, int] = {}
    for k in sorted(mats):
        if k < 2:
            continue
        if k - 1 not in mats:
            raise InputError(
                f"itemset table is not downward-closed: {k}-itemsets are "
                f"present but no {k - 1}-itemsets exist to serve as rule "
                "antecedents — the mining output (or --resume-from "
                "artifact) is incomplete; re-mine or re-save it"
            )
        mat, cnts = mats[k]
        pmat, pcnts = mats[k - 1]
        n_sets[k] = mat.shape[0]
        n_sets[k - 1] = pmat.shape[0]
        pview = _row_keys(pmat, f)
        porder = np.argsort(pview)
        psorted = pview[porder]
        ants, conss, confs = [], [], []
        rows_e = np.empty((k, mat.shape[0]), dtype=np.int32)
        dk = _deleted_row_keys(mat, f)  # [N, k] or None (wide rows)
        for j in range(k):
            ant = np.delete(mat, j, axis=1)  # sorted rows stay sorted
            keys = dk[:, j] if dk is not None else _row_keys(ant, f)
            idx, found = _lookup_rows(psorted, porder, keys)
            if not found.all():
                # lint: host-data -- host numpy row in the error message
                bad = frozenset(ant[int(np.argmin(found))].tolist())
                raise InputError(
                    f"itemset table is not downward-closed: antecedent "
                    f"{sorted(bad)} (ranks) of a {k}-itemset is missing "
                    "from the table — the mining output (or --resume-from "
                    "artifact) is incomplete; re-mine or re-save it"
                )
            # IEEE double division of two int counts — identical to the
            # reference's JVM division, so >= comparisons agree exactly.
            ants.append(ant)
            conss.append(mat[:, j])
            confs.append(cnts / pcnts[idx].astype(np.float64))
            rows_e[j] = idx
        raw[k - 1] = (
            np.concatenate(ants),
            np.concatenate(conss),
            np.concatenate(confs),
        )
        parent_rows[k] = rows_e

    if not raw:
        return []

    min_len = min(raw)
    max_len = max(raw)
    out: List[RuleArrays] = []

    surv_ant, surv_cons, surv_conf = raw[min_len]
    out.append((surv_ant, surv_cons, surv_conf))
    # Raw-indexed survival of the PREVIOUS antecedent size (every
    # min_len rule survives, matching the reference's base case).
    prev_surv = np.ones(len(surv_cons), dtype=bool)
    prev_conf = surv_conf
    for i in range(min_len + 1, max_len + 1):
        if i not in raw:
            surv_ant = np.zeros((0, i), np.int32)
            surv_cons = np.zeros(0, np.int32)
            surv_conf = np.zeros(0)
            prev_surv = np.zeros(0, dtype=bool)
            prev_conf = np.zeros(0)
            continue
        ant, cons, conf = raw[i]
        k = i + 1  # these rules come from k-itemsets
        n_k = n_sets[k]
        n_prev = n_sets[k - 1]
        rows_e = parent_rows[k]
        if prev_surv.size == 0 and n_prev > 0:
            # Defensive twin of the old key-lookup behavior after a
            # level gap (unreachable while the downward-closure guard
            # holds): no surviving parents -> every rule here is pruned.
            surv_ant = np.zeros((0, i), np.int32)
            surv_cons = np.zeros(0, np.int32)
            surv_conf = np.zeros(0)
            out.append((surv_ant, surv_cons, surv_conf))
            prev_surv = np.zeros(len(cons), dtype=bool)
            prev_conf = conf
            continue
        ok = np.ones(len(cons), dtype=bool)
        for j_pos in range(k):
            # This consequent position's slice of the raw arrays
            # (concatenation above is j-major).
            sl = slice(j_pos * n_k, (j_pos + 1) * n_k)
            conf_j = conf[sl]
            ok_j = ok[sl]
            for e_pos in range(k):
                if e_pos == j_pos:
                    continue
                # Parent rule (S - {e_pos}) -> S[j_pos]: consequent
                # position shifts down when the deleted column precedes
                # it.  Survive iff the parent survived with strictly
                # lower confidence (AssociationRules.scala:168,173).
                jp = j_pos - (e_pos < j_pos)
                pidx = jp * n_prev + rows_e[e_pos]
                ok_j &= prev_surv[pidx] & (prev_conf[pidx] < conf_j)
        surv_ant, surv_cons, surv_conf = ant[ok], cons[ok], conf[ok]
        out.append((surv_ant, surv_cons, surv_conf))
        prev_surv = ok
        prev_conf = conf
    return out


def _closure_error(k: int) -> InputError:
    return InputError(
        f"itemset table is not downward-closed: {k}-itemsets are "
        f"present but no {k - 1}-itemsets exist to serve as rule "
        "antecedents — the mining output (or --resume-from "
        "artifact) is incomplete; re-mine or re-save it"
    )


def _rule_arrays_device(
    mats: Dict[int, Tuple[np.ndarray, np.ndarray]],
    ctx,
    metrics=None,
    shards: int = 1,
    state: Optional[DeviceRuleState] = None,
) -> List[RuleArrays]:
    """Device engine for :func:`rule_arrays_from_tables` (ISSUE 4
    tentpole): upload each level's itemset table ONCE, run the k→(k-1)
    antecedent joins + dominance prune as one dispatch per level
    (ops/contain.py rule_level_kernel — all k column deletions batched,
    prune state device-resident between levels), fetch only the packed
    survivor bitmasks (async, overlapping later dispatches) and the
    surviving rules' denominators through the audited pow2-padded gather
    path (parallel/mesh.py gather_level_counts_start).  Confidences are
    then the SAME host f64 divisions of the same ints the host engine
    performs — bit-identical output, pinned by the differential suite
    (tests/test_rules_device.py).

    ``shards > 1`` (or a ``state`` to fill) runs the SHARDED join engine
    (ISSUE 8: ops/contain.py rule_level_shard_kernel — query rows split
    over the txn mesh axis, parent keys replicated via one in-kernel
    all_gather, survivor blocks merged with the packed-mask exchange) —
    still one dispatch per level, bit-identical output, per-level
    psum/gather bytes on the metrics event like the mining collectives;
    ``state`` keeps the per-level device arrays resident for the
    recommender's on-device scan-table build."""
    import time

    import jax.numpy as jnp

    from fastapriori_tpu.ops.bitmap import pad_axis as _pad_axis
    from fastapriori_tpu.ops.contain import (
        rule_key_bits,
        rule_shard_bytes,
        rule_shard_stage_bytes,
    )

    sharded = shards > 1 or state is not None
    t0 = time.perf_counter()
    f = 1 + max(
        (int(mat.max()) for mat, _ in mats.values() if mat.size), default=0
    )
    bits = rule_key_bits(f)
    ks = sorted(k for k in mats if k >= 2)
    if not ks:
        return []
    per_level: List[dict] = []
    comms: List[dict] = []
    gather_total = 0
    psum_total = 0
    prev_keys = None  # (skeys tuple, order) — previous table, sorted
    prev_cnts_dev = None  # previous level's padded counts (= pcnts)
    prev_rules = None  # (surv_flat, d_flat) — previous RULE level
    prev_n = 0
    for k in ks:
        if k - 1 not in mats:
            raise _closure_error(k)
        mat, cnts = mats[k]
        n = mat.shape[0]
        if sharded:
            # Rows divisible by 8·S so the per-shard survivor blocks
            # pack to whole bytes (the mask exchange's layout contract).
            n_pad = _pad_axis(_next_pow2(max(n, 8)), 8 * shards)
        else:
            n_pad = max(8, _next_pow2(n))
        mat_p = np.zeros((n_pad, k), np.int32)
        mat_p[:n] = mat
        cnts_p = np.ones(n_pad, np.int32)
        cnts_p[:n] = cnts
        if sharded:
            mat_dev = ctx.shard_rule_rows(mat_p)
            cnts_dev = ctx.shard_rule_rows(cnts_p)
        else:
            mat_dev = ctx.device0_put(mat_p)
            cnts_dev = ctx.device0_put(cnts_p)
        first = k == 2
        if first:
            # Parents are the 1-itemsets: an identity table — the kernel
            # skips the search, so only the counts upload is real.
            # lint: host-data -- 1-itemset counts are host numpy
            p1 = np.asarray(mats[1][1], dtype=np.int32)
            pcnts_dev = (
                ctx.replicate_rule_table(p1)
                if sharded
                else ctx.device0_put(p1)
            )
            dummy_u32 = jnp.zeros(1, jnp.uint32)
            psorted = (dummy_u32,)
            porder = jnp.zeros(1, jnp.int32)
            prev_surv = jnp.zeros(1, bool)
            prev_d = jnp.zeros(1, jnp.int32)
            np_real = 0
        else:
            psorted, porder = prev_keys
            pcnts_dev = prev_cnts_dev
            prev_surv, prev_d = prev_rules
            np_real = prev_n
        if sharded:
            fn = ctx.rule_level_join_sharded(k, bits, first)
        else:
            fn = ctx.rule_level_join(k, bits, first)
        # Per-level join span (ISSUE 11): nests under the recommender's
        # gen_rules span; the overlapped rule_mask[_shard] fetches show
        # as their own audited-fetch spans when consumed below.
        with trace.span(
            "rules.level", k=k, n=n, shards=shards if sharded else 1
        ):
            out = fn(
                mat_dev,
                cnts_dev,
                jnp.int32(n),
                psorted,
                porder,
                pcnts_dev,
                jnp.int32(np_real),
                prev_surv,
                prev_d,
            )
        if sharded:
            packed, skeys, order, d_flat, surv_flat, mat_full, cnts_full = (
                out
            )
            # Non-blocking audited fetch: the j-major survivor bitmask
            # (+ 4-byte miss count) crosses the link while the next
            # levels dispatch.  Distinct site from the single-chip
            # engine so injection/coverage track the sharded path.
            fetch = retry.fetch_async(packed, "rule_mask_shard")
            xspec = ctx.exchange_spec
            g_b, p_b = rule_shard_bytes(k, n_pad, shards, xspec)
            i_b, e_b, msgs = rule_shard_stage_bytes(
                k, n_pad, shards, xspec
            )
            comms.append(
                {
                    "k": k,
                    "gather_bytes": g_b,
                    "psum_bytes": p_b,
                    "exchange": "hier" if xspec is not None else "flat",
                    "intra_bytes": i_b,
                    "inter_bytes": e_b,
                    "inter_msgs": msgs,
                }
            )
            gather_total += g_b
            psum_total += p_b
        else:
            packed, skeys, order, d_flat, surv_flat = out
            cnts_full = cnts_dev
            mat_full = None
            fetch = retry.fetch_async(packed, "rule_mask")
        per_level.append(
            {
                "k": k,
                "n": n,
                "n_pad": n_pad,
                "mat": mat,
                "cnts": cnts,
                "d_dev": d_flat,
                "surv_dev": surv_flat,
                "mat_dev": mat_full,
                "cnts_dev": cnts_full,
                "fetch": fetch,
            }
        )
        prev_keys = (skeys, order)
        prev_cnts_dev = cnts_full
        prev_rules = (surv_flat, d_flat)
        prev_n = n
    dispatch_ms = (time.perf_counter() - t0) * 1e3

    # Consume the masks (fetches overlapped the dispatch loop above) and
    # collect each survivor's flat position for the ONE denominator
    # gather dispatch + fetch (u24: counts < 2^24 by the engine gate).
    pend = []
    offsets = []
    total_surv = 0
    for lv in per_level:
        out_b = lv["fetch"].result()
        miss = int.from_bytes(out_b[-4:].tobytes(), "little")
        if miss:
            raise InputError(
                f"itemset table is not downward-closed: {miss} "
                f"antecedent(s) of the {lv['k']}-itemsets are missing "
                "from the table — the mining output (or --resume-from "
                "artifact) is incomplete; re-mine or re-save it"
            )
        surv = (
            np.unpackbits(out_b[:-4])
            .reshape(lv["k"], lv["n_pad"])[:, : lv["n"]]
            .astype(bool)
        )
        lv["surv"] = surv
        rows = [np.flatnonzero(surv[j]) for j in range(lv["k"])]
        lv["rows"] = rows
        # Emission offsets for the device scan-table build: slot base of
        # this level's j-major survivor stream.
        offsets.append(total_surv)
        total_surv += int(surv.sum())
        pos = np.concatenate(
            [j * lv["n_pad"] + r for j, r in enumerate(rows)]
        ) if any(r.size for r in rows) else np.empty(0, np.int64)
        pend.append((lv["d_dev"], pos))
    have = [(d, p) for d, p in pend if p.size]
    den = (
        ctx.gather_level_counts_start(have, u24=True, site="rule_counts")
        .result()
        if have
        else np.empty(0, np.int64)
    )

    out: List[RuleArrays] = []
    off = 0
    for lv in per_level:
        k = lv["k"]
        mat, cnts = lv["mat"], lv["cnts"]
        cols = np.arange(k)
        ants, conss, confs = [], [], []
        for j in range(k):
            rows_j = lv["rows"][j]
            d_j = den[off : off + rows_j.size].astype(np.float64)
            off += rows_j.size
            ants.append(mat[np.ix_(rows_j, np.delete(cols, j))])
            conss.append(mat[rows_j, j])
            # The SAME f64 int division the host engine performs — the
            # device only located the denominators.
            confs.append(cnts[rows_j] / d_j)
        out.append(
            (
                np.concatenate(ants)
                if ants
                else np.zeros((0, k - 1), np.int32),
                np.concatenate(conss) if conss else np.zeros(0, np.int32),
                np.concatenate(confs) if confs else np.zeros(0),
            )
        )
    if state is not None and sharded:
        state.populate(
            shards=shards,
            levels=[
                (
                    lv["k"],
                    lv["n_pad"],
                    (lv["mat_dev"], lv["cnts_dev"], lv["d_dev"],
                     lv["surv_dev"]),
                )
                for lv in per_level
            ],
            offsets=offsets,
            total=total_surv,
            gather_bytes=gather_total,
            psum_bytes=psum_total,
        )
    if metrics is not None:
        metrics.emit(
            "rule_gen_device",
            levels=len(per_level),
            shards=shards if sharded else 1,
            dispatches=len(per_level) + (1 if have else 0),
            raw_rules=_raw_rule_count(mats),
            survivors=sum(int(c.size) for _, c, _ in out),
            dispatch_ms=round(dispatch_ms, 1),
            wall_ms=round((time.perf_counter() - t0) * 1e3, 1),
            # Per-level mesh collective payloads (the mining phases'
            # psum/gather-byte convention); empty on the 1-chip engine.
            gather_bytes=gather_total,
            psum_bytes=psum_total,
            exchange=(
                comms[0]["exchange"] if comms else "flat"
            ),
            comms=comms,
        )
    return out


def _rules_from_tables(
    mats: Dict[int, Tuple[np.ndarray, np.ndarray]]
) -> List[Rule]:
    out: List[Rule] = []
    for ant, cons, conf in rule_arrays_from_tables(mats):
        out.extend(
            (frozenset(a), int(c), float(cf))
            # lint: host-data -- survivor arrays are host numpy
            for a, c, cf in zip(ant.tolist(), cons.tolist(), conf.tolist())
        )
    return out


def gen_rule_arrays_levels(
    levels, item_counts, context=None, config=None, metrics=None,
    scan_state=None,
) -> List[RuleArrays]:
    """Matrix-form twin of :func:`gen_rules_levels` returning survivor
    ARRAYS (see rule_arrays_from_tables) — the production recommender
    path never builds per-rule Python objects.  ``context``/``config``
    opt into the device join engine (bit-identical; host stays the
    oracle and the small-input fallback); ``scan_state`` keeps the
    sharded engine's per-level device state resident for the
    recommender's on-device scan-table build."""
    return rule_arrays_from_tables(
        _level_tables(levels, item_counts),
        context=context,
        config=config,
        metrics=metrics,
        scan_state=scan_state,
    )


def _consequent_priority(freq_items: Sequence[str]) -> np.ndarray:
    """Per-rank position under the reference's consequent tie order
    (integer-parsed ascending, non-integers after by string —
    :func:`sort_rules`'s key, computed once per ITEM instead of once per
    rule)."""

    def key(item: str):
        try:
            return (0, int(item), item)
        except ValueError:
            return (1, 0, item)

    order = sorted(range(len(freq_items)), key=lambda r: key(freq_items[r]))
    pr = np.empty(len(freq_items), dtype=np.int64)
    pr[order] = np.arange(len(freq_items))
    return pr


def sort_rule_arrays(
    survivors: Sequence[RuleArrays], freq_items: Sequence[str]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Global recommendation priority order over survivor arrays —
    ``(ant int32 [R, k_max] (0-padded; read lens), lens int32 [R],
    cons int32 [R], conf f64 [R])`` ordered exactly like
    :func:`sort_rules` on the object form: confidence desc, consequent
    priority asc, original order on full ties (np.lexsort is stable,
    like Python's sort).  One vectorized sort replaces a Python
    key-function sort that cost minutes at 10^7-rule scale."""
    blocks = [s for s in survivors if len(s[1])]
    if not blocks:
        z = np.zeros(0, np.int32)
        return np.zeros((0, 1), np.int32), z, z, np.zeros(0)
    r_total = sum(len(c) for _, c, _ in blocks)
    k_max = max(a.shape[1] for a, _, _ in blocks)
    ant = np.zeros((r_total, k_max), dtype=np.int32)
    lens = np.empty(r_total, dtype=np.int32)
    cons = np.empty(r_total, dtype=np.int32)
    conf = np.empty(r_total, dtype=np.float64)
    at = 0
    for a, c, cf in blocks:
        n, w = a.shape
        ant[at : at + n, :w] = a
        lens[at : at + n] = w
        cons[at : at + n] = c
        conf[at : at + n] = cf
        at += n
    pr = _consequent_priority(freq_items)
    order = np.lexsort((pr[cons], -conf))
    return ant[order], lens[order], cons[order], conf[order]


def rule_objects_from_arrays(
    ant: np.ndarray, lens: np.ndarray, cons: np.ndarray, conf: np.ndarray
) -> List[Rule]:
    """Materialize the object form from (already sorted) rule arrays —
    only the host first-match fallback and API-parity callers pay this."""
    return [
        (frozenset(a[:n]), int(c), float(cf))
        for a, n, c, cf in zip(
            # lint: host-data -- sorted rule arrays are host numpy
            ant.tolist(), lens.tolist(), cons.tolist(), conf.tolist()
        )
    ]


def sort_rules(rules: Sequence[Rule], freq_items: Sequence[str]) -> List[Rule]:
    """Recommendation priority order: confidence desc, consequent item
    parsed as an integer asc (associationRulesSort,
    AssociationRules.scala:116-120 — the reference assumes integer item
    strings there; non-integer items would crash it, we fall back to the
    string)."""

    def key(r: Rule):
        item = freq_items[r[1]]
        try:
            return (-r[2], 0, int(item), item)
        except ValueError:
            return (-r[2], 1, 0, item)

    return sorted(rules, key=key)
