"""Association-rule generation with the reference's dominance prune
(component C11, AssociationRules.scala:122-188).

Host-side: the rule table is tiny next to counting (SURVEY.md §2 C11).
Semantics — the part that defines output parity — reproduced exactly:

1. For every frequent itemset S with |S| >= 2 and every item i in S, a raw
   rule ``(S - {i}) → i`` with confidence ``count(S)/count(S - {i})``
   (:129-145).  Note the denominator for size-1 antecedents is the raw
   *occurrence* count from phase C3, not a basket support — the reference
   feeds its 1-itemset table straight into the lookup (:130).
2. Level-wise "cut leaves" prune (:147-182): every rule at the minimum
   antecedent size survives; a rule at antecedent size i survives iff for
   EACH element e of its antecedent A, the rule ``(A - {e}) → consequent``
   survived level i-1 (:173 via targets.nonEmpty, and the consequent-group
   lookup :159) AND has strictly lower confidence (:168 — any
   ``subset.conf >= conf`` kills the rule).  Net: only rules on strictly
   confidence-increasing chains survive.

Confidence is an IEEE double division of two ints, identical in Python and
on the JVM, so the >=-comparisons agree bit-for-bit with the reference.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Sequence, Tuple

Rule = Tuple[FrozenSet[int], int, float]  # (antecedent, consequent, confidence)


def gen_rules(
    freq_itemsets: Sequence[Tuple[FrozenSet[int], int]]
) -> List[Rule]:
    support: Dict[FrozenSet[int], int] = dict(freq_itemsets)

    raw_by_len: Dict[int, List[Rule]] = {}
    for s, c in freq_itemsets:
        if len(s) < 2:
            continue
        for item in s:
            ant = s - {item}
            raw_by_len.setdefault(len(ant), []).append(
                (ant, item, c / support[ant])
            )

    if not raw_by_len:
        return []

    min_len = min(raw_by_len)
    max_len = max(raw_by_len)
    survivors: List[Rule] = list(raw_by_len[min_len])
    low_level = survivors
    for i in range(min_len + 1, max_len + 1):
        # Surviving lower-level rules indexed by (antecedent, consequent).
        low_conf: Dict[Tuple[FrozenSet[int], int], float] = {
            (ant, cons): conf for ant, cons, conf in low_level
        }
        level: List[Rule] = []
        for ant, cons, conf in raw_by_len.get(i, ()):
            ok = True
            for e in ant:
                sub_conf = low_conf.get((ant - {e}, cons))
                if sub_conf is None or sub_conf >= conf:
                    ok = False
                    break
            if ok:
                level.append((ant, cons, conf))
        survivors.extend(level)
        low_level = level
    return survivors


def sort_rules(rules: Sequence[Rule], freq_items: Sequence[str]) -> List[Rule]:
    """Recommendation priority order: confidence desc, consequent item
    parsed as an integer asc (associationRulesSort,
    AssociationRules.scala:116-120 — the reference assumes integer item
    strings there; non-integer items would crash it, we fall back to the
    string)."""

    def key(r: Rule):
        item = freq_items[r[1]]
        try:
            return (-r[2], 0, int(item), item)
        except ValueError:
            return (-r[2], 1, 0, item)

    return sorted(rules, key=key)
