"""Association-rule generation with the reference's dominance prune
(component C11, AssociationRules.scala:122-188).

Host-side: the rule table is tiny next to counting (SURVEY.md §2 C11).
Semantics — the part that defines output parity — reproduced exactly:

1. For every frequent itemset S with |S| >= 2 and every item i in S, a raw
   rule ``(S - {i}) → i`` with confidence ``count(S)/count(S - {i})``
   (:129-145).  Note the denominator for size-1 antecedents is the raw
   *occurrence* count from phase C3, not a basket support — the reference
   feeds its 1-itemset table straight into the lookup (:130).
2. Level-wise "cut leaves" prune (:147-182): every rule at the minimum
   antecedent size survives; a rule at antecedent size i survives iff for
   EACH element e of its antecedent A, the rule ``(A - {e}) → consequent``
   survived level i-1 (:173 via targets.nonEmpty, and the consequent-group
   lookup :159) AND has strictly lower confidence (:168 — any
   ``subset.conf >= conf`` kills the rule).  Net: only rules on strictly
   confidence-increasing chains survive.

Confidence is an IEEE double division of two ints, identical in Python and
on the JVM, so the >=-comparisons agree bit-for-bit with the reference.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Sequence, Tuple

import numpy as np

from fastapriori_tpu.errors import InputError

Rule = Tuple[FrozenSet[int], int, float]  # (antecedent, consequent, confidence)


def _rows_view(m: np.ndarray) -> np.ndarray:
    """View an int32 [N, K] matrix as N comparable composite scalars so
    whole rows sort/search as single keys."""
    m = np.ascontiguousarray(m)
    return m.view([("", m.dtype)] * m.shape[1]).ravel()


def _lookup_rows(
    sorted_keys: np.ndarray, order: np.ndarray, keys: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """(position-in-original-order, found) for each key row."""
    pos = np.searchsorted(sorted_keys, keys)
    found = np.zeros(len(keys), dtype=bool)
    inb = pos < len(sorted_keys)
    found[inb] = sorted_keys[pos[inb]] == keys[inb]
    safe = np.minimum(pos, max(len(sorted_keys) - 1, 0))
    return (order[safe] if len(order) else safe), found


def gen_rules(
    freq_itemsets: Sequence[Tuple[FrozenSet[int], int]]
) -> List[Rule]:
    # Group itemsets by size into sorted-row matrices; all raw-rule
    # generation and the level-wise prune are then vectorized row joins
    # (the pure-Python dict/frozenset formulation was the cold-start
    # bottleneck at 10^5-itemset scale).
    by_len: Dict[int, List[Tuple[FrozenSet[int], int]]] = {}
    for s, c in freq_itemsets:
        by_len.setdefault(len(s), []).append((s, c))
    mats: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    for k, entries in by_len.items():
        if k == 0:
            continue
        mat = np.fromiter(
            (r for s, _ in entries for r in sorted(s)),
            np.int32,
            len(entries) * k,
        ).reshape(-1, k)
        cnts = np.fromiter((c for _, c in entries), np.int64, len(entries))
        mats[k] = (mat, cnts)
    return _rules_from_tables(mats)


def gen_rules_levels(levels, item_counts) -> List[Rule]:
    """Matrix-form twin of :func:`gen_rules`: consumes the raw mining
    path's level matrices directly (FastApriori.run_file_raw) instead of
    rebuilding them from frozensets — the size-grouped tables ARE the
    levels.  ``item_counts`` are the per-rank raw occurrence counts (C3),
    the size-1 rule denominators."""
    mats: Dict[int, Tuple[np.ndarray, np.ndarray]] = {
        1: (
            np.arange(len(item_counts), dtype=np.int32)[:, None],
            np.asarray(item_counts, dtype=np.int64),
        )
    }
    for mat, cnts in levels:
        if mat.shape[0]:
            mats[mat.shape[1]] = (mat, np.asarray(cnts, dtype=np.int64))
    return _rules_from_tables(mats)


def _rules_from_tables(
    mats: Dict[int, Tuple[np.ndarray, np.ndarray]]
) -> List[Rule]:
    # Raw rules (S - {i}) -> i with confidence count(S)/count(S - {i})
    # (:129-145); the size-1 denominator is the raw occurrence count, via
    # the 1-itemset table.  Downward closure guarantees every antecedent
    # is present (InputError otherwise — reachable only via corrupted
    # --resume-from artifacts; the reference would throw a bare
    # NoSuchElementException from its table lookup).
    raw: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
    for k in sorted(mats):
        if k < 2:
            continue
        if k - 1 not in mats:
            raise InputError(
                f"itemset table is not downward-closed: {k}-itemsets are "
                f"present but no {k - 1}-itemsets exist to serve as rule "
                "antecedents — the mining output (or --resume-from "
                "artifact) is incomplete; re-mine or re-save it"
            )
        mat, cnts = mats[k]
        pmat, pcnts = mats[k - 1]
        pview = _rows_view(pmat)
        porder = np.argsort(pview)
        psorted = pview[porder]
        ants, conss, confs = [], [], []
        for j in range(k):
            ant = np.delete(mat, j, axis=1)  # sorted rows stay sorted
            idx, found = _lookup_rows(psorted, porder, _rows_view(ant))
            if not found.all():
                bad = frozenset(ant[int(np.argmin(found))].tolist())
                raise InputError(
                    f"itemset table is not downward-closed: antecedent "
                    f"{sorted(bad)} (ranks) of a {k}-itemset is missing "
                    "from the table — the mining output (or --resume-from "
                    "artifact) is incomplete; re-mine or re-save it"
                )
            # IEEE double division of two int counts — identical to the
            # reference's JVM division, so >= comparisons agree exactly.
            ants.append(ant)
            conss.append(mat[:, j])
            confs.append(cnts / pcnts[idx].astype(np.float64))
        raw[k - 1] = (
            np.concatenate(ants),
            np.concatenate(conss),
            np.concatenate(confs),
        )

    if not raw:
        return []

    min_len = min(raw)
    max_len = max(raw)
    out: List[Rule] = []

    def emit(ant: np.ndarray, cons: np.ndarray, conf: np.ndarray) -> None:
        out.extend(
            (frozenset(a), int(c), float(f))
            for a, c, f in zip(ant.tolist(), cons.tolist(), conf.tolist())
        )

    surv_ant, surv_cons, surv_conf = raw[min_len]
    emit(surv_ant, surv_cons, surv_conf)
    for i in range(min_len + 1, max_len + 1):
        # Surviving lower-level rules keyed by (antecedent cols, cons).
        low_key = _rows_view(
            np.concatenate([surv_ant, surv_cons[:, None]], axis=1)
        )
        lorder = np.argsort(low_key)
        lsorted = low_key[lorder]
        lconf = surv_conf
        if i not in raw:
            surv_ant = np.zeros((0, i), np.int32)
            surv_cons = np.zeros(0, np.int32)
            surv_conf = np.zeros(0)
            continue
        ant, cons, conf = raw[i]
        ok = np.ones(len(cons), dtype=bool)
        for e in range(i):
            key = _rows_view(
                np.concatenate(
                    [np.delete(ant, e, axis=1), cons[:, None]], axis=1
                )
            )
            idx, found = _lookup_rows(lsorted, lorder, key)
            # Survive iff EVERY (ant - {e}) -> cons survived below (:173)
            # with strictly lower confidence (:168).
            sub_conf = np.where(
                found, lconf[idx] if len(lconf) else 0.0, np.inf
            )
            ok &= found & (sub_conf < conf)
        surv_ant, surv_cons, surv_conf = ant[ok], cons[ok], conf[ok]
        emit(surv_ant, surv_cons, surv_conf)
    return out


def sort_rules(rules: Sequence[Rule], freq_items: Sequence[str]) -> List[Rule]:
    """Recommendation priority order: confidence desc, consequent item
    parsed as an integer asc (associationRulesSort,
    AssociationRules.scala:116-120 — the reference assumes integer item
    strings there; non-integer items would crash it, we fall back to the
    string)."""

    def key(r: Rule):
        item = freq_items[r[1]]
        try:
            return (-r[2], 0, int(item), item)
        except ValueError:
            return (-r[2], 1, 0, item)

    return sorted(rules, key=key)
