"""JAX version compatibility shims.

The codebase targets the current jax API spellings (``jax.shard_map``,
``jax.typeof``, varying-manual-axes on ``ShapeDtypeStruct``); the pinned
CI/dev environment runs 0.4.37 where those live elsewhere or don't exist.
Every version probe belongs HERE — scattering try/except AttributeError
through the op modules is exactly the pattern that let the conftest
``jax_num_cpu_devices`` probe rot unnoticed (graftlint G006 now polices
the scattered form).
"""

from __future__ import annotations

from typing import Optional

import jax

try:
    shard_map = jax.shard_map
except AttributeError:  # narrow catch: version probe, fallback below
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, **kwargs):  # type: ignore[misc]
        # The experimental spelling's check_rep has no replication rule
        # for `while` (the fused engine's device loop).  Current jax
        # replaced that checker with vma tracking — whose annotations
        # (pcast, ShapeDtypeStruct vma) are no-ops on this version — so
        # disabling the retired checker here matches current-jax
        # semantics, it does not weaken them.
        kwargs.setdefault("check_rep", False)
        return _experimental_shard_map(f, **kwargs)


def typeof(x):
    """``jax.typeof`` (>= 0.6); older releases expose the same aval via
    ``jax.core.get_aval`` (no ``vma`` attribute there — callers already
    treat it as optional)."""
    fn = getattr(jax, "typeof", None)
    if fn is not None:
        return fn(x)
    return jax.core.get_aval(x)


def shape_dtype_struct(shape, dtype, vma: Optional[frozenset] = None):
    """``jax.ShapeDtypeStruct`` with the varying-manual-axes annotation
    when this jax supports it (needed under shard_map check_vma); older
    releases don't check vma, so dropping it is correct there."""
    if vma is not None:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:  # narrow catch: version probe, falls through
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def pcast(x, axis_names, to="varying"):
    """``lax.pcast`` (the check_vma-era varying-axes annotation).  Older
    releases have no vma tracking at all, so the annotation is an
    identity there — nothing to annotate, nothing to check."""
    from jax import lax

    fn = getattr(lax, "pcast", None)
    if fn is not None:
        return fn(x, axis_names, to=to)
    return x
