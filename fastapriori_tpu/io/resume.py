"""Phase-1 checkpoint/resume (reference C14, Utils.scala:65-81).

The reference has a manual, hardcoded restart hook: ``Utils.getAll`` reloads
previously saved ``freqItemset``/``FreqItems``/``ItemsToRank`` files from
fixed HDFS paths and reconstructs the mining result triple so phase 2
(recommendation) can re-run without re-mining; the matching writer is the
unused ``saveFreqItemsetWithCount`` (counts embedded as ``...[count]``,
parsed back at Utils.scala:75-77).  Here it is a first-class
``--resume-from`` flag: :func:`save_phase1` writes the three artifacts under
a prefix, :func:`load_phase1` round-trips them.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Sequence, Tuple

from fastapriori_tpu.errors import InputError
from fastapriori_tpu.io.reader import _open, split_lines_java
from fastapriori_tpu.io.writer import (
    _ensure_parent,
    open_write,
    save_freq_itemsets_with_count,
)

ItemsetWithCount = Tuple[FrozenSet[int], int]


def save_phase1(
    prefix: str,
    freq_itemsets: Sequence[ItemsetWithCount],
    freq_items: Sequence[str],
    item_to_rank: Dict[str, int],
) -> None:
    """Write ``<prefix>freqItems`` (itemsets with [count] suffixes,
    Utils.scala:51-63), ``<prefix>FreqItems`` (one item per line) and
    ``<prefix>ItemsToRank`` ("item rank" per line, the format
    Utils.getAll parses at Utils.scala:72)."""
    save_freq_itemsets_with_count(prefix, freq_itemsets, freq_items)
    save_phase1_aux(prefix, freq_items, item_to_rank)


def save_phase1_aux(
    prefix: str, freq_items: Sequence[str], item_to_rank: Dict[str, int]
) -> None:
    """The two small phase-1 artifacts (FreqItems, ItemsToRank); the
    itemset table itself comes from either writer variant (frozenset or
    matrix form)."""
    path_items = prefix + "FreqItems"
    _ensure_parent(path_items)
    with open_write(path_items) as f:
        f.writelines(item + "\n" for item in freq_items)
    path_ranks = prefix + "ItemsToRank"
    _ensure_parent(path_ranks)
    with open_write(path_ranks) as f:
        f.writelines(f"{item} {rank}\n" for item, rank in item_to_rank.items())


def _read_artifact(prefix: str, name: str) -> List[str]:
    path = prefix + name
    try:
        # \n-only splitting (split_lines_java): an item token containing
        # \x85, \x1c-\x1e or U+2028 is legal (not Java \s), and
        # str.splitlines() would split artifacts the writer itself
        # produced into bogus lines.
        with _open(path) as f:
            return split_lines_java(f.read())
    except FileNotFoundError:
        raise InputError(
            f"resume artifact {path!r} not found — --resume-from needs the "
            "three files a --save-counts run writes (freqItems, FreqItems, "
            "ItemsToRank) under the given prefix"
        ) from None


def load_phase1(
    prefix: str,
) -> Tuple[List[ItemsetWithCount], Dict[str, int], List[str]]:
    """Reconstruct ``(freqItemsets, itemToRank, freqItems)`` from saved
    artifacts (mirrors Utils.getAll, Utils.scala:65-81: rank map parsed
    from "item rank" lines; items sorted by rank; itemset lines split on
    ``[`` with the trailing count).

    Malformed lines raise :class:`InputError` naming the file and line —
    the reference's parser (hardcoded paths, blind splits) would throw a
    bare NumberFormatException/MatchError instead."""
    item_to_rank: Dict[str, int] = {}
    for lineno, line in enumerate(_read_artifact(prefix, "ItemsToRank"), 1):
        if not line:
            continue
        try:
            item, rank = line.split(" ")
            item_to_rank[item] = int(rank)
        except ValueError:
            raise InputError(
                f"malformed resume artifact {prefix + 'ItemsToRank'!r} "
                f"line {lineno}: expected '<item> <rank>', got {line!r}"
            ) from None

    freq_items = [l for l in _read_artifact(prefix, "FreqItems") if l != ""]
    try:
        freq_items.sort(key=lambda i: item_to_rank[i])
    except KeyError as e:
        raise InputError(
            f"resume artifacts disagree: item {e.args[0]!r} appears in "
            f"{prefix + 'FreqItems'!r} but not in "
            f"{prefix + 'ItemsToRank'!r} — the artifacts are from "
            "different runs or were edited"
        ) from None

    freq_itemsets: List[ItemsetWithCount] = []
    for lineno, line in enumerate(_read_artifact(prefix, "freqItems"), 1):
        if not line:
            continue
        # "<item> <item> ...[count]" (Utils.scala:60,75-77).  Strict: the
        # [count] suffix is required — a permissive split would silently
        # misparse "7 8" (no count) as itemset {7} with count 8.
        body, sep, cnt = line.rpartition("[")
        try:
            if not sep or not cnt.endswith("]"):
                raise ValueError
            count = int(cnt[:-1])
            items = body.split(" ")
            freq_itemsets.append(
                (frozenset(item_to_rank[i] for i in items), count)
            )
        except (ValueError, KeyError):
            raise InputError(
                f"malformed resume artifact {prefix + 'freqItems'!r} "
                f"line {lineno}: expected '<item> <item> ...[count]' with "
                f"items from ItemsToRank, got {line!r}"
            ) from None
    return freq_itemsets, item_to_rank, freq_items
