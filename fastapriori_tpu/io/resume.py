"""Phase-1 checkpoint/resume (reference C14, Utils.scala:65-81) with
manifest-backed integrity validation.

The reference has a manual, hardcoded restart hook: ``Utils.getAll`` reloads
previously saved ``freqItemset``/``FreqItems``/``ItemsToRank`` files from
fixed HDFS paths and reconstructs the mining result triple so phase 2
(recommendation) can re-run without re-mining; the matching writer is the
unused ``saveFreqItemsetWithCount`` (counts embedded as ``...[count]``,
parsed back at Utils.scala:75-77).  Here it is a first-class
``--resume-from`` flag: :func:`save_phase1` writes the three artifacts under
a prefix, :func:`load_phase1` round-trips them.

Every artifact read first validates against the run's
``<prefix>MANIFEST.json`` (written by ``fastapriori_tpu.io.writer``):
size + sha256 of the *intended* content.  A truncated or corrupted
artifact — a torn copy, a disk-full write from a pre-manifest tool, an
injected ``write.<name>:truncate@N`` failpoint — raises
:class:`InputError` naming the file instead of parsing cleanly into a
silently-smaller lattice.  A missing manifest skips validation
(artifacts from older runs stay loadable).
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from fastapriori_tpu.errors import InputError
from fastapriori_tpu.io.reader import _open, _open_bytes, split_lines_java
from fastapriori_tpu.io.writer import (
    MANIFEST_NAME,
    save_freq_itemsets_with_count,
    write_artifact,
    write_manifest,
)

ItemsetWithCount = Tuple[FrozenSet[int], int]


def save_phase1(
    prefix: str,
    freq_itemsets: Sequence[ItemsetWithCount],
    freq_items: Sequence[str],
    item_to_rank: Dict[str, int],
) -> None:
    """Write ``<prefix>freqItems`` (itemsets with [count] suffixes,
    Utils.scala:51-63), ``<prefix>FreqItems`` (one item per line) and
    ``<prefix>ItemsToRank`` ("item rank" per line, the format
    Utils.getAll parses at Utils.scala:72), plus the run manifest."""
    manifest: Dict[str, dict] = {}
    save_freq_itemsets_with_count(
        prefix, freq_itemsets, freq_items, manifest=manifest
    )
    save_phase1_aux(prefix, freq_items, item_to_rank, manifest=manifest)
    from fastapriori_tpu.reliability import quorum

    write_manifest(prefix, manifest,
                   fence=quorum.writer_fence())


def save_phase1_aux(
    prefix: str,
    freq_items: Sequence[str],
    item_to_rank: Dict[str, int],
    manifest: Optional[Dict[str, dict]] = None,
) -> None:
    """The two small phase-1 artifacts (FreqItems, ItemsToRank); the
    itemset table itself comes from either writer variant (frozenset or
    matrix form)."""
    write_artifact(
        prefix + "FreqItems",
        (item + "\n" for item in freq_items),
        "FreqItems",
        manifest,
    )
    write_artifact(
        prefix + "ItemsToRank",
        (f"{item} {rank}\n" for item, rank in item_to_rank.items()),
        "ItemsToRank",
        manifest,
    )


def load_manifest(prefix: str) -> Optional[Dict[str, dict]]:
    """The artifact table of ``<prefix>MANIFEST.json``, or None when no
    manifest exists (pre-manifest runs).  A manifest that exists but
    cannot be parsed is an InputError — integrity metadata that cannot
    be read must not silently disable integrity checking."""
    path = prefix + MANIFEST_NAME
    try:
        with _open_bytes(path) as f:
            raw = f.read()
    except FileNotFoundError:
        return None
    try:
        doc = json.loads(raw.decode("utf-8"))
        artifacts = doc["artifacts"]
        if not isinstance(artifacts, dict):
            raise ValueError("artifacts is not an object")
    except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
        raise InputError(
            f"corrupt run manifest {path!r}: {e} — delete it to skip "
            "integrity validation, or re-run the producing step"
        ) from None
    return artifacts


def manifest_fence(prefix: str) -> Optional[int]:
    """The quorum fence epoch recorded in ``<prefix>MANIFEST.json``
    (reliability/quorum.py fenced checkpoints — ISSUE 12), or None when
    no manifest exists or no fence was ever stamped (single-process
    runs).  Parse failures return None rather than raising: the
    artifact-table reader (:func:`load_manifest`) owns the loud corrupt-
    manifest contract; the fence is an ADDITIONAL cross-check."""
    path = prefix + MANIFEST_NAME
    try:
        with _open_bytes(path) as f:
            doc = json.loads(f.read().decode("utf-8"))
        fence = doc.get("fence")
        return fence if isinstance(fence, int) else None
    except (OSError, ValueError, UnicodeDecodeError):
        return None


def validate_artifact_bytes(
    prefix: str,
    name: str,
    raw: bytes,
    manifest: Optional[Dict[str, dict]] = None,
) -> None:
    """Check ``raw`` (the full content of ``<prefix><name>``) against the
    run manifest; InputError naming the file on any mismatch.  No-op when
    no manifest exists or the manifest has no entry for ``name``."""
    # lint: waive G020 -- per-artifact integrity primitive, not a resume entry point: the fence is validated once per manifest by the callers that seed a resume (load_phase1, checkpoint.load_checkpoint)
    artifacts = load_manifest(prefix) if manifest is None else manifest
    entry = (artifacts or {}).get(name)
    if entry is None:
        return
    expected_bytes = entry.get("bytes")
    expected_sha = entry.get("sha256")
    if len(raw) != expected_bytes:
        raise InputError(
            f"artifact {prefix + name!r} fails manifest validation: "
            f"expected {expected_bytes} bytes, found {len(raw)} — the "
            "file is truncated or was modified after the run wrote it; "
            "re-run the producing step"
        )
    if hashlib.sha256(raw).hexdigest() != expected_sha:
        raise InputError(
            f"artifact {prefix + name!r} fails manifest validation: "
            "content checksum mismatch — the file was modified or "
            "corrupted after the run wrote it; re-run the producing step"
        )


def _read_artifact(prefix: str, name: str) -> List[str]:
    path = prefix + name
    try:
        with _open_bytes(path) as f:
            raw = f.read()
    except FileNotFoundError:
        raise InputError(
            f"resume artifact {path!r} not found — --resume-from needs the "
            "three files a --save-counts run writes (freqItems, FreqItems, "
            "ItemsToRank) under the given prefix"
        ) from None
    validate_artifact_bytes(prefix, name, raw)
    # \n-only splitting (split_lines_java): an item token containing
    # \x85, \x1c-\x1e or U+2028 is legal (not Java \s), and
    # str.splitlines() would split artifacts the writer itself
    # produced into bogus lines.
    return split_lines_java(raw.decode("utf-8"))


def phase1_available(prefix: str) -> bool:
    """True when the phase-1 resume artifact set exists under ``prefix``
    (probe: the freqItems table — the other two cannot be written
    without it)."""
    try:
        with _open(prefix + "freqItems"):
            return True
    except FileNotFoundError:
        return False


def load_phase1(
    prefix: str,
) -> Tuple[List[ItemsetWithCount], Dict[str, int], List[str]]:
    """Reconstruct ``(freqItemsets, itemToRank, freqItems)`` from saved
    artifacts (mirrors Utils.getAll, Utils.scala:65-81: rank map parsed
    from "item rank" lines; items sorted by rank; itemset lines split on
    ``[`` with the trailing count).

    Malformed lines raise :class:`InputError` naming the file and line —
    the reference's parser (hardcoded paths, blind splits) would throw a
    bare NumberFormatException/MatchError instead."""
    # Fenced-resume validation (mirrors io/checkpoint.py load_checkpoint):
    # on an active multi-process domain a phase-1 artifact set stamped by
    # a superseded coordinator must never seed a resume; without a domain
    # the fence stays informational and no extra manifest read happens.
    from fastapriori_tpu.reliability import quorum

    if quorum.active() is not None:
        quorum.validate_resume_fence(manifest_fence(prefix))
    item_to_rank: Dict[str, int] = {}
    for lineno, line in enumerate(_read_artifact(prefix, "ItemsToRank"), 1):
        if not line:
            continue
        try:
            item, rank = line.split(" ")
            item_to_rank[item] = int(rank)
        except ValueError:
            raise InputError(
                f"malformed resume artifact {prefix + 'ItemsToRank'!r} "
                f"line {lineno}: expected '<item> <rank>', got {line!r}"
            ) from None

    freq_items = [l for l in _read_artifact(prefix, "FreqItems") if l != ""]
    try:
        freq_items.sort(key=lambda i: item_to_rank[i])
    except KeyError as e:
        raise InputError(
            f"resume artifacts disagree: item {e.args[0]!r} appears in "
            f"{prefix + 'FreqItems'!r} but not in "
            f"{prefix + 'ItemsToRank'!r} — the artifacts are from "
            "different runs or were edited"
        ) from None

    freq_itemsets: List[ItemsetWithCount] = []
    for lineno, line in enumerate(_read_artifact(prefix, "freqItems"), 1):
        if not line:
            continue
        # "<item> <item> ...[count]" (Utils.scala:60,75-77).  Strict: the
        # [count] suffix is required — a permissive split would silently
        # misparse "7 8" (no count) as itemset {7} with count 8.
        body, sep, cnt = line.rpartition("[")
        try:
            if not sep or not cnt.endswith("]"):
                raise ValueError
            count = int(cnt[:-1])
            items = body.split(" ")
            freq_itemsets.append(
                (frozenset(item_to_rank[i] for i in items), count)
            )
        except (ValueError, KeyError):
            raise InputError(
                f"malformed resume artifact {prefix + 'freqItems'!r} "
                f"line {lineno}: expected '<item> <item> ...[count]' with "
                f"items from ItemsToRank, got {line!r}"
            ) from None
    return freq_itemsets, item_to_rank, freq_items
