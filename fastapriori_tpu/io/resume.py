"""Phase-1 checkpoint/resume (reference C14, Utils.scala:65-81).

The reference has a manual, hardcoded restart hook: ``Utils.getAll`` reloads
previously saved ``freqItemset``/``FreqItems``/``ItemsToRank`` files from
fixed HDFS paths and reconstructs the mining result triple so phase 2
(recommendation) can re-run without re-mining; the matching writer is the
unused ``saveFreqItemsetWithCount`` (counts embedded as ``...[count]``,
parsed back at Utils.scala:75-77).  Here it is a first-class
``--resume-from`` flag: :func:`save_phase1` writes the three artifacts under
a prefix, :func:`load_phase1` round-trips them.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Sequence, Tuple

from fastapriori_tpu.io.reader import _open
from fastapriori_tpu.io.writer import (
    _ensure_parent,
    open_write,
    save_freq_itemsets_with_count,
)

ItemsetWithCount = Tuple[FrozenSet[int], int]


def save_phase1(
    prefix: str,
    freq_itemsets: Sequence[ItemsetWithCount],
    freq_items: Sequence[str],
    item_to_rank: Dict[str, int],
) -> None:
    """Write ``<prefix>freqItems`` (itemsets with [count] suffixes,
    Utils.scala:51-63), ``<prefix>FreqItems`` (one item per line) and
    ``<prefix>ItemsToRank`` ("item rank" per line, the format
    Utils.getAll parses at Utils.scala:72)."""
    save_freq_itemsets_with_count(prefix, freq_itemsets, freq_items)
    path_items = prefix + "FreqItems"
    _ensure_parent(path_items)
    with open_write(path_items) as f:
        f.writelines(item + "\n" for item in freq_items)
    path_ranks = prefix + "ItemsToRank"
    _ensure_parent(path_ranks)
    with open_write(path_ranks) as f:
        f.writelines(f"{item} {rank}\n" for item, rank in item_to_rank.items())


def load_phase1(
    prefix: str,
) -> Tuple[List[ItemsetWithCount], Dict[str, int], List[str]]:
    """Reconstruct ``(freqItemsets, itemToRank, freqItems)`` from saved
    artifacts (mirrors Utils.getAll, Utils.scala:65-81: rank map parsed
    from "item rank" lines; items sorted by rank; itemset lines split on
    ``[`` with the trailing count)."""
    item_to_rank: Dict[str, int] = {}
    with _open(prefix + "ItemsToRank") as f:
        for line in f.read().splitlines():
            if not line:
                continue
            item, rank = line.split(" ")
            item_to_rank[item] = int(rank)

    with _open(prefix + "FreqItems") as f:
        freq_items = [l for l in f.read().splitlines() if l != ""]
    freq_items.sort(key=lambda i: item_to_rank[i])

    freq_itemsets: List[ItemsetWithCount] = []
    with _open(prefix + "freqItems") as f:
        for line in f.read().splitlines():
            if not line:
                continue
            # "<item> <item> ...[count]" (Utils.scala:60,75-77)
            body = line.replace("[", " ").replace("]", "")
            parts = body.split(" ")
            items, count = parts[:-1], int(parts[-1])
            freq_itemsets.append(
                (frozenset(item_to_rank[i] for i in items), count)
            )
    return freq_itemsets, item_to_rank, freq_items
