"""Output writers (reference: Utils.scala:29-63).

The reference writes through Spark ``saveAsTextFile``, producing a directory
(``<output>freqItemset/part-00000``).  This framework writes a single plain
file at ``<output>freqItemset`` / ``<output>recommends`` with byte-identical
*content*: itemset lines print ranks in descending order mapped back to item
strings, the whole file sorted lexicographically (Utils.scala:36-39);
recommends are sorted by row index, one item per line (Utils.scala:48).

Remote output prefixes (``hdfs://``, ``gs://``, ``memory://`` …) go through
fsspec, mirroring the reader's ingest path — the reference wrote its
results to HDFS (Utils.scala:36-40,48; run instructions README.md:33), so
a remote *output* is part of the parity surface, not just input.
"""

from __future__ import annotations

import os
from typing import Iterable, Sequence, Tuple


def _ensure_parent(path: str) -> None:
    if "://" in path:
        return  # remote filesystems create intermediate keys implicitly
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)


def open_write(path: str):
    """``open(path, "w")`` with an fsspec branch for remote URLs —
    the writer twin of ``fastapriori_tpu.io.reader._open``."""
    if "://" in path:
        try:
            import fsspec

            return fsspec.open(path, "w").open()
        except ImportError as e:  # pragma: no cover - environment dependent
            raise RuntimeError(
                f"remote output path {path!r} requires fsspec, which is "
                "not installed; write to a local path instead"
            ) from e
    return open(path, "w")


def format_itemset_line(ranks: Iterable[int], freq_items: Sequence[str]) -> str:
    """One itemset: ranks sorted descending, mapped to item strings, joined
    by a single space (Utils.scala:38 — ``sortBy(-_)``)."""
    return " ".join(freq_items[r] for r in sorted(ranks, reverse=True))


def save_freq_itemsets(
    output_prefix: str,
    freq_itemsets: Sequence[Tuple[frozenset, int]],
    freq_items: Sequence[str],
) -> str:
    """Write ``<output>freqItemset`` (Utils.scala:29-41).  Lines sorted
    lexicographically (``sortBy(x => x)`` on strings — code-unit order,
    which equals Python ``str`` sort for ASCII data)."""
    lines = [format_itemset_line(s, freq_items) for s, _ in freq_itemsets]
    lines.sort()
    path = output_prefix + "freqItemset"
    _ensure_parent(path)
    with open_write(path) as f:
        f.writelines(line + "\n" for line in lines)
    return path


def save_freq_itemsets_with_count(
    output_prefix: str,
    freq_itemsets: Sequence[Tuple[frozenset, int]],
    freq_items: Sequence[str],
) -> str:
    """Write ``<output>freqItems`` with counts embedded as ``...[count]``
    (Utils.scala:51-63) — the resume artifact parsed back by
    :func:`fastapriori_tpu.io.resume.load_freq_itemsets_with_count`
    (reference parser: Utils.scala:75-77)."""
    lines = [
        format_itemset_line(s, freq_items) + "[" + str(c) + "]"
        for s, c in freq_itemsets
    ]
    lines.sort()
    path = output_prefix + "freqItems"
    _ensure_parent(path)
    with open_write(path) as f:
        f.writelines(line + "\n" for line in lines)
    return path


def _level_joined(levels, freq_items: Sequence[str]):
    """Format level matrices (lex-sorted int32 [N, k] member matrices with
    counts) straight into per-level joined string arrays — no per-itemset
    Python set ever exists.  Members print in descending rank order
    (Utils.scala:38 ``sortBy(-_)``): matrix rows are ascending, so the
    reversed row is already the print order; ``numpy.char`` joins whole
    levels at once.  Yields ``(joined str array, counts)`` so callers can
    derive the ``[count]``-suffixed form without re-joining."""
    import numpy as np

    items_arr = np.asarray(freq_items, dtype=np.str_)
    for mat, cnts in levels:
        toks = items_arr[mat[:, ::-1]]  # [N, k] descending-rank strings
        joined = toks[:, 0]
        for j in range(1, toks.shape[1]):
            joined = np.char.add(np.char.add(joined, " "), toks[:, j])
        yield joined, cnts


def save_freq_itemsets_levels(
    output_prefix: str,
    levels,
    item_counts,
    freq_items: Sequence[str],
    with_counts_path: bool = False,
) -> str:
    """Matrix-form twin of :func:`save_freq_itemsets` (+ optionally the
    ``freqItems`` resume artifact of
    :func:`save_freq_itemsets_with_count`): formats the level matrices
    from the raw mining path (FastApriori.run_file_raw) plus the
    1-itemsets (every rank, counts from C3).  Byte-identical output —
    golden e2e tests compare it against the oracle's files."""
    import numpy as np

    lines: list = []
    clines: list = []
    for joined, cnts in _level_joined(levels, freq_items):
        lines.extend(joined.tolist())
        if with_counts_path:  # derive [count] form from the SAME join
            suffixed = np.char.add(
                np.char.add(joined, "["),
                np.char.add(cnts.astype(np.str_), "]"),
            )
            clines.extend(suffixed.tolist())
    lines.extend(freq_items)
    lines.sort()
    path = output_prefix + "freqItemset"
    _ensure_parent(path)
    with open_write(path) as f:
        f.writelines(line + "\n" for line in lines)
    if with_counts_path:
        clines.extend(
            f"{tok}[{int(c)}]"
            for tok, c in zip(freq_items, np.asarray(item_counts))
        )
        clines.sort()
        cpath = output_prefix + "freqItems"
        with open_write(cpath) as f:
            f.writelines(line + "\n" for line in clines)
    return path


def save_recommends(
    output_prefix: str, recommends: Sequence[Tuple[int, str]]
) -> str:
    """Write ``<output>recommends``: sorted by original row index, one
    recommended item (or "0") per line (Utils.scala:43-49)."""
    path = output_prefix + "recommends"
    _ensure_parent(path)
    with open_write(path) as f:
        f.writelines(
            item + "\n" for _, item in sorted(recommends, key=lambda x: x[0])
        )
    return path
