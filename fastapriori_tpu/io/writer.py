"""Output writers (reference: Utils.scala:29-63), crash-safe.

The reference writes through Spark ``saveAsTextFile``, producing a directory
(``<output>freqItemset/part-00000``) — and inherits atomicity from the
Hadoop output committer (write to ``_temporary``, rename on commit).  This
framework writes a single plain file at ``<output>freqItemset`` /
``<output>recommends`` with byte-identical *content*: itemset lines print
ranks in descending order mapped back to item strings, the whole file
sorted lexicographically (Utils.scala:36-39); recommends are sorted by row
index, one item per line (Utils.scala:48).

Every artifact goes through :func:`write_artifact`: the committer analog —
tmp file + fsync + atomic rename for local paths, so a crash mid-write
can never leave a half-written artifact under the final name.  Writers
optionally record each artifact's intended size + sha256 into a manifest
dict; :func:`write_manifest` persists it as ``<prefix>MANIFEST.json`` and
``fastapriori_tpu.io.resume`` validates artifacts against it on load, so
a truncated/corrupted artifact fails loudly instead of parsing cleanly.

Remote output prefixes (``hdfs://``, ``gs://``, ``memory://`` …) go through
fsspec, mirroring the reader's ingest path — the reference wrote its
results to HDFS (Utils.scala:36-40,48; run instructions README.md:33), so
a remote *output* is part of the parity surface, not just input.  Remote
writes stream without the tmp+rename step (object stores commit on close);
the manifest still guards them.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Iterable, Optional, Sequence, Tuple

from fastapriori_tpu.errors import InputError
from fastapriori_tpu.reliability import failpoints

MANIFEST_NAME = "MANIFEST.json"


def _ensure_parent(path: str) -> None:
    if "://" in path:
        return  # remote filesystems create intermediate keys implicitly
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)


def open_write(path: str):
    """``open(path, "w")`` with an fsspec branch for remote URLs —
    the writer twin of ``fastapriori_tpu.io.reader._open``.  Prefer
    :func:`write_artifact` for run artifacts: this raw handle has no
    atomicity, no manifest entry, and no failpoint instrumentation."""
    if "://" in path:
        try:
            import fsspec

            # lint: waive G009 -- the raw remote text handle write_artifact builds on
            return fsspec.open(path, "w").open()
        except ImportError as e:  # pragma: no cover - environment dependent
            raise InputError(
                f"remote output path {path!r} requires fsspec, which is "
                "not installed; write to a local path instead"
            ) from e
    # lint: waive G009 -- the raw local text handle write_artifact builds on
    return open(path, "w")


def _open_write_bytes(path: str):
    if "://" in path:
        try:
            import fsspec

            # lint: waive G009 -- write_artifact internals (atomic helper itself)
            return fsspec.open(path, "wb").open()
        except ImportError as e:  # pragma: no cover - environment dependent
            raise InputError(
                f"remote output path {path!r} requires fsspec, which is "
                "not installed; write to a local path instead"
            ) from e
    # lint: waive G009 -- write_artifact internals (atomic helper itself)
    return open(path, "wb")


def write_artifact_bytes(
    path: str,
    chunks: Iterable[bytes],
    name: str,
    manifest: Optional[Dict[str, dict]] = None,
) -> str:
    """Crash-safe artifact write: local paths write ``<path>.tmp`` +
    fsync + atomic ``os.replace`` (a crash mid-write leaves only the tmp
    file, never a torn artifact under the final name); remote paths
    stream.  Failpoint site ``write.<name>`` can inject OSError/OOM or
    truncate the physical bytes at byte N — the manifest entry records
    the FULL intended content (size + sha256), so an injected truncation
    is exactly what resume-side validation must catch.  Records into
    ``manifest[name]`` when given; returns ``path``."""
    site = "write." + name
    # lint: waive G013 -- write.<name> site family: one site per artifact name, enumerated by MANIFEST.json and armed per-name by the chaos schedules (a static census would need the artifact-name universe, which is data)
    failpoints.fire(site)
    trunc = failpoints.truncation(site)
    digest = hashlib.sha256()
    # The manifest records the INTENDED artifact (full size + full-content
    # sha256) even when a truncate failpoint shortens the physical file —
    # that mismatch is exactly the integrity violation resume-side
    # validation exists to catch.
    intended = 0
    written = 0
    _ensure_parent(path)
    local = "://" not in path
    tmp = path + ".tmp" if local else path
    f = _open_write_bytes(tmp)
    try:
        with f:
            for chunk in chunks:
                digest.update(chunk)
                intended += len(chunk)
                if trunc is not None:
                    chunk = chunk[: max(trunc - written, 0)]
                if chunk:
                    f.write(chunk)
                    written += len(chunk)
            if local:
                f.flush()
                os.fsync(f.fileno())
        if local:
            os.replace(tmp, path)
    except BaseException:
        if local and os.path.exists(tmp):
            os.unlink(tmp)
        raise
    if manifest is not None:
        manifest[name] = {
            "bytes": intended,
            "sha256": digest.hexdigest(),
        }
    return path


def write_artifact(
    path: str,
    lines: Iterable[str],
    name: str,
    manifest: Optional[Dict[str, dict]] = None,
) -> str:
    """Text form of :func:`write_artifact_bytes` (utf-8)."""
    return write_artifact_bytes(
        path, (line.encode("utf-8") for line in lines), name, manifest
    )


def write_manifest(
    prefix: str,
    entries: Dict[str, dict],
    fence: Optional[int] = None,
) -> str:
    """Persist ``<prefix>MANIFEST.json``, merging over any existing
    manifest at the same prefix (phase-1 artifacts and the recommends
    file are written at different times by the same run).  The manifest
    write itself is atomic; it is deliberately the LAST write, so a crash
    between an artifact and its manifest entry leaves a manifest that
    still validates the artifacts it lists.

    ``fence``: the quorum fence epoch a multi-process checkpoint commit
    carries (reliability/quorum.py — ISSUE 12): stamped as a top-level
    ``fence`` field, monotone under merge (a merge can never LOWER the
    recorded fence — a stale writer's concurrent rewrite cannot roll
    the manifest's epoch back even if its commit slips through)."""
    path = prefix + MANIFEST_NAME
    merged: Dict[str, dict] = {}
    prev_fence: Optional[int] = None
    try:
        # Remote prefixes merge too — a recommends-phase rewrite that
        # dropped the mining entries would silently disable integrity
        # validation for exactly the artifacts --resume-from parses.
        from fastapriori_tpu.io.reader import _open_bytes

        with _open_bytes(path) as f:
            prev = json.loads(f.read().decode("utf-8"))
        artifacts = prev.get("artifacts", {})
        if isinstance(artifacts, dict):
            merged.update(artifacts)
        if isinstance(prev.get("fence"), int):
            prev_fence = prev["fence"]
    except (OSError, ValueError, UnicodeDecodeError):
        pass  # absent or corrupt old manifest: superseded by the rewrite
    merged.update(entries)
    doc: Dict[str, object] = {"version": 1, "artifacts": merged}
    fences = [f for f in (fence, prev_fence) if f is not None]
    if fences:
        doc["fence"] = max(fences)
    body = json.dumps(doc, indent=2, sort_keys=True)
    return write_artifact(path, [body + "\n"], MANIFEST_NAME)


def format_itemset_line(ranks: Iterable[int], freq_items: Sequence[str]) -> str:
    """One itemset: ranks sorted descending, mapped to item strings, joined
    by a single space (Utils.scala:38 — ``sortBy(-_)``)."""
    return " ".join(freq_items[r] for r in sorted(ranks, reverse=True))


def save_freq_itemsets(
    output_prefix: str,
    freq_itemsets: Sequence[Tuple[frozenset, int]],
    freq_items: Sequence[str],
    manifest: Optional[Dict[str, dict]] = None,
) -> str:
    """Write ``<output>freqItemset`` (Utils.scala:29-41).  Lines sorted
    lexicographically (``sortBy(x => x)`` on strings — code-unit order,
    which equals Python ``str`` sort for ASCII data)."""
    lines = [format_itemset_line(s, freq_items) for s, _ in freq_itemsets]
    lines.sort()
    path = output_prefix + "freqItemset"
    return write_artifact(
        path, (line + "\n" for line in lines), "freqItemset", manifest
    )


def save_freq_itemsets_with_count(
    output_prefix: str,
    freq_itemsets: Sequence[Tuple[frozenset, int]],
    freq_items: Sequence[str],
    manifest: Optional[Dict[str, dict]] = None,
) -> str:
    """Write ``<output>freqItems`` with counts embedded as ``...[count]``
    (Utils.scala:51-63) — the resume artifact parsed back by
    :func:`fastapriori_tpu.io.resume.load_freq_itemsets_with_count`
    (reference parser: Utils.scala:75-77)."""
    lines = [
        format_itemset_line(s, freq_items) + "[" + str(c) + "]"
        for s, c in freq_itemsets
    ]
    lines.sort()
    path = output_prefix + "freqItems"
    return write_artifact(
        path, (line + "\n" for line in lines), "freqItems", manifest
    )


def _level_joined(levels, freq_items: Sequence[str]):
    """Format level matrices (lex-sorted int32 [N, k] member matrices with
    counts) straight into per-level joined string arrays — no per-itemset
    Python set ever exists.  Members print in descending rank order
    (Utils.scala:38 ``sortBy(-_)``): matrix rows are ascending, so the
    reversed row is already the print order; ``numpy.char`` joins whole
    levels at once.  Yields ``(joined str array, counts)`` so callers can
    derive the ``[count]``-suffixed form without re-joining."""
    import numpy as np

    items_arr = np.asarray(freq_items, dtype=np.str_)
    for mat, cnts in levels:
        toks = items_arr[mat[:, ::-1]]  # [N, k] descending-rank strings
        joined = toks[:, 0]
        for j in range(1, toks.shape[1]):
            joined = np.char.add(np.char.add(joined, " "), toks[:, j])
        yield joined, cnts


def save_freq_itemsets_levels(
    output_prefix: str,
    levels,
    item_counts,
    freq_items: Sequence[str],
    with_counts_path: bool = False,
    manifest: Optional[Dict[str, dict]] = None,
) -> str:
    """Matrix-form twin of :func:`save_freq_itemsets` (+ optionally the
    ``freqItems`` resume artifact of
    :func:`save_freq_itemsets_with_count`): formats the level matrices
    from the raw mining path (FastApriori.run_file_raw) plus the
    1-itemsets (every rank, counts from C3).  Byte-identical output —
    golden e2e tests compare it against the oracle's files."""
    import numpy as np

    lines: list = []
    clines: list = []
    for joined, cnts in _level_joined(levels, freq_items):
        lines.extend(joined.tolist())
        if with_counts_path:  # derive [count] form from the SAME join
            suffixed = np.char.add(
                np.char.add(joined, "["),
                np.char.add(cnts.astype(np.str_), "]"),
            )
            clines.extend(suffixed.tolist())
    lines.extend(freq_items)
    lines.sort()
    path = output_prefix + "freqItemset"
    write_artifact(
        path, (line + "\n" for line in lines), "freqItemset", manifest
    )
    if with_counts_path:
        clines.extend(
            f"{tok}[{int(c)}]"
            for tok, c in zip(freq_items, np.asarray(item_counts))
        )
        clines.sort()
        cpath = output_prefix + "freqItems"
        write_artifact(
            cpath, (line + "\n" for line in clines), "freqItems", manifest
        )
    return path


def save_recommends(
    output_prefix: str,
    recommends: Sequence[Tuple[int, str]],
    manifest: Optional[Dict[str, dict]] = None,
) -> str:
    """Write ``<output>recommends``: sorted by original row index, one
    recommended item (or "0") per line (Utils.scala:43-49)."""
    path = output_prefix + "recommends"
    return write_artifact(
        path,
        (
            item + "\n"
            for _, item in sorted(recommends, key=lambda x: x[0])
        ),
        "recommends",
        manifest,
    )
