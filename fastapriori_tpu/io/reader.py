"""Transaction/user-basket file ingestion (reference: Utils.scala:19-27).

The reference reads ``<input>D.dat`` and ``<input>U.dat`` as whitespace-
tokenized lines via Spark ``textFile`` (note: path *concatenation*, no
separator — ``path + "D.dat"`` at Utils.scala:21).  This loader reproduces
the exact tokenization (``trim().split("\\s+")``, which yields a single
empty token for an empty line — Java split semantics) on the host, with an
optional fsspec path for remote filesystems (HDFS/GCS) when available and a
native C++ fast path for large files (see fastapriori_tpu/native).
"""

from __future__ import annotations

import re
from typing import List, Tuple

from fastapriori_tpu.errors import InputError

# Java semantics, NOT Python's: String.trim() removes chars <= 0x20 (so
# control bytes like \x01 are trimmed, but \xa0 — which Python's
# str.strip() would eat — is kept), and regex \s is ASCII-only
# ([ \t\n\x0B\f\r]; Python's \s on str would also split on unicode
# spaces).  The native scanner (native/preprocess.cc is_ws/trim) and the
# reference (Utils.scala:21) both use the Java rules.
JAVA_WS = frozenset(" \t\n\x0b\f\r")  # regex \s under Java semantics
_WS = re.compile("[" + "".join(sorted(JAVA_WS)) + "]+")
_TRIM = "".join(chr(i) for i in range(0x21))


def _require_fsspec(path: str):
    """The fsspec module, or an InputError naming the remote path —
    shared by every remote-capable opener so the policy (scheme
    detection, error text) lives in one place."""
    try:
        import fsspec

        return fsspec
    except ImportError as e:  # pragma: no cover - environment dependent
        raise InputError(
            f"remote path {path!r} requires fsspec, which is not "
            "installed; copy the file locally instead"
        ) from e


def tokenize_line(line: str) -> List[str]:
    """Java-compatible ``line.trim().split("\\s+")`` (Utils.scala:21).

    Splitting the empty (trimmed) string returns ``[""]``, matching
    Java's single empty token, which Python's plain ``str.split()``
    would not."""
    return _WS.split(line.strip(_TRIM))


def _open(path: str):
    if "://" in path:
        return _require_fsspec(path).open(path, "r").open()
    return open(path, "r")


def _open_bytes(path: str):
    """Binary twin of :func:`_open` — manifest validation hashes raw
    bytes, so artifact reads must not round-trip through text decoding
    first."""
    if "://" in path:
        return _require_fsspec(path).open(path, "rb").open()
    return open(path, "rb")


def split_lines_java(content: str) -> List[str]:
    """Split on ``\\n`` ONLY, dropping the empty tail a trailing newline
    leaves — the record-splitting rule of the native scanner
    (native/preprocess.cc for_each_trimmed_line) and of Spark textFile.
    Python's ``str.splitlines()`` would additionally split on \\x0b,
    \\x0c, \\x1c-\\x1e, \\x85 and unicode line separators, silently
    changing line counts (and therefore minCount) on such bytes."""
    if not content:
        return []
    lines = content.split("\n")
    if lines[-1] == "":
        lines.pop()
    return lines


def read_dat(path: str) -> List[List[str]]:
    """Read one ``*.dat`` file into a list of token lists, one per line."""
    with _open(path) as f:
        return [tokenize_line(line) for line in split_lines_java(f.read())]


def read_input_dir(input_prefix: str) -> Tuple[List[List[str]], List[List[str]]]:
    """Read ``<prefix>D.dat`` and ``<prefix>U.dat`` (Utils.scala:21-23 —
    the reference concatenates without a path separator, so a trailing
    ``/`` in the prefix is the caller's responsibility, as with Spark)."""
    return read_dat(input_prefix + "D.dat"), read_dat(input_prefix + "U.dat")
