"""Transaction/user-basket file ingestion (reference: Utils.scala:19-27).

The reference reads ``<input>D.dat`` and ``<input>U.dat`` as whitespace-
tokenized lines via Spark ``textFile`` (note: path *concatenation*, no
separator — ``path + "D.dat"`` at Utils.scala:21).  This loader reproduces
the exact tokenization (``trim().split("\\s+")``, which yields a single
empty token for an empty line — Java split semantics) on the host, with an
optional fsspec path for remote filesystems (HDFS/GCS) when available and a
native C++ fast path for large files (see fastapriori_tpu/native).
"""

from __future__ import annotations

import re
from typing import List, Tuple

_WS = re.compile(r"\s+")


def tokenize_line(line: str) -> List[str]:
    """Java-compatible ``line.trim().split("\\s+")`` (Utils.scala:21).

    ``re.split(r"\\s+", "")`` returns ``[""]``, matching Java's behavior of
    returning a single empty token for an empty (trimmed) string, which
    Python's plain ``str.split()`` would not."""
    return _WS.split(line.strip())


def _open(path: str):
    if "://" in path:
        try:
            import fsspec

            return fsspec.open(path, "r").open()
        except ImportError as e:  # pragma: no cover - environment dependent
            raise RuntimeError(
                f"remote path {path!r} requires fsspec, which is not "
                "installed; copy the file locally instead"
            ) from e
    return open(path, "r")


def read_dat(path: str) -> List[List[str]]:
    """Read one ``*.dat`` file into a list of token lists, one per line."""
    with _open(path) as f:
        return [tokenize_line(line) for line in f.read().splitlines()]


def read_input_dir(input_prefix: str) -> Tuple[List[List[str]], List[List[str]]]:
    """Read ``<prefix>D.dat`` and ``<prefix>U.dat`` (Utils.scala:21-23 —
    the reference concatenates without a path separator, so a trailing
    ``/`` in the prefix is the caller's responsibility, as with Spark)."""
    return read_dat(input_prefix + "D.dat"), read_dat(input_prefix + "U.dat")
