"""Mid-mine checkpointing: each completed Apriori level's survivors as a
crash-safe artifact, so ``--resume-from`` can restart a multi-hour mine
from the deepest completed level instead of from scratch.

The reference got this property from Spark for free — RDD lineage
re-executes a lost partition, and its phase-1 boundary artifacts
(``Utils.getAll``) only cover the *completed* mining phase.  Here the
level loop (``models/apriori.py --checkpoint-every-level``) rewrites
``<prefix>checkpoint.npz`` after every completed level, through the
atomic writer + run manifest, so the artifact on disk is always a
complete, validated set of levels.

Format: one npz with ``meta`` = int64 ``[n_levels, n_raw, min_count,
num_items]`` and per-level ``mat_<i>`` (int32 [N, k] member matrix,
lex-sorted — the engine's inter-level representation) / ``cnt_<i>``
(int64 [N] weighted supports).  ``n_raw``/``min_count``/``num_items``
pin the checkpoint to its dataset + support threshold: resuming against
different data (or a different ``--min-support``) is an
:class:`InputError`, not a silently wrong lattice.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict, List, Tuple

import numpy as np

from fastapriori_tpu.errors import InputError
from fastapriori_tpu.io.reader import _open_bytes
from fastapriori_tpu.io.writer import write_artifact_bytes, write_manifest

CHECKPOINT_NAME = "checkpoint.npz"

Level = Tuple[np.ndarray, np.ndarray]


def save_checkpoint(
    prefix: str, levels: List[Level], meta: Dict[str, int]
) -> str:
    """Atomically (re)write ``<prefix>checkpoint.npz`` + its manifest
    entry.  ``meta`` needs ``n_raw``, ``min_count``, ``num_items``; an
    optional ``fence`` (the quorum fence epoch a multi-process writer
    holds — reliability/quorum.py, ISSUE 12) is stamped into BOTH the
    checkpoint meta and the run manifest, so a resume can reject a
    stale (split-brain) writer's artifact even when the writer's own
    commit-time fence check was raced past."""
    fence = int(meta.get("fence", 0))
    arrays = {
        "meta": np.array(
            [
                len(levels),
                meta["n_raw"],
                meta["min_count"],
                meta["num_items"],
                fence,
            ],
            dtype=np.int64,
        )
    }
    for i, (mat, cnt) in enumerate(levels):
        arrays[f"mat_{i}"] = np.ascontiguousarray(mat, dtype=np.int32)
        arrays[f"cnt_{i}"] = np.ascontiguousarray(cnt, dtype=np.int64)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    manifest: Dict[str, dict] = {}
    path = write_artifact_bytes(
        prefix + CHECKPOINT_NAME,
        [buf.getvalue()],
        CHECKPOINT_NAME,
        manifest,
    )
    write_manifest(prefix, manifest, fence=fence or None)
    return path


def checkpoint_available(prefix: str) -> bool:
    try:
        with _open_bytes(prefix + CHECKPOINT_NAME):
            return True
    except FileNotFoundError:
        return False


def load_checkpoint(
    prefix: str,
) -> Tuple[List[Level], Dict[str, int]]:
    """Load and validate ``<prefix>checkpoint.npz``; returns
    ``(levels, meta)`` with meta keys ``n_raw``/``min_count``/
    ``num_items``.  Manifest validation runs first (truncation is
    rejected by checksum before the zip parser sees the bytes); a
    structurally broken archive raises InputError naming the file."""
    from fastapriori_tpu.io.resume import validate_artifact_bytes

    path = prefix + CHECKPOINT_NAME
    try:
        with _open_bytes(path) as f:
            raw = f.read()
    except FileNotFoundError:
        raise InputError(
            f"checkpoint {path!r} not found — --resume-from mid-mine "
            "needs the checkpoint a --checkpoint-every-level run writes"
        ) from None
    try:
        validate_artifact_bytes(prefix, CHECKPOINT_NAME, raw)
    except InputError as e:
        # A manifest mismatch here is USUALLY a stale entry, not a bad
        # checkpoint: a crash can land between the atomic checkpoint
        # replace and the manifest rewrite (the per-level commit window
        # this feature exists for), leaving level k's npz described by
        # level k-1's entry.  The npz container is self-validating —
        # truncation loses the zip central directory and corruption
        # trips per-member CRCs, both raising below — so fall through
        # to structural validation instead of wedging resume, and say
        # so in the ledger.
        from fastapriori_tpu.reliability import ledger

        ledger.record(
            "checkpoint_manifest_stale", path=path, error=str(e)[:200]
        )
    try:
        with np.load(io.BytesIO(raw)) as z:
            m = z["meta"]
            n_levels = int(m[0])
            meta = {
                "n_raw": int(m[1]),
                "min_count": int(m[2]),
                "num_items": int(m[3]),
            }
            # Fence slot (ISSUE 12): absent on pre-fence checkpoints
            # (4-slot meta) — those stay loadable; fence 0 = unfenced.
            if m.shape[0] >= 5 and int(m[4]):
                meta["fence"] = int(m[4])
            levels = [
                (z[f"mat_{i}"], z[f"cnt_{i}"]) for i in range(n_levels)
            ]
    except (KeyError, ValueError, OSError, zipfile.BadZipFile) as e:
        raise InputError(
            f"corrupt checkpoint {path!r}: {e} — re-run with "
            "--checkpoint-every-level to regenerate it"
        ) from None
    for i, (mat, cnt) in enumerate(levels):
        if mat.ndim != 2 or mat.shape[1] != i + 2 or cnt.shape != (
            mat.shape[0],
        ):
            raise InputError(
                f"corrupt checkpoint {path!r}: level {i + 2} has shape "
                f"{mat.shape}/{cnt.shape} (expected [N, {i + 2}]/[N])"
            )
    # Fenced-resume validation (reliability/quorum.py): on an active
    # multi-process domain, a checkpoint whose fence (meta slot, cross-
    # checked against the manifest's monotone copy) is older than the
    # domain's FENCE was written by a superseded coordinator — a
    # split-brain artifact must never seed a resume.  Without a domain
    # the fence stays informational and the manifest is not re-read
    # (on a remote prefix that read is a whole extra GET per resume).
    from fastapriori_tpu.reliability import quorum

    if quorum.active() is not None:
        from fastapriori_tpu.io.resume import manifest_fence

        fences = [
            f
            for f in (meta.get("fence"), manifest_fence(prefix))
            if f is not None
        ]
        quorum.validate_resume_fence(max(fences) if fences else None)
    return levels, meta


def validate_checkpoint(prefix: str) -> Dict[str, int]:
    """Structural + manifest cross-validation of the checkpoint under
    ``prefix`` (the chaos harness's no-corrupt-artifact assertion and
    the multi-host resume test's process-side check): loads the
    checkpoint through the full validation path, additionally verifies
    the lattice is DOWNWARD-CONSISTENT — level i+1's width is level
    i's plus one, counts are positive and at least ``min_count`` —
    and returns the meta dict.  Raises
    :class:`~fastapriori_tpu.errors.InputError` naming the violation:
    a checkpoint that passes here is safe to seed a resume."""
    levels, meta = load_checkpoint(prefix)
    for i, (mat, cnt) in enumerate(levels):
        if cnt.size and int(cnt.min()) < meta["min_count"]:
            raise InputError(
                f"corrupt checkpoint under {prefix!r}: level "
                f"{i + 2} carries a count below min_count "
                f"({int(cnt.min())} < {meta['min_count']})"
            )
        if mat.size and (
            int(mat.min()) < 0 or int(mat.max()) >= meta["num_items"]
        ):
            raise InputError(
                f"corrupt checkpoint under {prefix!r}: level "
                f"{i + 2} references item ranks outside "
                f"[0, {meta['num_items']})"
            )
    return meta


def check_meta(meta: Dict[str, int], *, n_raw: int, min_count: int,
               num_items: int, prefix: str) -> None:
    """Reject a checkpoint written for different data or support.  The
    fence slot (writer identity, not dataset identity) is excluded —
    it is validated separately at load time."""
    got = {"n_raw": n_raw, "min_count": min_count, "num_items": num_items}
    meta = {k: v for k, v in meta.items() if k in got}
    if meta != got:
        raise InputError(
            f"checkpoint under {prefix!r} was written for different "
            f"data/support (checkpoint {json.dumps(meta)}, current run "
            f"{json.dumps(got)}) — it cannot seed this mine"
        )
