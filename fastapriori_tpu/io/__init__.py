from fastapriori_tpu.io.reader import read_dat, read_input_dir  # noqa: F401
from fastapriori_tpu.io.writer import (  # noqa: F401
    save_freq_itemsets,
    save_freq_itemsets_with_count,
    save_recommends,
)
