"""Friendly-failure paths: user mistakes produce one-line actionable
messages (exit code 2), never tracebacks — the reference stack-traces on
every one of these (missing HDFS path, blind parses in Utils.getAll,
NoSuchElementException in the rule-table lookup)."""

import pytest

from fastapriori_tpu.cli import main
from fastapriori_tpu.errors import InputError


def test_missing_input_dir(tmp_path, capsys):
    rc = main([str(tmp_path / "nope") + "/", str(tmp_path) + "/"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "error:" in err and ("U.dat" in err or "D.dat" in err)


def test_missing_d_dat_only(tmp_path, capsys):
    (tmp_path / "U.dat").write_text("1 2\n")
    rc = main([str(tmp_path) + "/", str(tmp_path) + "/"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "error:" in err and "D.dat" in err


def test_resume_prefix_missing(tmp_path, capsys):
    (tmp_path / "U.dat").write_text("1 2\n")
    rc = main(
        [
            str(tmp_path) + "/",
            str(tmp_path) + "/",
            "--resume-from",
            str(tmp_path / "ckpt") + "/",
        ]
    )
    assert rc == 2
    err = capsys.readouterr().err
    assert "error:" in err and "--save-counts" in err


@pytest.mark.parametrize(
    "name,content,needle",
    [
        ("ItemsToRank", "7 0\nbogus line here\n", "ItemsToRank"),
        ("ItemsToRank", "7 notanint\n", "ItemsToRank"),
        ("freqItems", "7[nope]\n", "freqItems"),
        ("freqItems", "7 8\n", "freqItems"),  # no [count]
        ("freqItems", "9[3]\n", "freqItems"),  # item missing from rank map
    ],
)
def test_malformed_resume_artifacts(tmp_path, capsys, name, content, needle):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    (ckpt / "ItemsToRank").write_text("7 0\n8 1\n")
    (ckpt / "FreqItems").write_text("7\n8\n")
    (ckpt / "freqItems").write_text("8 7[3]\n")
    (ckpt / name).write_text(content)
    (tmp_path / "U.dat").write_text("7 8\n")
    rc = main(
        [
            str(tmp_path) + "/",
            str(tmp_path) + "/",
            "--resume-from",
            str(ckpt) + "/",
        ]
    )
    assert rc == 2
    err = capsys.readouterr().err
    assert "error:" in err and needle in err
    assert "Traceback" not in err


def test_resume_artifacts_from_different_runs(tmp_path, capsys):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    (ckpt / "ItemsToRank").write_text("7 0\n")
    (ckpt / "FreqItems").write_text("7\n8\n")  # 8 not in rank map
    (ckpt / "freqItems").write_text("")
    (tmp_path / "U.dat").write_text("7\n")
    rc = main(
        [
            str(tmp_path) + "/",
            str(tmp_path) + "/",
            "--resume-from",
            str(ckpt) + "/",
        ]
    )
    assert rc == 2
    err = capsys.readouterr().err
    assert "disagree" in err and "Traceback" not in err


def test_resume_round_trips_non_java_ws_line_bytes(tmp_path):
    """Item tokens containing \\x85 / \\x1c / U+2028 are legal (not Java
    \\s, so never split by the tokenizer); artifacts the writer itself
    produced must load back — str.splitlines() would shred them."""
    from fastapriori_tpu.io.resume import load_phase1, save_phase1

    items = ["a\x85b", "c\x1cd", "e f"]
    item_to_rank = {t: r for r, t in enumerate(items)}
    itemsets = [(frozenset({0, 1}), 7), (frozenset({0}), 9),
                (frozenset({1}), 8), (frozenset({2}), 8)]
    prefix = str(tmp_path / "ckpt") + "/"
    save_phase1(prefix, itemsets, items, item_to_rank)
    got_sets, got_ranks, got_items = load_phase1(prefix)
    assert got_items == items
    assert got_ranks == item_to_rank
    assert sorted(got_sets, key=lambda x: sorted(x[0])) == sorted(
        itemsets, key=lambda x: sorted(x[0])
    )


def test_filenotfound_outside_input_not_mislabeled(
    tmp_path, capsys, monkeypatch
):
    """A FileNotFoundError raised past ingest (profile dir, output
    writes) must name its actual path, not blame the input prefix."""
    import fastapriori_tpu.cli as cli

    def boom(args):
        raise FileNotFoundError(2, "No such file", "/somewhere/else/trace")

    monkeypatch.setattr(cli, "_run", boom)
    rc = main([str(tmp_path) + "/", str(tmp_path) + "/"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "/somewhere/else/trace" in err
    assert "D.dat" not in err and "Traceback" not in err


def test_gen_rules_not_downward_closed():
    from fastapriori_tpu.rules.gen import gen_rules

    # 3-itemset with no 2-itemsets at all.
    with pytest.raises(InputError, match="downward-closed"):
        gen_rules([(frozenset({0, 1, 2}), 5), (frozenset({0}), 9)])

    # 2-itemsets exist but one antecedent is absent.
    with pytest.raises(InputError, match="downward-closed"):
        gen_rules(
            [
                (frozenset({0, 1, 2}), 5),
                (frozenset({0, 1}), 6),
                (frozenset({0, 2}), 6),
                # {1, 2} missing
                (frozenset({0}), 9),
                (frozenset({1}), 9),
                (frozenset({2}), 9),
            ]
        )


# ---------------------------------------------------------------------------
# G018 (graftlint v4): boundary failures the user can correct are
# InputError — one friendly line + exit 2, never a raw-builtin traceback.


def test_loadgen_bad_inputs_are_classified():
    from fastapriori_tpu.serve.loadgen import arrival_offsets, run_open_loop

    with pytest.raises(InputError, match="rate_rps"):
        arrival_offsets(10, 0.0, seed=1)
    with pytest.raises(InputError, match="basket pool"):
        run_open_loop(None, [], rate_rps=1.0, n_requests=1, seed=1)


def test_csrless_baskets_view_is_classified():
    import numpy as np

    from fastapriori_tpu.preprocess import CompressedData

    d = CompressedData(
        n_raw=2, min_count=1, freq_items=["7"], item_to_rank={"7": 0},
        item_counts=np.array([2], dtype=np.int64),
        basket_indices=np.zeros(0, dtype=np.int32),
        basket_offsets=np.zeros(1, dtype=np.int64),
        weights=np.ones(2, dtype=np.int32),
    )
    with pytest.raises(InputError, match="retain_csr"):
        d.baskets


def test_native_request_without_extension_is_classified():
    from fastapriori_tpu.native import native_available
    from fastapriori_tpu.preprocess import _use_native

    if native_available():
        pytest.skip("native extension built in this environment")
    with pytest.raises(InputError, match="native"):
        _use_native(True, 0)


def test_remote_path_without_fsspec_is_classified(monkeypatch):
    import sys

    from fastapriori_tpu.io import writer
    from fastapriori_tpu.io.reader import _require_fsspec

    # A None entry makes `import fsspec` raise ImportError even when the
    # package is installed — forces the missing-dependency path.
    monkeypatch.setitem(sys.modules, "fsspec", None)
    with pytest.raises(InputError, match="fsspec"):
        _require_fsspec("gs://bucket/D.dat")
    with pytest.raises(InputError, match="fsspec"):
        writer.open_write("gs://bucket/out")
    with pytest.raises(InputError, match="fsspec"):
        writer._open_write_bytes("gs://bucket/out")


def test_mesh_bad_cand_devices_is_classified():
    from fastapriori_tpu.parallel.mesh import DeviceContext

    with pytest.raises(InputError, match="cand_devices"):
        DeviceContext(cand_devices=0)
