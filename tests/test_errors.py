"""Friendly-failure paths: user mistakes produce one-line actionable
messages (exit code 2), never tracebacks — the reference stack-traces on
every one of these (missing HDFS path, blind parses in Utils.getAll,
NoSuchElementException in the rule-table lookup)."""

import pytest

from fastapriori_tpu.cli import main
from fastapriori_tpu.errors import InputError


def test_missing_input_dir(tmp_path, capsys):
    rc = main([str(tmp_path / "nope") + "/", str(tmp_path) + "/"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "error:" in err and ("U.dat" in err or "D.dat" in err)


def test_missing_d_dat_only(tmp_path, capsys):
    (tmp_path / "U.dat").write_text("1 2\n")
    rc = main([str(tmp_path) + "/", str(tmp_path) + "/"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "error:" in err and "D.dat" in err


def test_resume_prefix_missing(tmp_path, capsys):
    (tmp_path / "U.dat").write_text("1 2\n")
    rc = main(
        [
            str(tmp_path) + "/",
            str(tmp_path) + "/",
            "--resume-from",
            str(tmp_path / "ckpt") + "/",
        ]
    )
    assert rc == 2
    err = capsys.readouterr().err
    assert "error:" in err and "--save-counts" in err


@pytest.mark.parametrize(
    "name,content,needle",
    [
        ("ItemsToRank", "7 0\nbogus line here\n", "ItemsToRank"),
        ("ItemsToRank", "7 notanint\n", "ItemsToRank"),
        ("freqItems", "7[nope]\n", "freqItems"),
        ("freqItems", "7 8\n", "freqItems"),  # no [count]
        ("freqItems", "9[3]\n", "freqItems"),  # item missing from rank map
    ],
)
def test_malformed_resume_artifacts(tmp_path, capsys, name, content, needle):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    (ckpt / "ItemsToRank").write_text("7 0\n8 1\n")
    (ckpt / "FreqItems").write_text("7\n8\n")
    (ckpt / "freqItems").write_text("8 7[3]\n")
    (ckpt / name).write_text(content)
    (tmp_path / "U.dat").write_text("7 8\n")
    rc = main(
        [
            str(tmp_path) + "/",
            str(tmp_path) + "/",
            "--resume-from",
            str(ckpt) + "/",
        ]
    )
    assert rc == 2
    err = capsys.readouterr().err
    assert "error:" in err and needle in err
    assert "Traceback" not in err


def test_resume_artifacts_from_different_runs(tmp_path, capsys):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    (ckpt / "ItemsToRank").write_text("7 0\n")
    (ckpt / "FreqItems").write_text("7\n8\n")  # 8 not in rank map
    (ckpt / "freqItems").write_text("")
    (tmp_path / "U.dat").write_text("7\n")
    rc = main(
        [
            str(tmp_path) + "/",
            str(tmp_path) + "/",
            "--resume-from",
            str(ckpt) + "/",
        ]
    )
    assert rc == 2
    err = capsys.readouterr().err
    assert "disagree" in err and "Traceback" not in err


def test_resume_round_trips_non_java_ws_line_bytes(tmp_path):
    """Item tokens containing \\x85 / \\x1c / U+2028 are legal (not Java
    \\s, so never split by the tokenizer); artifacts the writer itself
    produced must load back — str.splitlines() would shred them."""
    from fastapriori_tpu.io.resume import load_phase1, save_phase1

    items = ["a\x85b", "c\x1cd", "e f"]
    item_to_rank = {t: r for r, t in enumerate(items)}
    itemsets = [(frozenset({0, 1}), 7), (frozenset({0}), 9),
                (frozenset({1}), 8), (frozenset({2}), 8)]
    prefix = str(tmp_path / "ckpt") + "/"
    save_phase1(prefix, itemsets, items, item_to_rank)
    got_sets, got_ranks, got_items = load_phase1(prefix)
    assert got_items == items
    assert got_ranks == item_to_rank
    assert sorted(got_sets, key=lambda x: sorted(x[0])) == sorted(
        itemsets, key=lambda x: sorted(x[0])
    )


def test_filenotfound_outside_input_not_mislabeled(
    tmp_path, capsys, monkeypatch
):
    """A FileNotFoundError raised past ingest (profile dir, output
    writes) must name its actual path, not blame the input prefix."""
    import fastapriori_tpu.cli as cli

    def boom(args):
        raise FileNotFoundError(2, "No such file", "/somewhere/else/trace")

    monkeypatch.setattr(cli, "_run", boom)
    rc = main([str(tmp_path) + "/", str(tmp_path) + "/"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "/somewhere/else/trace" in err
    assert "D.dat" not in err and "Traceback" not in err


def test_gen_rules_not_downward_closed():
    from fastapriori_tpu.rules.gen import gen_rules

    # 3-itemset with no 2-itemsets at all.
    with pytest.raises(InputError, match="downward-closed"):
        gen_rules([(frozenset({0, 1, 2}), 5), (frozenset({0}), 9)])

    # 2-itemsets exist but one antecedent is absent.
    with pytest.raises(InputError, match="downward-closed"):
        gen_rules(
            [
                (frozenset({0, 1, 2}), 5),
                (frozenset({0, 1}), 6),
                (frozenset({0, 2}), 6),
                # {1, 2} missing
                (frozenset({0}), 9),
                (frozenset({1}), 9),
                (frozenset({2}), 9),
            ]
        )
