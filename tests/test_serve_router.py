"""Serving mesh + pipelined dispatcher (ISSUE 19): the request
router's invariants — never a mixed-table response during a mesh
hot-swap, exact shed accounting under overload, kill-a-host-mid-burst
survivor continuation — plus the two-stage pipeline's correctness and
the strict FA_SERVE_PIPELINE_DEPTH / FA_SERVE_HOSTS knobs, the
pod-local spill order, and the mesh metrics merge/render helpers."""

import threading
import time

import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.obs import metrics as obs_metrics
from fastapriori_tpu.parallel.hier import spill_order
from fastapriori_tpu.preprocess import preprocess
from fastapriori_tpu.reliability import failpoints, ledger
from fastapriori_tpu.serve import (
    LocalHost,
    MeshRouter,
    RecommendServer,
    ServingState,
)
from fastapriori_tpu.serve import router as serve_router
from fastapriori_tpu.serve import server as serve_server


@pytest.fixture(autouse=True)
def _clean_state():
    failpoints.disarm_all()
    ledger.reset()
    serve_server.reload_from_env()
    serve_router.reload_from_env()
    yield
    failpoints.disarm_all()
    ledger.reset()
    serve_server.reload_from_env()
    serve_router.reload_from_env()


def _state(seed=6, min_support=0.05, engine="auto", **cfg_kw):
    d_lines = tokenized(random_dataset(seed, n_txns=250, max_len=8))
    data = preprocess(d_lines, min_support)
    cfg = MinerConfig(min_support=min_support, engine="level", **cfg_kw)
    miner = FastApriori(config=cfg)
    levels = miner.mine_levels_raw(data)
    return ServingState(
        levels, data.item_counts, data.freq_items, data.item_to_rank,
        config=cfg, context=miner.context, engine=engine,
    )


U_LINES = tokenized(random_dataset(60, n_txns=200))


def _gate_state(st):
    """Block the state's batch path behind an event — the no-timing-
    assumptions tool the single-server swap test established."""
    gate = threading.Event()
    orig = st.recommend_batch

    def gated(lines):
        gate.wait(30.0)
        return orig(lines)

    st.recommend_batch = gated
    return gate


# ---------------------------------------------------------------------------
# spill_order


def test_spill_order_flat_ring():
    assert spill_order(0, 4) == [0, 1, 2, 3]
    assert spill_order(2, 4) == [2, 3, 0, 1]
    assert spill_order(0, 1) == [0]


def test_spill_order_pod_local_first():
    # 8 hosts in 2 groups of 4: the primary's pod drains before the
    # ring crosses into the other pod.
    order = spill_order(5, 8, groups=2)
    assert order[:4] == [5, 6, 7, 4]  # pod {4..7}, ring from 5
    assert sorted(order[4:]) == [0, 1, 2, 3]
    # Every host appears exactly once regardless of grouping.
    assert sorted(spill_order(3, 8, groups=4)) == list(range(8))


def test_spill_order_primary_out_of_range():
    with pytest.raises(InputError, match="primary"):
        spill_order(4, 4)
    with pytest.raises(InputError, match="primary"):
        spill_order(-1, 4)


# ---------------------------------------------------------------------------
# Strict env knobs


def test_pipeline_depth_env_strict(monkeypatch):
    monkeypatch.setenv("FA_SERVE_PIPELINE_DEPTH", "3")
    serve_server.reload_from_env()
    assert serve_server.pipeline_depth_from_env() == 3
    monkeypatch.setenv("FA_SERVE_PIPELINE_DEPTH", "0")
    serve_server.reload_from_env()
    assert serve_server.pipeline_depth_from_env() == 0
    monkeypatch.setenv("FA_SERVE_PIPELINE_DEPTH", "deep")
    serve_server.reload_from_env()
    with pytest.raises(InputError, match="FA_SERVE_PIPELINE_DEPTH"):
        serve_server.pipeline_depth_from_env()
    monkeypatch.setenv("FA_SERVE_PIPELINE_DEPTH", "-1")
    serve_server.reload_from_env()
    with pytest.raises(InputError, match="FA_SERVE_PIPELINE_DEPTH"):
        serve_server.pipeline_depth_from_env()


def test_hosts_env_strict(monkeypatch):
    monkeypatch.setenv("FA_SERVE_HOSTS", "4")
    serve_router.reload_from_env()
    assert serve_router.hosts_from_env() == 4
    monkeypatch.setenv("FA_SERVE_HOSTS", "many")
    serve_router.reload_from_env()
    with pytest.raises(InputError, match="FA_SERVE_HOSTS"):
        serve_router.hosts_from_env()
    monkeypatch.setenv("FA_SERVE_HOSTS", "0")
    serve_router.reload_from_env()
    with pytest.raises(InputError, match="FA_SERVE_HOSTS"):
        serve_router.hosts_from_env()


def test_server_rejects_negative_pipeline_depth():
    st = _state()
    with pytest.raises(InputError, match="pipeline_depth"):
        RecommendServer(st, pipeline_depth=-2)


# ---------------------------------------------------------------------------
# Two-stage pipelined dispatcher


def test_pipelined_matches_serial_responses():
    """The pipeline split must not change a single byte of output:
    depth-2 (two-stage) responses == depth-0 (serial) responses ==
    the closed-batch answers."""
    expected = _state().recommend_batch(U_LINES)
    for depth in (0, 2):
        server = RecommendServer(
            _state(), batch_rows=32, linger_ms=0.5, pipeline_depth=depth
        ).start()
        reqs = [server.submit_wait(t) for t in U_LINES]
        assert server.wait_for(reqs, timeout_s=60.0)
        assert [r.item for r in reqs] == expected, f"depth={depth}"
        stats = server.stats()
        assert stats["pipeline_depth"] == depth
        assert server.stop()


def test_pipelined_ring_actually_buffers():
    """Under a gated scan the pack stage must run AHEAD of the scan
    stage: the hand-off ring fills (ring_peak > 0) while stage 2 is
    blocked — the overlap the two-stage split exists for."""
    st = _state()
    gate = _gate_state(st)
    server = RecommendServer(
        st, batch_rows=8, linger_ms=0.0, queue_depth=256,
        pipeline_depth=2,
    ).start(warm=False)
    reqs = [server.submit(t) for t in U_LINES[:80]]
    deadline = time.monotonic() + 10.0
    while (
        server.stats()["ring_peak"] < 1 and time.monotonic() < deadline
    ):
        time.sleep(0.005)
    peak = server.stats()["ring_peak"]
    gate.set()
    assert server.wait_for(reqs, timeout_s=60.0)
    assert peak >= 1
    # Bounded hand-off: the ring never exceeded its configured depth.
    assert server.stats()["ring_peak"] <= 2
    assert server.stop()


def test_pipelined_shed_conservation():
    """Exact accounting through the pipeline: every submitted request
    is served or shed, never both, never lost — even with the ring
    buffering batches between the stages."""
    st = _state()
    gate = _gate_state(st)
    server = RecommendServer(
        st, batch_rows=8, linger_ms=0.0, queue_depth=16,
        pipeline_depth=2,
    ).start(warm=False)
    reqs = [server.submit(t) for t in (U_LINES * 2)[:300]]
    gate.set()
    assert server.wait_for(reqs, timeout_s=60.0)
    shed = sum(1 for r in reqs if r.shed)
    served = sum(1 for r in reqs if not r.shed)
    assert served + shed == 300
    stats = server.stats()
    assert stats["served"] == served
    assert stats["shed"] == shed
    assert stats["submitted"] == 300
    assert server.stop()


def test_pipelined_hot_swap_never_mixes_tables():
    """The swap marker rides the queue AND the ring in FIFO order:
    batches packed against the old state keep its signature even when
    they scan after the swap commits."""
    st_a, st_b = _state(seed=6), _state(seed=7)
    assert st_a.signature != st_b.signature
    gate = _gate_state(st_a)
    server = RecommendServer(
        st_a, batch_rows=16, linger_ms=0.0, pipeline_depth=2
    ).start(warm=False)
    before = [server.submit(t) for t in U_LINES[:50]]
    ev = server.swap(st_b)
    after = [server.submit(t) for t in U_LINES[:50]]
    gate.set()
    assert server.wait_for(before + after, timeout_s=60.0)
    assert ev.wait(30.0)
    assert {r.model for r in before} == {st_a.signature}
    assert {r.model for r in after} == {st_b.signature}
    assert server.stop()


# ---------------------------------------------------------------------------
# MeshRouter on LocalHosts


def _mesh(n=2, seeds=None, gated=False, **server_kw):
    seeds = seeds or [6] * n
    states, gates, hosts = [], [], []
    for i, seed in enumerate(seeds):
        st = _state(seed=seed)
        if gated:
            gates.append(_gate_state(st))
        states.append(st)
        hosts.append(
            LocalHost(
                f"h{i}",
                RecommendServer(st, **server_kw).start(warm=False),
            )
        )
    return MeshRouter(hosts), hosts, states, gates


def test_mesh_routes_and_aggregates():
    expected = _state().recommend_batch(U_LINES)
    mesh, hosts, _, _ = _mesh(2, batch_rows=32, linger_ms=0.5)
    reqs = [mesh.submit(t) for t in U_LINES]
    assert mesh.wait_for(reqs, timeout_s=60.0)
    assert [r.item for r in reqs] == expected
    st = mesh.stats()
    assert st["served"] == len(U_LINES)
    assert st["shed"] == 0
    assert st["hosts"] == 2 and st["hosts_lost"] == 0
    # Round-robin really spread the load: both hosts served.
    per_host = {h["host"]: h["served"] for h in st["per_host"]}
    assert all(v > 0 for v in per_host.values()), per_host
    # Satellite 1: ONE merged scrape surface — per-host counters sum.
    snap = mesh.metrics_snapshot()
    assert snap["fa_serve_served_total"] == len(U_LINES)
    text = mesh.metrics_text()
    assert f"fa_serve_served_total {len(U_LINES)}" in text
    assert "fa_mesh_submitted_total" in text
    assert mesh.stop()


def test_mesh_hot_swap_never_mixes_tables():
    """The mesh swap barrier holds admission while EVERY live host
    enqueues its marker: pre-swap requests carry the old signature,
    post-swap ones the new — on whichever host they landed."""
    mesh, hosts, states, gates = _mesh(
        2, gated=True, batch_rows=16, linger_ms=0.0
    )
    old_sig = states[0].signature
    new_states = [_state(seed=7), _state(seed=7)]
    new_sig = new_states[0].signature
    assert old_sig != new_sig
    before = [mesh.submit(t) for t in U_LINES[:60]]

    done = threading.Event()

    def do_swap():
        mesh.swap(new_states, timeout_s=60.0)
        done.set()

    swapper = threading.Thread(target=do_swap, daemon=True)
    swapper.start()
    # The swap call blocks on the gated scans; release them.
    time.sleep(0.05)
    for g in gates:
        g.set()
    assert done.wait(60.0)
    after = [mesh.submit(t) for t in U_LINES[:60]]
    assert mesh.wait_for(before + after, timeout_s=60.0)
    assert {r.model for r in before} == {old_sig}
    assert {r.model for r in after} == {new_sig}
    assert mesh.stats()["swaps"] == 1
    assert mesh.stop()


def test_mesh_swap_payload_count_strict():
    mesh, _, _, _ = _mesh(2, batch_rows=16)
    with pytest.raises(InputError, match="payload"):
        mesh.swap([_state(seed=7)])
    assert mesh.stop()


def test_mesh_exact_shed_accounting_under_overload():
    """Global shed only when EVERY host refuses; each request counted
    by exactly one host or by the router, never both — submitted ==
    served + shed exactly, and the router's shed is the global
    remainder after both hosts' queues and in-flight absorption."""
    mesh, hosts, states, gates = _mesh(
        2, gated=True, batch_rows=8, linger_ms=0.0, queue_depth=8,
        pipeline_depth=2,
    )
    n = 400
    reqs = [mesh.submit((U_LINES * 2)[i % len(U_LINES)]) for i in range(n)]
    router_shed = sum(1 for r in reqs if r.done and r.shed)
    assert router_shed > 0  # both tiny queues filled during the gate
    for g in gates:
        g.set()
    assert mesh.wait_for(reqs, timeout_s=60.0)
    served = sum(1 for r in reqs if not r.shed)
    shed = sum(1 for r in reqs if r.shed)
    assert served + shed == n
    st = mesh.stats()
    assert st["submitted"] == n
    assert st["served"] == served
    assert st["shed"] == shed
    assert st["router_shed"] >= router_shed
    # Host sheds + router sheds partition the shed total.
    host_shed = sum(h["shed"] for h in st["per_host"])
    assert host_shed + st["router_shed"] == shed
    # Every overload episode walked the serving chain (once per
    # episode — an accepted request between sheds closes an episode,
    # so the interleaved gate can legally open several).
    cascades = [
        e for e in ledger.snapshot()
        if e.get("kind") == "cascade" and e.get("chain") == "serving"
        and e.get("reason") == "mesh_queue_full"
    ]
    assert len(cascades) >= 1
    assert mesh.stop()


def test_mesh_kill_host_mid_burst_survivors_serve():
    """Abrupt host death mid-burst: the dead host's in-flight share
    drains to the router as recorded sheds (lost_shed), survivors keep
    serving byte-correct responses, the loss lands on the ledger as
    serve_mesh full->degraded + serve_host_lost — and nothing hangs."""
    expected = _state().recommend_batch(U_LINES)
    mesh, hosts, states, gates = _mesh(
        2, gated=True, batch_rows=16, linger_ms=0.0, queue_depth=256
    )
    reqs = []
    for i in range(240):
        reqs.append(mesh.submit(U_LINES[i % len(U_LINES)]))
        if i == 90:
            hosts[0].kill()
    for g in gates:
        g.set()
    assert mesh.wait_for(reqs, timeout_s=60.0)
    assert all(r.done for r in reqs)
    st = mesh.stats()
    assert st["hosts_lost"] == 1
    assert st["lost_shed"] > 0
    # Every non-shed response is correct (the survivor's table).
    for i, r in enumerate(reqs):
        if not r.shed:
            assert r.item == expected[i % len(U_LINES)]
    # Exact accounting across the death: LocalHost counters don't lag.
    served = sum(1 for r in reqs if not r.shed)
    shed = sum(1 for r in reqs if r.shed)
    assert served + shed == 240
    assert st["shed"] == shed
    events = ledger.snapshot()
    assert any(
        e.get("kind") == "cascade" and e.get("chain") == "serve_mesh"
        and e.get("to") == "degraded"
        for e in events
    )
    assert any(e.get("kind") == "serve_host_lost" for e in events)
    assert mesh.stop()


def test_mesh_total_loss_sheds_globally():
    """Killing every host flips admission to permanent global shed —
    answered '0', counted at the router, serve_mesh_empty ledgered —
    never an exception, never a hang."""
    mesh, hosts, _, _ = _mesh(2, batch_rows=16, linger_ms=0.0)
    warm = [mesh.submit(t) for t in U_LINES[:8]]
    assert mesh.wait_for(warm, timeout_s=60.0)
    for h in hosts:
        h.kill()
    deadline = time.monotonic() + 10.0
    while (
        mesh.stats()["hosts_lost"] < 2 and time.monotonic() < deadline
    ):
        time.sleep(0.01)
    assert mesh.stats()["hosts_lost"] == 2
    reqs = [mesh.submit(t) for t in U_LINES[:10]]
    assert all(r.done and r.shed and r.item == "0" for r in reqs)
    assert any(
        e.get("kind") == "serve_mesh_empty" for e in ledger.snapshot()
    )
    assert mesh.stop()


# ---------------------------------------------------------------------------
# Metrics merge / render (satellite 1)


def test_merge_snapshots_counter_gauge_histogram():
    a = obs_metrics.MetricsRegistry()
    b = obs_metrics.MetricsRegistry()
    a.counter("c").inc(3)
    b.counter("c").inc(4)
    a.gauge("g").set(5)
    b.gauge("g").set(2)
    b.gauge("g").set(1)  # b's max is 2, value 1
    ha = a.histogram("h", bounds=(1.0, 10.0))
    hb = b.histogram("h", bounds=(1.0, 10.0))
    ha.observe(0.5)
    ha.observe(5.0)
    hb.observe(50.0)
    merged = obs_metrics.merge_snapshots([a.snapshot(), b.snapshot()])
    assert merged["c"] == 7  # counters sum
    assert merged["g"]["value"] == 5  # gauges max, not sum
    assert merged["g"]["max"] == 5
    assert merged["h"]["count"] == 3  # histograms add bucket-wise
    assert merged["h"]["buckets"]["1"] == 1
    assert merged["h"]["buckets"]["10"] == 1
    assert merged["h"]["buckets"]["+Inf"] == 1
    assert merged["h"]["sum"] == pytest.approx(55.5)


def test_merge_snapshots_bucket_mismatch_raises():
    a = obs_metrics.MetricsRegistry()
    b = obs_metrics.MetricsRegistry()
    a.histogram("h", bounds=(1.0, 10.0)).observe(1.0)
    b.histogram("h", bounds=(2.0, 20.0)).observe(1.0)
    with pytest.raises(ValueError, match="bucket"):
        obs_metrics.merge_snapshots([a.snapshot(), b.snapshot()])


def test_render_snapshot_prometheus_text():
    a = obs_metrics.MetricsRegistry()
    a.counter("fa_x_total", "things").inc(2)
    a.gauge("fa_depth").set(3)
    a.histogram("fa_lat_ms", bounds=(1.0, 10.0)).observe(0.5)
    text = obs_metrics.render_snapshot(
        a.snapshot(), helps={"fa_x_total": "things"}
    )
    assert "# TYPE fa_x_total counter" in text
    assert "fa_x_total 2" in text
    assert "# HELP fa_x_total things" in text
    assert "fa_depth 3" in text
    assert 'fa_lat_ms_bucket{le="1"} 1' in text
    assert 'fa_lat_ms_bucket{le="+Inf"} 1' in text
    assert "fa_lat_ms_count 1" in text
    # Merged mesh snapshots render through the same path.
    merged = obs_metrics.merge_snapshots([a.snapshot(), a.snapshot()])
    assert "fa_x_total 4" in obs_metrics.render_snapshot(merged)
