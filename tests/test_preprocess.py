"""Host preprocessing (C3/C4/C10) vs the oracle."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu import oracle
from fastapriori_tpu.preprocess import dedup_user_baskets, preprocess


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("min_support", [0.05, 0.15])
def test_preprocess_matches_oracle(seed, min_support):
    lines = tokenized(random_dataset(seed))
    data = preprocess(lines, min_support, native=False)

    import math

    min_count = math.ceil(min_support * len(lines))
    counts = oracle.count_items(lines)
    freq_items, item_to_rank = oracle.freq_items_and_ranks(counts, min_count)
    baskets, weights = oracle.dedup_transactions(lines, item_to_rank)

    assert data.n_raw == len(lines)
    assert data.min_count == min_count
    assert data.freq_items == freq_items
    assert data.item_to_rank == item_to_rank
    assert [counts[i] for i in freq_items] == list(data.item_counts)

    got = {tuple(b): int(w) for b, w in zip(data.baskets, data.weights)}
    expected = {
        tuple(sorted(b)): w for b, w in zip(baskets, weights)
    }
    assert got == expected
    assert all(len(b) >= 2 for b in data.baskets)


def test_dedup_user_baskets(tiny_u_lines):
    item_to_rank = {"1": 0, "2": 1, "3": 2}
    baskets, indexes, empty = dedup_user_baskets(tiny_u_lines, item_to_rank)
    # rows: "1 2"->{0,1}; "3"->{2}; "1 2 3"->{0,1,2}; ""->empty;
    # "5 9"->empty; "2 4"->{1}; "1 2"->{0,1} (dup of row 0)
    assert empty == [3, 4]
    got = {tuple(b): idxs for b, idxs in zip(baskets, indexes)}
    assert got == {
        (0, 1): [0, 6],
        (2,): [1],
        (0, 1, 2): [2],
        (1,): [5],
    }


def test_empty_dataset():
    data = preprocess([], 0.1, native=False)
    assert data.num_items == 0
    assert data.total_count == 0
    assert data.n_raw == 0


@pytest.mark.parametrize("bad", ["a b", "a\tb", "a\x0bb", "a\fb", "a\rb"])
def test_interior_whitespace_token_keeps_identity(bad):
    """A token with interior Java \\s (possible only via the public
    transactions= API) cannot round-trip through the native
    join/re-tokenize path — it must route to the Python path and stay
    ONE item, not silently split into two."""
    lines = [[bad, "c"]] * 10
    data = preprocess(lines, 0.5, native=True)
    ref = preprocess(lines, 0.5, native=False)
    assert bad in data.freq_items
    assert data.freq_items == ref.freq_items
    assert data.item_to_rank == ref.item_to_rank


def test_zero_token_line_routes_python():
    """[] has no serialized form (it would be indistinguishable from
    [""]); it must route to the Python path, not vacuously pass the
    serialization gate and grow a phantom '' item on the native path."""
    lines = [["a", "b"], []] * 10
    nat = preprocess(lines, 0.4, native=True)
    ref = preprocess(lines, 0.4, native=False)
    assert nat.n_raw == ref.n_raw == 20
    assert nat.freq_items == ref.freq_items
    assert "" not in nat.freq_items


def test_trailing_empty_line_not_dropped_native():
    """A final [""] transaction must still count toward n_raw (and thus
    minCount) on the native join/re-scan path — join_transactions'
    trailing newline is what keeps it visible to the scanner."""
    lines = [["a", "b"], ["a", "b"], ["b", "c"], [""]]
    nat = preprocess(lines, 0.5, native=True)
    ref = preprocess(lines, 0.5, native=False)
    assert nat.n_raw == ref.n_raw == 4
    assert nat.min_count == ref.min_count == 2
    assert nat.freq_items == ref.freq_items


def test_interior_non_ws_control_char_native_ok():
    """Interior control chars that are NOT Java \\s (\\x1c) round-trip
    through the native scanner — identical results on both paths."""
    lines = [["a\x1cb", "c"]] * 10
    nat = preprocess(lines, 0.5, native=True)
    ref = preprocess(lines, 0.5, native=False)
    assert "a\x1cb" in ref.freq_items
    assert nat.freq_items == ref.freq_items
