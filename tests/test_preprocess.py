"""Host preprocessing (C3/C4/C10) vs the oracle."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu import oracle
from fastapriori_tpu.preprocess import dedup_user_baskets, preprocess


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("min_support", [0.05, 0.15])
def test_preprocess_matches_oracle(seed, min_support):
    lines = tokenized(random_dataset(seed))
    data = preprocess(lines, min_support, native=False)

    import math

    min_count = math.ceil(min_support * len(lines))
    counts = oracle.count_items(lines)
    freq_items, item_to_rank = oracle.freq_items_and_ranks(counts, min_count)
    baskets, weights = oracle.dedup_transactions(lines, item_to_rank)

    assert data.n_raw == len(lines)
    assert data.min_count == min_count
    assert data.freq_items == freq_items
    assert data.item_to_rank == item_to_rank
    assert [counts[i] for i in freq_items] == list(data.item_counts)

    got = {tuple(b): int(w) for b, w in zip(data.baskets, data.weights)}
    expected = {
        tuple(sorted(b)): w for b, w in zip(baskets, weights)
    }
    assert got == expected
    assert all(len(b) >= 2 for b in data.baskets)


def test_dedup_user_baskets(tiny_u_lines):
    item_to_rank = {"1": 0, "2": 1, "3": 2}
    baskets, indexes, empty = dedup_user_baskets(tiny_u_lines, item_to_rank)
    # rows: "1 2"->{0,1}; "3"->{2}; "1 2 3"->{0,1,2}; ""->empty;
    # "5 9"->empty; "2 4"->{1}; "1 2"->{0,1} (dup of row 0)
    assert empty == [3, 4]
    got = {tuple(b): idxs for b, idxs in zip(baskets, indexes)}
    assert got == {
        (0, 1): [0, 6],
        (2,): [1],
        (0, 1, 2): [2],
        (1,): [5],
    }


def test_empty_dataset():
    data = preprocess([], 0.1, native=False)
    assert data.num_items == 0
    assert data.total_count == 0
    assert data.n_raw == 0
