"""Serving tier (ISSUE 10): ServingState lifecycle, the admission-
controlled micro-batching server, hot-swap barrier semantics, shed
mode, the shared rec_batch_rows knob, and the seeded open-loop load
generator.  CPU-only (8 virtual devices via conftest)."""

import os
import threading
import time

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.models.recommender import AssociationRules
from fastapriori_tpu.preprocess import preprocess
from fastapriori_tpu.reliability import failpoints, ledger
from fastapriori_tpu.serve import (
    SERVING_NAME,
    RecommendServer,
    ServingState,
    arrival_offsets,
    model_signature,
    run_open_loop,
)


@pytest.fixture(autouse=True)
def _clean_state():
    failpoints.disarm_all()
    ledger.reset()
    yield
    failpoints.disarm_all()
    ledger.reset()


def _model(seed=6, min_support=0.05, n_txns=250, **cfg_kw):
    d_lines = tokenized(random_dataset(seed, n_txns=n_txns, max_len=8))
    data = preprocess(d_lines, min_support)
    cfg = MinerConfig(min_support=min_support, engine="level", **cfg_kw)
    miner = FastApriori(config=cfg)
    levels = miner.mine_levels_raw(data)
    return levels, data, cfg, miner


def _state(seed=6, min_support=0.05, engine="auto", **cfg_kw):
    levels, data, cfg, miner = _model(seed, min_support, **cfg_kw)
    return ServingState(
        levels, data.item_counts, data.freq_items, data.item_to_rank,
        config=cfg, context=miner.context, engine=engine,
    )


U_LINES = tokenized(random_dataset(60, n_txns=200))


# ---------------------------------------------------------------------------
# ServingState: build / engines / batch equivalence


def test_serving_state_matches_batch_recommender():
    """The serving data path must answer exactly what the batch
    pipeline answers, host and device engines alike."""
    levels, data, cfg, miner = _model()
    rec = AssociationRules(
        [], data.freq_items, data.item_to_rank, config=cfg,
        context=miner.context, levels=levels,
        item_counts=data.item_counts,
    )
    expected = [item for _, item in sorted(rec.run(U_LINES))]
    for engine in ("host", "device"):
        st = _state(engine=engine)
        assert st.recommend_batch(U_LINES) == expected, engine


def test_serving_state_resident_table_mounts():
    """With the sharded phase-2 engine, the serving state mounts the
    device-BUILT rank-strided table: resident, sharded, zero rule-table
    host bytes."""
    st = _state(num_devices=4, rule_engine="device")
    st.warm()
    d = st.describe()
    assert d["resident_table"] is True
    assert d["scan_shards"] == 4
    assert d["rule_table_host_bytes"] == 0
    assert st.resident_device_bytes() > 0
    host = _state(engine="host")
    assert st.recommend_batch(U_LINES) == host.recommend_batch(U_LINES)
    # Serving dispatched at least the warm batch + the real batches.
    assert st.scan_dispatches >= 2


def test_serving_state_empty_and_no_rules():
    st = _state(min_support=0.9)  # nothing frequent enough for rules
    assert st.n_rules == 0
    out = st.recommend_batch(U_LINES[:7])
    assert out == ["0"] * 7


def test_serving_state_engine_strictness():
    with pytest.raises(InputError, match="ServingState engine"):
        _state(engine="gpu")


def test_swap_readiness_barrier_probes_device_path():
    # device_ready() is the barrier the router worker runs before
    # handing a table to server.swap: a device table must serve one
    # dummy micro-batch end to end; a host engine has nothing to prove.
    assert _state(engine="host").device_ready() is False
    st = _state(engine="device")
    assert st.device_ready() is True
    assert st._handle is not None  # the probe warmed (and kept) the handle


def test_serving_state_signature_tracks_model():
    a = _state(seed=6)
    b = _state(seed=6)
    c = _state(seed=7)
    assert a.signature == b.signature
    assert a.signature != c.signature
    sig = model_signature(a.levels, a.item_counts, a.freq_items)
    assert sig == a.signature


# ---------------------------------------------------------------------------
# ServingState: checkpoint -> kill -> warm restart


def test_serving_checkpoint_warm_restart_byte_identical(tmp_path):
    """The satellite contract: save, drop the instance (the "kill"),
    load in a fresh state, serve byte-identically."""
    prefix = str(tmp_path) + os.sep
    st = _state()
    baseline = st.recommend_batch(U_LINES)
    st.save(prefix)
    sig = st.signature
    st.release()
    del st
    restored = ServingState.load(prefix, config=MinerConfig(
        min_support=0.05
    ))
    assert restored.signature == sig
    assert restored.source == "restart"
    assert restored.recommend_batch(U_LINES) == baseline
    events = [
        e for e in ledger.snapshot() if e["kind"] == "serving_restart"
    ]
    assert events and events[0]["signature"] == sig


def test_serving_checkpoint_truncation_rejected(tmp_path):
    """A truncated serving artifact must fail manifest validation at
    load — never silently serve a different model."""
    prefix = str(tmp_path) + os.sep
    st = _state()
    failpoints.arm("write." + SERVING_NAME, "truncate@64")
    st.save(prefix)
    failpoints.disarm_all()
    with pytest.raises(InputError, match=SERVING_NAME):
        ServingState.load(prefix)


def test_serving_checkpoint_missing_is_input_error(tmp_path):
    with pytest.raises(InputError, match="not found"):
        ServingState.load(str(tmp_path) + os.sep)


def test_serving_load_failpoint_armable(tmp_path):
    prefix = str(tmp_path) + os.sep
    _state().save(prefix)
    failpoints.arm("serving.load", "io")
    with pytest.raises(OSError):
        ServingState.load(prefix)
    failpoints.disarm_all()
    assert ServingState.load(prefix).n_rules > 0


def test_released_state_refuses_to_serve():
    st = _state()
    st.recommend_batch(U_LINES[:3])
    st.release()
    with pytest.raises(InputError, match="released"):
        st.recommend_batch(U_LINES[:3])


# ---------------------------------------------------------------------------
# rec_batch_rows: ONE knob for the batch path and the serving tier


def test_rec_batch_rows_pow2_bucketed_and_shared(monkeypatch):
    st = _state(rec_batch_rows=1000)
    # Config value pow2-buckets up.
    assert st.batch_rows() == 1024
    assert st._rec.rec_batch_rows() == 1024
    # Env override wins, strictly parsed, pow2-bucketed, floor 32.
    monkeypatch.setenv("FA_REC_BATCH", "100")
    assert st.batch_rows() == 128
    monkeypatch.setenv("FA_REC_BATCH", "7")
    assert st.batch_rows() == 32
    monkeypatch.setenv("FA_REC_BATCH", "lots")
    with pytest.raises(InputError, match="FA_REC_BATCH"):
        st.batch_rows()
    monkeypatch.setenv("FA_REC_BATCH", "-1")
    with pytest.raises(InputError, match="out of range"):
        st.batch_rows()


def test_rec_batch_rows_caps_batch_recommender_microbatch(monkeypatch):
    """The batch recommender's resident scan takes its micro-batch cap
    from the SAME knob (PR 8 residue: the static 4K constant is gone)."""
    monkeypatch.setenv("FA_REC_BATCH", "64")
    levels, data, cfg, miner = _model(num_devices=2, rule_engine="device")
    rec = AssociationRules(
        [], data.freq_items, data.item_to_rank, config=cfg,
        context=miner.context, levels=levels,
        item_counts=data.item_counts,
    )
    out = rec.run(U_LINES, use_device=True)
    fm = [
        r for r in rec.metrics.records
        if r.get("event") == "first_match" and r.get("device")
    ][-1]
    assert fm["resident_table"] is True
    n_distinct = [
        r for r in rec.metrics.records if r.get("event") == "user_dedup"
    ][-1]["distinct"]
    assert fm["scan_dispatches"] == -(-n_distinct // 64)
    host = rec.run(U_LINES, use_device=False)
    assert out == host


def test_server_pins_scan_shape_to_its_batch_knob():
    st = _state(engine="device")
    server = RecommendServer(st, batch_rows=48, linger_ms=0.0)
    server.start()
    assert st.batch_rows() == 64  # pow2 bucket of the server's knob
    server.stop()


# ---------------------------------------------------------------------------
# RecommendServer: micro-batching, linger, shed, swap


def test_server_serves_and_orders_responses():
    st = _state()
    expected = st.recommend_batch(U_LINES)
    server = RecommendServer(st, batch_rows=32, linger_ms=1.0).start()
    reqs = [server.submit_wait(t) for t in U_LINES]
    assert server.wait_for(reqs, timeout_s=60.0)
    assert [r.item for r in reqs] == expected
    stats = server.stats()
    assert stats["served"] == len(U_LINES)
    assert stats["shed"] == 0
    assert stats["batches"] >= 1
    assert server.stop()


def test_server_linger_zero_dispatches_immediately():
    st = _state()
    server = RecommendServer(st, batch_rows=4096, linger_ms=0.0).start()
    req = server.submit(U_LINES[0])
    assert server.wait_for([req], timeout_s=30.0)
    assert req.item is not None and not req.shed
    assert server.stop()


def test_server_shed_mode_answers_zero_with_ledger_event():
    """The satellite contract: a full queue sheds with "0" + a serving
    cascade event — and never hangs (every wait here is bounded)."""
    st = _state()
    # pipeline_depth=0: the serial dispatcher's absorption is exactly
    # queue + one in-flight batch — the bound below; the hand-off ring
    # would absorb pipeline_depth more batches, timing-dependently.
    server = RecommendServer(
        st, batch_rows=32, linger_ms=0.0, queue_depth=8,
        pipeline_depth=0,
    )
    # NOT started: the dispatcher never drains, so the 9th+ submits MUST
    # overflow deterministically... except submit on a stopped server
    # sheds outright; start it with a blocked dispatcher instead.
    barrier = threading.Event()
    orig = st.recommend_batch

    def slow_batch(lines):
        barrier.wait(10.0)
        return orig(lines)

    st.recommend_batch = slow_batch
    server.start(warm=False)
    reqs = [server.submit(t) for t in U_LINES[:60]]
    shed = [r for r in reqs if r.shed]
    live = [r for r in reqs if not r.shed]
    # Queue bound 8 (+ up to one batch of 32 in flight): the rest shed.
    assert len(shed) >= 60 - 8 - 32
    assert all(r.item == "0" and r.done for r in shed)
    cascade = [
        e for e in ledger.snapshot()
        if e["kind"] == "cascade" and e.get("chain") == "serving"
    ]
    assert cascade and cascade[0]["frm"] == "accept"
    assert cascade[0]["to"] == "shed"
    barrier.set()
    assert server.wait_for(live, timeout_s=60.0)
    assert all(not r.shed and r.item is not None for r in live)
    assert server.stop()


def test_server_shed_recovery_records_new_episode():
    st = _state()
    server = RecommendServer(
        st, batch_rows=32, linger_ms=0.0, queue_depth=4
    )
    # Stopped server: every submit sheds (episode 1).
    r = server.submit(U_LINES[0])
    assert r.shed and r.item == "0"
    server.start(warm=False)
    ok = server.submit_wait(U_LINES[0], timeout_s=30.0)
    assert server.wait_for([ok], timeout_s=30.0) and not ok.shed
    assert server.stop()


def test_server_hot_swap_never_mixes_tables():
    """Requests enqueued before the swap barrier are served by the OLD
    model, after it by the new — pinned via per-response model
    signatures on a blocked-then-released dispatcher (no timing
    assumptions)."""
    st_a = _state(seed=6)
    st_b = _state(seed=7)
    assert st_a.signature != st_b.signature
    gate = threading.Event()
    orig_a = st_a.recommend_batch

    def gated_batch(lines):
        gate.wait(30.0)
        return orig_a(lines)

    st_a.recommend_batch = gated_batch
    server = RecommendServer(st_a, batch_rows=16, linger_ms=0.0).start(
        warm=False
    )
    before = [server.submit(t) for t in U_LINES[:50]]
    ev = server.swap(st_b)
    after = [server.submit(t) for t in U_LINES[:50]]
    gate.set()
    assert server.wait_for(before + after, timeout_s=60.0)
    assert ev.is_set()
    assert {r.model for r in before} == {st_a.signature}
    assert {r.model for r in after} == {st_b.signature}
    # The outgoing model was released at the barrier.
    assert st_a._released
    assert server.state is st_b
    ledger_swaps = [
        e for e in ledger.snapshot() if e["kind"] == "serve_swap"
    ]
    assert ledger_swaps and ledger_swaps[0]["frm"] == st_a.signature
    assert server.stats()["swaps"] == 1
    assert server.stop()


def test_server_swap_responses_match_new_model():
    st_a = _state(seed=6)
    st_b = _state(seed=7)
    expected_b = st_b.recommend_batch(U_LINES)
    server = RecommendServer(st_a, batch_rows=64, linger_ms=0.5).start()
    server.swap(st_b)
    reqs = [server.submit_wait(t) for t in U_LINES]
    assert server.wait_for(reqs, timeout_s=60.0)
    assert [r.item for r in reqs] == expected_b
    assert server.stop()


def test_server_stop_is_bounded_even_when_blocked():
    st = _state()
    orig = st.recommend_batch
    release = threading.Event()

    def blocked(lines):
        release.wait(5.0)
        return orig(lines)

    st.recommend_batch = blocked
    server = RecommendServer(st, batch_rows=8, linger_ms=0.0).start(
        warm=False
    )
    server.submit(U_LINES[0])
    t0 = time.monotonic()
    assert not server.drain(timeout_s=0.2)  # bounded, reports failure
    assert time.monotonic() - t0 < 2.0
    release.set()
    assert server.stop(drain=True, timeout_s=30.0)


def test_server_survives_fatal_batch_error():
    """A batch whose recommend raises a non-transient error answers "0"
    (ledger serve_error) and the dispatcher keeps serving."""
    st = _state()
    orig = st.recommend_batch
    state = {"n": 0}

    def flaky(lines):
        state["n"] += 1
        if state["n"] == 1:
            raise ValueError("model bug")
        return orig(lines)

    st.recommend_batch = flaky
    server = RecommendServer(st, batch_rows=8, linger_ms=0.0).start(
        warm=False
    )
    bad = server.submit_wait(U_LINES[0])
    assert server.wait_for([bad], timeout_s=30.0)
    assert bad.item == "0"
    good = server.submit_wait(U_LINES[0])
    assert server.wait_for([good], timeout_s=30.0)
    assert good.item == orig([U_LINES[0]])[0]
    errs = [e for e in ledger.snapshot() if e["kind"] == "serve_error"]
    assert errs and "model bug" in errs[0]["error"]
    assert server.stop()


def test_serve_transient_exhaustion_walks_rule_scan_cascade(monkeypatch):
    """A device scan whose transients survive the retry budget degrades
    to the host oracle (forward-only, ledger-recorded) instead of
    killing the server."""
    monkeypatch.setenv("FA_RETRY_MAX", "2")
    monkeypatch.setenv("FA_RETRY_BACKOFF_MS", "0")
    from fastapriori_tpu.reliability import retry

    retry.reload_policy_from_env()
    try:
        st = _state(engine="device")
        baseline_host = _state(engine="host").recommend_batch(U_LINES)
        st.warm()
        failpoints.arm("fetch.serve_match", "oom")  # unlimited
        out = st.recommend_batch(U_LINES)
        failpoints.disarm_all()
        assert out == baseline_host
        assert st._engine == "host"  # stays degraded (forward-only)
        # The degraded server must not pin the dead device table's HBM
        # for its lifetime (the cascade is forward-only, it never serves
        # from the device again).
        assert st._handle is None
        assert st._rec._scan_table is None and st._rec._rule_dev is None
        assert st.resident_device_bytes() == 0
        cascade = [
            e for e in ledger.snapshot()
            if e["kind"] == "cascade" and e.get("chain") == "rule_scan"
        ]
        assert cascade and cascade[0]["frm"] == "device"
        assert cascade[0]["to"] == "host"
        # Later batches stay on the host engine without re-arming.
        assert st.recommend_batch(U_LINES) == baseline_host
    finally:
        retry.reload_policy_from_env()


def _patch_serve_pallas(monkeypatch):
    """Force the interpreter-mode serving Pallas plan on CPU (the
    production hook is TPU-gated); honors the sticky cascade switch
    like the real _serve_pallas_plan."""
    from fastapriori_tpu.parallel.mesh import DeviceContext

    def plan(self, chunk):
        if self._serve_pallas_off:
            return None
        return (chunk, True)

    monkeypatch.setattr(DeviceContext, "_serve_pallas_plan", plan)


def test_serving_pallas_first_match_interpreter_pin(monkeypatch):
    """ISSUE 18: the Pallas strided first-match kernel (interpreter
    mode) mounts on the resident sharded table and answers
    bit-identically to the host oracle — the running-min tile scan has
    no early exit, so exactness is by construction, pinned here."""
    _patch_serve_pallas(monkeypatch)
    st = _state(num_devices=4, rule_engine="device")
    st.warm()
    assert st._handle is not None and st._handle.pallas is True
    assert st.describe()["resident_table"] is True
    host = _state(engine="host")
    assert st.recommend_batch(U_LINES) == host.recommend_batch(U_LINES)


def test_serve_scan_pallas_cascade_walks_to_xla(monkeypatch):
    """serve_scan transient exhaustion with the Pallas kernel mounted:
    the first walk drops only the kernel (pallas->xla; the device rule
    table survives for the re-warm); the still-armed fetch then
    exhausts the XLA scan too and rule_scan walks device->host — both
    forward-only, both on the ledger, answers staying exact."""
    monkeypatch.setenv("FA_RETRY_MAX", "2")
    monkeypatch.setenv("FA_RETRY_BACKOFF_MS", "0")
    from fastapriori_tpu.reliability import retry

    retry.reload_policy_from_env()
    try:
        _patch_serve_pallas(monkeypatch)
        st = _state(num_devices=4, rule_engine="device")
        baseline_host = _state(engine="host").recommend_batch(U_LINES)
        st.warm()
        assert st._handle is not None and st._handle.pallas is True
        failpoints.arm("fetch.serve_match", "oom")  # unlimited
        out = st.recommend_batch(U_LINES)
        failpoints.disarm_all()
        assert out == baseline_host
        casc = [
            e for e in ledger.snapshot() if e["kind"] == "cascade"
        ]
        assert any(
            e["chain"] == "serve_scan"
            and e["frm"] == "pallas"
            and e["to"] == "xla"
            for e in casc
        )
        assert any(
            e["chain"] == "rule_scan"
            and e["frm"] == "device"
            and e["to"] == "host"
            for e in casc
        )
    finally:
        retry.reload_policy_from_env()


# ---------------------------------------------------------------------------
# open-loop load generation


def test_arrival_offsets_deterministic_and_rate_shaped():
    a = arrival_offsets(5000, 1000.0, seed=3)
    b = arrival_offsets(5000, 1000.0, seed=3)
    assert np.array_equal(a, b)
    c = arrival_offsets(5000, 1000.0, seed=4)
    assert not np.array_equal(a, c)
    assert np.all(np.diff(a) >= 0)
    # Mean inter-arrival ~ 1/rate (law of large numbers at n=5000).
    assert a[-1] / 5000 == pytest.approx(1e-3, rel=0.1)
    with pytest.raises(InputError, match="rate_rps"):
        arrival_offsets(10, 0.0, seed=1)


def test_open_loop_serves_below_capacity():
    st = _state()
    expected = {tuple(t): v for t, v in zip(U_LINES,
                                            st.recommend_batch(U_LINES))}
    server = RecommendServer(st, batch_rows=64, linger_ms=1.0).start()
    reqs = []
    res = run_open_loop(
        server, U_LINES, rate_rps=2000.0, n_requests=400, seed=11,
        drain_timeout_s=60.0, requests_out=reqs,
    )
    assert res["drained"] is True
    assert res["served"] + res["shed"] == 400
    assert res["n_requests"] == 400
    assert res["p50_ms"] is not None and res["p99_ms"] is not None
    assert res["p50_ms"] <= res["p95_ms"] <= res["p99_ms"]
    assert len(reqs) == 400
    # Responses match the model (request i = pool[i % len(pool)]).
    for i, r in enumerate(reqs):
        if not r.shed:
            assert r.item == expected[tuple(U_LINES[i % len(U_LINES)])]
    assert server.stop()


def test_open_loop_overload_sheds_and_stays_bounded():
    st = _state()
    gate = threading.Event()
    orig = st.recommend_batch

    def slow(lines):
        time.sleep(0.02)
        return orig(lines)

    st.recommend_batch = slow
    server = RecommendServer(
        st, batch_rows=32, linger_ms=0.0, queue_depth=64
    ).start(warm=False)
    res = run_open_loop(
        server, U_LINES, rate_rps=20000.0, n_requests=3000, seed=12,
        drain_timeout_s=60.0, label="overload",
    )
    gate.set()
    assert res["drained"] is True
    assert res["shed"] > 0
    assert res["served"] + res["shed"] == 3000
    assert res["max_queue"] <= 64
    cascade = [
        e for e in ledger.snapshot()
        if e["kind"] == "cascade" and e.get("chain") == "serving"
    ]
    assert cascade
    # A later gentle scenario on the SAME server reports its own queue
    # peak, not the overload's server-lifetime maximum.
    gentle = run_open_loop(
        server, U_LINES, rate_rps=50.0, n_requests=20, seed=13,
        drain_timeout_s=60.0, label="gentle",
    )
    assert gentle["drained"] and gentle["max_queue"] < res["max_queue"]
    assert server.stop()
