"""Test harness: run everything on 8 fake CPU devices so the real
Mesh/shard_map code paths execute without TPU hardware (SURVEY.md §4 —
``--xla_force_host_platform_device_count``).  Counting is int32-exact, so
single-device vs multi-device equality assertions are strict."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may pre-import jax with a hardware backend selected
# (e.g. the axon TPU tunnel registers itself from sitecustomize before
# pytest starts), so env vars alone are not enough — force the CPU
# platform and the 8-device split through the live config too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # JAX >= 0.5 spells the device split as a config option; 0.4.x
    # (e.g. the pinned 0.4.37) rejects the name — there the XLA flag
    # set above is the only (and sufficient) mechanism.
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # narrow catch; the XLA flag set above already covers 0.4.x

import random  # noqa: E402
from typing import List  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    # Tier-1 runs with ``-m 'not slow'`` (ROADMAP); register the mark
    # so slow-tagged cases (e.g. the 16/32-virtual-device subprocess
    # differentials in test_hier_exchange.py) deselect cleanly.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 gate (-m 'not slow')"
    )


def random_dataset(
    seed: int,
    n_items: int = 12,
    n_txns: int = 80,
    max_len: int = 6,
    with_edge_cases: bool = True,
) -> List[str]:
    """Skewed random transaction lines exercising the reference's edge
    semantics: duplicate items within a line, duplicate lines, empty lines,
    extra whitespace."""
    rng = random.Random(seed)
    items = [str(i) for i in range(1, n_items + 1)]
    # Zipf-ish weights so some items are frequent and some are not.
    weights = [1.0 / (i + 1) for i in range(n_items)]
    lines = []
    for _ in range(n_txns):
        k = rng.randint(1, max_len)
        txn = rng.choices(items, weights=weights, k=k)
        lines.append(" ".join(txn))
    if with_edge_cases:
        lines.append("")  # empty line -> single empty token (Java split)
        lines.append("  3   1  3 ")  # duplicate item + stray whitespace
        if lines:
            lines.append(lines[0])  # duplicate transaction
    return lines


@pytest.fixture
def tiny_d_lines() -> List[List[str]]:
    """Hand-written dataset with known frequent itemsets."""
    raw = [
        "1 2 3",
        "1 2",
        "1 3",
        "2 3",
        "1 2 3 4",
        "4 5",
        "1 2 4",
        "2 3 4",
        "1 2 3",
        "5",
    ]
    from fastapriori_tpu.io.reader import tokenize_line

    return [tokenize_line(l) for l in raw]


@pytest.fixture
def tiny_u_lines() -> List[List[str]]:
    raw = ["1 2", "3", "1 2 3", "", "5 9", "2 4", "1 2"]
    from fastapriori_tpu.io.reader import tokenize_line

    return [tokenize_line(l) for l in raw]


def tokenized(lines: List[str]) -> List[List[str]]:
    from fastapriori_tpu.io.reader import tokenize_line

    return [tokenize_line(l) for l in lines]
