"""Device counting kernels (C5/C6/C8) vs numpy references, including the
base-128 weight-digit decomposition and padding discipline."""

import numpy as np
import pytest

from fastapriori_tpu.ops.bitmap import (
    build_bitmap,
    pad_axis,
    weight_digits,
)
from fastapriori_tpu.parallel.mesh import DeviceContext


def _random_bitmap_case(seed, t=37, f=23, max_w=1):
    rng = np.random.default_rng(seed)
    baskets = []
    for _ in range(t):
        size = rng.integers(2, min(f, 6) + 1)
        baskets.append(
            np.sort(rng.choice(f, size=size, replace=False)).astype(np.int32)
        )
    weights = rng.integers(1, max_w + 1, size=t).astype(np.int32)
    return baskets, weights


def test_pad_axis():
    assert pad_axis(0, 8) == 8
    assert pad_axis(1, 8) == 8
    assert pad_axis(8, 8) == 8
    assert pad_axis(9, 8) == 16


def test_build_bitmap_padding_and_content():
    baskets = [np.array([0, 2], np.int32), np.array([1, 2, 3], np.int32)]
    b = build_bitmap(baskets, 4, txn_multiple=8, item_multiple=128)
    assert b.shape == (8, 128)
    assert b[0, 0] == 1 and b[0, 2] == 1 and b[0, 1] == 0
    assert b[1, 1] == 1 and b[1, 2] == 1 and b[1, 3] == 1
    # guaranteed zero column at index F and all padding zero
    assert b[:, 4:].sum() == 0 and b[2:].sum() == 0


@pytest.mark.parametrize("max_w", [1, 5, 130, 40000])
def test_weight_digits_roundtrip(max_w):
    rng = np.random.default_rng(0)
    w = rng.integers(1, max_w + 1, size=50).astype(np.int32)
    digits, scales = weight_digits(w, 64)
    recon = sum(s * digits[d].astype(np.int64) for d, s in enumerate(scales))
    assert (recon[:50] == w).all()
    assert (recon[50:] == 0).all()
    assert digits.dtype == np.int8 and (digits >= 0).all()


@pytest.mark.parametrize("n_devices", [1, 8])
@pytest.mark.parametrize("max_w", [1, 300])
def test_pair_counts_match_numpy(n_devices, max_w):
    baskets, weights = _random_bitmap_case(1, max_w=max_w)
    f = 23
    ctx = DeviceContext(num_devices=n_devices)
    b = build_bitmap(baskets, f, txn_multiple=32 * ctx.n_devices)
    digits, scales = weight_digits(weights, b.shape[0])

    got = np.asarray(
        ctx.pair_counts(
            ctx.shard_bitmap(b), ctx.shard_weight_digits(digits), scales
        )
    )
    dense = b.astype(np.int64)
    w_pad = np.zeros(b.shape[0], np.int64)
    w_pad[: len(weights)] = weights
    expected = (dense * w_pad[:, None]).T @ dense
    assert got.shape == expected.shape
    assert (got == expected).all()


@pytest.mark.parametrize("n_devices", [1, 8])
def test_level_counts_match_numpy(n_devices):
    baskets, weights = _random_bitmap_case(2, max_w=7)
    f = 23
    ctx = DeviceContext(num_devices=n_devices)
    b = build_bitmap(baskets, f, txn_multiple=32 * ctx.n_devices)
    digits, scales = weight_digits(weights, b.shape[0])

    # prefixes of width 2, incl. a padded row pointing at the zero column.
    prefix_cols = np.array(
        [[0, 1], [2, 5], [7, 8], [f, f]], dtype=np.int32
    )
    got = np.asarray(
        ctx.level_counts(
            ctx.shard_bitmap(b),
            ctx.shard_weight_digits(digits),
            scales,
            prefix_cols,
        )
    )
    dense = b.astype(np.int64)
    w_pad = np.zeros(b.shape[0], np.int64)
    w_pad[: len(weights)] = weights
    for row, cols in enumerate(prefix_cols):
        common = dense[:, cols[0]] * dense[:, cols[1]]
        expected = (common * w_pad) @ dense
        assert (got[row] == expected).all()
    assert (got[3] == 0).all(), "padded prefix row must count zero"


@pytest.mark.parametrize("n_devices", [1, 8])
def test_item_supports(n_devices):
    baskets, weights = _random_bitmap_case(3, max_w=3)
    f = 23
    ctx = DeviceContext(num_devices=n_devices)
    b = build_bitmap(baskets, f, txn_multiple=32 * ctx.n_devices)
    digits, scales = weight_digits(weights, b.shape[0])
    got = np.asarray(
        ctx.item_supports(
            ctx.shard_bitmap(b), ctx.shard_weight_digits(digits), scales
        )
    )
    w_pad = np.zeros(b.shape[0], np.int64)
    w_pad[: len(weights)] = weights
    expected = (b.astype(np.int64) * w_pad[:, None]).sum(axis=0)
    assert (got == expected).all()
