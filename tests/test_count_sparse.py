"""Sparse count-reduction engine (ISSUE 6, ROADMAP item 2): the
threshold-sparse exchange (ops/count.py local_sparse_psum — local prune
at the weighted-pigeonhole threshold, packed-mask union all_gather,
compact segment psum) must be BIT-EXACT against the dense psum on every
corpus shape, across all three counting paths (level kernels, pair
gather, fused whole-loop engine), and its engine
selection/env/overflow contracts mirror the rule-engine table
(tests/test_rules_device.py)."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.reliability import failpoints, ledger


@pytest.fixture(autouse=True)
def _clean_state():
    failpoints.disarm_all()
    ledger.reset()
    yield
    failpoints.disarm_all()
    ledger.reset()


def _mine(lines, min_support, **cfg):
    miner = FastApriori(
        config=MinerConfig(min_support=min_support, **cfg)
    )
    got, _, _ = miner.run(lines)
    return dict(got), miner


def _engine_events(miner=None):
    return [
        e for e in ledger.snapshot() if e["kind"] == "count_reduce_engine"
    ]


# ---------------------------------------------------------------------------
# differential suite: sparse vs dense, bit-exact counts per corpus shape


def _t10i4_shaped():
    """IBM-Quest-style power-law lines (the T10I4 family datagen
    reproduces) — the corpus class the sparse exchange exists for."""
    from fastapriori_tpu.utils.datagen import generate_transactions

    return [
        l.split()
        for l in generate_transactions(
            n_txns=1500, n_items=90, avg_txn_len=9, n_patterns=30,
            avg_pattern_len=4, corruption=0.35, seed=11,
        )
    ]


def _webdocs_shaped():
    """Skewed long-tail baskets with duplicate lines/items (the
    random_dataset edge semantics) — webdocs-like support skew."""
    return tokenized(
        random_dataset(23, n_txns=400, n_items=40, max_len=12)
    )


def _deep_lattice():
    """Few items, long correlated baskets: the lattice goes deep (k well
    past 5), exercising many per-level reductions."""
    return tokenized(
        random_dataset(13, n_txns=200, n_items=14, max_len=9)
    )


def _no_survivor_level():
    """High support: level 2 (or 3) has candidates but zero survivors —
    the sparse union must come back empty without tripping anything."""
    return tokenized(random_dataset(3, n_txns=120))


@pytest.mark.parametrize(
    "lines_fn, min_support",
    [
        (_t10i4_shaped, 0.03),
        (_webdocs_shaped, 0.04),
        (_deep_lattice, 0.05),
        (_no_survivor_level, 0.4),
    ],
    ids=["t10i4", "webdocs", "deep-lattice", "no-survivor"],
)
@pytest.mark.parametrize("engine", ["level", "fused"])
def test_sparse_bitexact_vs_dense(lines_fn, min_support, engine):
    lines = lines_fn()
    exp, _ = _mine(
        lines, min_support, engine=engine, num_devices=8,
        count_reduce="dense",
    )
    got, miner = _mine(
        lines, min_support, engine=engine, num_devices=8,
        count_reduce="sparse", count_sparse_min=1,
    )
    assert got == exp
    assert _engine_events()  # the choice landed on the ledger


def test_sparse_overflow_falls_back_dense_and_stays_exact():
    """A forced-tiny compaction budget overflows the union on every
    non-trivial reduction; the engine must detect it (the union census
    rides the survivor fetch), recount dense, record the ledger event,
    and still produce bit-exact itemsets."""
    lines = _t10i4_shaped()
    exp, _ = _mine(
        lines, 0.03, engine="level", num_devices=8, count_reduce="dense"
    )
    got, miner = _mine(
        lines, 0.03, engine="level", num_devices=8,
        count_reduce="sparse", count_sparse_min=1, count_sparse_cap=8,
    )
    assert got == exp
    kinds = [e["kind"] for e in ledger.snapshot()]
    assert "count_sparse_overflow" in kinds
    # The grown budget was memoized: a repeat mine on the same context
    # sizes the compaction right and pays no second overflow.
    ledger.reset()
    got2, _, _ = FastApriori(
        config=MinerConfig(
            min_support=0.03, engine="level", num_devices=8,
            count_reduce="sparse", count_sparse_min=1, count_sparse_cap=8,
        ),
        context=miner.context,
    ).run(lines)
    assert dict(got2) == exp
    assert not [
        e
        for e in ledger.snapshot()
        if e["kind"] == "count_sparse_overflow"
    ]


def test_fused_sparse_overflow_reruns_dense():
    lines = _deep_lattice()
    exp, _ = _mine(
        lines, 0.05, engine="fused", num_devices=8, count_reduce="dense"
    )
    got, miner = _mine(
        lines, 0.05, engine="fused", num_devices=8,
        count_reduce="sparse", count_sparse_min=1, count_sparse_cap=8,
    )
    assert got == exp
    events = [
        e
        for e in ledger.snapshot()
        if e["kind"] == "count_sparse_overflow"
    ]
    assert events and events[0]["site"] == "fused"
    # The kernel reported the true union census and the host memoized
    # it: a repeat mine on the same context sizes the compaction right
    # and never re-pays the wasted sparse dispatch + dense redo.
    assert events[0].get("n_union", 0) > 8
    ledger.reset()
    got2, _, _ = FastApriori(
        config=MinerConfig(
            min_support=0.05, engine="fused", num_devices=8,
            count_reduce="sparse", count_sparse_min=1, count_sparse_cap=8,
        ),
        context=miner.context,
    ).run(lines)
    assert dict(got2) == exp
    assert not [
        e
        for e in ledger.snapshot()
        if e["kind"] == "count_sparse_overflow"
    ]


# ---------------------------------------------------------------------------
# engine selection / fallback / env strictness (the rule-engine table)


def test_auto_stays_dense_on_one_device():
    lines = _deep_lattice()
    _, miner = _mine(
        lines, 0.05, engine="level", num_devices=1, count_reduce="auto"
    )
    recs = [
        r
        for r in miner.metrics.records
        if r.get("event") == "count_reduce"
    ]
    assert recs and recs[0]["engine"] == "dense"
    assert not _engine_events()


def test_auto_picks_sparse_on_multi_device():
    lines = _deep_lattice()
    _, miner = _mine(
        lines, 0.05, engine="level", num_devices=8, count_reduce="auto"
    )
    recs = [
        r
        for r in miner.metrics.records
        if r.get("event") == "count_reduce"
    ]
    assert recs and recs[0]["engine"] == "sparse"


def test_forced_sparse_on_one_device_falls_back_with_ledger():
    lines = _deep_lattice()
    got, _ = _mine(
        lines, 0.05, engine="level", num_devices=1, count_reduce="sparse"
    )
    exp, _ = _mine(
        lines, 0.05, engine="level", num_devices=1, count_reduce="dense"
    )
    assert got == exp
    falls = [
        e
        for e in ledger.snapshot()
        if e["kind"] == "count_reduce_fallback"
    ]
    assert falls and falls[0]["reason"] == "one_txn_shard"


def test_forced_sparse_on_cand_mesh_falls_back():
    lines = _deep_lattice()
    got, _ = _mine(
        lines, 0.05, engine="level", num_devices=8, cand_devices=2,
        count_reduce="sparse",
    )
    exp, _ = _mine(
        lines, 0.05, engine="level", num_devices=8, cand_devices=2,
        count_reduce="dense",
    )
    assert got == exp
    falls = [
        e
        for e in ledger.snapshot()
        if e["kind"] == "count_reduce_fallback"
    ]
    assert falls and falls[0]["reason"] == "cand_mesh"


def test_tiny_levels_stay_dense_under_auto():
    """The count_sparse_min floor: candidate spaces under it keep the
    dense psum even when the mine selected sparse (per-dispatch
    decision — the exchange's two collectives cost more than a small
    dense payload)."""
    lines = _deep_lattice()
    _, miner = _mine(
        lines, 0.05, engine="level", num_devices=8,
        count_reduce="sparse", count_sparse_min=1 << 30,
    )
    lvl = [
        r
        for r in miner.metrics.records
        if r.get("event") == "level" and r.get("k", 0) >= 3
    ]
    assert lvl and all(r.get("reduce") == "dense" for r in lvl)
    # ...and the fallback is a recorded degradation (config.py's
    # tiny-candidate-set contract), one event per dense level.
    falls = [
        e
        for e in ledger.snapshot()
        if e["kind"] == "count_reduce_fallback"
        and e.get("reason") == "tiny_candidate_set"
    ]
    assert falls


def test_config_count_reduce_strictly_validated():
    lines = _deep_lattice()
    with pytest.raises(InputError, match="count_reduce"):
        _mine(lines, 0.05, engine="level", count_reduce="sprase")


def test_env_count_reduce_strictly_parsed(monkeypatch):
    from fastapriori_tpu.utils.env import env_choice

    monkeypatch.setenv("FA_COUNT_REDUCE", "  DENSE ")
    assert env_choice("FA_COUNT_REDUCE", ("auto", "dense", "sparse")) == (
        "dense"
    )
    monkeypatch.setenv("FA_COUNT_REDUCE", "sprase")  # the typo class
    with pytest.raises(InputError, match="FA_COUNT_REDUCE"):
        env_choice("FA_COUNT_REDUCE", ("auto", "dense", "sparse"))


def test_env_overrides_config(monkeypatch):
    """FA_COUNT_REDUCE=dense beats a sparse config — no sparse engine
    event lands on the ledger."""
    monkeypatch.setenv("FA_COUNT_REDUCE", "dense")
    lines = _deep_lattice()
    _, miner = _mine(
        lines, 0.05, engine="level", num_devices=8, count_reduce="sparse"
    )
    assert not _engine_events()
    recs = [
        r
        for r in miner.metrics.records
        if r.get("event") == "count_reduce"
    ]
    assert recs and recs[0]["engine"] == "dense"


def test_env_sparse_cap_strictly_parsed(monkeypatch):
    monkeypatch.setenv("FA_COUNT_SPARSE_CAP", "64k")
    lines = _deep_lattice()
    with pytest.raises(InputError, match="FA_COUNT_SPARSE_CAP"):
        _mine(
            lines, 0.05, engine="level", num_devices=8,
            count_reduce="sparse", count_sparse_min=1,
        )


# ---------------------------------------------------------------------------
# shallow-tail fold sparse reduction (ISSUE 7 satellite: the PR-6
# residue — the fold was the last counting path still dense-psumming
# its per-iteration [p_cap, F] counts)


def _tail_lines():
    return tokenized(
        random_dataset(2, n_txns=150, max_len=8) + ["1 2 3 4 5 6 7"] * 20
    )


def _tail_mine(**cfg):
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.04, engine="level", num_devices=8,
            tail_fuse_rows=1 << 20, **cfg,
        )
    )
    got, _, _ = miner.run(_tail_lines())
    return dict(got), miner


def test_tail_fold_sparse_reduction_bitexact_with_bytes():
    """The fold's per-iteration count reduction runs the threshold-
    sparse exchange under count_reduce=sparse — bit-exact, with the
    per-engine comms bytes on the tail_fuse record (and strictly below
    the dense psum payload)."""
    exp, md = _tail_mine(count_reduce="dense")
    got, ms = _tail_mine(count_reduce="sparse", count_sparse_min=1)
    assert got == exp
    t_d = [r for r in md.metrics.records if r["event"] == "tail_fuse"]
    t_s = [r for r in ms.metrics.records if r["event"] == "tail_fuse"]
    assert t_d and t_d[0]["reduce"] == "dense"
    assert t_s and t_s[0]["reduce"] == "sparse"
    assert (
        t_s[0]["psum_bytes"] + t_s[0]["gather_bytes"]
        < t_d[0]["psum_bytes"]
    )


def test_tail_fold_sparse_overflow_resumes_per_level_exact():
    """A forced-tiny union budget overflows inside the fold: the level
    carries the bad sentinel, the host resumes per-level from the last
    complete level (exact), the ledger names the tail site, and the
    grown budget is memoized for repeat runs."""
    exp, _ = _tail_mine(count_reduce="dense")
    got, miner = _tail_mine(
        count_reduce="sparse", count_sparse_min=1, count_sparse_cap=8
    )
    assert got == exp
    ovf = [
        e
        for e in ledger.snapshot()
        if e["kind"] == "count_sparse_overflow" and e.get("site") == "tail"
    ]
    assert ovf and ovf[0]["n_union"] > 8
    tails = [
        r for r in miner.metrics.records if r["event"] == "tail_fuse"
    ]
    assert tails and tails[0]["incomplete"]
    # The per-level engine finished the lattice after the failed fold.
    assert [
        r
        for r in miner.metrics.records
        if r["event"] == "level" and r.get("k", 0) >= 4
    ]
    # Memoized: a repeat mine on the same context folds clean.
    ledger.reset()
    got2, _, _ = FastApriori(
        config=MinerConfig(
            min_support=0.04, engine="level", num_devices=8,
            tail_fuse_rows=1 << 20, count_reduce="sparse",
            count_sparse_min=1, count_sparse_cap=8,
        ),
        context=miner.context,
    ).run(_tail_lines())
    assert dict(got2) == exp
    assert not [
        e
        for e in ledger.snapshot()
        if e["kind"] == "count_sparse_overflow"
        and e.get("site") == "tail"
    ]


# ---------------------------------------------------------------------------
# the primitive itself


def test_sparse_union_cap_buckets():
    from fastapriori_tpu.ops.count import sparse_union_cap

    assert sparse_union_cap(1 << 18) == (1 << 18) // 16
    assert sparse_union_cap(4096) == 1024  # floor
    assert sparse_union_cap(512) == 512  # never above the space itself
    assert sparse_union_cap(1 << 18, override=3000) == 4096  # pow2 bucket
    assert sparse_union_cap(1024, override=1 << 20) == 1024  # clamped


def test_sparse_thresholds_pigeonhole():
    """Per-shard thresholds must satisfy the pigeonhole: a candidate
    below every shard's threshold sums below min_count."""
    from fastapriori_tpu.preprocess import preprocess

    lines = _deep_lattice()
    miner = FastApriori(
        config=MinerConfig(min_support=0.05, num_devices=8)
    )
    data = preprocess(lines, 0.05)
    s = miner.context.txn_shards
    t_pad = ((data.total_count + s - 1) // s) * s
    thr = miner._sparse_thresholds(data, t_pad, heavy=False)
    assert thr.shape == (s,) and thr.dtype == np.int32
    assert (thr >= 1).all()
    # Σ (thr_s - 1) < min_count is exactly the no-lost-candidate bound.
    assert int((thr.astype(np.int64) - 1).sum()) < data.min_count
