"""Native C++ preprocessor vs the Python path: exact equality on the same
inputs (tokenization, tie-breaks, dedup, CSR layout)."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.native import native_available
from fastapriori_tpu.preprocess import preprocess, preprocess_file

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native extension not built"
)


def _assert_equal(a, b):
    assert a.n_raw == b.n_raw
    assert a.min_count == b.min_count
    assert a.freq_items == b.freq_items
    assert a.item_to_rank == b.item_to_rank
    assert (a.item_counts == b.item_counts).all()
    # Basket order may differ (Python dict order vs C++ first-seen — both
    # are first-seen, but compare as a multiset to be robust).
    got = {
        tuple(x): int(w)
        for x, w in zip(a.baskets, a.weights)
    }
    expected = {
        tuple(x): int(w)
        for x, w in zip(b.baskets, b.weights)
    }
    assert got == expected


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("min_support", [0.03, 0.1, 0.25])
def test_native_matches_python_random(seed, min_support):
    lines = tokenized(random_dataset(seed))
    _assert_equal(
        preprocess(lines, min_support, native=True),
        preprocess(lines, min_support, native=False),
    )


def test_native_edge_tokens(tmp_path):
    raw = (
        "007 7 7 007\n"  # numeric ties with distinct tokens
        "\n"  # empty line -> single empty token
        "\t  x\t y  \n"  # tabs, non-numeric tokens
        "-3 +4 -3\n"  # signed integers
        "x 007 7\n"
        "99999999999999999999999 1\n"  # > int64: lexical fallback
        "99999999999999999999999 1"
    )
    p = tmp_path / "D.dat"
    p.write_text(raw)
    a = preprocess_file(str(p), 0.2, native=True)
    b = preprocess_file(str(p), 0.2, native=False)
    _assert_equal(a, b)


def test_native_file_no_trailing_newline(tmp_path):
    p = tmp_path / "D.dat"
    p.write_text("1 2\n1 2\n1 3")
    a = preprocess_file(str(p), 0.3, native=True)
    b = preprocess_file(str(p), 0.3, native=False)
    assert a.n_raw == 3
    _assert_equal(a, b)


def test_native_crlf(tmp_path):
    p = tmp_path / "D.dat"
    p.write_bytes(b"1 2\r\n1 2\r\n2 3\r\n")
    a = preprocess_file(str(p), 0.3, native=True)
    b = preprocess_file(str(p), 0.3, native=False)
    _assert_equal(a, b)


def test_native_empty_file(tmp_path):
    p = tmp_path / "D.dat"
    p.write_text("")
    a = preprocess_file(str(p), 0.5, native=True)
    assert a.n_raw == 0 and a.num_items == 0 and a.total_count == 0


def test_native_large_weights():
    # >128 duplicate baskets (two weight digits downstream) and >=2^15 rows.
    lines = tokenized(["1 2 3"] * 300 + ["4 5"] * 2 + ["1 2"] * 40000)
    _assert_equal(
        preprocess(lines, 0.001, native=True),
        preprocess(lines, 0.001, native=False),
    )


def test_miner_with_native_preprocess_end_to_end(tmp_path):
    from fastapriori_tpu import oracle
    from fastapriori_tpu.models.apriori import FastApriori

    raw = random_dataset(9, n_txns=300)
    p = tmp_path / "D.dat"
    p.write_text("\n".join(raw) + "\n")
    expected, _, _ = oracle.mine(tokenized(raw), 0.05)

    miner = FastApriori(0.05, num_devices=1)
    data = preprocess_file(str(p), 0.05, native=True)
    got = miner.mine_compressed(data)
    assert dict(got) == dict(expected)


def test_native_packed_bitmap_matches_numpy():
    # The native bit-filler and the dense-build + packbits fallback must
    # produce identical packed bytes (MSB-first within each byte).
    from fastapriori_tpu.native.loader import fill_packed_bitmap, get_lib
    from fastapriori_tpu.ops.bitmap import (
        build_bitmap_csr,
        build_packed_bitmap_csr,
    )

    if get_lib() is None:
        import pytest

        pytest.skip("native library unavailable")
    rng = np.random.default_rng(5)
    baskets = [
        np.unique(rng.integers(0, 300, size=rng.integers(2, 20)))
        for _ in range(57)
    ]
    indices = np.concatenate(baskets).astype(np.int32)
    offsets = np.concatenate(
        [[0], np.cumsum([len(b) for b in baskets])]
    ).astype(np.int64)
    packed, f_pad = build_packed_bitmap_csr(indices, offsets, 300, 32, 128)
    dense = build_bitmap_csr(indices, offsets, 300, 32, 128)
    assert packed.shape == (dense.shape[0], f_pad // 8)
    assert (np.packbits(dense.astype(bool), axis=1) == packed).all()


def test_native_large_f_sort_fallback():
    # F > 4096 frequent items bypasses the bitset per-line sort in the
    # native scanner's pass 2 (std::sort fallback) — equality with the
    # Python path must hold there too.
    import random

    rng = random.Random(3)
    n_items = 5000
    lines = tokenized(
        [
            " ".join(
                str(rng.randint(1, n_items)) for _ in range(rng.randint(2, 8))
            )
            for _ in range(4000)
        ]
    )
    a = preprocess(lines, 0.0001, native=True)
    b = preprocess(lines, 0.0001, native=False)
    assert a.num_items > 4096, a.num_items
    _assert_equal(a, b)


@pytest.mark.parametrize("n_shards", [1, 2, 3, 5])
def test_sharded_preprocess_equivalent_support(tmp_path, n_shards):
    """preprocess_file_sharded across K simulated processes must yield
    shards whose UNION carries exactly the plain path's weighted
    support: same global tables, and per-item / per-pair weighted counts
    identical (cross-shard duplicate baskets stay separate rows, so row
    counts may differ — the weighted bitmap must not)."""
    import pickle

    from conftest import random_dataset
    from fastapriori_tpu.preprocess import (
        preprocess_file,
        preprocess_file_sharded,
        read_shard,
    )
    from fastapriori_tpu.native.loader import count_buffer

    d_raw = (
        ["1 2 3"] * 140  # heavy basket (2-digit weight) in shard 0
        + random_dataset(21, n_txns=200, n_items=30, max_len=9)
        + ["1 2 3"] * 7  # same basket near the end of the file
    )
    path = tmp_path / "D.dat"
    path.write_text("".join(l + "\n" for l in d_raw))

    plain = preprocess_file(str(path), 0.05)

    # Simulate the allgather: phase 1 blobs computed for every shard up
    # front; the per-shard local stats exchanged on the second call.
    p1 = [
        pickle.dumps(count_buffer(read_shard(str(path), i, n_shards)), 4)
        for i in range(n_shards)
    ]
    shards = []
    for i in range(n_shards):
        calls = {"n": 0}

        def ag(blob, i=i, calls=calls):
            calls["n"] += 1
            if calls["n"] == 1:
                return p1
            # second exchange: local (count, max weight) — recompute all
            import pickle as pk

            from fastapriori_tpu.native.loader import compress_with_ranks

            out = []
            for j in range(n_shards):
                if j == i:
                    out.append(blob)
                else:
                    dj = read_shard(str(path), j, n_shards)
                    _, _, _, wj = compress_with_ranks(
                        dj, shards_freq
                    )
                    out.append(
                        pk.dumps(
                            (len(wj), int(wj.max()) if len(wj) else 1), 4
                        )
                    )
            return out

        # freq_items needed by the fake allgather's second round: derive
        # once from the plain path (identical by the first assertion).
        shards_freq = plain.freq_items
        shards.append(
            preprocess_file_sharded(
                str(path), 0.05,
                process_id=i, num_processes=n_shards, allgather=ag,
            )
        )

    for s in shards:
        assert s.freq_items == plain.freq_items
        assert s.min_count == plain.min_count and s.n_raw == plain.n_raw
        assert (s.item_counts == plain.item_counts).all()
        # Global max weight over SHARD-LOCAL rows (cross-shard duplicates
        # stay separate, so this can be below the merged-dedup max).
        assert s.shard.max_weight == max(
            int(x.weights.max()) if len(x.weights) else 1 for x in shards
        )
        assert s.shard.local_counts == [len(x.weights) for x in shards]

    # Weighted support equivalence: per-item and per-pair weighted counts
    # over the union of shards == the plain path's.
    f = plain.num_items

    def weighted_gram(data_list):
        g = np.zeros((f, f), dtype=np.int64)
        for d in data_list:
            for i in range(d.total_count):
                row = np.asarray(
                    d.basket_indices[
                        d.basket_offsets[i]: d.basket_offsets[i + 1]
                    ]
                )
                w = int(d.weights[i])
                g[np.ix_(row, row)] += w
        return g

    assert (weighted_gram(shards) == weighted_gram([plain])).all()


def test_tokenization_java_semantics_raw_bytes(tmp_path):
    """The SAME raw file bytes through the native scanner and the Python
    path (which must not re-serialize): control chars at line edges are
    Java-trimmed, interior ones are tokens, and only '\\n' terminates a
    record (str.splitlines' extra terminators \\x0b/\\x1c/\\x85 must NOT
    split lines — they change n_raw and therefore minCount)."""
    raw = (
        "\x01 7 8\n"     # control char trimmed at the start
        "7 8 \x02\n"     # ...and at the end
        "7 \x01 8\n"     # mid-line control char is its own token
        "7\x0b8 9\n"     # \x0b IS ASCII \s in Java: splits tokens, not lines
        "7\x1c8 9\n"     # \x1c is NOT whitespace and NOT a terminator
        "\x03\x04\n"     # trims to empty -> [""]
        "7 8\n"
        "7 8"            # no trailing newline
    )
    p = tmp_path / "D.dat"
    p.write_bytes(raw.encode("utf-8"))
    a = preprocess_file(str(p), 0.2, native=True)
    b = preprocess_file(str(p), 0.2, native=False)
    assert a.n_raw == b.n_raw == 8
    _assert_equal(a, b)
    # '7\x1c8' must survive as one (infrequent) token — check via a run
    # with min_support 0 on the python path only.
    from fastapriori_tpu.io.reader import read_dat

    lines = read_dat(str(p))
    assert lines[4] == ["7\x1c8", "9"]
    assert lines[3] == ["7", "8", "9"]


def test_preprocess_in_memory_edge_tokens_fall_back():
    """In-memory token lists whose tokens could not survive the native
    byte round trip (leading/trailing chars <= 0x20) must route to the
    Python path and still produce Java-exact results."""
    lines = [["\x01a", "b"], ["\x01a", "b"], ["a", "b"], ["b", "\x01a"]]
    # Even an explicit native=True must not ship these tokens through
    # the lossy byte round trip — the guard falls back to Python.
    a = preprocess(lines, 0.3, native=True)
    b = preprocess(lines, 0.3, native=False)
    assert a.freq_items == b.freq_items
    assert (a.item_counts == b.item_counts).all()
    assert (a.weights == b.weights).all()
    assert "\x01a" in a.freq_items  # identity preserved


def test_tokenization_java_semantics_control_and_unicode():
    """Java String.trim removes chars <= 0x20 (so \\x01 at the ends goes)
    while regex \\s is ASCII-only (so \\xa0 never splits or trims) — the
    Python tokenizer, the oracle, and the native scanner must agree on
    these edge bytes (Utils.scala:21 semantics; Python's str.strip() and
    unicode-aware \\s would both diverge)."""
    from fastapriori_tpu import oracle
    from fastapriori_tpu.io.reader import tokenize_line
    from fastapriori_tpu.preprocess import preprocess

    lines_raw = [
        "\x01 7 8",      # control char trimmed at the start
        "7 8 \x02",      # ...and at the end
        "a\xa0b 7",      # \xa0 is NOT whitespace in Java: one token
        "7 \x01 8",      # mid-line control char is its own token
        "\x03\x04",      # trims to empty -> [""]
        "7 8",
    ]
    py_tokens = [tokenize_line(l) for l in lines_raw]
    assert py_tokens[0] == ["7", "8"]
    assert py_tokens[1] == ["7", "8"]
    assert py_tokens[2] == ["a\xa0b", "7"]
    assert py_tokens[3] == ["7", "\x01", "8"]
    assert py_tokens[4] == [""]
    assert py_tokens[5] == ["7", "8"]
    assert [oracle.tokenize(l) for l in lines_raw] == py_tokens

    # Native vs Python end-to-end on the same raw bytes.
    a = preprocess(py_tokens, 0.3, native=True)
    b = preprocess(py_tokens, 0.3, native=False)
    assert a.freq_items == b.freq_items
    assert (a.item_counts == b.item_counts).all()
    assert (a.weights == b.weights).all()


@pytest.mark.parametrize("seed", range(4))
def test_sharded_preprocess_adversarial_boundaries(tmp_path, seed):
    """Byte-range sharding against adversarial content: control bytes at
    line edges, \\x0b/\\x1c mid-token, blank and whitespace-only lines,
    varying line lengths, no trailing newline — the shards must
    partition the bytes exactly and conserve the total line count (a
    byte-alignment bug would double- or zero-count the boundary line,
    shifting n_raw and minCount).  Weighted-support equivalence over the
    union is covered by test_sharded_preprocess_equivalent_support."""
    import random

    from fastapriori_tpu.native.loader import count_buffer
    from fastapriori_tpu.preprocess import preprocess_file, read_shard

    rng = random.Random(seed)
    pool = ["7", "8", "9", "10", "\x01", "a\x0bb", "7\x1c8", "007", "x"]
    lines = []
    for _ in range(rng.randint(40, 120)):
        r = rng.random()
        if r < 0.08:
            lines.append("")
        elif r < 0.12:
            lines.append("  \t ")
        else:
            lines.append(
                " ".join(rng.choices(pool, k=rng.randint(1, 7)))
            )
    raw = "\n".join(lines)
    if rng.random() < 0.5:
        raw += "\n"
    path = tmp_path / "D.dat"
    path.write_bytes(raw.encode("utf-8"))

    plain = preprocess_file(str(path), 0.1)
    full = path.read_bytes()
    for n in (2, 3, 4, 7):
        parts = [read_shard(str(path), i, n) for i in range(n)]
        assert b"".join(parts) == full, (seed, n)
        # Line-count conservation through the split phases.
        tot = sum(count_buffer(p)[0] for p in parts)
        assert tot == plain.n_raw, (seed, n, tot, plain.n_raw)


def test_read_shard_remote_fsspec(tmp_path):
    """Byte-range sharding of a REMOTE D.dat (fsspec ranged reads) — the
    HDFS case the reference actually ran (Utils.scala:21,
    /root/reference/README.md:22-35).  Shards of the memory:// object
    must equal shards of the same bytes on local disk, and the full
    sharded preprocess must work against the remote URL."""
    import fsspec

    from conftest import random_dataset
    from fastapriori_tpu.preprocess import preprocess_file, read_shard

    d_raw = ["1 2 3"] * 60 + random_dataset(31, n_txns=90, n_items=20)
    raw = "".join(l + "\n" for l in d_raw).encode("utf-8")
    path = tmp_path / "D.dat"
    path.write_bytes(raw)
    with fsspec.open("memory://shard_in/D.dat", "wb") as f:
        f.write(raw)

    for n in (1, 2, 3, 5):
        local = [read_shard(str(path), i, n) for i in range(n)]
        remote = [
            read_shard("memory://shard_in/D.dat", i, n) for i in range(n)
        ]
        assert remote == local
        assert b"".join(remote) == raw

    # Full sharded preprocess against the remote URL (2 simulated
    # processes; the first allgather round is precomputed from remote
    # shard reads, the second from each shard's local stats).
    import pickle

    from fastapriori_tpu.native.loader import (
        compress_with_ranks,
        count_buffer,
    )
    from fastapriori_tpu.preprocess import preprocess_file_sharded

    plain = preprocess_file(str(path), 0.05)
    url = "memory://shard_in/D.dat"
    p1 = [
        pickle.dumps(count_buffer(read_shard(url, i, 2)), 4)
        for i in range(2)
    ]

    def second_round():
        out = []
        for j in range(2):
            _, _, _, wj = compress_with_ranks(
                read_shard(url, j, 2), plain.freq_items
            )
            out.append(
                pickle.dumps((len(wj), int(wj.max()) if len(wj) else 1), 4)
            )
        return out

    for i in range(2):
        calls = {"n": 0}

        def ag(blob, calls=calls):
            calls["n"] += 1
            return p1 if calls["n"] == 1 else second_round()

        s = preprocess_file_sharded(
            url, 0.05, process_id=i, num_processes=2, allgather=ag
        )
        assert s.freq_items == plain.freq_items
        assert s.n_raw == plain.n_raw and s.min_count == plain.min_count


def test_simd_scan_matches_scalar_scan(tmp_path):
    """The AVX-512 pass-1 fast path (digits+whitespace alphabet) must
    produce byte-identical results to the scalar path on the same
    buffer: counts, ranks, baskets, weights, offsets.  FA_NO_SIMD
    forces the scalar path (checked at call time)."""
    import os

    import numpy as np

    from fastapriori_tpu.native.loader import preprocess_buffer_blocks

    rng = np.random.default_rng(31)
    lines = []
    for _ in range(4000):
        k = rng.integers(0, 9)
        toks = rng.integers(0, 900, size=k).astype(str)
        sep = rng.choice([" ", "  ", "\t", " \t", "\x0b"])
        lines.append(sep.join(toks))
    # Edge shapes the masks must survive: empty lines, whitespace-only
    # lines, leading-zero tokens, >7-digit tokens, a 100-digit run that
    # crosses 64-byte block boundaries, no trailing newline.
    lines += ["", "   ", "\t\t", "007 7 07", "12345678901 5", "9" * 100]
    buf = ("\n".join(lines) + " 3 5").encode()

    def run():
        got = []

        def on_block(f, offsets, items, weights):
            got.append(
                (f, offsets.copy(), items.copy(), weights.copy())
            )

        out = preprocess_buffer_blocks(buf, 0.01, 4, on_block)
        return out, got

    os.environ.pop("FA_NO_SIMD", None)
    out_fast, blocks_fast = run()
    os.environ["FA_NO_SIMD"] = "1"
    try:
        out_scalar, blocks_scalar = run()
    finally:
        del os.environ["FA_NO_SIMD"]
    assert out_fast[:2] == out_scalar[:2]  # n_raw, min_count
    assert out_fast[2] == out_scalar[2]  # freq item order
    assert np.array_equal(out_fast[3], out_scalar[3])  # item counts
    assert len(blocks_fast) == len(blocks_scalar)
    for a, b in zip(blocks_fast, blocks_scalar):
        assert a[0] == b[0]
        for x, y in zip(a[1:], b[1:]):
            assert np.array_equal(x, y)
