"""Native C++ preprocessor vs the Python path: exact equality on the same
inputs (tokenization, tie-breaks, dedup, CSR layout)."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.native import native_available
from fastapriori_tpu.preprocess import preprocess, preprocess_file

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native extension not built"
)


def _assert_equal(a, b):
    assert a.n_raw == b.n_raw
    assert a.min_count == b.min_count
    assert a.freq_items == b.freq_items
    assert a.item_to_rank == b.item_to_rank
    assert (a.item_counts == b.item_counts).all()
    # Basket order may differ (Python dict order vs C++ first-seen — both
    # are first-seen, but compare as a multiset to be robust).
    got = {
        tuple(x): int(w)
        for x, w in zip(a.baskets, a.weights)
    }
    expected = {
        tuple(x): int(w)
        for x, w in zip(b.baskets, b.weights)
    }
    assert got == expected


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("min_support", [0.03, 0.1, 0.25])
def test_native_matches_python_random(seed, min_support):
    lines = tokenized(random_dataset(seed))
    _assert_equal(
        preprocess(lines, min_support, native=True),
        preprocess(lines, min_support, native=False),
    )


def test_native_edge_tokens(tmp_path):
    raw = (
        "007 7 7 007\n"  # numeric ties with distinct tokens
        "\n"  # empty line -> single empty token
        "\t  x\t y  \n"  # tabs, non-numeric tokens
        "-3 +4 -3\n"  # signed integers
        "x 007 7\n"
        "99999999999999999999999 1\n"  # > int64: lexical fallback
        "99999999999999999999999 1"
    )
    p = tmp_path / "D.dat"
    p.write_text(raw)
    a = preprocess_file(str(p), 0.2, native=True)
    b = preprocess_file(str(p), 0.2, native=False)
    _assert_equal(a, b)


def test_native_file_no_trailing_newline(tmp_path):
    p = tmp_path / "D.dat"
    p.write_text("1 2\n1 2\n1 3")
    a = preprocess_file(str(p), 0.3, native=True)
    b = preprocess_file(str(p), 0.3, native=False)
    assert a.n_raw == 3
    _assert_equal(a, b)


def test_native_crlf(tmp_path):
    p = tmp_path / "D.dat"
    p.write_bytes(b"1 2\r\n1 2\r\n2 3\r\n")
    a = preprocess_file(str(p), 0.3, native=True)
    b = preprocess_file(str(p), 0.3, native=False)
    _assert_equal(a, b)


def test_native_empty_file(tmp_path):
    p = tmp_path / "D.dat"
    p.write_text("")
    a = preprocess_file(str(p), 0.5, native=True)
    assert a.n_raw == 0 and a.num_items == 0 and a.total_count == 0


def test_native_large_weights():
    # >128 duplicate baskets (two weight digits downstream) and >=2^15 rows.
    lines = tokenized(["1 2 3"] * 300 + ["4 5"] * 2 + ["1 2"] * 40000)
    _assert_equal(
        preprocess(lines, 0.001, native=True),
        preprocess(lines, 0.001, native=False),
    )


def test_miner_with_native_preprocess_end_to_end(tmp_path):
    from fastapriori_tpu import oracle
    from fastapriori_tpu.models.apriori import FastApriori

    raw = random_dataset(9, n_txns=300)
    p = tmp_path / "D.dat"
    p.write_text("\n".join(raw) + "\n")
    expected, _, _ = oracle.mine(tokenized(raw), 0.05)

    miner = FastApriori(0.05, num_devices=1)
    data = preprocess_file(str(p), 0.05, native=True)
    got = miner.mine_compressed(data)
    assert dict(got) == dict(expected)


def test_native_packed_bitmap_matches_numpy():
    # The native bit-filler and the dense-build + packbits fallback must
    # produce identical packed bytes (MSB-first within each byte).
    from fastapriori_tpu.native.loader import fill_packed_bitmap, get_lib
    from fastapriori_tpu.ops.bitmap import (
        build_bitmap_csr,
        build_packed_bitmap_csr,
    )

    if get_lib() is None:
        import pytest

        pytest.skip("native library unavailable")
    rng = np.random.default_rng(5)
    baskets = [
        np.unique(rng.integers(0, 300, size=rng.integers(2, 20)))
        for _ in range(57)
    ]
    indices = np.concatenate(baskets).astype(np.int32)
    offsets = np.concatenate(
        [[0], np.cumsum([len(b) for b in baskets])]
    ).astype(np.int64)
    packed, f_pad = build_packed_bitmap_csr(indices, offsets, 300, 32, 128)
    dense = build_bitmap_csr(indices, offsets, 300, 32, 128)
    assert packed.shape == (dense.shape[0], f_pad // 8)
    assert (np.packbits(dense.astype(bool), axis=1) == packed).all()


def test_native_large_f_sort_fallback():
    # F > 4096 frequent items bypasses the bitset per-line sort in the
    # native scanner's pass 2 (std::sort fallback) — equality with the
    # Python path must hold there too.
    import random

    rng = random.Random(3)
    n_items = 5000
    lines = tokenized(
        [
            " ".join(
                str(rng.randint(1, n_items)) for _ in range(rng.randint(2, 8))
            )
            for _ in range(4000)
        ]
    )
    a = preprocess(lines, 0.0001, native=True)
    b = preprocess(lines, 0.0001, native=False)
    assert a.num_items > 4096, a.num_items
    _assert_equal(a, b)
