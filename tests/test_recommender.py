"""Recommender (C10+C12): device containment kernel vs host scan vs oracle."""

import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu import oracle
from fastapriori_tpu.models.recommender import AssociationRules
from fastapriori_tpu.parallel.mesh import DeviceContext


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("use_device", [False, True])
def test_recommender_matches_oracle(seed, use_device):
    d_lines = tokenized(random_dataset(seed))
    u_lines = tokenized(random_dataset(seed + 50, n_txns=40))
    itemsets, item_to_rank, freq_items = oracle.mine(d_lines, 0.08)
    rules = oracle.gen_rules(itemsets)
    expected = oracle.recommend(u_lines, rules, freq_items, item_to_rank)

    rec = AssociationRules(itemsets, freq_items, item_to_rank)
    got = rec.run(u_lines, use_device=use_device)
    assert sorted(got) == sorted(expected)


def test_recommender_empty_rules(tiny_u_lines):
    # No frequent itemsets of size >= 2 -> no rules -> all "0".
    itemsets = [(frozenset((0,)), 5), (frozenset((1,)), 4)]
    rec = AssociationRules(itemsets, ["1", "2"], {"1": 0, "2": 1})
    got = rec.run(tiny_u_lines)
    assert got == [(i, "0") for i in range(len(tiny_u_lines))] or sorted(
        got
    ) == sorted((i, "0") for i in range(len(tiny_u_lines)))


def test_host_scan_matches_scalar_reference():
    """The vectorized host first-match (the bench baseline since ISSUE 4
    gave it the full user population) must equal the reference's scalar
    per-rule scan (AssociationRules.scala:88-102) rule for rule."""
    import numpy as np

    d_lines = tokenized(random_dataset(11, n_txns=300, max_len=8))
    u_lines = tokenized(random_dataset(77, n_txns=120))
    itemsets, item_to_rank, freq_items = oracle.mine(d_lines, 0.04)
    rec = AssociationRules(itemsets, freq_items, item_to_rank)
    from fastapriori_tpu.preprocess import dedup_user_baskets

    baskets, _, _ = dedup_user_baskets(u_lines, item_to_rank)
    rec._ensure_rules()
    got = rec._host_first_match(baskets)

    prepared = [
        (frozenset(a), c, len(a)) for a, c, _ in rec._rule_objects()
    ]
    for b, g in zip(baskets, got):
        basket = frozenset(int(x) for x in b)
        want = -1
        for ant, cons, size in prepared:
            if (
                size <= len(basket)
                and cons not in basket
                and ant <= basket
            ):
                want = cons
                break
        assert g == want


def test_recommender_no_users():
    itemsets = [
        (frozenset((0,)), 5),
        (frozenset((1,)), 4),
        (frozenset((0, 1)), 3),
    ]
    rec = AssociationRules(itemsets, ["1", "2"], {"1": 0, "2": 1})
    assert rec.run([]) == []


def test_recommender_dedup_fanout():
    # Identical baskets must all receive the fanned-out recommendation
    # (AssociationRules.scala:104-105).
    itemsets = [
        (frozenset((0,)), 6),
        (frozenset((1,)), 5),
        (frozenset((0, 1)), 4),
    ]
    u_lines = tokenized(["1", "1", "2", "zzz"])
    rec = AssociationRules(itemsets, ["1", "2"], {"1": 0, "2": 1})
    got = dict(rec.run(u_lines))
    # basket {1} -> rule {0}->1 fires -> item "2"; basket {2} -> item "1";
    # unknown item -> "0".
    assert got == {0: "2", 1: "2", 2: "1", 3: "0"}


@pytest.mark.parametrize("rule_chunk", [128, 256])
def test_device_first_match_resident_scan(rule_chunk):
    # Force the on-device while_loop scan across several chunks of the
    # resident rule table; must agree with the host scan exactly
    # (including users whose first match lands in a late chunk and users
    # with no match at all, who pin the loop to full length).
    from fastapriori_tpu.config import MinerConfig

    d_lines = tokenized(
        random_dataset(23, n_txns=200, n_items=30, max_len=8)
    )
    u_lines = tokenized(
        random_dataset(24, n_txns=80, n_items=30)
        + ["", "999 998"]  # empty + all-infrequent baskets
    )
    itemsets, item_to_rank, freq_items = oracle.mine(d_lines, 0.02)
    rules = oracle.gen_rules(itemsets)
    assert len(rules) > 256, len(rules)  # several chunks at both params
    cfg = MinerConfig(
        min_support=0.02, num_devices=8, rule_chunk=rule_chunk,
    )
    rec = AssociationRules(
        itemsets, freq_items, item_to_rank, config=cfg,
        context=DeviceContext(num_devices=8),
    )
    rec_dev = rec.run(u_lines, use_device=True)
    rec_host = AssociationRules(
        itemsets, freq_items, item_to_rank, config=cfg,
        context=DeviceContext(num_devices=1),
    ).run(u_lines, use_device=False)
    assert sorted(rec_dev) == sorted(rec_host)
    # The resident table is uploaded once per instance: a second run
    # must reuse it and still agree.
    assert rec._rule_dev is not None
    assert sorted(rec.run(u_lines, use_device=True)) == sorted(rec_host)
