"""Pallas fused counting kernel vs the plain jnp formulation (interpret
mode on CPU; tests_tpu/test_pallas_hw.py runs it compiled on the chip).

The kernel is a REFERENCE implementation, not wired into the engine: at
production shapes it measured parity with the XLA level kernel on v5e
(round 3), so the engine keeps the single XLA path; the kernel stays as
the VMEM-resident formulation for future wider-item workloads."""

import numpy as np
import pytest
import jax.numpy as jnp

from fastapriori_tpu.ops.pallas_level import (
    M_TILE,
    T_TILE,
    level_counts_pallas,
)


def _case(seed, t, m, f, k, max_w=5, n_digits=1):
    rng = np.random.default_rng(seed)
    bitmap = (rng.random((t, f)) < 0.2).astype(np.int8)
    s = np.zeros((m, f), dtype=np.int8)
    # valid prefix rows of size k-1
    for i in range(m // 2):
        cols = rng.choice(f, size=k - 1, replace=False)
        s[i, cols] = 1
    w = rng.integers(1, max_w + 1, size=t).astype(np.int64)
    digits = []
    rem = w.copy()
    for _ in range(n_digits):
        digits.append((rem % 128).astype(np.int8))
        rem //= 128
    assert (rem == 0).all()
    w_digits = np.stack(digits)
    return bitmap, w, w_digits, s


def _expected(bitmap, w, s, k):
    overlap = bitmap.astype(np.int64) @ s.astype(np.int64).T  # [T, M]
    common = overlap == (k - 1)
    return ((common * w[:, None]).T @ bitmap.astype(np.int64)).astype(
        np.int64
    )


@pytest.mark.parametrize("k", [2, 3, 5])
def test_pallas_level_counts_interpret(k):
    bitmap, w, w_digits, s = _case(0, T_TILE * 2, M_TILE, 256, k)
    got = np.asarray(
        level_counts_pallas(
            jnp.asarray(bitmap),
            jnp.asarray(w_digits),
            jnp.asarray(s),
            jnp.int32(k - 1),
            interpret=True,
        )
    )
    assert (got == _expected(bitmap, w, s, k)).all()


def test_pallas_level_counts_two_digits():
    bitmap, w, w_digits, s = _case(
        1, T_TILE, M_TILE, 128, 3, max_w=300, n_digits=2
    )
    got = np.asarray(
        level_counts_pallas(
            jnp.asarray(bitmap),
            jnp.asarray(w_digits),
            jnp.asarray(s),
            jnp.int32(2),
            interpret=True,
        )
    )
    assert (got == _expected(bitmap, w, s, 3)).all()


def test_pallas_multiple_m_tiles():
    bitmap, w, w_digits, s = _case(2, T_TILE, M_TILE * 2, 128, 3)
    got = np.asarray(
        level_counts_pallas(
            jnp.asarray(bitmap),
            jnp.asarray(w_digits),
            jnp.asarray(s),
            jnp.int32(2),
            interpret=True,
        )
    )
    assert (got == _expected(bitmap, w, s, 3)).all()
