"""Pallas fused counting kernel vs the plain numpy formulation
(interpret mode on CPU; tests_tpu/test_pallas_hw.py runs it compiled on
the chip).

The kernel IS wired into the mining engine (parallel/mesh.py
level_gather_batch picks it on TPU backends with a single weight digit
and tile-divisible shapes); these tests pin its semantics —
``counts[m, f] = Σ_t w_t · [basket t ⊇ prefix m] · B[t, f]`` with the
weights pre-folded into ``wb = w ⊙ B``."""

import numpy as np
import pytest
import jax.numpy as jnp

from fastapriori_tpu.ops.pallas_level import (
    level_counts_pallas,
    pick_tile,
)

# Interpret mode is slow: keep test tiles small.
T_TILE = 512
M_TILE = 512


def _case(seed, t, m, f, k, max_w=5):
    rng = np.random.default_rng(seed)
    bitmap = (rng.random((t, f)) < 0.2).astype(np.int8)
    s = np.zeros((m, f), dtype=np.int8)
    # valid prefix rows of size k-1
    for i in range(m // 2):
        cols = rng.choice(f, size=k - 1, replace=False)
        s[i, cols] = 1
    w = rng.integers(1, max_w + 1, size=t).astype(np.int64)
    wb = (bitmap * w[:, None]).astype(np.int8)
    return bitmap, w, wb, s


def _expected(bitmap, w, s, k):
    overlap = bitmap.astype(np.int64) @ s.astype(np.int64).T  # [T, M]
    common = overlap == (k - 1)
    return ((common * w[:, None]).T @ bitmap.astype(np.int64)).astype(
        np.int64
    )


def _run(bitmap, wb, s, km1):
    return np.asarray(
        level_counts_pallas(
            jnp.asarray(bitmap),
            jnp.asarray(wb),
            jnp.asarray(s),
            jnp.int32(km1),
            t_tile=T_TILE,
            m_tile=M_TILE,
            interpret=True,
        )
    )


@pytest.mark.parametrize("k", [2, 3, 5])
def test_pallas_level_counts_interpret(k):
    bitmap, w, wb, s = _case(0, T_TILE * 2, M_TILE, 256, k)
    got = _run(bitmap, wb, s, k - 1)
    assert (got == _expected(bitmap, w, s, k)).all()


def test_pallas_multiple_m_tiles():
    bitmap, w, wb, s = _case(2, T_TILE, M_TILE * 2, 128, 3)
    got = _run(bitmap, wb, s, 2)
    assert (got == _expected(bitmap, w, s, 3)).all()


def test_pick_tile():
    assert pick_tile(4096 * 13) == 4096
    assert pick_tile(1024 * 3) == 1024
    assert pick_tile(256 * 5) == 256
    assert pick_tile(100) == 0
