"""Device-vs-host rule-generation differential suite (ISSUE 4 tentpole).

The device engine (rules/gen.py `_rule_arrays_device` + ops/contain.py
`rule_level_kernel`) must be BIT-IDENTICAL to the host engine — same
antecedent/consequent arrays, byte-identical f64 confidences, same
order — on every corpus, including the no-rules datasets; plus the
engine-selection contract (config.rule_engine / FA_RULE_ENGINE, the
count gate, the size floor) and the failpoint sites on the new
upload/fetch path with a kill-and-resume case.  CPU-only."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.parallel.mesh import DeviceContext
from fastapriori_tpu.preprocess import preprocess
from fastapriori_tpu.reliability import failpoints, ledger
from fastapriori_tpu.rules.gen import (
    _level_tables,
    _rule_arrays_device,
    _rule_arrays_host,
    rule_arrays_from_tables,
    rule_engine_from_env,
)


@pytest.fixture(autouse=True)
def _clean_state():
    failpoints.disarm_all()
    ledger.reset()
    yield
    failpoints.disarm_all()
    ledger.reset()


@pytest.fixture(scope="module")
def ctx():
    return DeviceContext(num_devices=1)


def _mined_tables(seed, min_support, n_txns=250, max_len=8, lines=None):
    lines = lines if lines is not None else tokenized(
        random_dataset(seed, n_txns=n_txns, max_len=max_len)
    )
    data = preprocess(lines, min_support)
    miner = FastApriori(
        config=MinerConfig(
            min_support=min_support, engine="level", num_devices=1
        )
    )
    levels = miner.mine_levels_raw(data)
    return _level_tables(levels, data.item_counts)


def _assert_bit_identical(host, dev):
    assert len(host) == len(dev)
    for (ha, hc, hf), (da, dc, df) in zip(host, dev):
        assert np.array_equal(ha, da)
        assert np.array_equal(hc, dc)
        # Confidences must agree BITWISE (both sides divide the same
        # ints in f64; the device only located the denominators).
        assert hf.tobytes() == df.tobytes()


@pytest.mark.parametrize(
    "seed,min_support",
    [(0, 0.05), (1, 0.1), (2, 0.05), (3, 0.08), (4, 0.02), (5, 0.15)],
)
def test_device_matches_host_bit_exact(ctx, seed, min_support):
    mats = _mined_tables(seed, min_support)
    _assert_bit_identical(
        _rule_arrays_host(mats), _rule_arrays_device(mats, ctx)
    )


def test_device_matches_host_deep_lattice(ctx):
    """A dense corpus driving the lattice to k >= 5 (incl. an empty top
    rule level), the multi-column-key path for 8-bit ranks."""
    lines = tokenized(
        ["1 2 3 4 5 6"] * 50
        + ["1 2 3 4 5"] * 30
        + ["2 3 4 5 6"] * 20
        + random_dataset(5, n_txns=60, max_len=6)
    )
    mats = _mined_tables(0, 0.05, lines=lines)
    assert max(mats) >= 5  # the corpus must actually reach depth
    _assert_bit_identical(
        _rule_arrays_host(mats), _rule_arrays_device(mats, ctx)
    )


def _remap_ranks(mats, mult, off, f_big):
    """Widen the item space (rank -> rank*mult + off) without changing
    the lattice — exercises the 16/32-bit key packing and multi-column
    lexicographic search paths."""
    out = {}
    for k, (mat, cnts) in mats.items():
        if k == 1:
            m = np.arange(f_big, dtype=np.int32)[:, None]
            c = np.ones(f_big, dtype=np.int64)
            c[mats[1][0][:, 0] * mult + off] = mats[1][1]
            out[1] = (m, c)
        else:
            out[k] = ((mat * mult + off).astype(np.int32), cnts)
    return out


@pytest.mark.parametrize(
    "mult,off,f_big",
    [
        (600, 3, 600 * 20 + 10),  # f > 256: 16-bit ranks, 2 per lane
        (9000, 7, 9000 * 20 + 10),  # f > 65536: 32-bit ranks, 1 per lane
    ],
)
def test_device_matches_host_wide_keys(ctx, mult, off, f_big):
    mats = _remap_ranks(_mined_tables(2, 0.05), mult, off, f_big)
    _assert_bit_identical(
        _rule_arrays_host(mats), _rule_arrays_device(mats, ctx)
    )


def test_device_no_rules_corpora(ctx):
    """The no-rules datasets: empty tables, singletons only, and a
    corpus whose frequent itemsets stop at size 1."""
    assert _rule_arrays_device({}, ctx) == []
    singles = {
        1: (
            np.arange(3, dtype=np.int32)[:, None],
            np.array([5, 4, 3], dtype=np.int64),
        )
    }
    assert _rule_arrays_device(singles, ctx) == []
    # Real corpus with support too high for any pair to survive.
    lines = tokenized(random_dataset(9, n_txns=60, max_len=3))
    mats = _mined_tables(9, 0.9, lines=lines)
    assert max(mats) == 1
    assert _rule_arrays_host(mats) == []
    assert _rule_arrays_device(mats, ctx) == []


def test_device_downward_closure_errors(ctx):
    mats = _mined_tables(0, 0.05)
    assert max(mats) >= 3
    missing_level = dict(mats)
    missing_level.pop(2)
    with pytest.raises(InputError, match="downward-closed"):
        _rule_arrays_device(missing_level, ctx)
    # Drop one 2-itemset row that a 3-itemset references: the device
    # join's miss counter must surface as the same InputError class.
    torn = {k: (m.copy(), c.copy()) for k, (m, c) in mats.items()}
    m2, c2 = torn[2]
    torn[2] = (m2[1:], c2[1:])
    with pytest.raises(InputError, match="downward-closed"):
        _rule_arrays_device(torn, ctx)
    with pytest.raises(InputError, match="downward-closed"):
        _rule_arrays_host(torn)


# ---------------------------------------------------------------------------
# engine selection


def test_auto_stays_on_host_below_floor_and_on_cpu(ctx):
    """Small corpora (and cpu platforms generally) keep the host engine
    under "auto" — no device events, no ledger entries."""
    mats = _mined_tables(1, 0.05)
    cfg = MinerConfig(rule_engine="auto")
    out = rule_arrays_from_tables(mats, context=ctx, config=cfg)
    _assert_bit_identical(_rule_arrays_host(mats), out)
    assert not [
        e for e in ledger.snapshot() if e["kind"] == "rule_gen_engine"
    ]


def test_forced_device_records_engine_choice(ctx):
    mats = _mined_tables(1, 0.05)
    cfg = MinerConfig(rule_engine="device")
    out = rule_arrays_from_tables(mats, context=ctx, config=cfg)
    _assert_bit_identical(_rule_arrays_host(mats), out)
    evs = [e for e in ledger.snapshot() if e["kind"] == "rule_gen_engine"]
    assert evs and evs[0]["engine"] == "device"


def test_count_gate_falls_back_to_host_with_ledger(ctx):
    """Counts >= 2^24 break the exact-compare equivalence — the device
    path must REFUSE (host fallback + ledger event), not miscompare."""
    mats = {
        k: (m.copy(), c.copy())
        for k, (m, c) in _mined_tables(1, 0.05).items()
    }
    mats[1][1][0] = 1 << 24  # push one count past the gate
    cfg = MinerConfig(rule_engine="device")
    out = rule_arrays_from_tables(mats, context=ctx, config=cfg)
    _assert_bit_identical(_rule_arrays_host(mats), out)
    evs = [e for e in ledger.snapshot() if e["kind"] == "rule_gen_fallback"]
    assert evs and evs[0]["reason"] == "counts_exceed_2^24"


def test_forced_device_without_context_falls_back(ctx):
    mats = _mined_tables(1, 0.05)
    cfg = MinerConfig(rule_engine="device")
    out = rule_arrays_from_tables(mats, context=None, config=cfg)
    _assert_bit_identical(_rule_arrays_host(mats), out)
    evs = [e for e in ledger.snapshot() if e["kind"] == "rule_gen_fallback"]
    assert evs and evs[0]["reason"] == "no_device_context"


def test_rule_engine_config_strictly_parsed(ctx):
    mats = _mined_tables(1, 0.05)
    cfg = MinerConfig(rule_engine="devcie")  # the typo class
    with pytest.raises(InputError, match="rule_engine"):
        rule_arrays_from_tables(mats, context=ctx, config=cfg)


def test_rule_engine_env_strictly_parsed(monkeypatch):
    monkeypatch.setenv("FA_RULE_ENGINE", "device")
    assert rule_engine_from_env() == "device"
    monkeypatch.setenv("FA_RULE_ENGINE", "  HOST ")
    assert rule_engine_from_env() == "host"
    monkeypatch.delenv("FA_RULE_ENGINE")
    assert rule_engine_from_env() is None
    monkeypatch.setenv("FA_RULE_ENGINE", "devcie")  # the typo class
    with pytest.raises(InputError, match="FA_RULE_ENGINE"):
        rule_engine_from_env()


def test_env_overrides_config(ctx, monkeypatch):
    monkeypatch.setenv("FA_RULE_ENGINE", "device")
    mats = _mined_tables(3, 0.08)
    cfg = MinerConfig(rule_engine="host")  # env wins
    rule_arrays_from_tables(mats, context=ctx, config=cfg)
    assert [e for e in ledger.snapshot() if e["kind"] == "rule_gen_engine"]


# ---------------------------------------------------------------------------
# failpoints on the upload/fetch path + kill-and-resume


def test_upload_failpoint_fires(ctx):
    mats = _mined_tables(0, 0.05)
    failpoints.arm("rules.upload", "io*1")
    with pytest.raises(OSError, match="injected"):
        _rule_arrays_device(mats, ctx)


@pytest.mark.parametrize("site", ["fetch.rule_mask", "fetch.rule_counts"])
def test_transient_fetch_fault_is_absorbed(ctx, site):
    """A one-shot RESOURCE_EXHAUSTED on the mask or denominator fetch is
    a transient: the audited retry path absorbs it and the output stays
    bit-identical (with the retry on the ledger)."""
    mats = _mined_tables(0, 0.05)
    clean = _rule_arrays_host(mats)
    failpoints.arm(site, "oom*1")
    _assert_bit_identical(clean, _rule_arrays_device(mats, ctx))
    retries = [e for e in ledger.snapshot() if e["kind"] == "retry"]
    assert retries and retries[0]["site"] == site


def test_kill_and_resume_bit_exact(ctx, tmp_path):
    """Kill-and-resume on the rule path: a hard abort mid-phase-2 (the
    mask fetch) leaves the phase-1 mining artifacts intact; the resumed
    run regenerates the rules from them bit-identically — the CLI's
    --resume-from phase-1 restart shape, driven in-process."""
    from fastapriori_tpu.io import checkpoint as ckpt

    lines = tokenized(random_dataset(4, n_txns=250, max_len=8))
    data = preprocess(lines, 0.05)
    miner = FastApriori(
        config=MinerConfig(min_support=0.05, engine="level", num_devices=1)
    )
    levels = miner.mine_levels_raw(data)
    # Persist the mining result the way a checkpointing run would.
    prefix = str(tmp_path) + "/"
    ckpt.save_checkpoint(
        prefix,
        levels,
        {
            "n_raw": data.n_raw,
            "min_count": data.min_count,
            "num_items": data.num_items,
        },
    )
    mats = _level_tables(levels, data.item_counts)
    clean = _rule_arrays_device(mats, ctx)

    failpoints.arm("fetch.rule_mask", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        _rule_arrays_device(mats, ctx)
    failpoints.disarm_all()

    # Resume: reload the checkpointed levels (what --resume-from does)
    # and regenerate — bit-identical to the uninterrupted run.
    got_levels, meta = ckpt.load_checkpoint(prefix)
    ckpt.check_meta(
        meta,
        n_raw=data.n_raw,
        min_count=data.min_count,
        num_items=data.num_items,
        prefix=prefix,
    )
    resumed = _rule_arrays_device(
        _level_tables(got_levels, data.item_counts), ctx
    )
    _assert_bit_identical(clean, resumed)


# ---------------------------------------------------------------------------
# end-to-end: the recommender pipeline over the device engine


def test_recommender_device_rule_engine_matches_host_engine():
    """AssociationRules with rule_engine="device" must recommend exactly
    what the host engine recommends (same rules, same priority order,
    same first match)."""
    from fastapriori_tpu.models.recommender import AssociationRules

    d_lines = tokenized(random_dataset(6, n_txns=250, max_len=8))
    u_lines = tokenized(random_dataset(60, n_txns=50))
    data = preprocess(d_lines, 0.05)
    outs = {}
    for engine in ("host", "device"):
        cfg = MinerConfig(
            min_support=0.05, engine="level", num_devices=1,
            rule_engine=engine,
        )
        miner = FastApriori(config=cfg)
        levels = miner.mine_levels_raw(data)
        rec = AssociationRules(
            [], data.freq_items, data.item_to_rank, config=cfg,
            context=miner.context, levels=levels,
            item_counts=data.item_counts,
        )
        outs[engine] = rec.run(u_lines)
        if engine == "device":
            assert rec._rule_arrays is not None
    assert outs["host"] == outs["device"]
