"""End-to-end golden tests: CLI output files byte-identical to the oracle
pipeline (SURVEY.md §4), plus resume round-trip."""

import pytest

from conftest import random_dataset
from fastapriori_tpu import oracle
from fastapriori_tpu.cli import main
from fastapriori_tpu.io.reader import read_dat, read_input_dir, tokenize_line


def _write_inputs(tmp_path, d_raw, u_raw):
    (tmp_path / "in").mkdir()
    (tmp_path / "out").mkdir()
    (tmp_path / "in" / "D.dat").write_text(
        "".join(l + "\n" for l in d_raw)
    )
    (tmp_path / "in" / "U.dat").write_text(
        "".join(l + "\n" for l in u_raw)
    )
    return str(tmp_path / "in") + "/", str(tmp_path / "out") + "/"


@pytest.mark.parametrize("seed", [0, 3])
def test_cli_end_to_end_matches_oracle(tmp_path, seed):
    d_raw = random_dataset(seed)
    u_raw = random_dataset(seed + 10, n_txns=25)
    inp, outp = _write_inputs(tmp_path, d_raw, u_raw)

    rc = main([inp, outp, "ignored-tmp-arg", "--min-support", "0.08"])
    assert rc == 0

    d_lines = [tokenize_line(l) for l in d_raw]
    u_lines = [tokenize_line(l) for l in u_raw]
    exp_freq, exp_rec = oracle.run_pipeline(d_lines, u_lines, 0.08)

    assert (tmp_path / "out" / "freqItemset").read_text() == exp_freq
    assert (tmp_path / "out" / "recommends").read_text() == exp_rec


def test_cli_resume_round_trip(tmp_path):
    d_raw = random_dataset(1)
    u_raw = random_dataset(11, n_txns=20)
    inp, outp = _write_inputs(tmp_path, d_raw, u_raw)

    rc = main([inp, outp, "--min-support", "0.08", "--save-counts"])
    assert rc == 0
    rec_first = (tmp_path / "out" / "recommends").read_text()

    # Re-run phase 2 only from the saved artifacts into a fresh output dir.
    (tmp_path / "out2").mkdir()
    outp2 = str(tmp_path / "out2") + "/"
    rc = main([inp, outp2, "--resume-from", outp])
    assert rc == 0
    assert (tmp_path / "out2" / "recommends").read_text() == rec_first


def test_reader_round_trip(tmp_path):
    inp, _ = _write_inputs(tmp_path, ["1 2", "", " 3 "], ["7"])
    d, u = read_input_dir(inp)
    assert d == [["1", "2"], [""], ["3"]]
    assert u == [["7"]]


@pytest.mark.parametrize("seed", range(20, 26))
def test_cli_fuzz_adversarial_tokens_matches_oracle(tmp_path, seed):
    # Full-pipeline fuzz over token forms that straddle the native
    # scanner's dense/side split: canonical decimal ids, non-canonical
    # numerics ("007", "+5", "-3" — distinct tokens from "7"/"5"/"3"),
    # the 7-digit dense-id boundary, arbitrary-precision integers
    # (BigInt rank ordering), and non-numeric tokens.
    import random

    rng = random.Random(seed)
    pool = (
        [str(i) for i in range(1, 10)]
        + ["007", "0", "9999999", "12345678", "x9", "+5", "-3",
           "99999999999999999999"]
    )
    d_raw = [
        " ".join(rng.choices(pool, k=rng.randint(1, 6))) for _ in range(70)
    ] + ["", "  007 7 007  ", "\t0 0\t"]
    u_raw = [
        " ".join(rng.choices(pool, k=rng.randint(1, 4))) for _ in range(20)
    ] + [""]
    min_support = rng.choice([0.05, 0.1, 0.25])
    inp, outp = _write_inputs(tmp_path, d_raw, u_raw)

    rc = main([inp, outp, "--min-support", str(min_support)])
    assert rc == 0

    d_lines = [tokenize_line(l) for l in d_raw]
    u_lines = [tokenize_line(l) for l in u_raw]
    exp_freq, exp_rec = oracle.run_pipeline(d_lines, u_lines, min_support)
    assert (tmp_path / "out" / "freqItemset").read_text() == exp_freq
    assert (tmp_path / "out" / "recommends").read_text() == exp_rec


def test_cli_end_to_end_remote_output_via_fsspec(tmp_path):
    # Remote OUTPUT prefix (the reference saved its results to HDFS,
    # Utils.scala:36-40,48): the full CLI pipeline writing freqItemset /
    # recommends to fsspec's in-process memory filesystem, byte-identical
    # to a local run.  Resume artifacts round-trip remotely too.
    fsspec = pytest.importorskip("fsspec")
    d_raw = random_dataset(5)
    u_raw = random_dataset(15, n_txns=20)
    inp, outp = _write_inputs(tmp_path, d_raw, u_raw)

    rc = main([inp, outp, "--min-support", "0.08"])
    assert rc == 0
    rc = main(
        [inp, "memory://fa_out/", "--min-support", "0.08", "--save-counts"]
    )
    assert rc == 0

    fs = fsspec.filesystem("memory")
    for name in ("freqItemset", "recommends"):
        assert (
            fs.cat(f"/fa_out/{name}").decode()
            == (tmp_path / "out" / name).read_text()
        )
    # Phase-2-only resume FROM the remote artifacts into a local dir.
    (tmp_path / "out2").mkdir()
    outp2 = str(tmp_path / "out2") + "/"
    rc = main([inp, outp2, "--resume-from", "memory://fa_out/"])
    assert rc == 0
    assert (tmp_path / "out2" / "recommends").read_text() == (
        tmp_path / "out" / "recommends"
    ).read_text()


def test_reader_remote_path_via_fsspec():
    # The "://"-triggered fsspec branch (HDFS/GCS analog of the
    # reference's sc.textFile over HDFS, Utils.scala:21) — exercised with
    # fsspec's in-process memory filesystem.
    fsspec = pytest.importorskip("fsspec")
    with fsspec.open("memory://fa_test/D.dat", "w") as f:
        f.write("1 2\n\n 3  1 \n")
    assert read_dat("memory://fa_test/D.dat") == [
        ["1", "2"],
        [""],
        ["3", "1"],
    ]
