"""Sharded rule generation + device-resident priority scan differential
suite (ISSUE 8 tentpole).

The sharded join engine (rules/gen.py `_rule_arrays_device(shards=S)` +
ops/contain.py `rule_level_shard_kernel`) must be BIT-IDENTICAL to the
host oracle — same rule set, byte-identical f64 confidences, same order
— on every corpus shape at 1/2/4/8 virtual devices, still one dispatch
per level, with per-level psum/gather bytes recorded; and the
recommender's device-resident scan (conf-desc 49-bit key device sort +
rank-strided sharded first-match) must recommend exactly what the host
scan recommends at every device count.  CPU-only."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.parallel.mesh import DeviceContext
from fastapriori_tpu.preprocess import preprocess
from fastapriori_tpu.reliability import failpoints, ledger
from fastapriori_tpu.rules.gen import (
    DeviceRuleState,
    _level_tables,
    _rule_arrays_device,
    _rule_arrays_host,
    resolve_rule_shards,
)
from fastapriori_tpu.utils.logging import MetricsLogger


@pytest.fixture(autouse=True)
def _clean_state():
    failpoints.disarm_all()
    ledger.reset()
    yield
    failpoints.disarm_all()
    ledger.reset()


_CTXS = {}


def _ctx(n):
    # Module-lifetime contexts: a DeviceContext caches its compiled
    # kernels, so the 4 device counts compile once each.
    if n not in _CTXS:
        _CTXS[n] = DeviceContext(num_devices=n)
    return _CTXS[n]


def _mined_tables(seed, min_support, n_txns=250, max_len=8, lines=None):
    lines = lines if lines is not None else tokenized(
        random_dataset(seed, n_txns=n_txns, max_len=max_len)
    )
    data = preprocess(lines, min_support)
    miner = FastApriori(
        config=MinerConfig(
            min_support=min_support, engine="level", num_devices=1
        )
    )
    levels = miner.mine_levels_raw(data)
    return _level_tables(levels, data.item_counts), data


def _assert_bit_identical(host, dev):
    assert len(host) == len(dev)
    for (ha, hc, hf), (da, dc, df) in zip(host, dev):
        assert np.array_equal(ha, da)
        assert np.array_equal(hc, dc)
        assert hf.tobytes() == df.tobytes()


def _wide_key_remap(mats, mult, off, f_big):
    out = {}
    for k, (mat, cnts) in mats.items():
        if k == 1:
            m = np.arange(f_big, dtype=np.int32)[:, None]
            c = np.ones(f_big, dtype=np.int64)
            c[mats[1][0][:, 0] * mult + off] = mats[1][1]
            out[1] = (m, c)
        else:
            out[k] = ((mat * mult + off).astype(np.int32), cnts)
    return out


def _corpus(shape):
    """The 4 corpus shapes of the differential matrix."""
    if shape == "random":
        return _mined_tables(0, 0.05)[0]
    if shape == "deep":
        lines = tokenized(
            ["1 2 3 4 5 6"] * 50
            + ["1 2 3 4 5"] * 30
            + ["2 3 4 5 6"] * 20
            + random_dataset(5, n_txns=60, max_len=6)
        )
        mats = _mined_tables(0, 0.05, lines=lines)[0]
        assert max(mats) >= 5
        return mats
    if shape == "wide_keys":
        return _wide_key_remap(
            _mined_tables(2, 0.05)[0], 600, 3, 600 * 20 + 10
        )
    assert shape == "no_rules"
    lines = tokenized(random_dataset(9, n_txns=60, max_len=3))
    mats = _mined_tables(9, 0.9, lines=lines)[0]
    assert max(mats) == 1
    return mats


@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
@pytest.mark.parametrize(
    "shape", ["random", "deep", "wide_keys", "no_rules"]
)
def test_sharded_join_bit_exact(shape, n_dev):
    mats = _corpus(shape)
    host = _rule_arrays_host(mats)
    state = DeviceRuleState()
    dev = _rule_arrays_device(
        mats, _ctx(n_dev), shards=n_dev, state=state
    )
    _assert_bit_identical(host, dev)
    if host:
        assert state.ready
        assert state.shards == n_dev
        assert state.total == sum(len(c) for _, c, _ in host)


@pytest.mark.parametrize("n_dev", [2, 4])
def test_sharded_join_records_comms_and_one_dispatch_per_level(n_dev):
    mats = _corpus("random")
    metrics = MetricsLogger(enabled=False)
    _rule_arrays_device(mats, _ctx(n_dev), metrics=metrics, shards=n_dev)
    ev = [
        r for r in metrics.records if r.get("event") == "rule_gen_device"
    ][-1]
    n_levels = len([k for k in mats if k >= 2])
    assert ev["shards"] == n_dev
    # Still one join dispatch per level (+1 denominator gather).
    assert ev["dispatches"] == n_levels + 1
    assert ev["gather_bytes"] > 0 and ev["psum_bytes"] == 4 * n_dev * (
        n_levels
    )
    assert [c["k"] for c in ev["comms"]] == sorted(
        k for k in mats if k >= 2
    )
    assert all(c["gather_bytes"] > 0 for c in ev["comms"])


def test_conf_sort_keys_reproduce_f64_order():
    """The 49-bit rational key must order random confidences exactly as
    the host's f64 division does (the frac_less24 spacing argument as an
    order embedding), including num == den and equal-ratio ties."""
    import jax.numpy as jnp

    from fastapriori_tpu.ops.contain import conf_sort_keys

    rng = np.random.default_rng(0)
    den = rng.integers(1, (1 << 24) - 1, size=4096, dtype=np.int64)
    num = np.minimum(
        rng.integers(1, 1 << 24, size=4096, dtype=np.int64), den
    )
    # Force some exact ties and num == den cases.
    num[:64] = den[:64]
    num[64:128], den[64:128] = 3, 9
    num[128:192], den[128:192] = 1, 3
    hi, lo = conf_sort_keys(jnp.asarray(num), jnp.asarray(den))
    hi = np.asarray(hi).astype(np.uint64)
    lo = np.asarray(lo).astype(np.uint64)
    key = (hi << np.uint64(24)) | lo
    conf = num.astype(np.float64) / den.astype(np.float64)
    # Pairwise order on a sample: key order must equal f64 order, with
    # exact-rational ties (3/9 vs 1/3, num == den) agreeing too.
    idx = rng.integers(0, 4096, size=(20000, 2))
    a, b = idx[:, 0], idx[:, 1]
    f64_lt = conf[a] < conf[b]
    key_lt = key[a] < key[b]
    assert np.array_equal(f64_lt, key_lt)
    f64_eq = conf[a] == conf[b]
    key_eq = key[a] == key[b]
    assert np.array_equal(f64_eq, key_eq)


@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
def test_resident_scan_matches_host_oracle(n_dev):
    """End-to-end recommend over the sharded engine: the device-built,
    rank-strided resident scan table must produce exactly the host
    scan's recommendations at every device count."""
    from fastapriori_tpu.models.recommender import AssociationRules

    d_lines = tokenized(random_dataset(6, n_txns=250, max_len=8))
    u_lines = tokenized(random_dataset(60, n_txns=200))
    data = preprocess(d_lines, 0.05)
    cfg = MinerConfig(
        min_support=0.05, engine="level", num_devices=n_dev,
        rule_engine="device",
    )
    miner = FastApriori(config=cfg)
    levels = miner.mine_levels_raw(data)
    rec = AssociationRules(
        [], data.freq_items, data.item_to_rank, config=cfg,
        context=miner.context, levels=levels,
        item_counts=data.item_counts,
    )
    out_dev = rec.run(u_lines, use_device=True)
    # The resident table was built on device from the join state.
    assert rec._scan_table is not None
    assert rec._rule_dev is None  # the host-built table path never ran
    out_host = rec.run(u_lines, use_device=False)
    assert out_dev == out_host
    fm = [
        r for r in rec.metrics.records
        if r.get("event") == "first_match" and r.get("device")
    ][-1]
    assert fm["resident_table"] is True
    assert fm["rule_table_host_bytes"] == 0
    assert fm["scan_dispatches"] >= 1
    assert fm["shards"] == n_dev


def test_resident_scan_repeat_run_reuses_table():
    """The second run() must not rebuild the table (the serving-tier
    contract: uploads once, scans forever)."""
    from fastapriori_tpu.models.recommender import AssociationRules

    d_lines = tokenized(random_dataset(6, n_txns=250, max_len=8))
    u_lines = tokenized(random_dataset(61, n_txns=80))
    data = preprocess(d_lines, 0.05)
    cfg = MinerConfig(
        min_support=0.05, engine="level", num_devices=2,
        rule_engine="device",
    )
    miner = FastApriori(config=cfg)
    levels = miner.mine_levels_raw(data)
    rec = AssociationRules(
        [], data.freq_items, data.item_to_rank, config=cfg,
        context=miner.context, levels=levels,
        item_counts=data.item_counts,
    )
    first = rec.run(u_lines, use_device=True)
    table = rec._scan_table
    assert table is not None
    assert rec.run(u_lines, use_device=True) == first
    assert rec._scan_table is table  # same resident arrays
    fm = [
        r for r in rec.metrics.records
        if r.get("event") == "first_match" and r.get("device")
    ][-1]
    assert fm["rule_upload_ms"] == 0.0  # no rebuild on the warm run


# ---------------------------------------------------------------------------
# FA_RULE_SHARDS / config.rule_shards resolution


def test_rule_shards_auto_uses_full_mesh():
    assert resolve_rule_shards(_ctx(4), MinerConfig()) == 4
    assert resolve_rule_shards(_ctx(1), MinerConfig()) == 1


def test_rule_shards_one_pins_single_device(monkeypatch):
    monkeypatch.setenv("FA_RULE_SHARDS", "1")
    assert resolve_rule_shards(_ctx(4), MinerConfig()) == 1


def test_rule_shards_env_strictly_parsed(monkeypatch):
    monkeypatch.setenv("FA_RULE_SHARDS", "two")  # the typo class
    with pytest.raises(InputError, match="FA_RULE_SHARDS"):
        resolve_rule_shards(_ctx(2), MinerConfig())
    monkeypatch.setenv("FA_RULE_SHARDS", "-1")
    with pytest.raises(InputError, match="FA_RULE_SHARDS"):
        resolve_rule_shards(_ctx(2), MinerConfig())


def test_rule_shards_must_match_mesh(monkeypatch):
    monkeypatch.setenv("FA_RULE_SHARDS", "4")
    with pytest.raises(InputError, match="txn axis"):
        resolve_rule_shards(_ctx(2), MinerConfig())
    assert resolve_rule_shards(_ctx(4), MinerConfig()) == 4


def test_rule_shards_config_validated():
    with pytest.raises(InputError, match="rule_shards"):
        resolve_rule_shards(_ctx(2), MinerConfig(rule_shards=-2))
    assert resolve_rule_shards(_ctx(2), MinerConfig(rule_shards=2)) == 2


def test_rule_shards_pin_one_on_multi_device_mesh_uses_host_table():
    """rule_shards=1 on a multi-device mesh pins phase 2 to the PR-4
    device-0 engine: the resident-scan state must NOT be kept (its 8·S
    row-padding layout only matches the full-mesh sharded kernel), so
    the recommender falls back to the host-built-table scan — and still
    recommends exactly what the unpinned sharded path does."""
    from fastapriori_tpu.models.recommender import AssociationRules

    d_lines = tokenized(random_dataset(6, n_txns=250, max_len=8))
    u_lines = tokenized(random_dataset(64, n_txns=80))
    data = preprocess(d_lines, 0.05)
    miner = FastApriori(
        config=MinerConfig(min_support=0.05, engine="level", num_devices=2)
    )
    levels = miner.mine_levels_raw(data)

    def run(shards):
        cfg = MinerConfig(
            min_support=0.05, engine="level", num_devices=2,
            rule_engine="device", rule_shards=shards,
        )
        rec = AssociationRules(
            [], data.freq_items, data.item_to_rank, config=cfg,
            context=miner.context, levels=levels,
            item_counts=data.item_counts,
        )
        return rec.run(u_lines, use_device=True), rec

    out_pin, rec_pin = run(1)
    assert rec_pin._scan_table is None  # host-built replicated table
    assert rec_pin._rule_dev is not None
    out_auto, rec_auto = run(0)
    assert rec_auto._scan_table is not None
    assert out_pin == out_auto


def test_rule_shards_cand_mesh_falls_back_to_single_device():
    ctx = DeviceContext(num_devices=4, cand_devices=2)
    assert resolve_rule_shards(ctx, MinerConfig()) == 1
    import os

    os.environ["FA_RULE_SHARDS"] = "2"
    try:
        with pytest.raises(InputError, match="single-process"):
            resolve_rule_shards(ctx, MinerConfig())
    finally:
        del os.environ["FA_RULE_SHARDS"]


# ---------------------------------------------------------------------------
# failpoints on the sharded upload/fetch path + kill-and-resume


def test_sharded_upload_failpoint_fires():
    mats = _corpus("random")
    failpoints.arm("rules.upload", "io*1")
    with pytest.raises(OSError, match="injected"):
        _rule_arrays_device(mats, _ctx(2), shards=2)


def test_sharded_mask_transient_fault_is_absorbed():
    """A one-shot RESOURCE_EXHAUSTED on the sharded survivor-mask fetch
    is absorbed by the audited retry path, output bit-identical."""
    mats = _corpus("random")
    clean = _rule_arrays_host(mats)
    failpoints.arm("fetch.rule_mask_shard", "oom*1")
    _assert_bit_identical(
        clean, _rule_arrays_device(mats, _ctx(2), shards=2)
    )
    retries = [e for e in ledger.snapshot() if e["kind"] == "retry"]
    assert retries and retries[0]["site"] == "fetch.rule_mask_shard"


def test_rec_match_transient_fault_is_absorbed():
    """A one-shot transient on the resident scan's result fetch is
    absorbed mid-recommend; the output stays identical to a clean run."""
    from fastapriori_tpu.models.recommender import AssociationRules

    d_lines = tokenized(random_dataset(6, n_txns=250, max_len=8))
    u_lines = tokenized(random_dataset(62, n_txns=80))
    data = preprocess(d_lines, 0.05)
    cfg = MinerConfig(
        min_support=0.05, engine="level", num_devices=2,
        rule_engine="device",
    )
    miner = FastApriori(config=cfg)
    levels = miner.mine_levels_raw(data)

    def fresh():
        return AssociationRules(
            [], data.freq_items, data.item_to_rank, config=cfg,
            context=miner.context, levels=levels,
            item_counts=data.item_counts,
        )

    clean = fresh().run(u_lines, use_device=True)
    ledger.reset()
    failpoints.arm("fetch.rec_match", "oom*1")
    assert fresh().run(u_lines, use_device=True) == clean
    retries = [e for e in ledger.snapshot() if e["kind"] == "retry"]
    assert retries and retries[0]["site"] == "fetch.rec_match"


def test_sharded_kill_and_resume_bit_exact(tmp_path):
    """Hard abort on the sharded mask fetch mid-phase-2; the resumed run
    regenerates from the checkpointed mining artifacts bit-identically
    (the CLI --resume-from phase-1 restart shape, driven in-process)."""
    from fastapriori_tpu.io import checkpoint as ckpt

    lines = tokenized(random_dataset(4, n_txns=250, max_len=8))
    data = preprocess(lines, 0.05)
    miner = FastApriori(
        config=MinerConfig(min_support=0.05, engine="level", num_devices=1)
    )
    levels = miner.mine_levels_raw(data)
    prefix = str(tmp_path) + "/"
    ckpt.save_checkpoint(
        prefix,
        levels,
        {
            "n_raw": data.n_raw,
            "min_count": data.min_count,
            "num_items": data.num_items,
        },
    )
    mats = _level_tables(levels, data.item_counts)
    ctx = _ctx(2)
    clean = _rule_arrays_device(mats, ctx, shards=2)

    failpoints.arm("fetch.rule_mask_shard", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        _rule_arrays_device(mats, ctx, shards=2)
    failpoints.disarm_all()

    got_levels, meta = ckpt.load_checkpoint(prefix)
    ckpt.check_meta(
        meta,
        n_raw=data.n_raw,
        min_count=data.min_count,
        num_items=data.num_items,
        prefix=prefix,
    )
    resumed = _rule_arrays_device(
        _level_tables(got_levels, data.item_counts), ctx, shards=2
    )
    _assert_bit_identical(clean, resumed)


def test_rec_match_kill_then_rerun_identical():
    """An abort on the scan fetch kills the run(); a fresh run() on the
    SAME instance (the resident table survives the failure) completes
    and matches the clean output — the serving tier's crash-retry
    shape."""
    from fastapriori_tpu.models.recommender import AssociationRules

    d_lines = tokenized(random_dataset(6, n_txns=250, max_len=8))
    u_lines = tokenized(random_dataset(63, n_txns=80))
    data = preprocess(d_lines, 0.05)
    cfg = MinerConfig(
        min_support=0.05, engine="level", num_devices=2,
        rule_engine="device",
    )
    miner = FastApriori(config=cfg)
    levels = miner.mine_levels_raw(data)
    rec = AssociationRules(
        [], data.freq_items, data.item_to_rank, config=cfg,
        context=miner.context, levels=levels,
        item_counts=data.item_counts,
    )
    clean = rec.run(u_lines, use_device=True)
    failpoints.arm("fetch.rec_match", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        rec.run(u_lines, use_device=True)
    failpoints.disarm_all()
    assert rec.run(u_lines, use_device=True) == clean
