"""Vertical (Eclat-style) tid-lane mining engine (ISSUE 7, ROADMAP
item 3): the AND+popcount engine (ops/vertical.py) must be BIT-EXACT
against the bitmap-matmul oracle on every corpus shape and mesh size,
its engine selection/env/fallback contracts mirror the rule-engine and
count-reduce tables (tests/test_rules_device.py,
tests/test_count_sparse.py), and it composes with the PR-6 sparse count
reduction (overflow fallback included)."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.reliability import failpoints, ledger


@pytest.fixture(autouse=True)
def _clean_state():
    failpoints.disarm_all()
    ledger.reset()
    yield
    failpoints.disarm_all()
    ledger.reset()


def _mine(lines, min_support, **cfg):
    miner = FastApriori(
        config=MinerConfig(min_support=min_support, **cfg)
    )
    got, _, _ = miner.run(lines)
    return dict(got), miner


def _engine_events():
    return [
        e for e in ledger.snapshot() if e["kind"] == "mine_engine"
    ]


# ---------------------------------------------------------------------------
# differential suite: vertical vs the bitmap oracle, bit-exact per corpus


def _t10i4_shaped():
    from fastapriori_tpu.utils.datagen import generate_transactions

    return [
        l.split()
        for l in generate_transactions(
            n_txns=1500, n_items=90, avg_txn_len=9, n_patterns=30,
            avg_pattern_len=4, corruption=0.35, seed=11,
        )
    ]


def _webdocs_shaped():
    return tokenized(
        random_dataset(23, n_txns=400, n_items=40, max_len=12)
    )


def _deep_lattice():
    return tokenized(
        random_dataset(13, n_txns=200, n_items=14, max_len=9)
    )


def _no_survivor_level():
    return tokenized(random_dataset(3, n_txns=120))


@pytest.mark.parametrize(
    "lines_fn, min_support",
    [
        (_t10i4_shaped, 0.03),
        (_webdocs_shaped, 0.04),
        (_deep_lattice, 0.05),
        (_no_survivor_level, 0.4),
    ],
    ids=["t10i4", "webdocs", "deep-lattice", "no-survivor"],
)
@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_vertical_bitexact_vs_bitmap(lines_fn, min_support, n_devices):
    lines = lines_fn()
    exp, _ = _mine(
        lines, min_support, engine="level", num_devices=n_devices,
        mine_engine="bitmap",
    )
    got, miner = _mine(
        lines, min_support, engine="level", num_devices=n_devices,
        mine_engine="vertical",
    )
    assert got == exp
    assert _engine_events()  # the choice landed on the ledger
    # ...and the metrics stream names the engine per level.
    lv = [
        r
        for r in miner.metrics.records
        if r.get("event") == "level" and r.get("k") == 2
    ]
    assert lv and lv[0].get("engine") == "vertical"


def test_vertical_non_pow2_f_pad_levels():
    """f_pad = 384 — a 128-multiple that is NOT a power of two: the
    candidate scan-chunk must divide the clamped candidate budget
    (regression: a pow2 chunk above the f_pad-clamped budget tripped
    the kernel's divisibility assert at k>=3)."""
    rng = np.random.RandomState(5)
    lines = [
        [str(x) for x in rng.choice(300, 4, replace=False)]
        for _ in range(800)
    ]
    lines += [["1", "2", "3", "4"]] * 60
    exp, _ = _mine(lines, 0.002, engine="level", mine_engine="bitmap")
    got, miner = _mine(
        lines, 0.002, engine="level", mine_engine="vertical"
    )
    assert got == exp
    # The corpus really exercised the non-pow2 clamp.
    assert miner._vertical_chunk(384) == 128


def test_vertical_heavy_weights_exact():
    """Multiplicity >= 128 rides the weight bit-planes (no base-128
    digit split, no heavy-row correction) — exact against the bitmap
    engine's heavy-split path."""
    lines = tokenized(
        ["1 2 3 4 5"] * 200 + ["1 2 3 4"] * 40 + ["2 3 4 5 6"] * 9
        + ["6 7"] * 3
    )
    ms = 8.0 / len(lines)
    exp, _ = _mine(lines, ms, engine="level", mine_engine="bitmap")
    got, miner = _mine(lines, ms, engine="level", mine_engine="vertical")
    assert got == exp
    arena = [
        r
        for r in miner.metrics.records
        if r.get("event") == "arena_build"
    ]
    assert arena and arena[0]["planes"] >= 8  # weights up to 200


def test_vertical_pair_cap_overflow_regather_exact():
    """n2 above the pair budget: the overflow re-extracts at the exact
    pow2 budget over the RESIDENT [F, F] matrix (the bitmap engine's
    regather, shared verbatim)."""
    lines = _t10i4_shaped()
    exp, _ = _mine(lines, 0.03, engine="level", mine_engine="bitmap")
    got, miner = _mine(
        lines, 0.03, engine="level", mine_engine="vertical", pair_cap=8,
    )
    assert got == exp
    kinds = [e["kind"] for e in ledger.snapshot()]
    assert "pair_cap_overflow" in kinds


# ---------------------------------------------------------------------------
# composition with the sparse count reduction (ISSUE 6 machinery)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_vertical_sparse_count_reduce_bitexact(n_devices):
    lines = _t10i4_shaped()
    exp, _ = _mine(
        lines, 0.03, engine="level", num_devices=n_devices,
        mine_engine="bitmap", count_reduce="dense",
    )
    got, miner = _mine(
        lines, 0.03, engine="level", num_devices=n_devices,
        mine_engine="vertical", count_reduce="sparse",
        count_sparse_min=1,
    )
    assert got == exp
    lv = [
        r
        for r in miner.metrics.records
        if r.get("event") == "level" and r.get("reduce") == "sparse"
    ]
    assert lv  # at least one level actually ran the sparse exchange
    assert all("gather_bytes" in r for r in lv)


def test_vertical_sparse_overflow_falls_back_dense_and_stays_exact():
    lines = _t10i4_shaped()
    exp, _ = _mine(
        lines, 0.03, engine="level", num_devices=8,
        mine_engine="bitmap", count_reduce="dense",
    )
    got, miner = _mine(
        lines, 0.03, engine="level", num_devices=8,
        mine_engine="vertical", count_reduce="sparse",
        count_sparse_min=1, count_sparse_cap=8,
    )
    assert got == exp
    kinds = [e["kind"] for e in ledger.snapshot()]
    assert "count_sparse_overflow" in kinds
    # Budget memoized: a repeat mine on the same context pays no second
    # overflow (the pair-cap-hint pattern).
    ledger.reset()
    got2, _, _ = FastApriori(
        config=MinerConfig(
            min_support=0.03, engine="level", num_devices=8,
            mine_engine="vertical", count_reduce="sparse",
            count_sparse_min=1, count_sparse_cap=8,
        ),
        context=miner.context,
    ).run(lines)
    assert dict(got2) == exp
    assert not [
        e
        for e in ledger.snapshot()
        if e["kind"] == "count_sparse_overflow"
    ]


# ---------------------------------------------------------------------------
# engine selection / fallback / env strictness (the rule-engine table)


def _sparse_corpus():
    """Wide item axis, short baskets: density well under the auto
    threshold with >= vertical_min_items frequent items."""
    rng = np.random.RandomState(7)
    return [
        [str(x) for x in rng.choice(1500, size=rng.randint(2, 6),
                                    replace=False)]
        for _ in range(3000)
    ]


def test_auto_picks_vertical_on_sparse_corpus():
    lines = _sparse_corpus()
    _, miner = _mine(lines, 0.001, mine_engine="auto")
    recs = [
        r
        for r in miner.metrics.records
        if r.get("event") == "mine_engine"
    ]
    assert recs and recs[0]["engine"] == "vertical"
    ev = _engine_events()
    assert ev and "density" in ev[0]  # the decision input is recorded


def test_auto_stays_bitmap_on_dense_corpus():
    lines = _deep_lattice()
    _, miner = _mine(lines, 0.05, engine="level", mine_engine="auto")
    recs = [
        r
        for r in miner.metrics.records
        if r.get("event") == "mine_engine"
    ]
    assert recs and recs[0]["engine"] == "bitmap"
    assert not _engine_events()


def test_auto_density_threshold_is_a_knob():
    """Raising vertical_density_max flips the dense corpus to vertical;
    zeroing it pins even the sparse corpus to bitmap."""
    lines = _deep_lattice()
    _, miner = _mine(
        lines, 0.05, engine="level", mine_engine="auto",
        vertical_density_max=1.0, vertical_min_items=1,
    )
    recs = [
        r
        for r in miner.metrics.records
        if r.get("event") == "mine_engine"
    ]
    assert recs and recs[0]["engine"] == "vertical"
    _, miner2 = _mine(
        _sparse_corpus(), 0.001, mine_engine="auto",
        vertical_density_max=0.0,
    )
    recs2 = [
        r
        for r in miner2.metrics.records
        if r.get("event") == "mine_engine"
    ]
    assert recs2 and recs2[0]["engine"] == "bitmap"


def test_forced_vertical_on_cand_mesh_falls_back_with_ledger():
    lines = _deep_lattice()
    got, _ = _mine(
        lines, 0.05, engine="level", num_devices=8, cand_devices=2,
        mine_engine="vertical",
    )
    exp, _ = _mine(
        lines, 0.05, engine="level", num_devices=8, cand_devices=2,
        mine_engine="bitmap",
    )
    assert got == exp
    falls = [
        e
        for e in ledger.snapshot()
        if e["kind"] == "mine_engine_fallback"
    ]
    assert falls and falls[0]["reason"] == "cand_mesh"


def test_config_mine_engine_strictly_validated():
    lines = _deep_lattice()
    with pytest.raises(InputError, match="mine_engine"):
        _mine(lines, 0.05, mine_engine="vretical")


def test_env_mine_engine_strictly_parsed(monkeypatch):
    from fastapriori_tpu.utils.env import env_choice

    monkeypatch.setenv("FA_MINE_ENGINE", "  BITMAP ")
    assert env_choice(
        "FA_MINE_ENGINE", ("auto", "bitmap", "vertical")
    ) == "bitmap"
    monkeypatch.setenv("FA_MINE_ENGINE", "vreticle")  # the typo class
    with pytest.raises(InputError, match="FA_MINE_ENGINE"):
        env_choice("FA_MINE_ENGINE", ("auto", "bitmap", "vertical"))


def test_env_overrides_config(monkeypatch):
    """FA_MINE_ENGINE=bitmap beats a vertical config — no vertical
    engine event lands on the ledger."""
    monkeypatch.setenv("FA_MINE_ENGINE", "bitmap")
    lines = _deep_lattice()
    _, miner = _mine(
        lines, 0.05, engine="level", mine_engine="vertical"
    )
    assert not _engine_events()
    recs = [
        r
        for r in miner.metrics.records
        if r.get("event") == "mine_engine"
    ]
    assert recs and recs[0]["engine"] == "bitmap"


def test_env_vertical_chunk_strictly_parsed(monkeypatch):
    monkeypatch.setenv("FA_VERTICAL_CHUNK", "4k")
    lines = _deep_lattice()
    with pytest.raises(InputError, match="FA_VERTICAL_CHUNK"):
        _mine(lines, 0.05, engine="level", mine_engine="vertical")


def test_forced_vertical_without_csr_falls_back(tmp_path):
    """retain_csr=False capture ingest produces a CSR-less
    CompressedData — a forced vertical mine of it falls back to bitmap
    WITH a ledger event rather than mining an empty arena (and the
    pipelined run_file path skips pipelining up front instead)."""
    from fastapriori_tpu.preprocess import CompressedData

    lines = _deep_lattice()
    exp, _ = _mine(lines, 0.05, engine="level", mine_engine="bitmap")
    from fastapriori_tpu.preprocess import preprocess

    data = preprocess(lines, 0.05)
    gutted = CompressedData(
        n_raw=data.n_raw,
        min_count=data.min_count,
        freq_items=data.freq_items,
        item_to_rank=data.item_to_rank,
        item_counts=data.item_counts,
        basket_indices=np.empty(0, np.int32),
        basket_offsets=np.zeros(1, np.int64),
        weights=data.weights,
    )
    assert not FastApriori._has_csr(gutted)
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.05, engine="level", mine_engine="vertical"
        )
    )
    eng, req = miner._mine_engine(gutted)
    assert eng == "bitmap" and req == "vertical"
    falls = [
        e
        for e in ledger.snapshot()
        if e["kind"] == "mine_engine_fallback"
    ]
    assert falls and falls[0]["reason"] == "no_csr"


def test_vertical_run_file_matches_bitmap(tmp_path):
    """run_file with a forced vertical engine (through whichever ingest
    flavor serves this mesh — since ISSUE 8 the capture pipeline takes
    vertical mines too, retaining block CSRs instead of packing
    bitmaps) still mines bit-exact."""
    lines = _t10i4_shaped()
    p = tmp_path / "d.dat"
    p.write_text("\n".join(" ".join(l) for l in lines) + "\n")
    exp = FastApriori(
        config=MinerConfig(
            min_support=0.03, engine="level", mine_engine="bitmap"
        )
    ).run_file(str(p))[0]
    got = FastApriori(
        config=MinerConfig(
            min_support=0.03, engine="level", mine_engine="vertical"
        )
    ).run_file(str(p))[0]
    assert dict(got) == dict(exp)


# ---------------------------------------------------------------------------
# pass-1 density probe under the pipelined ingest (ISSUE 8 satellite)


def _native_capture_available():
    from fastapriori_tpu.native import native_available
    from fastapriori_tpu.native.loader import (
        has_pass1_probe,
        has_preprocess_buffer_blocks,
    )

    return (
        native_available()
        and has_preprocess_buffer_blocks()
        and has_pass1_probe()
    )


@pytest.mark.skipif(
    not _native_capture_available(),
    reason="native capture ingest with pass-1 probe not built",
)
def test_pipelined_capture_auto_probe_picks_vertical(tmp_path):
    """Auto engine choice under the CAPTURE pipelined ingest: the pass-1
    probe (native on_pass1 callback) picks vertical BEFORE any block
    commits to the bitmap layout — the PR-7 residue where auto-vertical
    forfeited the capture overlap — with the choice + density + probe
    site ledger-recorded, and the mine bit-exact vs the bitmap oracle."""
    lines = _sparse_corpus()
    p = tmp_path / "d.dat"
    p.write_text("\n".join(" ".join(l) for l in lines) + "\n")
    exp = FastApriori(
        config=MinerConfig(
            min_support=0.001, engine="level", mine_engine="bitmap",
            num_devices=1,
        )
    ).run_file(str(p))[0]
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.001, engine="level", mine_engine="auto",
            num_devices=1,
        )
    )
    got = miner.run_file(str(p))[0]
    assert dict(got) == dict(exp)
    pre = [
        r for r in miner.metrics.records if r.get("event") == "preprocess"
    ]
    assert pre and pre[0].get("capture") and pre[0]["engine"] == "vertical"
    ev = _engine_events()
    assert ev and ev[0]["engine"] == "vertical"
    assert ev[0].get("probe") == "pass1"
    assert "density" in ev[0]


@pytest.mark.skipif(
    not _native_capture_available(),
    reason="native capture ingest with pass-1 probe not built",
)
def test_pipelined_capture_forced_vertical_keeps_pipeline(tmp_path):
    """A FORCED vertical mine no longer disables the pipelined capture
    ingest: the blocks replay threaded and retain their CSRs, the arena
    mines bit-exact, and the preprocess record shows the capture path."""
    lines = _t10i4_shaped()
    p = tmp_path / "d.dat"
    p.write_text("\n".join(" ".join(l) for l in lines) + "\n")
    exp = FastApriori(
        config=MinerConfig(
            min_support=0.03, engine="level", mine_engine="bitmap",
            num_devices=1,
        )
    ).run_file(str(p))[0]
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.03, engine="level", mine_engine="vertical",
            num_devices=1,
        )
    )
    got = miner.run_file(str(p))[0]
    assert dict(got) == dict(exp)
    pre = [
        r for r in miner.metrics.records if r.get("event") == "preprocess"
    ]
    assert pre and pre[0].get("pipelined") and pre[0].get("capture")
    assert pre[0]["engine"] == "vertical"
    ev = _engine_events()
    assert ev and ev[0].get("probe") == "pass1"


@pytest.mark.skipif(
    not _native_capture_available(),
    reason="native capture ingest with pass-1 probe not built",
)
def test_pipelined_capture_dense_corpus_stays_bitmap(tmp_path):
    """The probe must NOT flip dense corpora: the capture ingest keeps
    the bitmap commit and the preprocess record says so."""
    lines = _t10i4_shaped()
    p = tmp_path / "d.dat"
    p.write_text("\n".join(" ".join(l) for l in lines) + "\n")
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.03, engine="level", mine_engine="auto",
            num_devices=1,
        )
    )
    miner.run_file(str(p))
    pre = [
        r for r in miner.metrics.records if r.get("event") == "preprocess"
    ]
    assert pre and pre[0]["engine"] == "bitmap"
    assert not _engine_events()


# ---------------------------------------------------------------------------
# threaded arena build (ISSUE 8 satellite: the PR-7 reduceat residue)


def test_arena_build_threaded_identical(monkeypatch):
    """The run-aligned thread split of the reduceat pass must produce a
    byte-identical arena (OR is associative; runs stay whole per
    thread) for thread counts that divide the runs evenly and not."""
    from fastapriori_tpu.ops import vertical as vops

    rng = np.random.RandomState(5)
    t = 4000
    sizes = rng.randint(1, 12, size=t)
    indices = np.concatenate(
        [
            np.sort(rng.choice(600, size=s, replace=False))
            for s in sizes
        ]
    ).astype(np.int32)
    offsets = np.zeros(t + 1, dtype=np.int64)
    offsets[1:] = np.cumsum(sizes)
    base, f_pad, t_pad = vops.build_tid_arena_csr(
        indices, offsets, 600, n_threads=1
    )
    # Drop the size floor so the small fixture actually exercises the
    # thread split (production corpora clear it naturally).
    monkeypatch.setattr(vops, "_ARENA_THREAD_MIN_RUNS", 1)
    for n_threads in (2, 3, 8):
        arena, f2, t2 = vops.build_tid_arena_csr(
            indices, offsets, 600, n_threads=n_threads
        )
        assert (f2, t2) == (f_pad, t_pad)
        assert arena.tobytes() == base.tobytes()


# ---------------------------------------------------------------------------
# the layout primitives


def test_arena_matches_bitmap_transpose():
    from fastapriori_tpu.ops.bitmap import build_bitmap_csr
    from fastapriori_tpu.ops.vertical import build_tid_arena_csr

    rng = np.random.RandomState(0)
    baskets = [
        np.unique(rng.randint(0, 10, rng.randint(1, 6)))
        for _ in range(100)
    ]
    lens = np.array([len(b) for b in baskets])
    indices = np.concatenate(baskets).astype(np.int32)
    offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    arena, f_pad, t_pad = build_tid_arena_csr(indices, offsets, 10)
    dense = build_bitmap_csr(indices, offsets, 10, t_pad, 128)
    assert arena.shape == (f_pad + 1, t_pad // 32)
    # LSB-first within each uint32 lane: tid t <-> lane t//32 bit t%32.
    shifts = np.arange(32, dtype=np.uint32)
    unpacked = (
        (arena[:f_pad, :, None] >> shifts[None, None, :]) & 1
    ).reshape(f_pad, t_pad)
    assert (unpacked == dense.T[:f_pad, : t_pad]).all()
    assert (arena[f_pad] == np.uint32(0xFFFFFFFF)).all()


def test_compress_arena_roundtrip_and_payload():
    import jax.numpy as jnp

    from fastapriori_tpu.ops.vertical import (
        assemble_arena,
        build_tid_arena_csr,
        compress_arena,
    )

    rng = np.random.RandomState(1)
    baskets = [
        np.unique(rng.randint(0, 200, rng.randint(1, 4)))
        for _ in range(400)
    ]
    lens = np.array([len(b) for b in baskets])
    indices = np.concatenate(baskets).astype(np.int32)
    offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    arena, f_pad, t_pad = build_tid_arena_csr(indices, offsets, 200)
    buckets, payload, stats = compress_arena(arena, f_pad)
    # Sparse corpus: the pow2-bucketed segment form is much smaller
    # than the dense arena.
    assert payload < arena[:f_pad].nbytes
    assert 0 < stats["occupancy"] < 0.5
    re = np.asarray(
        assemble_arena(
            [
                (jnp.asarray(i), jnp.asarray(s), jnp.asarray(w))
                for i, s, w in buckets
            ],
            f_pad,
            arena.shape[1],
        )
    )
    assert (re == arena).all()


def test_weight_bit_planes_reassemble():
    from fastapriori_tpu.ops.vertical import weight_bit_planes

    w = np.array([1, 2, 127, 128, 300, 65535], np.int32)
    planes, scales = weight_bit_planes(w, 32)
    assert scales == [1 << b for b in range(16)]
    shifts = np.arange(32, dtype=np.uint32)
    total = np.zeros(32, np.int64)
    for p, s in zip(planes, scales):
        bits = ((p[:, None] >> shifts[None, :]) & 1).reshape(-1)
        total += bits.astype(np.int64) * s
    assert (total[:6] == w).all() and (total[6:] == 0).all()
