"""Reliability layer tests: failpoints, retry classification, the
degradation ledger, atomic writes + manifest validation, and the
kill→resume round trip (interrupt after level k, resume, bit-exact
output vs an uninterrupted run).

Every failure here is injected deterministically through
``fastapriori_tpu.reliability.failpoints`` — no real hardware faults,
no subprocess kills, CPU-only."""

import errno
import json
import os

import numpy as np
import pytest

from conftest import random_dataset
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.io import checkpoint as ckpt
from fastapriori_tpu.io import resume as resume_io
from fastapriori_tpu.io import writer
from fastapriori_tpu.io.reader import tokenize_line
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.reliability import failpoints, ledger, retry, watchdog
from fastapriori_tpu.utils.logging import MetricsLogger


@pytest.fixture(autouse=True)
def _clean_reliability_state():
    failpoints.disarm_all()
    ledger.reset()
    watchdog.reload_from_env()
    watchdog.reset_abandoned()
    yield
    failpoints.disarm_all()
    ledger.reset()
    watchdog.reload_from_env()
    watchdog.reset_abandoned()


# ---------------------------------------------------------------------------
# failpoints


def test_failpoint_spec_parsing():
    specs = failpoints.parse_spec(
        "fetch.pair:oom*1,write.freqItems:truncate@17,x.y:delay@5"
    )
    assert specs["fetch.pair"].kind == "oom"
    assert specs["fetch.pair"].remaining == 1
    assert specs["write.freqItems"].arg == 17
    assert specs["x.y"].kind == "delay"


@pytest.mark.parametrize(
    "bad",
    [
        "nocolon",
        "site:unknownkind",
        "site:oom*notanint",
        "site:truncate@NaN",
        "site:truncate",  # arg required
        "site:delay",  # arg required
    ],
)
def test_failpoint_malformed_specs_raise(bad):
    with pytest.raises(InputError):
        failpoints.parse_spec(bad)


def test_failpoint_oom_fires_then_exhausts():
    failpoints.arm("fetch.test", "oom*2")
    for _ in range(2):
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            failpoints.fire("fetch.test")
    failpoints.fire("fetch.test")  # exhausted: no-op


def test_failpoint_kinds():
    failpoints.arm("a", "io")
    with pytest.raises(OSError):
        failpoints.fire("a")
    failpoints.arm("b", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        failpoints.fire("b")
    # abort is a BaseException: no `except Exception` path can eat it.
    assert not issubclass(failpoints.InjectedAbort, Exception)
    failpoints.fire("unarmed.site")  # no-op


def test_failpoint_env_reload(monkeypatch):
    monkeypatch.setenv("FA_FAILPOINTS", "x.y:io*1")
    failpoints.reload_from_env()
    assert failpoints.active() == {"x.y": "io"}
    monkeypatch.delenv("FA_FAILPOINTS")
    failpoints.reload_from_env()
    assert failpoints.active() == {}


# ---------------------------------------------------------------------------
# retry classification + policy


def test_classify():
    assert retry.classify(InputError("x")) == "user"
    assert retry.classify(FileNotFoundError(2, "x")) == "user"
    assert retry.classify(OSError(errno.EIO, "flaky")) == "transient"
    assert retry.classify(OSError(errno.EPERM, "denied")) == "fatal"
    assert retry.classify(RuntimeError("RESOURCE_EXHAUSTED: oom")) == (
        "transient"
    )
    assert retry.classify(RuntimeError("UNAVAILABLE: link down")) == (
        "transient"
    )
    assert retry.classify(RuntimeError("INVALID_ARGUMENT: shape")) == "fatal"
    assert retry.classify(ValueError("nope")) == "fatal"


def test_retry_absorbs_transient_and_records():
    failpoints.arm("fetch.t", "oom*1")
    calls = []
    out = retry.call_with_retries(
        lambda: calls.append(1) or 42, "fetch.t", sleep=lambda s: None
    )
    assert out == 42 and calls == [1]
    kinds = [e["kind"] for e in ledger.snapshot()]
    assert kinds == ["retry"]
    assert ledger.snapshot()[0]["site"] == "fetch.t"


# The package-wide audited-fetch-site inventory (graftlint G013 checks
# every literal `retry.fetch`/`fetch_async` label is unique AND armed
# somewhere as a `fetch.<label>` failpoint — this table is that
# coverage, and the census forces it to grow with every new site).
# Labels passed as variables use their documented spellings.
FETCH_SITE_INVENTORY = [
    "fetch.pair",  # parallel/mesh.py pair-phase packed fetch
    "fetch.pair_pre",  # models/apriori.py overlapped-ingest pair fetch
    "fetch.pair_regather",  # parallel/mesh.py overflow-retry re-pack
    "fetch.local_rows",  # parallel/mesh.py per-process row fetch
    "fetch.fused",  # models/apriori.py whole-loop engine result
    "fetch.tail",  # models/apriori.py tail-fold packed result
    "fetch.counts",  # parallel/mesh.py deferred count gather (site arg)
    "fetch.counts_drain",  # models/apriori.py byte-budgeted mid-mine drain
    "fetch.counts_resolve",  # models/apriori.py tail-fold count resolve
    "fetch.level_bits",  # models/apriori.py per-level survivor bitmask
    "fetch.level_bits_sparse",  # models/apriori.py sparse-engine bitmask+union census
    "fetch.level_counts",  # models/apriori.py end-of-mine count fetch
    "fetch.pair_sparse",  # parallel/mesh.py sparse-engine pair packed fetch
    "fetch.rule_mask",  # rules/gen.py device-engine survivor bitmask
    "fetch.rule_mask_shard",  # rules/gen.py SHARDED-engine survivor bitmask
    "fetch.rule_counts",  # rules/gen.py surviving-denominator gather
    "fetch.rec_match",  # models/recommender.py resident-scan result batch
    "fetch.serve_match",  # serve/state.py serving micro-batch result
    "fetch.serve_swap_ready",  # serve/state.py swap readiness barrier
    "fetch.vpair",  # parallel/mesh.py vertical-engine pair packed fetch
    "fetch.vpair_sparse",  # parallel/mesh.py vertical pair + union census
    "fetch.vlevel_bits",  # models/apriori.py vertical survivor bitmask
    "fetch.vlevel_bits_sparse",  # models/apriori.py vertical bitmask + census
]


@pytest.mark.parametrize("site", FETCH_SITE_INVENTORY)
def test_every_inventoried_fetch_site_is_armable_and_retried(site):
    """Each audited fetch site must be reachable by the injection
    machinery: arming `<site>:oom*1` makes the first attempt fail
    transiently, the retry wrapper absorbs it, and the ledger names the
    site.  (End-to-end injection through the production dispatch paths
    is exercised per-site in the suites below and in
    tools/failpoint_smoke.py.)"""
    failpoints.arm(site, "oom*1")
    label = site[len("fetch."):]
    out = retry.fetch(lambda: 7, label, policy=retry.RetryPolicy(
        max_attempts=2, base_delay_s=0.0
    ))
    assert out == 7
    events = [e for e in ledger.snapshot() if e["kind"] == "retry"]
    assert events and events[0]["site"] == site


def test_retry_gives_up_after_policy_bound():
    failpoints.arm("fetch.t", "oom")  # unlimited
    policy = retry.RetryPolicy(max_attempts=3, base_delay_s=0.0)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        retry.call_with_retries(
            lambda: 1, "fetch.t", policy=policy, sleep=lambda s: None
        )
    assert len(ledger.snapshot()) == 2  # attempts 1 and 2 retried


def test_retry_fatal_and_user_not_retried():
    failpoints.arm("w.t", "io")  # EIO-less OSError -> errno None -> fatal
    with pytest.raises(OSError):
        retry.call_with_retries(lambda: 1, "w.t", sleep=lambda s: None)
    assert ledger.snapshot() == []

    def bad():
        raise InputError("user problem")

    with pytest.raises(InputError):
        retry.call_with_retries(bad, "other.site", sleep=lambda s: None)
    assert ledger.snapshot() == []


def test_retry_backoff_is_bounded():
    p = retry.RetryPolicy(
        max_attempts=5, base_delay_s=0.1, factor=4.0, max_delay_s=0.5
    )
    assert [p.delay(i) for i in range(4)] == [0.1, 0.4, 0.5, 0.5]


# ---------------------------------------------------------------------------
# ledger


def test_ledger_records_and_forwards_to_metrics():
    m = MetricsLogger(enabled=False).bind_global_ledger()
    ledger.record("pallas_disabled", reason="FA_NO_PALLAS", value="1")
    ledger.record("pallas_disabled", reason="FA_NO_PALLAS", value="1")
    assert ledger.summary() == {"pallas_disabled": 2}
    degraded = [r for r in m.records if r["event"] == "degraded"]
    assert len(degraded) == 2
    assert degraded[0]["kind"] == "pallas_disabled"


def test_ledger_warns_once_per_key(capsys):
    ledger.record("int8_widen", once_key="level", k1=130)
    ledger.record("int8_widen", once_key="level", k1=131)
    ledger.record("int8_widen", once_key="tail", k0=120, l_max=10)
    err = capsys.readouterr().err
    assert err.count("degraded: int8_widen") == 2  # once per key


# ---------------------------------------------------------------------------
# atomic writes + manifest


def test_write_artifact_atomic_and_manifest(tmp_path):
    path = str(tmp_path / "out" / "freqItemset")
    manifest = {}
    writer.write_artifact(path, ["a\n", "b\n"], "freqItemset", manifest)
    assert open(path).read() == "a\nb\n"
    assert not os.path.exists(path + ".tmp")
    ent = manifest["freqItemset"]
    assert ent["bytes"] == 4
    resume_io.validate_artifact_bytes(
        str(tmp_path / "out") + "/", "freqItemset", b"a\nb\n", manifest
    )


def test_write_artifact_injected_io_error_leaves_no_torn_file(tmp_path):
    path = str(tmp_path / "freqItemset")
    failpoints.arm("write.freqItemset", "io")
    with pytest.raises(OSError):
        writer.write_artifact(path, ["a\n"], "freqItemset")
    assert not os.path.exists(path)
    assert not os.path.exists(path + ".tmp")


def test_truncated_artifact_rejected_by_manifest(tmp_path):
    prefix = str(tmp_path) + "/"
    failpoints.arm("write.freqItems", "truncate@7")
    resume_io.save_phase1(
        prefix,
        [(frozenset([0, 1]), 10), (frozenset([1, 2]), 9)],
        ["a", "b", "c"],
        {"a": 0, "b": 1, "c": 2},
    )
    # Physical file is truncated; the manifest records full content.
    assert os.path.getsize(prefix + "freqItems") == 7
    manifest = resume_io.load_manifest(prefix)
    assert manifest["freqItems"]["bytes"] > 7
    with pytest.raises(InputError) as ei:
        resume_io.load_phase1(prefix)
    assert "freqItems" in str(ei.value)
    assert "manifest" in str(ei.value).lower()


def test_phase1_round_trip_with_manifest(tmp_path):
    prefix = str(tmp_path) + "/"
    itemsets = [(frozenset([0, 1]), 7), (frozenset([2]), 5)]
    items = ["a", "b", "c"]
    ranks = {"a": 0, "b": 1, "c": 2}
    resume_io.save_phase1(prefix, itemsets, items, ranks)
    assert os.path.exists(prefix + "MANIFEST.json")
    got_sets, got_ranks, got_items = resume_io.load_phase1(prefix)
    assert sorted(got_sets) == sorted(itemsets)
    assert got_ranks == ranks and got_items == items
    # Corrupt one byte -> checksum mismatch names the file.
    with open(prefix + "FreqItems", "r+b") as f:
        f.write(b"Z")
    with pytest.raises(InputError, match="FreqItems"):
        resume_io.load_phase1(prefix)


def test_corrupt_manifest_is_loud(tmp_path):
    prefix = str(tmp_path) + "/"
    resume_io.save_phase1(prefix, [], ["a"], {"a": 0})
    for corrupt in ("{not json", "[]", '"str"', '{"artifacts": 3}'):
        with open(prefix + "MANIFEST.json", "w") as f:
            f.write(corrupt)
        with pytest.raises(InputError, match="MANIFEST"):
            resume_io.load_phase1(prefix)


def test_missing_manifest_skips_validation(tmp_path):
    prefix = str(tmp_path) + "/"
    resume_io.save_phase1(prefix, [(frozenset([0]), 3)], ["a"], {"a": 0})
    os.unlink(prefix + "MANIFEST.json")
    got_sets, _, _ = resume_io.load_phase1(prefix)
    assert got_sets == [(frozenset([0]), 3)]


# ---------------------------------------------------------------------------
# checkpoints


def _meta(n_raw=100, min_count=5, num_items=7):
    return {"n_raw": n_raw, "min_count": min_count, "num_items": num_items}


def test_checkpoint_round_trip(tmp_path):
    prefix = str(tmp_path) + "/"
    levels = [
        (np.array([[0, 1], [0, 2]], np.int32), np.array([9, 8], np.int64)),
        (np.array([[0, 1, 2]], np.int32), np.array([7], np.int64)),
    ]
    ckpt.save_checkpoint(prefix, levels, _meta())
    assert ckpt.checkpoint_available(prefix)
    got, meta = ckpt.load_checkpoint(prefix)
    assert meta == _meta()
    for (m0, c0), (m1, c1) in zip(levels, got):
        np.testing.assert_array_equal(m0, m1)
        np.testing.assert_array_equal(c0, c1)


def test_checkpoint_truncation_rejected(tmp_path):
    prefix = str(tmp_path) + "/"
    levels = [
        (np.array([[0, 1]], np.int32), np.array([9], np.int64)),
    ]
    failpoints.arm("write.checkpoint.npz", "truncate@40")
    ckpt.save_checkpoint(prefix, levels, _meta())
    with pytest.raises(InputError, match="checkpoint.npz"):
        ckpt.load_checkpoint(prefix)


def test_checkpoint_survives_stale_manifest_crash_window(tmp_path):
    """A crash between the atomic checkpoint replace and the manifest
    rewrite leaves level k's npz described by level k-1's manifest
    entry; resume must shrug (ledger event) and load the structurally
    valid checkpoint, not wedge the whole mine."""
    prefix = str(tmp_path) + "/"
    lv2 = [(np.array([[0, 1]], np.int32), np.array([9], np.int64))]
    lv3 = lv2 + [
        (np.array([[0, 1, 2]], np.int32), np.array([7], np.int64))
    ]
    ckpt.save_checkpoint(prefix, lv2, _meta())
    stale_manifest = open(prefix + "MANIFEST.json", "rb").read()
    ckpt.save_checkpoint(prefix, lv3, _meta())
    # Simulate the crash window: new checkpoint, old manifest.
    with open(prefix + "MANIFEST.json", "wb") as f:
        f.write(stale_manifest)
    levels, meta = ckpt.load_checkpoint(prefix)
    assert len(levels) == 2 and meta == _meta()
    assert any(
        e["kind"] == "checkpoint_manifest_stale" for e in ledger.snapshot()
    )


def test_write_manifest_merges_on_remote_prefix(tmp_path):
    fsspec = pytest.importorskip("fsspec")
    prefix = "memory://fa_manifest_test/"
    writer.write_manifest(prefix, {"freqItemset": {"bytes": 3, "sha256": "x"}})
    writer.write_manifest(prefix, {"recommends": {"bytes": 5, "sha256": "y"}})
    arts = resume_io.load_manifest(prefix)
    assert set(arts) == {"freqItemset", "recommends"}


def test_checkpoint_meta_mismatch_rejected(tmp_path):
    prefix = str(tmp_path) + "/"
    levels = [(np.array([[0, 1]], np.int32), np.array([9], np.int64))]
    ckpt.save_checkpoint(prefix, levels, _meta())
    _, meta = ckpt.load_checkpoint(prefix)
    with pytest.raises(InputError, match="different data/support"):
        ckpt.check_meta(
            meta, n_raw=101, min_count=5, num_items=7, prefix=prefix
        )


# ---------------------------------------------------------------------------
# engine integration


def _mine_config(**kw):
    return MinerConfig(min_support=0.08, engine="level", **kw)


def _dataset():
    return [tokenize_line(l) for l in random_dataset(7, n_txns=120)]


def test_transient_fetch_failure_is_retried_and_run_succeeds():
    """Acceptance: an injected transient fetch failure is retried and the
    mine still succeeds, with the degradation recorded."""
    txns = _dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    ledger.reset()
    failpoints.arm("fetch.pair", "oom*1")
    # Dense engine pinned: on this 8-device mesh auto now selects the
    # sparse exchange, whose pair fetch is its own site (pair_sparse,
    # exercised below).
    miner = FastApriori(config=_mine_config(count_reduce="dense"))
    got = miner.run(txns)[0]
    assert sorted(got) == sorted(clean)
    retries = [e for e in ledger.snapshot() if e["kind"] == "retry"]
    assert retries and retries[0]["site"] == "fetch.pair"
    # The degradation also reached the miner's metrics record stream.
    assert any(r.get("event") == "degraded" for r in miner.metrics.records)


def test_injected_oom_without_retry_budget_still_fails():
    failpoints.arm("fetch.pair", "oom")  # every attempt
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        FastApriori(config=_mine_config(count_reduce="dense")).run(
            _dataset()
        )


def _sparse_config(**kw):
    """Multi-device sparse count-reduction engine (ISSUE 6): the
    compact-exchange fetch sites only exist on a >= 2 device mesh."""
    return _mine_config(
        num_devices=8, count_reduce="sparse", count_sparse_min=1, **kw
    )


def test_sparse_engine_fetch_failpoints_retried_end_to_end():
    """The sparse engine's compact-exchange fetches (pair_sparse +
    level_bits_sparse) are audited sites: an injected transient on each
    must be absorbed by the retry wrapper inside a real sparse mine,
    bit-exact against the clean run."""
    txns = _dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    ledger.reset()
    failpoints.arm("fetch.pair_sparse", "oom*1")
    failpoints.arm("fetch.level_bits_sparse", "oom*1")
    miner = FastApriori(config=_sparse_config())
    got = miner.run(txns)[0]
    assert sorted(got) == sorted(clean)
    sites = {
        e["site"] for e in ledger.snapshot() if e["kind"] == "retry"
    }
    assert {"fetch.pair_sparse", "fetch.level_bits_sparse"} <= sites


def test_vertical_engine_fetch_failpoints_retried_end_to_end():
    """ISSUE 7 satellite: the vertical (Eclat) engine's survivor
    fetches — the packed pair output and the per-level bitmask — are
    audited sites; an injected transient on each must be absorbed
    inside a real vertical mine, bit-exact against the bitmap run."""
    txns = _dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    ledger.reset()
    failpoints.arm("fetch.vpair", "oom*1")
    failpoints.arm("fetch.vlevel_bits", "oom*1")
    miner = FastApriori(
        config=_mine_config(mine_engine="vertical", count_reduce="dense")
    )
    got = miner.run(txns)[0]
    assert sorted(got) == sorted(clean)
    sites = {
        e["site"] for e in ledger.snapshot() if e["kind"] == "retry"
    }
    assert {"fetch.vpair", "fetch.vlevel_bits"} <= sites


def test_vertical_sparse_fetch_failpoints_retried_end_to_end():
    """Vertical + sparse count reduction: the census-carrying fetch
    variants are their own armable sites (G013)."""
    txns = _dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    ledger.reset()
    failpoints.arm("fetch.vpair_sparse", "oom*1")
    failpoints.arm("fetch.vlevel_bits_sparse", "oom*1")
    miner = FastApriori(
        config=_sparse_config(mine_engine="vertical")
    )
    got = miner.run(txns)[0]
    assert sorted(got) == sorted(clean)
    sites = {
        e["site"] for e in ledger.snapshot() if e["kind"] == "retry"
    }
    assert {"fetch.vpair_sparse", "fetch.vlevel_bits_sparse"} <= sites


def test_vertical_kill_resume_round_trip_bit_exact(tmp_path):
    """ISSUE 7 satellite: kill-and-resume must stay byte-identical
    under the vertical engine — interrupt after a completed level,
    resume from the checkpoint with the vertical engine still
    selected, writer output byte-equal to the uninterrupted bitmap
    run's."""
    txns = _dataset()
    prefix = str(tmp_path) + "/"
    clean_sets, _, clean_items = FastApriori(config=_mine_config()).run(
        txns
    )
    failpoints.arm("level.3", "abort")  # die right after level 3 commits
    miner = FastApriori(
        config=_mine_config(
            mine_engine="vertical", checkpoint_prefix=prefix
        )
    )
    with pytest.raises(failpoints.InjectedAbort):
        miner.run(txns)
    failpoints.disarm_all()
    levels, meta = ckpt.load_checkpoint(prefix)
    assert levels[-1][0].shape[1] == 3
    resumed = FastApriori(config=_mine_config(mine_engine="vertical"))
    resumed.set_resume_levels(levels, meta, label=prefix)
    got_sets, _, got_items = resumed.run(txns)
    assert got_items == clean_items
    out_a, out_b = str(tmp_path / "a_"), str(tmp_path / "b_")
    writer.save_freq_itemsets(out_a, clean_sets, clean_items)
    writer.save_freq_itemsets(out_b, got_sets, got_items)
    assert (
        open(out_a + "freqItemset", "rb").read()
        == open(out_b + "freqItemset", "rb").read()
    )


def test_sparse_kill_resume_round_trip_bit_exact(tmp_path):
    """ISSUE 6 satellite: kill-and-resume must stay byte-identical under
    the sparse count-reduction engine — interrupt after a completed
    level, resume from the checkpoint with the sparse engine still
    selected, writer output byte-equal to the uninterrupted dense
    run's."""
    txns = _dataset()
    prefix = str(tmp_path) + "/"
    clean_sets, _, clean_items = FastApriori(config=_mine_config()).run(
        txns
    )
    failpoints.arm("level.3", "abort")  # die right after level 3 commits
    miner = FastApriori(
        config=_sparse_config(checkpoint_prefix=prefix)
    )
    with pytest.raises(failpoints.InjectedAbort):
        miner.run(txns)
    failpoints.disarm_all()
    levels, meta = ckpt.load_checkpoint(prefix)
    assert levels[-1][0].shape[1] == 3
    resumed = FastApriori(config=_sparse_config())
    resumed.set_resume_levels(levels, meta, label=prefix)
    got_sets, _, got_items = resumed.run(txns)
    assert got_items == clean_items
    out_a, out_b = str(tmp_path / "a_"), str(tmp_path / "b_")
    writer.save_freq_itemsets(out_a, clean_sets, clean_items)
    writer.save_freq_itemsets(out_b, got_sets, got_items)
    assert (
        open(out_a + "freqItemset", "rb").read()
        == open(out_b + "freqItemset", "rb").read()
    )


# ---------------------------------------------------------------------------
# retry policy env knobs (FA_RETRY_MAX / FA_RETRY_BACKOFF_MS)


@pytest.fixture
def _fresh_retry_env(monkeypatch):
    monkeypatch.delenv("FA_RETRY_MAX", raising=False)
    monkeypatch.delenv("FA_RETRY_BACKOFF_MS", raising=False)
    retry.reload_policy_from_env()
    yield monkeypatch
    retry.reload_policy_from_env()


def test_retry_policy_env_defaults(_fresh_retry_env):
    assert retry.policy_from_env() is retry.DEFAULT_POLICY


def test_retry_policy_env_knobs_apply(_fresh_retry_env):
    _fresh_retry_env.setenv("FA_RETRY_MAX", "5")
    _fresh_retry_env.setenv("FA_RETRY_BACKOFF_MS", "12.5")
    retry.reload_policy_from_env()
    pol = retry.policy_from_env()
    assert pol.max_attempts == 5
    assert pol.base_delay_s == 0.0125
    # The knob actually governs call_with_retries: 4 transient failures
    # succeed on the 5th attempt under FA_RETRY_MAX=5 (the default
    # policy of 3 would have re-raised).
    failpoints.arm("knob.site", "oom*4")
    sleeps = []
    out = retry.call_with_retries(
        lambda: "ok", "knob.site", sleep=sleeps.append
    )
    assert out == "ok" and len(sleeps) == 4
    assert sleeps[0] == pytest.approx(0.0125)


@pytest.mark.parametrize(
    "var,val",
    [
        ("FA_RETRY_MAX", "three"),
        ("FA_RETRY_MAX", "0"),
        ("FA_RETRY_BACKOFF_MS", "fast"),
        ("FA_RETRY_BACKOFF_MS", "-1"),
    ],
)
def test_retry_policy_env_strictly_parsed(_fresh_retry_env, var, val):
    """The FA_NO_PALLAS contract: a typo'd ops knob must fail loudly,
    not silently run the default policy on a flaky link."""
    _fresh_retry_env.setenv(var, val)
    retry.reload_policy_from_env()
    with pytest.raises(InputError, match=var):
        retry.policy_from_env()


def test_kill_resume_round_trip_bit_exact(tmp_path):
    """Acceptance: interrupt after a completed level (failpoint abort),
    resume from the checkpoint, byte-identical freqItems output vs an
    uninterrupted run."""
    txns = _dataset()
    prefix = str(tmp_path) + "/"

    clean_sets, _, clean_items = FastApriori(config=_mine_config()).run(txns)

    failpoints.arm("level.3", "abort")  # die right after level 3 commits
    miner = FastApriori(
        config=_mine_config(checkpoint_prefix=prefix)
    )
    with pytest.raises(failpoints.InjectedAbort):
        miner.run(txns)
    failpoints.disarm_all()

    levels, meta = ckpt.load_checkpoint(prefix)
    assert levels[-1][0].shape[1] == 3  # deepest completed level
    resumed = FastApriori(config=_mine_config())
    resumed.set_resume_levels(levels, meta, label=prefix)
    got_sets, _, got_items = resumed.run(txns)
    assert got_items == clean_items
    assert sorted(got_sets) == sorted(clean_sets)
    # The writer output (the real artifact) is byte-identical.
    out_a, out_b = str(tmp_path / "a_"), str(tmp_path / "b_")
    writer.save_freq_itemsets(out_a, clean_sets, clean_items)
    writer.save_freq_itemsets(out_b, got_sets, got_items)
    assert (
        open(out_a + "freqItemset", "rb").read()
        == open(out_b + "freqItemset", "rb").read()
    )


def test_resume_levels_are_one_shot(tmp_path):
    """A later mine() on the same instance must NOT re-graft the stale
    checkpoint lattice (check_meta pins only three ints)."""
    txns = _dataset()
    prefix = str(tmp_path) + "/"
    failpoints.arm("level.3", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        FastApriori(config=_mine_config(checkpoint_prefix=prefix)).run(txns)
    failpoints.disarm_all()
    levels, meta = ckpt.load_checkpoint(prefix)
    resumed = FastApriori(config=_mine_config())
    resumed.set_resume_levels(levels, meta, label=prefix)
    first = resumed.run(txns)[0]
    assert resumed._resume_levels is None  # consumed
    second = resumed.run(txns)[0]  # a fresh, full mine
    assert sorted(first) == sorted(second)


def test_resume_meta_mismatch_is_input_error(tmp_path):
    txns = _dataset()
    prefix = str(tmp_path) + "/"
    failpoints.arm("level.2", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        FastApriori(config=_mine_config(checkpoint_prefix=prefix)).run(txns)
    failpoints.disarm_all()
    levels, meta = ckpt.load_checkpoint(prefix)
    resumed = FastApriori(config=_mine_config())
    resumed.set_resume_levels(levels, meta, label=prefix)
    with pytest.raises(InputError, match="different data/support"):
        resumed.run(txns[: len(txns) // 2])  # different dataset


def test_checkpoint_written_every_level(tmp_path):
    txns = _dataset()
    prefix = str(tmp_path) + "/"
    events = FastApriori(
        config=_mine_config(checkpoint_prefix=prefix)
    )
    events.run(txns)
    recs = [r for r in events.metrics.records if r["event"] == "checkpoint"]
    assert len(recs) >= 2  # level 2 plus at least one deeper level
    assert ckpt.checkpoint_available(prefix)
    levels, _ = ckpt.load_checkpoint(prefix)
    assert levels[0][0].shape[1] == 2


# ---------------------------------------------------------------------------
# CLI round trip


def _write_inputs(tmp_path, d_raw, u_raw):
    (tmp_path / "in").mkdir()
    (tmp_path / "in" / "D.dat").write_text(
        "".join(l + "\n" for l in d_raw)
    )
    (tmp_path / "in" / "U.dat").write_text(
        "".join(l + "\n" for l in u_raw)
    )
    return str(tmp_path / "in") + "/"


def test_cli_checkpoint_kill_resume_round_trip(tmp_path):
    from fastapriori_tpu.cli import main

    d_raw = random_dataset(7, n_txns=120)
    u_raw = random_dataset(13, n_txns=20)
    inp = _write_inputs(tmp_path, d_raw, u_raw)
    out_clean = str(tmp_path / "clean") + "/"
    out_ckpt = str(tmp_path / "ckpt") + "/"
    os.makedirs(out_clean)
    os.makedirs(out_ckpt)

    assert main([inp, out_clean, "--min-support", "0.08"]) == 0

    failpoints.arm("level.3", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        main(
            [inp, out_ckpt, "--min-support", "0.08",
             "--checkpoint-every-level"]
        )
    failpoints.disarm_all()
    assert os.path.exists(out_ckpt + "checkpoint.npz")
    assert not os.path.exists(out_ckpt + "freqItemset")

    rc = main(
        [inp, out_ckpt, "--min-support", "0.08", "--resume-from", out_ckpt]
    )
    assert rc == 0
    for name in ("freqItemset", "recommends"):
        assert (
            open(out_ckpt + name, "rb").read()
            == open(out_clean + name, "rb").read()
        )
    manifest = json.load(open(out_ckpt + "MANIFEST.json"))
    assert "freqItemset" in manifest["artifacts"]


def test_cli_truncated_resume_artifact_rejected(tmp_path, capsys):
    from fastapriori_tpu.cli import main

    d_raw = random_dataset(4)
    u_raw = random_dataset(14, n_txns=15)
    inp = _write_inputs(tmp_path, d_raw, u_raw)
    outp = str(tmp_path / "out") + "/"
    os.makedirs(outp)
    failpoints.arm("write.freqItems", "truncate@25")
    assert main([inp, outp, "--min-support", "0.08", "--save-counts"]) == 0
    failpoints.disarm_all()

    rc = main([inp, outp, "--resume-from", outp])
    assert rc == 2
    err = capsys.readouterr().err
    assert "freqItems" in err and "manifest" in err.lower()


def test_cli_torn_phase1_falls_back_to_checkpoint(tmp_path):
    """A crash window between the freqItems write and its aux artifacts
    must not wedge --resume-from when a valid checkpoint exists."""
    from fastapriori_tpu.cli import main

    d_raw = random_dataset(7, n_txns=120)
    inp = _write_inputs(tmp_path, d_raw, random_dataset(17, n_txns=15))
    out_clean = str(tmp_path / "clean") + "/"
    outp = str(tmp_path / "out") + "/"
    os.makedirs(out_clean)
    os.makedirs(outp)
    assert main([inp, out_clean, "--min-support", "0.08"]) == 0

    failpoints.arm("level.3", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        main([inp, outp, "--min-support", "0.08",
              "--checkpoint-every-level"])
    failpoints.disarm_all()
    # Simulate the torn phase-1 set: freqItems exists, aux files don't.
    with open(outp + "freqItems", "w") as f:
        f.write("a[1]\n")
    rc = main([inp, outp, "--min-support", "0.08", "--resume-from", outp])
    assert rc == 0
    assert (
        open(outp + "freqItemset", "rb").read()
        == open(out_clean + "freqItemset", "rb").read()
    )


def test_cli_resume_from_nothing_is_input_error(tmp_path, capsys):
    from fastapriori_tpu.cli import main

    inp = _write_inputs(tmp_path, random_dataset(5), ["1 2"])
    outp = str(tmp_path / "out") + "/"
    os.makedirs(outp)
    rc = main([inp, outp, "--resume-from", str(tmp_path / "empty") + "/"])
    assert rc == 2
    assert "neither" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# FA_NO_PALLAS strict parsing


def test_fa_no_pallas_strict_values(monkeypatch):
    from fastapriori_tpu.parallel.mesh import pallas_disabled_by_env

    for v in ("", "0", "false", "no"):
        monkeypatch.setenv("FA_NO_PALLAS", v)
        assert pallas_disabled_by_env() is False
    for v in ("1", "true", "yes", "on", " ON "):
        monkeypatch.setenv("FA_NO_PALLAS", v)
        assert pallas_disabled_by_env() is True
    for v in ("of", "fasle", "2", "disable"):
        monkeypatch.setenv("FA_NO_PALLAS", v)
        with pytest.raises(InputError, match="FA_NO_PALLAS"):
            pallas_disabled_by_env()


def test_fa_no_pallas_typo_fails_the_dispatch(monkeypatch):
    monkeypatch.setenv("FA_NO_PALLAS", "fasle")
    with pytest.raises(InputError, match="FA_NO_PALLAS"):
        FastApriori(config=_mine_config()).run(_dataset())


def test_env_helpers_are_strict(monkeypatch):
    """utils/env.py — the shared strict parsers every knob that is not
    itself a bespoke parser (bench link gates, compile-cache opt-outs)
    now routes through; graftlint G012 enforces the routing."""
    from fastapriori_tpu.utils.env import env_flag, env_float, env_int

    monkeypatch.setenv("FA_X", "yes")
    assert env_flag("FA_X") is True
    monkeypatch.setenv("FA_X", "0")
    assert env_flag("FA_X", default=True) is False
    monkeypatch.delenv("FA_X", raising=False)
    assert env_flag("FA_X", default=True) is True
    monkeypatch.setenv("FA_X", "fasle")
    with pytest.raises(InputError, match="FA_X"):
        env_flag("FA_X")
    monkeypatch.setenv("FA_X", "12")
    assert env_int("FA_X", 3) == 12
    monkeypatch.setenv("FA_X", "1.5")
    with pytest.raises(InputError, match="integer"):
        env_int("FA_X", 3)
    monkeypatch.setenv("FA_X", "-1")
    with pytest.raises(InputError, match="out of range"):
        env_int("FA_X", 3, minimum=0)
    monkeypatch.setenv("FA_X", "2.5")
    assert env_float("FA_X", 1.0) == 2.5
    monkeypatch.setenv("FA_X", "fast")
    with pytest.raises(InputError, match="number"):
        env_float("FA_X", 1.0)


# ---------------------------------------------------------------------------
# int8 -> int32 membership widening


def test_wide_member_guard_records_and_counts_exactly():
    """k1 >= 128 levels must dispatch the int32 membership path (int8
    would wrap at 128 and silently miscount) and leave a ledger event."""
    import jax.numpy as jnp

    from fastapriori_tpu.parallel.mesh import DeviceContext

    ctx = DeviceContext(num_devices=1)
    f_pad = 256
    k1 = 130
    t = 8
    # One basket containing items 0..k1 (plus padding rows of zeros).
    bitmap_np = np.zeros((t, f_pad), np.int8)
    bitmap_np[0, :k1] = 1
    bitmap_np[1, :k1] = 1
    bitmap = ctx.shard_bitmap(bitmap_np)
    w = ctx.shard_weight_digits(np.ones((1, t), np.int8))
    # One prefix block: the k1-item prefix, one candidate extension
    # (item k1, absent) and one flat index pointing at item 0 (present).
    zcol = f_pad - 1
    prefix = np.full((1, 8, 136), zcol, np.int16)
    prefix[0, 0, :k1] = np.arange(k1, dtype=np.int16)
    cand = np.zeros((1, 16), np.int32)
    cand[0, 0] = 0 * f_pad + k1  # extension beyond the basket: count 0
    bits, counts = ctx.level_gather_batch(
        bitmap, w, (1,), prefix, k1, 1, cand, 1
    )
    counts_np = np.asarray(counts)
    # Both rows contain the 130-item prefix; extension k1 is in neither.
    assert counts_np[0, 0] == 0
    widen = [e for e in ledger.snapshot() if e["kind"] == "int8_widen"]
    assert widen and widen[0]["k1"] == k1


# ---------------------------------------------------------------------------
# native loader hardening


def test_native_arena_view_is_read_only():
    from fastapriori_tpu.native.loader import (
        has_preprocess_buffer_blocks,
        preprocess_buffer_blocks,
    )

    if not has_preprocess_buffer_blocks():
        pytest.skip("native library unavailable")
    data = b"1 2 3\n2 3\n1 2 3\n"
    seen = []

    def on_block(f, offsets, items, weights):
        seen.append(items.flags.writeable)
        with pytest.raises((ValueError, RuntimeError)):
            items[0] = 99

    preprocess_buffer_blocks(data, 0.5, 1, on_block, copy_items=False)
    assert seen and not any(seen)


def test_native_load_failpoint_degrades_to_python_path():
    import fastapriori_tpu.native.loader as loader

    if not os.path.exists(loader._SO):
        pytest.skip("native library not built")
    old = loader._lib
    loader._lib = None
    try:
        failpoints.arm("native.load", "io*1")
        assert loader.get_lib() is None
        assert any(
            e["kind"] == "native_unavailable" for e in ledger.snapshot()
        )
        # Next call (failpoint exhausted) loads normally.
        assert loader.get_lib() is not None
    finally:
        loader._lib = old


# ---------------------------------------------------------------------------
# r6 async-fetch drain path (ISSUE 3): FA_FAILPOINTS through drain.counts


def test_drain_bounded_pending_and_bit_exact():
    """A tiny pending-byte budget forces mid-mine drains (counts_drain
    events, one dispatch each); the resolved counts must stay bit-exact
    vs the unbounded run."""
    txns = _dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    miner = FastApriori(
        config=_mine_config(pending_fetch_budget_bytes=1)
    )
    got = miner.run(txns)[0]
    drains = [
        r for r in miner.metrics.records if r.get("event") == "counts_drain"
    ]
    assert drains, "tiny budget fired no drain"
    assert all(r.get("dispatches") == 1 for r in drains)
    assert sorted(got) == sorted(clean)


def test_drain_async_fetch_transient_retried():
    """A transient failure on the drain's async fetch consume
    (fetch.counts_drain) is absorbed by the retry policy and recorded."""
    txns = _dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    ledger.reset()
    failpoints.arm("fetch.counts_drain", "oom*1")
    miner = FastApriori(
        config=_mine_config(pending_fetch_budget_bytes=1)
    )
    got = miner.run(txns)[0]
    assert sorted(got) == sorted(clean)
    retries = [e for e in ledger.snapshot() if e["kind"] == "retry"]
    assert retries and retries[0]["site"] == "fetch.counts_drain"


def test_drain_delay_failpoint_only_slows():
    """FA_FAILPOINTS delay@MS at the drain site is a pure slow-link
    simulation: results stay exact."""
    txns = _dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    failpoints.arm("drain.counts", "delay@30")
    got = FastApriori(
        config=_mine_config(pending_fetch_budget_bytes=1)
    ).run(txns)[0]
    assert sorted(got) == sorted(clean)


def test_kill_mid_drain_then_resume_bit_exact(tmp_path):
    """Acceptance (ISSUE 3 satellite): kill mid-drain → --resume-from a
    checkpoint still produces byte-identical output, with the drain path
    active during the resumed mine too."""
    txns = _dataset()
    prefix = str(tmp_path) + "/"
    clean_sets, _, clean_items = FastApriori(config=_mine_config()).run(txns)

    # The durable lattice comes from a checkpointed twin killed after
    # level 3 (checkpointing forces eager counts, so the drain path is
    # deferred-only by design)...
    failpoints.arm("level.3", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        FastApriori(config=_mine_config(checkpoint_prefix=prefix)).run(txns)
    failpoints.disarm_all()

    # ...a plain (deferred) run dies mid-drain...
    failpoints.arm("drain.counts", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        FastApriori(
            config=_mine_config(pending_fetch_budget_bytes=1)
        ).run(txns)
    failpoints.disarm_all()

    # ...and the --resume-from restart (drains still armed by the tiny
    # budget) is byte-identical.
    levels, meta = ckpt.load_checkpoint(prefix)
    resumed = FastApriori(
        config=_mine_config(pending_fetch_budget_bytes=1)
    )
    resumed.set_resume_levels(levels, meta, label=prefix)
    got_sets, _, got_items = resumed.run(txns)
    assert got_items == clean_items
    assert sorted(got_sets) == sorted(clean_sets)
    out_a, out_b = str(tmp_path / "a_"), str(tmp_path / "b_")
    writer.save_freq_itemsets(out_a, clean_sets, clean_items)
    writer.save_freq_itemsets(out_b, got_sets, got_items)
    assert (
        open(out_a + "freqItemset", "rb").read()
        == open(out_b + "freqItemset", "rb").read()
    )


# ---------------------------------------------------------------------------
# dispatch watchdog (FA_DISPATCH_TIMEOUT_S) — ISSUE 9


def test_watchdog_disabled_is_passthrough():
    assert watchdog.dispatch_timeout_s() == 0.0
    assert watchdog.guard(lambda: 41 + 1, "fetch.x") == 42


def test_watchdog_timeout_classified_transient_and_recorded():
    import time as _time

    with pytest.raises(watchdog.DispatchTimeout) as ei:
        watchdog.guard(
            lambda: _time.sleep(0.5) or 1, "fetch.hang", timeout_s=0.05
        )
    # The contract: the abandoned dispatch classifies TRANSIENT (the
    # retry policy gets its bounded shot) and names the site.
    assert retry.classify(ei.value) == "transient"
    assert "fetch.hang" in str(ei.value)
    kinds = [e["kind"] for e in ledger.snapshot()]
    assert "watchdog_timeout" in kinds


def test_watchdog_propagates_thunk_errors():
    def boom():
        raise ValueError("real bug")

    with pytest.raises(ValueError, match="real bug"):
        watchdog.guard(boom, "fetch.x", timeout_s=5.0)


def test_watchdog_env_strictly_parsed(monkeypatch):
    monkeypatch.setenv("FA_DISPATCH_TIMEOUT_S", "fast")
    watchdog.reload_from_env()
    with pytest.raises(InputError, match="FA_DISPATCH_TIMEOUT_S"):
        watchdog.dispatch_timeout_s()
    monkeypatch.setenv("FA_DISPATCH_TIMEOUT_S", "-1")
    watchdog.reload_from_env()
    with pytest.raises(InputError, match="out of range"):
        watchdog.dispatch_timeout_s()
    monkeypatch.setenv("FA_DISPATCH_TIMEOUT_S", "2.5")
    watchdog.reload_from_env()
    assert watchdog.dispatch_timeout_s() == 2.5


def test_watchdog_bounds_retried_fetch_end_to_end(monkeypatch):
    """The guard rides INSIDE call_with_retries: a hung fetch times out,
    retries (transient), and exhaustion raises the classified
    DispatchTimeout — a bounded stall, never a hang."""
    import time as _time

    monkeypatch.setenv("FA_DISPATCH_TIMEOUT_S", "0.05")
    watchdog.reload_from_env()
    calls = []

    def hang():
        calls.append(1)
        _time.sleep(0.4)
        return 7

    with pytest.raises(watchdog.DispatchTimeout):
        retry.call_with_retries(
            hang, "fetch.hang2",
            policy=retry.RetryPolicy(max_attempts=2, base_delay_s=0.0),
            sleep=lambda s: None,
        )
    assert len(calls) == 2  # first try + one retry, both bounded
    kinds = [e["kind"] for e in ledger.snapshot()]
    assert "watchdog_timeout" in kinds and "retry" in kinds


def test_watchdog_abandoned_count_rides_ledger_event():
    """Every trip carries the live abandoned-thread census (ISSUE 10
    satellite / PR 9 residue: the leak is now a number, not a
    surprise)."""
    import threading as _threading

    gate = _threading.Event()
    try:
        with pytest.raises(watchdog.DispatchTimeout):
            watchdog.guard(gate.wait, "fetch.hang_a", timeout_s=0.05)
        assert watchdog.abandoned_live() == 1
        with pytest.raises(watchdog.DispatchTimeout):
            watchdog.guard(gate.wait, "fetch.hang_b", timeout_s=0.05)
        events = [
            e for e in ledger.snapshot()
            if e["kind"] == "watchdog_timeout"
        ]
        assert [e["abandoned_live"] for e in events] == [1, 2]
    finally:
        gate.set()  # free the workers; the registry prunes dead threads


def test_watchdog_abandoned_cap_trips_fatal(monkeypatch):
    """A trip past FA_DISPATCH_MAX_ABANDONED is FATAL (not transient):
    a runtime wedged hard enough to strand the cap's worth of threads
    will strand one more per retry — the classified error must stop the
    run instead of leaking unboundedly."""
    import threading as _threading

    monkeypatch.setenv("FA_DISPATCH_MAX_ABANDONED", "2")
    # The end-to-end call_with_retries leg below takes its bound from
    # the env knob — without it guard() is a passthrough and the hung
    # thunk would block THIS thread forever.
    monkeypatch.setenv("FA_DISPATCH_TIMEOUT_S", "0.02")
    watchdog.reload_from_env()
    gate = _threading.Event()
    try:
        for site in ("fetch.cap_a", "fetch.cap_b"):
            with pytest.raises(watchdog.DispatchTimeout):
                watchdog.guard(gate.wait, site, timeout_s=0.02)
        with pytest.raises(watchdog.AbandonedThreadCap) as ei:
            watchdog.guard(gate.wait, "fetch.cap_c", timeout_s=0.02)
        assert retry.classify(ei.value) == "fatal"
        assert "FA_DISPATCH_MAX_ABANDONED" in str(ei.value)
        # End to end: the fatal cap error is NOT retried (one attempt).
        calls = []

        def hang():
            calls.append(1)
            gate.wait()

        with pytest.raises(watchdog.AbandonedThreadCap):
            retry.call_with_retries(
                hang, "fetch.cap_d", sleep=lambda s: None,
                policy=retry.RetryPolicy(max_attempts=3, base_delay_s=0.0),
            )
        assert len(calls) == 1
        # Every trip still carried the census.
        events = [
            e for e in ledger.snapshot()
            if e["kind"] == "watchdog_timeout"
        ]
        assert [e["abandoned_live"] for e in events] == [1, 2, 3, 4]
    finally:
        gate.set()


def test_watchdog_abandoned_cap_zero_disables(monkeypatch):
    import threading as _threading

    monkeypatch.setenv("FA_DISPATCH_MAX_ABANDONED", "0")
    watchdog.reload_from_env()
    gate = _threading.Event()
    try:
        for i in range(3):
            with pytest.raises(watchdog.DispatchTimeout):
                watchdog.guard(gate.wait, f"fetch.nocap{i}",
                               timeout_s=0.02)
        assert watchdog.abandoned_live() == 3
    finally:
        gate.set()


def test_watchdog_abandoned_registry_prunes_dead_threads():
    import threading as _threading

    gate = _threading.Event()
    with pytest.raises(watchdog.DispatchTimeout):
        watchdog.guard(gate.wait, "fetch.prune", timeout_s=0.02)
    assert watchdog.abandoned_live() == 1
    gate.set()  # the worker finishes; the registry prunes it
    deadline = _time_mod().monotonic() + 5.0
    while watchdog.abandoned_live() and _time_mod().monotonic() < deadline:
        _time_mod().sleep(0.01)
    assert watchdog.abandoned_live() == 0


def _time_mod():
    import time as _t

    return _t


def test_watchdog_max_abandoned_strictly_parsed(monkeypatch):
    monkeypatch.setenv("FA_DISPATCH_MAX_ABANDONED", "many")
    watchdog.reload_from_env()
    with pytest.raises(InputError, match="FA_DISPATCH_MAX_ABANDONED"):
        watchdog.max_abandoned()
    monkeypatch.setenv("FA_DISPATCH_MAX_ABANDONED", "-3")
    watchdog.reload_from_env()
    with pytest.raises(InputError, match="out of range"):
        watchdog.max_abandoned()
    monkeypatch.setenv("FA_DISPATCH_MAX_ABANDONED", "5")
    watchdog.reload_from_env()
    assert watchdog.max_abandoned() == 5
    monkeypatch.delenv("FA_DISPATCH_MAX_ABANDONED")
    watchdog.reload_from_env()
    assert watchdog.max_abandoned() == 8  # the documented default


def test_watchdog_recovered_fetch_succeeds(monkeypatch):
    """A timeout on attempt 1 followed by a fast attempt 2 = the flap
    the watchdog+retry pairing exists for."""
    import time as _time

    monkeypatch.setenv("FA_DISPATCH_TIMEOUT_S", "0.08")
    watchdog.reload_from_env()
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] == 1:
            _time.sleep(0.5)
        return 7

    out = retry.call_with_retries(
        flaky, "fetch.flap",
        policy=retry.RetryPolicy(max_attempts=3, base_delay_s=0.0),
        sleep=lambda s: None,
    )
    assert out == 7


# ---------------------------------------------------------------------------
# unified degradation cascade — ISSUE 9


def test_cascade_chain_ordering_pinned():
    """The escalation policy is ONE table; reordering it changes the
    semantics of every fallback site, so the exact orders are pinned."""
    assert watchdog.CHAINS == {
        "engine": ("fused", "tail", "level"),
        "mine_engine": ("vertical", "bitmap"),
        "count_reduce": ("sparse", "dense"),
        "exchange": ("hier", "flat"),
        "rule_engine": ("sharded", "device", "host"),
        "rule_scan": ("device", "host"),
        "serving": ("accept", "shed"),
        "elastic": ("continue", "abort"),
        "vertical_kernel": ("pallas", "xla"),
        "serve_scan": ("pallas", "xla"),
        "serve_mesh": ("full", "degraded"),
    }
    assert watchdog.chain_rank("engine", "fused") == 0
    assert watchdog.chain_rank("engine", "level") == 2


def test_cascade_forward_only():
    watchdog.downgrade("engine", "fused", "level", reason="test")
    with pytest.raises(ValueError, match="backward"):
        watchdog.downgrade("engine", "level", "fused", reason="up")
    with pytest.raises(ValueError, match="backward"):
        watchdog.downgrade("engine", "tail", "tail", reason="noop")
    with pytest.raises(ValueError, match="unknown cascade chain"):
        watchdog.downgrade("nope", "a", "b", reason="x")


def test_cascade_event_shape_reaches_metrics():
    m = MetricsLogger(enabled=False).bind_global_ledger()
    watchdog.downgrade(
        "count_reduce", "sparse", "dense", reason="union_overflow",
        site="level", k=4,
    )
    ev = [e for e in ledger.snapshot() if e["kind"] == "cascade"]
    assert ev == [
        {
            "kind": "cascade", "chain": "count_reduce", "frm": "sparse",
            "to": "dense", "rank": 1, "reason": "union_overflow",
            "site": "level", "k": 4,
        }
    ]
    degraded = [r for r in m.records if r.get("event") == "degraded"]
    assert degraded and degraded[0]["chain"] == "count_reduce"


def _deep_dataset():
    """A lattice reaching k=6 (a planted 6-itemset) plus noise — deep
    enough for multi-segment fused checkpointing and tail folds."""
    rng_lines = random_dataset(5, n_txns=110)
    return [
        tokenize_line(l)
        for l in (["1 2 3 4 5 6"] * 50 + rng_lines)
    ]


def test_fused_transient_exhaustion_cascades_to_level_engine():
    """Unlimited oom at fetch.fused exhausts the retry budget; the
    cascade walks engine fused->level and the mine still succeeds,
    bit-exact."""
    txns = _deep_dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    ledger.reset()
    failpoints.arm("fetch.fused", "oom")  # every attempt
    miner = FastApriori(
        config=MinerConfig(min_support=0.08, engine="fused")
    )
    got = miner.run(txns)[0]
    assert sorted(got) == sorted(clean)
    casc = [e for e in ledger.snapshot() if e["kind"] == "cascade"]
    assert any(
        e["chain"] == "engine"
        and e["frm"] == "fused"
        and e["to"] == "level"
        and e["reason"] == "transient_exhausted"
        for e in casc
    )


def test_tail_transient_exhaustion_cascades_to_level_engine():
    """Unlimited oom at fetch.tail: the fold's fetch exhausts, the
    cascade records tail->level, and the per-level engine finishes the
    lattice bit-exact."""
    txns = _deep_dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    ledger.reset()
    failpoints.arm("fetch.tail", "oom")
    miner = FastApriori(
        config=_mine_config(tail_fuse_rows=1 << 20)  # force folding
    )
    got = miner.run(txns)[0]
    assert sorted(got) == sorted(clean)
    casc = [e for e in ledger.snapshot() if e["kind"] == "cascade"]
    assert any(
        e["chain"] == "engine" and e["frm"] == "tail" and e["to"] == "level"
        for e in casc
    )


def test_vertical_transient_exhaustion_cascades_to_bitmap():
    txns = _dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    ledger.reset()
    failpoints.arm("fetch.vpair", "oom")  # every attempt
    miner = FastApriori(
        config=_mine_config(mine_engine="vertical", count_reduce="dense")
    )
    got = miner.run(txns)[0]
    assert sorted(got) == sorted(clean)
    casc = [e for e in ledger.snapshot() if e["kind"] == "cascade"]
    assert any(
        e["chain"] == "mine_engine"
        and e["frm"] == "vertical"
        and e["to"] == "bitmap"
        and e["reason"] == "transient_exhausted"
        for e in casc
    )


def test_vertical_transient_exhaustion_cascades_on_file_pipeline(
    tmp_path,
):
    """The walk-the-chain contract must hold on the REAL ingest path:
    run_file's pipelined paths enter the vertical engine directly, not
    through mine() — regression for the cascade arm living only on the
    mine() entry point."""
    lines = random_dataset(7, n_txns=120)
    d_path = tmp_path / "D.dat"
    d_path.write_text("".join(l + "\n" for l in lines))
    clean = FastApriori(config=_mine_config()).run_file(str(d_path))[0]
    ledger.reset()
    failpoints.arm("fetch.vpair", "oom")  # every attempt
    miner = FastApriori(
        config=_mine_config(mine_engine="vertical", count_reduce="dense")
    )
    got = miner.run_file(str(d_path))[0]
    assert sorted(got) == sorted(clean)
    casc = [e for e in ledger.snapshot() if e["kind"] == "cascade"]
    assert any(
        e["chain"] == "mine_engine"
        and e["frm"] == "vertical"
        and e["to"] == "bitmap"
        and e["reason"] == "transient_exhausted"
        for e in casc
    )


def test_vertical_transient_cascade_preserves_resume_state(tmp_path):
    """A resumed mine that cascades vertical->bitmap must re-seed the
    fallback from its checkpoint, not re-mine the lattice from scratch:
    the vertical attempt consumes the one-shot resume state before it
    fails, and the cascade arm restores it (regression — progress loss
    would still be byte-identical, so pin the level events too).  A
    planted 5-deep itemset keeps the lattice mining PAST the level-3
    kill point, so the resumed run genuinely dispatches (and floods)
    the deep-level fetch."""
    txns = _dataset() + [["1", "2", "3", "4", "5"]] * 30
    prefix = str(tmp_path) + "/"
    clean_sets, _, clean_items = FastApriori(config=_mine_config()).run(
        txns
    )
    failpoints.arm("level.3", "abort")  # die right after level 3 commits
    miner = FastApriori(config=_mine_config(checkpoint_prefix=prefix))
    with pytest.raises(failpoints.InjectedAbort):
        miner.run(txns)
    failpoints.disarm_all()
    ledger.reset()
    levels, meta = ckpt.load_checkpoint(prefix)
    # The resumed vertical mine starts at level 4 (pair level skipped),
    # so the deep-level fetch is the site to flood.
    failpoints.arm("fetch.vlevel_bits", "oom")  # every attempt
    resumed = FastApriori(
        config=_mine_config(mine_engine="vertical", count_reduce="dense")
    )
    resumed.set_resume_levels(levels, meta, label=prefix)
    got_sets, _, got_items = resumed.run(txns)
    assert got_items == clean_items
    assert sorted(got_sets) == sorted(clean_sets)
    casc = [e for e in ledger.snapshot() if e["kind"] == "cascade"]
    assert any(
        e["chain"] == "mine_engine" and e["reason"] == "transient_exhausted"
        for e in casc
    )
    # The checkpointed levels were honored: the bitmap fallback never
    # recounted a level the checkpoint already carried.
    ks = [
        r["k"]
        for r in resumed.metrics.records
        if r.get("event") == "level" and "k" in r
    ]
    assert ks and min(ks) > 3, ks


def test_sparse_transient_exhaustion_recounts_dense():
    """Unlimited oom at the sparse level fetch: the level recounts
    DENSE (cascade count_reduce sparse->dense) instead of dying — the
    dense fetch is its own audited site with a fresh budget."""
    txns = _dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    ledger.reset()
    failpoints.arm("fetch.level_bits_sparse", "oom")
    miner = FastApriori(config=_sparse_config())
    got = miner.run(txns)[0]
    assert sorted(got) == sorted(clean)
    casc = [e for e in ledger.snapshot() if e["kind"] == "cascade"]
    assert any(
        e["chain"] == "count_reduce"
        and e["frm"] == "sparse"
        and e["to"] == "dense"
        and e["reason"] == "transient_exhausted"
        for e in casc
    )


def test_forced_vertical_fallback_records_cascade():
    """A forced vertical engine on an ineligible shape (no CSR) walks
    mine_engine vertical->bitmap with the unified event, alongside the
    legacy mine_engine_fallback kind."""
    from fastapriori_tpu.preprocess import preprocess

    txns = _dataset()
    data = preprocess(txns, 0.08)
    data.basket_offsets = data.basket_offsets[:1]  # simulate no CSR
    miner = FastApriori(
        config=_mine_config(mine_engine="vertical")
    )
    ledger.reset()
    engine, _req = miner._mine_engine(data)
    assert engine == "bitmap"
    kinds = {e["kind"] for e in ledger.snapshot()}
    assert {"mine_engine_fallback", "cascade"} <= kinds


# ---------------------------------------------------------------------------
# fused-engine checkpointing: resumable segments — ISSUE 9 tentpole (a)


def _fused_ckpt_config(prefix, cadence):
    return MinerConfig(
        min_support=0.08, engine="fused",
        checkpoint_prefix=prefix, checkpoint_every_levels=cadence,
    )


def clean_sets_depth(sets):
    return max(len(s) for s, _c in sets)


@pytest.mark.parametrize(
    "cadence,kill_site",
    [(1, "level.3"), (2, "level.4"), (4, "level.6")],
)
def test_fused_checkpoint_kill_resume_byte_identical(
    tmp_path, cadence, kill_site
):
    """Acceptance (ISSUE 9): engine=fused under --checkpoint-every-level
    mines in segments; killing right after a segment commit and
    resuming produces BYTE-identical writer output, at every checkpoint
    cadence.  The kill site tracks the cadence — a segment of depth c
    commits (and fires) only its deepest level's hook."""
    txns = _deep_dataset()
    prefix = str(tmp_path) + "/"
    clean_sets, _, clean_items = FastApriori(config=_mine_config()).run(
        txns
    )
    failpoints.arm(kill_site, "abort")  # first segment commit at depth c
    miner = FastApriori(config=_fused_ckpt_config(prefix, cadence))
    with pytest.raises(failpoints.InjectedAbort):
        miner.run(txns)
    failpoints.disarm_all()
    levels, meta = ckpt.load_checkpoint(prefix)
    assert levels[-1][0].shape[1] >= 3
    resumed = FastApriori(config=_fused_ckpt_config(prefix, cadence))
    resumed.set_resume_levels(levels, meta, label=prefix)
    got_sets, _, got_items = resumed.run(txns)
    assert got_items == clean_items
    out_a, out_b = str(tmp_path / "a_"), str(tmp_path / "b_")
    writer.save_freq_itemsets(out_a, clean_sets, clean_items)
    writer.save_freq_itemsets(out_b, got_sets, got_items)
    assert (
        open(out_a + "freqItemset", "rb").read()
        == open(out_b + "freqItemset", "rb").read()
    )
    # The resumed mine really ran fused SEGMENTS, not the level loop
    # (except at the deepest-possible kill, where the lattice is
    # already complete and resume has nothing left to dispatch).
    segs = [
        r for r in resumed.metrics.records
        if r.get("event") == "tail_fuse" and r.get("checkpoint_segment")
    ]
    if levels[-1][0].shape[1] < clean_sets_depth(clean_sets):
        assert segs, "no fused checkpoint segment dispatched on resume"


def test_fused_checkpoint_cadence_controls_segments(tmp_path):
    """Cadence 1 dispatches one segment per level; a larger cadence
    folds several levels into each segment (fewer dispatches, same
    lattice) — and every segment commit is a durable checkpoint."""
    txns = _deep_dataset()
    counts = {}
    for cadence in (1, 3):
        prefix = str(tmp_path / f"c{cadence}") + "/"
        os.makedirs(prefix)
        miner = FastApriori(config=_fused_ckpt_config(prefix, cadence))
        miner.run(txns)
        segs = [
            r for r in miner.metrics.records
            if r.get("event") == "tail_fuse"
            and r.get("checkpoint_segment")
        ]
        assert segs and all(r["l_max"] == cadence for r in segs)
        # Each segment mines at most `cadence` levels.
        assert all(r["levels"] <= cadence for r in segs)
        counts[cadence] = len(segs)
        assert ckpt.checkpoint_available(prefix)
    assert counts[1] > counts[3]


def test_cli_fused_checkpoint_kill_resume(tmp_path):
    """The CLI spelling of the same acceptance: --engine fused
    --checkpoint-every-level --checkpoint-cadence 2, killed and resumed
    byte-identically."""
    from fastapriori_tpu.cli import main

    d_raw = ["1 2 3 4 5 6"] * 50 + random_dataset(5, n_txns=110)
    u_raw = random_dataset(13, n_txns=20)
    inp = _write_inputs(tmp_path, d_raw, u_raw)
    out_clean = str(tmp_path / "clean") + "/"
    out_ckpt = str(tmp_path / "ckpt") + "/"
    os.makedirs(out_clean)
    os.makedirs(out_ckpt)
    assert main([inp, out_clean, "--min-support", "0.08"]) == 0

    failpoints.arm("level.4", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        main(
            [inp, out_ckpt, "--min-support", "0.08",
             "--engine", "fused", "--checkpoint-every-level",
             "--checkpoint-cadence", "2"]
        )
    failpoints.disarm_all()
    assert os.path.exists(out_ckpt + "checkpoint.npz")
    rc = main(
        [inp, out_ckpt, "--min-support", "0.08", "--engine", "fused",
         "--checkpoint-every-level", "--checkpoint-cadence", "2",
         "--resume-from", out_ckpt]
    )
    assert rc == 0
    for name in ("freqItemset", "recommends"):
        assert (
            open(out_ckpt + name, "rb").read()
            == open(out_clean + name, "rb").read()
        )


def test_fused_checkpoint_segment_overflow_degrades_to_per_level(
    tmp_path,
):
    """A segment whose level outgrows the (headroomed) row budget walks
    the cascade to per-level dispatches — ledger-visible — and the mine
    stays bit-exact.  min_prefix_bucket pins the budget floor tiny so
    the planted lattice overflows it."""
    txns = _deep_dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    prefix = str(tmp_path) + "/"
    ledger.reset()
    cfg = MinerConfig(
        min_support=0.08, engine="fused", checkpoint_prefix=prefix,
        checkpoint_every_levels=2, min_prefix_bucket=1,
        fused_hbm_budget_bytes=1 << 14,  # starve the memory model
    )
    got = FastApriori(config=cfg).run(txns)[0]
    assert sorted(got) == sorted(clean)
    casc = [e for e in ledger.snapshot() if e["kind"] == "cascade"]
    assert any(
        e["chain"] == "engine" and e["to"] == "level" for e in casc
    )


# ---------------------------------------------------------------------------
# chaos harness determinism — ISSUE 9 tentpole (c)


def test_chaos_schedule_deterministic():
    from tools import chaos

    s1 = chaos.make_schedule(42)
    s2 = chaos.make_schedule(42)
    assert s1 == s2
    assert s1["failpoints"]  # never an empty schedule
    assert any(
        chaos.make_schedule(seed) != s1 for seed in (43, 44, 45)
    )


def test_chaos_sites_enroll_from_lint_census():
    """The schedule space is drawn from the lint-censused inventory:
    every censused fetch site is armable, so a NEW fetch site joins the
    soak the moment the inventory regenerates."""
    from tools import chaos

    sites = chaos.enrolled_sites()
    census = chaos.fetch_sites_from_inventory()
    assert set(census) <= set(sites)
    # Spot-pin the core engine sites (present since PR 2-8).
    for s in ("fetch.fused", "fetch.tail", "fetch.pair", "fetch.vpair"):
        assert s in sites
    for seed in range(20):
        sch = chaos.make_schedule(seed, sites)
        assert sch["failpoints"]
        assert set(sch["failpoints"]) <= set(sites)
        if any(v.startswith("abort") for v in sch["failpoints"].values()):
            assert sch["checkpoint"], "abort schedules must checkpoint"


def test_chaos_schedule_respects_kind_menu():
    from tools import chaos

    sites = chaos.enrolled_sites()
    for seed in range(30):
        for site, spec in chaos.make_schedule(seed, sites)[
            "failpoints"
        ].items():
            kind = spec.split("@")[0].split("*")[0]
            assert kind in sites[site], (site, spec)
            failpoints.parse_spec(f"{site}:{spec}")  # armable


# ---------------------------------------------------------------------------
# multi-host checkpoint path (simulated; the real 2-process case is
# version-gated in tests/test_distributed.py) — ISSUE 9 satellite


def test_multiprocess_checkpoint_only_process0_writes(
    tmp_path, monkeypatch
):
    """The process-0-writes discipline (ROADMAP residue): a non-zero
    process under a checkpoint prefix must mine identically but NEVER
    write the checkpoint — two processes racing the same atomic rename
    is exactly the torn-artifact class the committer exists to kill."""
    import jax

    txns = _dataset()
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    prefix = str(tmp_path) + "/"
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    miner = FastApriori(config=_mine_config(checkpoint_prefix=prefix))
    got = miner.run(txns)[0]
    assert sorted(got) == sorted(clean)
    assert not os.path.exists(prefix + "checkpoint.npz")
    # The level.<k> kill hooks still fired on this process (they gate
    # SPMD-global kill points, not the write).
    assert not any(
        r.get("event") == "checkpoint" for r in miner.metrics.records
    )


def test_multiprocess_checkpoint_resume_with_manifest_cross_check(
    tmp_path, monkeypatch
):
    """Process 0 writes the checkpoint; a SIMULATED peer process
    validates it against the manifest (bytes + sha256 + structural
    lattice check) and resumes from it bit-exact — without ever
    rewriting process 0's artifact."""
    import jax

    txns = _dataset()
    prefix = str(tmp_path) + "/"
    clean = FastApriori(config=_mine_config()).run(txns)[0]
    failpoints.arm("level.3", "abort")
    with pytest.raises(failpoints.InjectedAbort):
        FastApriori(
            config=_mine_config(checkpoint_prefix=prefix)
        ).run(txns)
    failpoints.disarm_all()

    # Manifest cross-check: committed bytes match the recorded intent.
    manifest = resume_io.load_manifest(prefix)
    raw = open(prefix + "checkpoint.npz", "rb").read()
    resume_io.validate_artifact_bytes(
        prefix, "checkpoint.npz", raw, manifest
    )
    meta = ckpt.validate_checkpoint(prefix)
    assert meta["min_count"] >= 1

    # The peer process resumes; process-0-writes keeps its hands off.
    levels, meta2 = ckpt.load_checkpoint(prefix)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    resumed = FastApriori(
        config=_mine_config(checkpoint_prefix=prefix)
    )
    resumed.set_resume_levels(levels, meta2, label=prefix)
    got = resumed.run(txns)[0]
    assert sorted(got) == sorted(clean)
    assert open(prefix + "checkpoint.npz", "rb").read() == raw


def test_validate_checkpoint_rejects_corrupt_lattice(tmp_path):
    """validate_checkpoint (the chaos harness's no-corrupt-artifact
    check) rejects structurally valid npz files whose lattice violates
    the mining contract."""
    prefix = str(tmp_path) + "/"
    bad_counts = [
        (np.array([[0, 1]], np.int32), np.array([2], np.int64)),
    ]
    ckpt.save_checkpoint(prefix, bad_counts, _meta(min_count=5))
    with pytest.raises(InputError, match="below min_count"):
        ckpt.validate_checkpoint(prefix)
    bad_ranks = [
        (np.array([[0, 9]], np.int32), np.array([9], np.int64)),
    ]
    ckpt.save_checkpoint(prefix, bad_ranks, _meta(num_items=7))
    with pytest.raises(InputError, match="outside"):
        ckpt.validate_checkpoint(prefix)
    good = [
        (np.array([[0, 1]], np.int32), np.array([9], np.int64)),
    ]
    ckpt.save_checkpoint(prefix, good, _meta())
    assert ckpt.validate_checkpoint(prefix) == _meta()


# ---------------------------------------------------------------------------
# multi-process fault domain (ISSUE 12): cascade consensus, peer-death
# detection, fenced checkpoints — file-transport domains exercised
# in-process (threads sharing a quorum dir), plus real 2/4-subprocess
# meshes through tools/chaos.py's --procs harness.  The real
# jax.distributed transport version-gates in tests/test_distributed.py.

import threading as _threading
import time as _time

from fastapriori_tpu.reliability import quorum


@pytest.fixture(autouse=True)
def _clean_quorum_state():
    quorum.set_domain(None)
    yield
    quorum.set_domain(None)


@pytest.fixture
def qroot(tmp_path, monkeypatch):
    """Tight bounds so peer-death tests stay fast: one exchange waits
    at most 1 s, heartbeats publish every 40 ms."""
    monkeypatch.setenv("FA_QUORUM_TIMEOUT_S", "1.0")
    monkeypatch.setenv("FA_HEARTBEAT_MS", "40")
    return str(tmp_path / "q")


def _domain_pair(root, consensus=True):
    d0 = quorum.QuorumDomain(
        quorum.FileTransport(root, 0, 2), 0, 2, consensus=consensus
    )
    d1 = quorum.QuorumDomain(
        quorum.FileTransport(root, 1, 2), 1, 2, consensus=consensus
    )
    return d0, d1


def test_quorum_positions_forward_only(qroot):
    d = quorum.QuorumDomain(quorum.FileTransport(qroot, 0, 1), 0, 1)
    assert d.floor_stage("engine") == "fused"
    d.propose("engine", "level", "test")
    assert d.floor_stage("engine") == "level"
    assert not d.stage_allowed("engine", "fused")
    assert not d.stage_allowed("engine", "tail")
    assert d.stage_allowed("engine", "level")
    # Forward-only: a later less-degraded proposal can never move the
    # position back up the chain.
    d.propose("engine", "tail", "test")
    assert d.floor_stage("engine") == "level"
    # Non-consensus chains are ignored (host-local, never collective).
    d.propose("rule_scan", "host", "test")
    assert "rule_scan" not in d._pos
    d.close()


def test_quorum_wire_order_pinned():
    """The exchanged position vector's chain order is the protocol —
    reordering is a wire-format change (pin it)."""
    assert quorum.CONSENSUS_CHAINS == (
        "engine", "mine_engine", "count_reduce", "rule_engine",
        # ISSUE 15 / ISSUE 17 / ISSUE 18: appended at the END —
        # pre-existing position indices are unchanged (appending
        # extends the vector, it does not reorder it).
        "exchange",
        "elastic",
        "vertical_kernel",
    )
    for chain in quorum.CONSENSUS_CHAINS:
        assert chain in watchdog.CHAINS


def test_quorum_most_degraded_wins_with_originating_rank(qroot):
    """A peer's local degradation is adopted by everyone at the next
    exchange, ledger-recorded with the originating rank AND as the
    standard cascade event (lockstep degradation, the acceptance
    pin)."""
    d0, d1 = _domain_pair(qroot)
    try:
        d1.propose("count_reduce", "dense", "transient_exhausted")
        t = _threading.Thread(target=lambda: d1.sync("level.3"))
        t.start()
        d0.sync("level.3")
        t.join()
        assert d0.floor_stage("count_reduce") == "dense"
        events = ledger.snapshot()
        adopt = [e for e in events if e["kind"] == "quorum_adopt"]
        assert adopt and adopt[0]["chain"] == "count_reduce"
        assert adopt[0]["rank"] == 1  # the originating rank
        casc = [
            e for e in events
            if e["kind"] == "cascade" and e.get("reason") == "quorum"
        ]
        assert casc and casc[0]["src_rank"] == 1
    finally:
        d0.close()
        d1.close()


def test_quorum_downgrade_composes_with_cascade(qroot):
    """watchdog.downgrade IS the proposal channel: a local chain walk
    on a collective-shaping chain publishes immediately (forward-only
    composition with PR 9's cascade)."""
    d0, d1 = _domain_pair(qroot)
    quorum.set_domain(d0)
    try:
        watchdog.downgrade(
            "engine", "fused", "level", reason="transient_exhausted"
        )
        assert d0.floor_stage("engine") == "level"
        # The published state is already visible to a peer's poll.
        t = _threading.Thread(target=lambda: d1.sync("level.2"))
        t.start()
        t.join()
        assert d1.floor_stage("engine") == "level"
        # Host-local chains do not touch the domain.
        watchdog.downgrade("rule_scan", "device", "host", reason="x")
        assert "rule_scan" not in d0._pos
    finally:
        quorum.set_domain(None)
        d0.close()
        d1.close()


def test_quorum_epoch_monotonic(qroot):
    d0, d1 = _domain_pair(qroot)
    try:
        for k in (2, 3, 4):
            t = _threading.Thread(
                target=lambda k=k: d1.sync(f"level.{k}")
            )
            t.start()
            d0.sync(f"level.{k}")
            t.join()
        trail = d0.epoch_trail()
        assert [e["site"] for e in trail] == [
            "level.2", "level.3", "level.4",
        ]
        epochs = [e["epoch"] for e in trail]
        assert epochs == sorted(epochs) and len(set(epochs)) == len(epochs)
    finally:
        d0.close()
        d1.close()


def test_quorum_peer_lost_bounded_naming_rank(qroot):
    """A rendezvous against a peer that never starts surfaces as a
    classified PeerLost NAMING THE RANK within attempts x
    FA_QUORUM_TIMEOUT_S — never an indefinite wait."""
    d0 = quorum.QuorumDomain(quorum.FileTransport(qroot, 0, 2), 0, 2)
    try:
        t0 = _time.monotonic()
        with pytest.raises(quorum.PeerLost, match="rank 1"):
            d0.sync("run.start", wait=True)
        elapsed = _time.monotonic() - t0
        # 3 attempts x 1 s bound + backoff, with generous slack.
        assert elapsed < 8.0, elapsed
        # Classified transient (UNAVAILABLE) — the retry layer's shot
        # already happened; what escapes is the classified error.
        try:
            d0.sync("run.start", wait=True)
        except quorum.PeerLost as e:
            assert retry.classify(e) == "transient"
        assert any(
            e["kind"] == "peer_lost" for e in ledger.snapshot()
        )
    finally:
        d0.close()


def test_quorum_peer_exit_marker_fails_fast(qroot):
    """A peer that DIED (classified exit) is detected from its exit
    marker immediately — no staleness wait."""
    d0, d1 = _domain_pair(qroot)
    d1.close("crashed")  # posts the exit marker, stops heartbeats
    try:
        t0 = _time.monotonic()
        with pytest.raises(quorum.PeerLost, match="rank 1"):
            d0.sync("mine.end", wait=True)
        assert _time.monotonic() - t0 < 2.0
    finally:
        d0.close()


def test_divergence_without_consensus_bounded_with_consensus_lockstep(
    qroot,
):
    """THE acceptance pin: the same divergence (rank 1 walked the
    engine chain, rank 0 did not) HANGS a raw mesh — modeled by the
    consensus-off rendezvous, bounded by the quorum watchdog into a
    classified MeshDivergence — while with consensus ON both ranks
    converge and proceed in lockstep."""
    # Without consensus: digests differ -> bounded classified error.
    nc0, nc1 = _domain_pair(qroot + ".nc", consensus=False)
    nc1.propose("engine", "level", "injected")
    errs = []

    def go(d):
        try:
            d.sync("level.4", wait=True)
        except (quorum.MeshDivergence, quorum.PeerLost) as e:
            errs.append(e)

    t0 = _time.monotonic()
    t = _threading.Thread(target=go, args=(nc1,))
    t.start()
    go(nc0)
    t.join()
    elapsed = _time.monotonic() - t0
    assert any(isinstance(e, quorum.MeshDivergence) for e in errs)
    assert elapsed < 8.0, elapsed
    for e in errs:
        if isinstance(e, quorum.MeshDivergence):
            assert retry.classify(e) == "transient"  # ABORTED status
    nc0.close()
    nc1.close()

    # Consensus-off sanity: agreeing ranks rendezvous cleanly.
    ok0, ok1 = _domain_pair(qroot + ".ok", consensus=False)
    t = _threading.Thread(target=lambda: ok1.sync("level.2", wait=True))
    t.start()
    ok0.sync("level.2", wait=True)
    t.join()
    ok0.close()
    ok1.close()

    # With consensus: the SAME divergence converges — rank 0 adopts
    # and both floors agree (lockstep degradation instead of a hang).
    ledger.reset()
    c0, c1 = _domain_pair(qroot + ".c")
    c1.propose("engine", "level", "injected")
    t = _threading.Thread(target=lambda: c1.sync("level.4", wait=True))
    t.start()
    c0.sync("level.4", wait=True)
    t.join()
    assert c0.floor_stage("engine") == "level"
    assert c1.floor_stage("engine") == "level"
    assert any(
        e["kind"] == "quorum_adopt" and e["rank"] == 1
        for e in ledger.snapshot()
    )
    c0.close()
    c1.close()


# -- fenced checkpoints -----------------------------------------------


def test_fence_monotonic_and_stale_writer_rejected(qroot):
    """The split-brain pin: an old coordinator whose fence was
    superseded must be REJECTED at commit time, classified."""
    old = quorum.QuorumDomain(quorum.FileTransport(qroot, 0, 2), 0, 2)
    fence_old = old.checkpoint_fence()
    # A new coordinator (same domain dir — the flap's replacement
    # writer) acquires the next fence.
    new = quorum.QuorumDomain(quorum.FileTransport(qroot, 0, 2), 0, 2)
    fence_new = new.checkpoint_fence()
    assert fence_new == fence_old + 1
    with pytest.raises(quorum.StaleFenceError, match="checkpoint fence"):
        old.checkpoint_fence()  # the stale writer's next commit
    assert isinstance(quorum.StaleFenceError("x"), InputError)
    assert new.checkpoint_fence() == fence_new  # current writer is fine
    old.close()
    new.close()


def test_writer_fence_none_off_writer_and_epoch_untouched(qroot):
    """quorum.writer_fence(): the manifest-stamp helper every artifact
    writer uses (CLI outputs, serving state, phase-1 resume).  A
    non-writer rank gets None AND must not advance the shared fence —
    on file-transport domains jax.process_index() is 0 on every rank,
    so a rank-gated acquire here once fenced out the real coordinator
    mid-run (the mp divergence scenarios caught it).  The writer rank
    stamps its acquired epoch; no domain stamps None."""
    assert quorum.writer_fence() is None  # no domain: unfenced
    r1 = quorum.QuorumDomain(quorum.FileTransport(qroot, 1, 2), 1, 2)
    quorum.set_domain(r1)
    try:
        assert quorum.writer_fence() is None
        assert r1.transport.current_fence() == 0  # epoch untouched
        r0 = quorum.QuorumDomain(quorum.FileTransport(qroot, 0, 2), 0, 2)
        quorum.set_domain(r0)
        fence = quorum.writer_fence()
        assert fence == 1  # the writer acquires and stamps
        assert quorum.writer_fence() == fence  # acquired ONCE per run
        r0.close()
    finally:
        quorum.set_domain(None)
        r1.close()


def test_checkpoint_fence_roundtrip_and_stale_resume_rejected(
    tmp_path, qroot
):
    """save_checkpoint stamps the fence into the meta AND the manifest;
    a resume against a domain whose FENCE has advanced rejects the
    stale artifact (classified), while the current-epoch checkpoint
    loads cleanly."""
    prefix = str(tmp_path / "out") + "/"
    levels = [(np.array([[0, 1]], np.int32), np.array([9], np.int64))]
    writer_dom = quorum.QuorumDomain(
        quorum.FileTransport(qroot, 0, 2), 0, 2
    )
    fence = writer_dom.checkpoint_fence()
    ckpt.save_checkpoint(prefix, levels, dict(_meta(), fence=fence))
    assert resume_io.manifest_fence(prefix) == fence
    # Current epoch: loads, fence round-trips through the meta.
    quorum.set_domain(writer_dom)
    lv, meta = ckpt.load_checkpoint(prefix)
    assert meta["fence"] == fence
    # check_meta ignores the fence slot (writer identity, not dataset).
    ckpt.check_meta(meta, prefix=prefix, **_meta())
    # A NEW coordinator advances the fence; the old artifact is now a
    # split-brain relic and must not seed a resume.
    quorum.QuorumDomain(
        quorum.FileTransport(qroot, 0, 2), 0, 2
    ).checkpoint_fence()
    with pytest.raises(quorum.StaleFenceError, match="stale checkpoint"):
        ckpt.load_checkpoint(prefix)
    quorum.set_domain(None)
    # Without a domain the fence is informational: still loadable.
    lv2, meta2 = ckpt.load_checkpoint(prefix)
    assert meta2["fence"] == fence
    writer_dom.close()


def test_checkpoint_unfenced_stays_compatible(tmp_path):
    """Single-process checkpoints (no domain) carry fence 0 and a
    4/5-slot meta both load — no fence key, no manifest fence."""
    prefix = str(tmp_path) + "/"
    levels = [(np.array([[0, 1]], np.int32), np.array([9], np.int64))]
    ckpt.save_checkpoint(prefix, levels, _meta())
    assert resume_io.manifest_fence(prefix) is None
    _, meta = ckpt.load_checkpoint(prefix)
    assert "fence" not in meta


# -- knobs / plumbing -------------------------------------------------


def test_quorum_knob_strictness(monkeypatch):
    monkeypatch.setenv("FA_QUORUM_TIMEOUT_S", "soon")
    with pytest.raises(InputError, match="FA_QUORUM_TIMEOUT_S"):
        quorum.quorum_timeout_s()
    monkeypatch.setenv("FA_QUORUM_TIMEOUT_S", "0.0")
    with pytest.raises(InputError, match="out of range"):
        quorum.quorum_timeout_s()
    monkeypatch.delenv("FA_QUORUM_TIMEOUT_S")
    monkeypatch.setenv("FA_HEARTBEAT_MS", "fast")
    with pytest.raises(InputError, match="FA_HEARTBEAT_MS"):
        quorum.heartbeat_ms()
    monkeypatch.delenv("FA_HEARTBEAT_MS")
    monkeypatch.setenv("FA_QUORUM_DIR", "/tmp/nope")
    monkeypatch.setenv("FA_QUORUM_PROCS", "2")
    monkeypatch.setenv("FA_QUORUM_RANK", "2")
    quorum.reload_from_env()
    with pytest.raises(InputError, match="FA_QUORUM_RANK"):
        quorum.active()
    quorum.set_domain(None)


def test_quorum_rank_path_suffix(qroot):
    assert quorum.rank_path("out.trace.json") == "out.trace.json"
    dom = quorum.QuorumDomain(quorum.FileTransport(qroot, 1, 2), 1, 2)
    quorum.set_domain(dom)
    try:
        assert quorum.rank_suffix() == ".rank1"
        assert quorum.rank_path("out.trace.json") == "out.trace.rank1.json"
        assert quorum.rank_path("noext") == "noext.rank1"
    finally:
        quorum.set_domain(None)
        dom.close()


def test_flight_merge_orders_across_ranks(tmp_path):
    """tools/flight_merge.py: per-rank dumps interleave into one
    chronological stream tagged by source rank."""
    from fastapriori_tpu.obs import flight as _flight
    from tools.flight_merge import merge_flights

    out = str(tmp_path) + "/"
    r0 = _flight.FlightRecorder(cap=16)
    r0.note("ledger", event="a")
    p0 = r0.dump(out + "rank0.", "test r0")
    _time.sleep(0.02)
    r1 = _flight.FlightRecorder(cap=16)
    r1.note("ledger", event="b")
    r1.note("quorum", epoch=1, site="level.2")
    p1 = r1.dump(out + "rank1.", "test r1")
    merged = merge_flights([p0, p1])
    assert [s["src"] for s in merged["sources"]] == ["rank0", "rank1"]
    assert len(merged["events"]) == 3
    times = [e["t_abs_s"] for e in merged["events"]]
    assert times == sorted(times)
    assert merged["events"][0]["src"] == "rank0"
    assert {e["src"] for e in merged["events"]} == {"rank0", "rank1"}


# -- elastic mesh: collective-epoch abort/retry (ISSUE 17) -------------


def _domain_trio(root):
    return tuple(
        quorum.QuorumDomain(
            quorum.FileTransport(root, r, 3), r, 3
        )
        for r in range(3)
    )


def _survive(dom, site):
    """One elastic level boundary: rendezvous, absorbing a peer death
    through the rejoin arm (the models/apriori.py except-arm shape)."""
    while True:
        try:
            dom.sync(site, wait=True)
            return
        except (quorum.PeerLost, quorum.MeshEpochAbort) as exc:
            dom.elastic_rejoin(exc)


def test_elastic_rejoin_survivors_continue(qroot, monkeypatch):
    """The tentpole pin: a dead rank's loss is ABSORBED — survivors
    abort, re-rendezvous under mesh epoch 1 with the shrunk member
    set, writership stays with the lowest survivor, and the next
    level boundary completes between the two of them.  Post-abort
    markers are epoch-namespaced so they can never pair with a
    pre-abort payload."""
    monkeypatch.setenv("FA_EPOCH_RETRY_MAX", "2")
    d0, d1, d2 = _domain_trio(qroot)
    d2.close("crash")  # exit marker: rank 2 is demonstrably dead
    t = _threading.Thread(target=lambda: _survive(d1, "level.2"))
    t.start()
    _survive(d0, "level.2")
    t.join()
    assert (d0.mesh_epoch, d1.mesh_epoch) == (1, 1)
    assert d0.members == [0, 1] and d1.members == [0, 1]
    assert d0.is_writer() and not d1.is_writer()
    # The shrunk mesh keeps rendezvousing — and under the NEW epoch's
    # marker namespace (satellite: e1.* filenames, never bare site
    # names a pre-abort straggler could still be holding).
    t2 = _threading.Thread(target=lambda: d1.sync("level.3", wait=True))
    t2.start()
    d0.sync("level.3", wait=True)
    t2.join()
    names = os.listdir(qroot)
    assert any("e1.level.3" in n for n in names), names
    assert not any("e0.level.3" in n for n in names), names
    # The transition is ledger-recorded with the survivor set (the
    # chaos soak and the merged flight timeline both key off this).
    ev = [e for e in ledger.snapshot() if e["kind"] == "mesh_epoch"]
    assert ev and ev[0]["epoch"] == 1 and ev[0]["dead"] == [2]
    assert ev[0]["members"] == [0, 1]
    trail = d0.epoch_trail()
    assert any(e.get("mesh_epoch") == 1 for e in trail)
    d0.close()
    d1.close()


def test_elastic_disabled_budget_zero_reraises(qroot):
    """FA_EPOCH_RETRY_MAX=0 (the default) keeps the protocol inert:
    the SAME PeerLost re-raises, no elastic cascade event fires, and
    elastic_enabled() is False (the level loop's defer gate)."""
    d0 = quorum.QuorumDomain(quorum.FileTransport(qroot, 0, 2), 0, 2)
    quorum.set_domain(d0)
    try:
        assert not quorum.elastic_enabled()
        exc = quorum.PeerLost(1, "level.2", "peer exited")
        with pytest.raises(quorum.PeerLost) as ei:
            d0.elastic_rejoin(exc)
        assert ei.value is exc
        assert d0.mesh_epoch == 0 and d0.members == [0, 1]
        assert not any(
            e["kind"] == "cascade" and e.get("chain") == "elastic"
            for e in ledger.snapshot()
        )
    finally:
        quorum.set_domain(None)
        d0.close()


def test_elastic_exhaustion_classifies_and_clamps(qroot, monkeypatch):
    """Deaths past the budget: the rejoin arm walks the consensus
    elastic chain continue→abort (peers adopt at their next exchange)
    and re-raises the ORIGINAL classified PeerLost; a MeshEpochAbort
    original is converted to a classified PeerLost naming the budget.
    The clamped chain makes every later rejoin abort immediately."""
    monkeypatch.setenv("FA_EPOCH_RETRY_MAX", "1")
    d0 = quorum.QuorumDomain(quorum.FileTransport(qroot, 0, 2), 0, 2)
    quorum.set_domain(d0)
    try:
        d0.mesh_epoch = 1  # one retry already consumed
        exc = quorum.PeerLost(1, "level.4", "no heartbeat")
        with pytest.raises(quorum.PeerLost) as ei:
            d0.elastic_rejoin(exc)
        assert ei.value is exc
        casc = [
            e for e in ledger.snapshot()
            if e["kind"] == "cascade" and e.get("chain") == "elastic"
        ]
        assert casc and casc[0]["frm"] == "continue"
        assert casc[0]["to"] == "abort"
        assert not d0.stage_allowed("elastic", "continue")
        # Clamped chain: even a budget-respecting abort now re-raises.
        d0.mesh_epoch = 0
        with pytest.raises(quorum.PeerLost):
            d0.elastic_rejoin(
                quorum.PeerLost(1, "level.5", "peer exited")
            )
    finally:
        quorum.set_domain(None)
        d0.close()
    # A MeshEpochAbort original past the budget becomes a classified
    # PeerLost (retry-exhaustion always surfaces under ONE type).
    d = quorum.QuorumDomain(
        quorum.FileTransport(qroot + ".x", 0, 2), 0, 2
    )
    d.mesh_epoch = 1
    with pytest.raises(quorum.PeerLost, match="retry budget exhausted"):
        d.elastic_rejoin(
            quorum.MeshEpochAbort(2, [1], "level.3", "peer at epoch 2")
        )
    d.close()


def test_elastic_writer_handoff_fences_preabort_artifacts(
    tmp_path, qroot, monkeypatch
):
    """Satellite pin: after a rejoin that removes the writer, the new
    writer's EAGER fence re-acquire turns every pre-abort artifact
    stale — load_checkpoint AND load_phase1 reject them on the
    post-abort domain, and the superseded straggler-writer's next
    commit raises StaleFenceError instead of publishing."""
    monkeypatch.setenv("FA_EPOCH_RETRY_MAX", "1")
    prefix = str(tmp_path / "out") + "/"
    levels = [(np.array([[0, 1]], np.int32), np.array([9], np.int64))]
    d0, d1 = _domain_pair(qroot)
    quorum.set_domain(d0)
    fence = d0.checkpoint_fence()
    ckpt.save_checkpoint(prefix, levels, dict(_meta(), fence=fence))
    resume_io.save_phase1(
        prefix, [(frozenset([0]), 3)], ["a"], {"a": 0}
    )
    assert resume_io.manifest_fence(prefix) == fence
    d0.close("crash")  # the pre-abort coordinator dies
    quorum.set_domain(d1)
    d1.elastic_rejoin(quorum.PeerLost(0, "level.3", "peer exited"))
    assert d1.members == [1] and d1.is_writer()
    assert d1.transport.current_fence() == fence + 1  # eager re-acquire
    with pytest.raises(quorum.StaleFenceError, match="stale checkpoint"):
        ckpt.load_checkpoint(prefix)
    with pytest.raises(quorum.StaleFenceError, match="stale checkpoint"):
        resume_io.load_phase1(prefix)
    # The superseded straggler-writer's commit path is fenced too.
    with pytest.raises(quorum.StaleFenceError, match="checkpoint fence"):
        d0.checkpoint_fence()
    quorum.set_domain(None)
    d1.close()


def test_elastic_straggler_fenced_out(qroot, monkeypatch):
    """A rank the survivors declared dead but that is still RUNNING:
    its next rendezvous sees the advanced epoch, and its rejoin is
    refused with a classified StaleFenceError — it must never mine on
    (or commit into) a domain that has moved on without it."""
    monkeypatch.setenv("FA_EPOCH_RETRY_MAX", "1")
    d0, d1 = _domain_pair(qroot)
    # Rank 0 judged rank 1 dead (a stall, not a death) and moved on.
    d0.elastic_rejoin(quorum.PeerLost(1, "level.2", "no heartbeat"))
    assert d0.members == [0] and d0.mesh_epoch == 1
    with pytest.raises(
        quorum.StaleFenceError, match="fenced this rank out"
    ):
        _survive(d1, "level.2")
    d0.close()
    d1.close()


def test_flight_merge_mesh_epoch_timeline(tmp_path):
    """Satellite pin: the merged post-mortem carries the mesh-epoch
    timeline — quorum transitions (abort reason, dead ranks, survivor
    set) and the level loop's reseed notes — pulled out of the
    interleaved stream."""
    from fastapriori_tpu.obs import flight as _flight
    from tools.flight_merge import merge_flights

    out = str(tmp_path) + "/"
    r0 = _flight.FlightRecorder(cap=16)
    r0.note("ledger", event="other")
    r0.note(
        "mesh_epoch", mesh_epoch=1, from_epoch=0, dead=[1],
        members=[0], reason="PeerLost",
    )
    r0.note(
        "mesh_epoch_reseed", mesh_epoch=1, members=[0],
        resume_from_k=3, levels_kept=2, respec={"exchange": "flat"},
    )
    p0 = r0.dump(out + "rank0.", "test r0")
    merged = merge_flights([p0])
    tl = merged["mesh_epochs"]
    assert [e["kind"] for e in tl] == ["mesh_epoch", "mesh_epoch_reseed"]
    assert tl[0]["dead"] == [1] and tl[0]["members"] == [0]
    assert tl[1]["resume_from_k"] == 3
    assert tl[1]["respec"] == {"exchange": "flat"}
    assert all(e["src"] == "rank0" for e in tl)


# -- real 2/4-subprocess meshes (tools/chaos.py --procs harness) -------


@pytest.fixture(scope="module")
def mp_fixture(tmp_path_factory):
    """Shared inputs + clean-run baseline for the subprocess-mesh
    scenarios (one in-process clean mine, reused by every case)."""
    from fastapriori_tpu.cli import main as cli_main
    from tools import chaos

    root = str(tmp_path_factory.mktemp("mp"))
    inp = chaos.make_inputs(root)
    out_clean = os.path.join(root, "clean") + os.sep
    os.makedirs(out_clean)
    assert cli_main([inp, out_clean, "--min-support", "0.08"]) == 0
    clean = {
        n: open(out_clean + n, "rb").read()
        for n in ("freqItemset", "recommends")
    }
    return root, inp, clean


def _mp_schedule_of_kind(kind, procs, start=0):
    from tools import chaos

    for seed in range(start, start + 400):
        sch = chaos.make_mp_schedule(seed, procs)
        if sch["kind"] == kind:
            return sch
    raise AssertionError(f"no {kind} schedule in range")


def test_mp_two_process_kill_mid_level(mp_fixture):
    """Kill-one-rank-mid-level on a real 2-subprocess mesh: the killed
    rank dies on its injected abort; the survivor must NOT hang — it
    classifies the loss naming the dead rank (PeerLost exit 3) or
    finishes; never silent divergence, never a mixed-epoch
    checkpoint."""
    from tools import chaos

    root, inp, clean = mp_fixture
    sch = _mp_schedule_of_kind("kill", 2)
    out = chaos.run_mp_scenario(sch, inp, root, clean, timeout_s=120.0)
    assert out.kind == "classified", out.detail


def test_mp_two_process_divergence_lockstep(mp_fixture):
    """Divergence injection (failpoint arming a chain walk on one rank
    only) on a real 2-subprocess mesh: with cascade consensus the run
    COMPLETES — the target walks its chain, the peer adopts
    (quorum_adopt), and all outputs stay byte-identical."""
    from tools import chaos

    root, inp, clean = mp_fixture
    sch = _mp_schedule_of_kind("divergence", 2)
    out = chaos.run_mp_scenario(sch, inp, root, clean, timeout_s=120.0)
    assert out.kind == "degraded", out.detail


def test_mp_four_process_divergence(mp_fixture):
    """The 4-process flavor: one rank's walk must reach THREE peers."""
    from tools import chaos

    root, inp, clean = mp_fixture
    sch = _mp_schedule_of_kind("divergence", 4)
    out = chaos.run_mp_scenario(sch, inp, root, clean, timeout_s=150.0)
    assert out.kind == "degraded", out.detail


def test_mp_two_process_elastic_kill(mp_fixture):
    """The elastic continuation pin (ISSUE 17) on a real 2-subprocess
    mesh: kill one rank mid-level with FA_EPOCH_RETRY_MAX armed — the
    survivor must abort the in-flight level, re-rendezvous alone under
    mesh epoch 1, finish, and produce output byte-identical to the
    clean run."""
    from tools import chaos

    root, inp, clean = mp_fixture
    sch = _mp_schedule_of_kind("elastic_kill", 2)
    out = chaos.run_mp_scenario(sch, inp, root, clean, timeout_s=120.0)
    assert out.kind == "elastic", out.detail


def test_mp_two_process_elastic_exhaust(mp_fixture):
    """Retry-budget exhaustion stays CLASSIFIED: with the budget at
    zero the first death must surface as PeerLost naming the rank on
    every survivor — never a hang, never an unclassified crash."""
    from tools import chaos

    root, inp, clean = mp_fixture
    sch = _mp_schedule_of_kind("elastic_exhaust", 2)
    out = chaos.run_mp_scenario(sch, inp, root, clean, timeout_s=120.0)
    assert out.kind == "classified", out.detail


def test_mp_schedule_deterministic():
    from tools import chaos

    for seed in range(30):
        a = chaos.make_mp_schedule(seed, 2)
        b = chaos.make_mp_schedule(seed, 2)
        assert a == b
        assert a["kind"] in chaos.MP_KINDS
        for spec in a["failpoints_by_rank"].values():
            site, _, rest = spec.partition(":")
            failpoints.parse_spec(f"{site}:{rest}")  # armable
        if a["kind"].startswith("elastic"):
            assert "epoch_retry_max" in a
