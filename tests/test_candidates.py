"""Join-based candidate generation (models/candidates.py) vs a direct
transcription of the reference's enumeration+prune semantics
(FastApriori.scala:167-193)."""

import random

import pytest

from fastapriori_tpu.models.candidates import gen_candidates


def reference_style(k_items, num_items):
    """The reference's algorithm shape: enumerate extensions above max(x),
    prune by per-element subset membership."""
    k_set = frozenset(k_items)
    out = []
    for x in k_items:
        cands = set(range(max(x) + 1, num_items)) - x
        for e in x:
            if not cands:
                break
            sub = x - {e}
            cands = {y for y in cands if (sub | {y}) in k_set}
        if cands:
            out.append((tuple(sorted(x)), sorted(cands)))
    return out


@pytest.mark.parametrize("seed", range(5))
def test_join_equals_reference_enumeration(seed):
    rng = random.Random(seed)
    for _ in range(100):
        f = rng.randint(4, 14)
        s = rng.randint(2, 4)
        m = rng.randint(1, 40)
        items = list(
            {frozenset(rng.sample(range(f), s)) for _ in range(m)}
        )
        assert dict(gen_candidates(items, f)) == dict(
            reference_style(items, f)
        )


def test_empty_and_singleton():
    assert gen_candidates([], 5) == []
    assert gen_candidates([frozenset((0, 1))], 5) == []


def test_known_triangle():
    # {0,1},{0,2},{1,2} -> candidate {0,1,2} from prefix (0,1) ext 2.
    items = [frozenset(p) for p in [(0, 1), (0, 2), (1, 2)]]
    assert gen_candidates(items, 3) == [((0, 1), [2])]


def test_arrays_equals_host_oracle():
    # The vectorized join+prune (the level engine's path) must produce
    # exactly the host oracle's candidate set on random levels.
    import numpy as np

    from fastapriori_tpu.models.candidates import gen_candidates_arrays

    rng = random.Random(7)
    for _ in range(60):
        f = rng.randint(4, 16)
        s = rng.randint(1, 5)
        m = rng.randint(1, 60)
        seen = {
            tuple(sorted(rng.sample(range(f), min(s, f))))
            for _ in range(m)
        }
        level = np.array(sorted(seen), dtype=np.int32)
        xi, ys = gen_candidates_arrays(level)
        got = sorted(
            (tuple(level[i].tolist()), int(y)) for i, y in zip(xi, ys)
        )
        want = sorted(
            (p, y)
            for p, exts in gen_candidates(
                [frozenset(t) for t in seen], f
            )
            for y in exts
        )
        assert got == want


def test_arrays_empty_and_tiny():
    import numpy as np

    from fastapriori_tpu.models.candidates import gen_candidates_arrays

    xi, ys = gen_candidates_arrays(np.empty((0, 2), dtype=np.int32))
    assert xi.size == 0 and ys.size == 0
    xi, ys = gen_candidates_arrays(np.array([[0, 1]], dtype=np.int32))
    assert xi.size == 0


def test_native_candidates_match_numpy():
    """fa_gen_candidates must emit exactly gen_candidates_arrays'
    (x_idx, y) stream — same survivors, same global order — across
    random levels of several widths, including join-heavy shapes."""
    import numpy as np
    import pytest

    from fastapriori_tpu.native import native_available

    if not native_available():
        pytest.skip("native extension not built")
    from fastapriori_tpu.models.candidates import (
        gen_candidates_arrays,
        gen_candidates_stream,
    )
    from fastapriori_tpu.native.loader import gen_candidates_native

    rng = np.random.default_rng(5)
    for s in (1, 2, 3, 5, 8):
        for m in (2, 7, 300):
            rows = np.unique(
                np.sort(
                    rng.integers(0, 10 + s, size=(m, s)), axis=1
                ),
                axis=0,
            )
            # strictly increasing rows only (valid itemsets)
            keep = np.all(np.diff(rows, axis=1) > 0, axis=1) if s > 1 else (
                np.ones(rows.shape[0], dtype=bool)
            )
            lvl = rows[keep].astype(np.int32)
            if lvl.shape[0] < 2:
                continue
            x0, y0 = gen_candidates_arrays(lvl)
            x1, y1 = gen_candidates_native(lvl)
            assert (x0 == x1).all() and (y0 == y1).all(), (s, lvl.shape)
            # the engine-facing stream picks the native path and agrees
            blocks = list(gen_candidates_stream(lvl))
            if x0.size:
                xs = np.concatenate([b[0] for b in blocks])
                ys = np.concatenate([b[1] for b in blocks])
                assert (xs == x0).all() and (ys == y0).all()
            else:
                assert blocks == []
