"""Join-based candidate generation (models/candidates.py) vs a direct
transcription of the reference's enumeration+prune semantics
(FastApriori.scala:167-193)."""

import random

import pytest

from fastapriori_tpu.models.candidates import gen_candidates


def reference_style(k_items, num_items):
    """The reference's algorithm shape: enumerate extensions above max(x),
    prune by per-element subset membership."""
    k_set = frozenset(k_items)
    out = []
    for x in k_items:
        cands = set(range(max(x) + 1, num_items)) - x
        for e in x:
            if not cands:
                break
            sub = x - {e}
            cands = {y for y in cands if (sub | {y}) in k_set}
        if cands:
            out.append((tuple(sorted(x)), sorted(cands)))
    return out


@pytest.mark.parametrize("seed", range(5))
def test_join_equals_reference_enumeration(seed):
    rng = random.Random(seed)
    for _ in range(100):
        f = rng.randint(4, 14)
        s = rng.randint(2, 4)
        m = rng.randint(1, 40)
        items = list(
            {frozenset(rng.sample(range(f), s)) for _ in range(m)}
        )
        assert dict(gen_candidates(items, f)) == dict(
            reference_style(items, f)
        )


def test_empty_and_singleton():
    assert gen_candidates([], 5) == []
    assert gen_candidates([frozenset((0, 1))], 5) == []


def test_known_triangle():
    # {0,1},{0,2},{1,2} -> candidate {0,1,2} from prefix (0,1) ext 2.
    items = [frozenset(p) for p in [(0, 1), (0, 2), (1, 2)]]
    assert gen_candidates(items, 3) == [((0, 1), [2])]
