"""The full mining engine vs the oracle (C6-C9)."""

import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu import oracle
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.models.apriori import FastApriori


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("min_support", [0.05, 0.1, 0.2])
def test_miner_matches_oracle(seed, min_support):
    lines = tokenized(random_dataset(seed))
    expected, exp_rank, exp_items = oracle.mine(lines, min_support)

    miner = FastApriori(min_support, num_devices=1)
    got, item_to_rank, freq_items = miner.run(lines)

    assert freq_items == exp_items
    assert item_to_rank == exp_rank
    assert dict(got) == dict(expected)
    assert len(got) == len(expected)


def test_miner_dense_data_many_levels():
    # Highly correlated baskets force levels >= 4.
    lines = tokenized(
        ["1 2 3 4 5"] * 10
        + ["1 2 3 4"] * 5
        + ["2 3 4 5"] * 5
        + ["6 7"] * 3
        + ["1", "8 9"]
    )
    expected, _, _ = oracle.mine(lines, 0.2)
    miner = FastApriori(0.2, num_devices=1)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    assert max(len(s) for s, _ in got) >= 4


def test_miner_no_frequent_pairs():
    lines = tokenized(["1 2", "3 4", "5 6", "7 8"])
    miner = FastApriori(0.5, num_devices=1)
    got, _, freq_items = miner.run(lines)
    assert got == [] and freq_items == []


def test_miner_only_singletons():
    lines = tokenized(["1", "1", "2", "1 2"])
    expected, _, _ = oracle.mine(lines, 0.5)
    miner = FastApriori(0.5, num_devices=1)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    assert all(len(s) == 1 for s, _ in got)


def test_miner_repeated_baskets_weighting():
    # 60 identical baskets dedupe to one row with weight 60; exercises the
    # weighted counting path (weight < 128, single digit).
    lines = tokenized(["10 20 30"] * 60 + ["10 20"] * 5 + ["40"] * 3)
    expected, _, _ = oracle.mine(lines, 0.1)
    got, _, _ = FastApriori(0.1, num_devices=1).run(lines)
    assert dict(got) == dict(expected)


def test_miner_weight_overflow_digit():
    # >128 identical baskets forces a second base-128 digit.
    lines = tokenized(["1 2 3"] * 300 + ["4 5"] * 10)
    expected, _, _ = oracle.mine(lines, 0.05)
    got, _, _ = FastApriori(0.05, num_devices=1).run(lines)
    assert dict(got) == dict(expected)


def test_miner_small_prefix_bucket():
    # Tiny bucket forces multi-chunk level counting.
    lines = tokenized(random_dataset(7, n_items=10, n_txns=100))
    cfg = MinerConfig(min_support=0.05, min_prefix_bucket=2, num_devices=1)
    expected, _, _ = oracle.mine(lines, 0.05)
    got, _, _ = FastApriori(0.05, config=cfg).run(lines)
    assert dict(got) == dict(expected)


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("engine", ["fused", "level"])
def test_apriori_invariants(seed, engine):
    """SURVEY §4 property tests on the miner's own output:

    - downward closure: every (k-1)-subset of a frequent k-set (k >= 3)
      is itself in the result, and every 2-subset too;
    - count monotonicity: count(S) <= count(S - {i}).  For |S| = 2 the
      comparison is against the 1-itemsets' RAW occurrence counts
      (within-line duplicates and dropped size<=1 baskets included,
      FastApriori.scala:55 vs :70), which can only exceed the
      deduplicated basket support.
    """
    lines = tokenized(
        random_dataset(seed, n_txns=150, n_items=14, max_len=7)
    )
    itemsets, _, _ = FastApriori(
        config=MinerConfig(min_support=0.04, engine=engine, num_devices=8)
    ).run(lines)
    counts = dict(itemsets)
    assert itemsets, "degenerate dataset"
    for s, c in itemsets:
        assert c > 0
        if len(s) < 2:
            continue
        for item in s:
            sub = s - {item}
            assert sub in counts, (s, sub)
            assert c <= counts[sub], (s, c, sub, counts[sub])


from fastapriori_tpu.native import native_available


@pytest.mark.skipif(
    not native_available(), reason="native extension not built"
)
@pytest.mark.parametrize(
    "seed,blocks,threads",
    [(3, 2, 1), (5, 4, 3), (9, 8, 2), (11, 3, None)],
)
def test_pipelined_ingest_matches_plain(tmp_path, seed, blocks, threads):
    """The pipelined single-host ingest (per-block compress + async
    upload, models/apriori.py _run_file_pipelined) must produce level
    matrices and global tables BIT-EXACT vs the plain path — cross-block
    duplicate baskets stay separate weighted rows, which cannot change
    weighted counts."""
    from conftest import random_dataset
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.models.apriori import FastApriori
    from fastapriori_tpu.parallel.mesh import DeviceContext

    d_raw = (
        ["1 2 3"] * 140  # heavy basket: 2-digit weight if not split
        + random_dataset(seed, n_txns=250, n_items=25, max_len=9)
        + ["1 2 3"] * 7
        + ["", "  "]  # empty-ish lines
    )
    path = tmp_path / "D.dat"
    path.write_text("".join(l + "\n" for l in d_raw))

    ctx = DeviceContext(num_devices=1)
    cfg_pipe = MinerConfig(
        min_support=0.05, engine="level", ingest_pipeline_blocks=blocks,
        ingest_threads=threads,
    )
    cfg_plain = MinerConfig(
        min_support=0.05, engine="level", ingest_pipeline_blocks=1
    )
    miner_pipe = FastApriori(config=cfg_pipe, context=ctx)
    assert miner_pipe._can_pipeline_ingest(str(path))
    lv_pipe, d_pipe = miner_pipe.run_file_raw(str(path))
    miner_plain = FastApriori(config=cfg_plain, context=ctx)
    assert not miner_plain._can_pipeline_ingest(str(path))
    lv_plain, d_plain = miner_plain.run_file_raw(str(path))

    assert d_pipe.n_raw == d_plain.n_raw
    assert d_pipe.min_count == d_plain.min_count
    assert d_pipe.freq_items == d_plain.freq_items
    assert (d_pipe.item_counts == d_plain.item_counts).all()
    assert len(lv_pipe) == len(lv_plain)
    for (m_a, c_a), (m_b, c_b) in zip(lv_pipe, lv_plain):
        assert (m_a == m_b).all() and (c_a == c_b).all()
    # Weighted support is conserved even though row counts may differ
    # (cross-block duplicates kept separate).
    assert d_pipe.weights.sum() == d_plain.weights.sum()
    assert d_pipe.total_count >= d_plain.total_count


@pytest.mark.skipif(
    not native_available(), reason="native extension not built"
)
@pytest.mark.parametrize("engine", ["level", "auto"])
def test_ingest_overlapped_pair_matches_plain(tmp_path, engine):
    """The ingest-overlapped pair program (mesh.ingest_pair_miner: one
    dispatch for concat+unpack+f32 Gram+threshold, submitted before host
    assembly) must be bit-exact vs the classic post-assembly pair gather
    — including through a pair-cap overflow (regather over the resident
    count matrix) and under the engine auto-choice, whose n2/census now
    come from the same fetch."""
    from conftest import random_dataset
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.models.apriori import FastApriori
    from fastapriori_tpu.parallel.mesh import DeviceContext

    d_raw = (
        ["4 7 9 11"] * 150  # heavy rows: exercises w>=128 exactness
        + random_dataset(21, n_txns=400, n_items=18, max_len=10)
    )
    path = tmp_path / "D.dat"
    path.write_text("".join(l + "\n" for l in d_raw))

    ctx = DeviceContext(num_devices=1)
    # pair_cap=4 forces the overflow/regather path over pair_pre's
    # resident count matrix (18 items make far more than 4 pairs).
    cfg_pipe = MinerConfig(
        min_support=0.03, engine=engine, ingest_pipeline_blocks=4,
        ingest_threads=1, pair_cap=4,
    )
    miner_pipe = FastApriori(config=cfg_pipe, context=ctx)
    lv_pipe, d_pipe = miner_pipe.run_file_raw(str(path))
    pre_events = [
        r
        for r in miner_pipe.metrics.records
        if r.get("event") == "bitmap_build"
    ]
    assert pre_events and pre_events[0].get("pair_overlapped") is True

    cfg_plain = MinerConfig(
        min_support=0.03, engine=engine, ingest_pipeline_blocks=1
    )
    lv_plain, d_plain = FastApriori(
        config=cfg_plain, context=DeviceContext(num_devices=1)
    ).run_file_raw(str(path))
    assert len(lv_pipe) == len(lv_plain)
    for (m_a, c_a), (m_b, c_b) in zip(lv_pipe, lv_plain):
        assert (m_a == m_b).all() and (c_a == c_b).all()
    assert (d_pipe.item_counts == d_plain.item_counts).all()


@pytest.mark.skipif(
    not native_available(), reason="native extension not built"
)
def test_capture_ingest_without_csr_matches_plain(tmp_path):
    """retain_csr=False: the capture ingest skips the global basket-CSR
    copies (items are consumed inside the callback — bitmap packing +
    heavy-row extraction); levels must stay bit-exact vs the plain path
    including the heavy-row weight split, and the CSR-consuming paths
    must fail loudly on the CSR-less CompressedData."""
    from conftest import random_dataset
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.models.apriori import FastApriori
    from fastapriori_tpu.parallel.mesh import DeviceContext

    d_raw = (
        ["2 5 8"] * 150  # heavy rows: w >= 128 forces the heavy split
        + random_dataset(31, n_txns=300, n_items=20, max_len=9)
    )
    path = tmp_path / "D.dat"
    path.write_text("".join(l + "\n" for l in d_raw))

    ctx = DeviceContext(num_devices=1)
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.04, engine="level", ingest_pipeline_blocks=4,
            ingest_threads=1, retain_csr=False,
        ),
        context=ctx,
    )
    lv, d = miner.run_file_raw(str(path))
    assert d.basket_indices.size == 0  # CSR really skipped
    assert d.total_count > 0

    lv_plain, d_plain = FastApriori(
        config=MinerConfig(
            min_support=0.04, engine="level", ingest_pipeline_blocks=1
        ),
        context=DeviceContext(num_devices=1),
    ).run_file_raw(str(path))
    assert len(lv) == len(lv_plain)
    for (m_a, c_a), (m_b, c_b) in zip(lv, lv_plain):
        assert (m_a == m_b).all() and (c_a == c_b).all()
    assert d.weights.sum() == d_plain.weights.sum()

    # CSR-consuming paths refuse the CSR-less data instead of silently
    # mining an empty lattice.
    with pytest.raises(InputError, match="retain_csr"):
        miner._mine_levels(d)


def test_split_buffer_ranges_matches_read_shard(tmp_path):
    """split_buffer_ranges must agree byte-for-byte with read_shard's
    alignment rule on adversarial content (no trailing newline, empty
    lines, long lines)."""
    import random

    from fastapriori_tpu.preprocess import read_shard, split_buffer_ranges

    rng = random.Random(77)
    lines = []
    for _ in range(200):
        r = rng.random()
        if r < 0.1:
            lines.append("")
        else:
            lines.append(
                " ".join(str(rng.randint(0, 30)) for _ in range(rng.randint(1, 40)))
            )
    raw = "\n".join(lines)
    for trailing in ("", "\n"):
        data = (raw + trailing).encode()
        path = tmp_path / "D.dat"
        path.write_bytes(data)
        for n in (1, 2, 3, 5, 8, 50):
            ranges = split_buffer_ranges(data, n)
            assert ranges[0][0] == 0 and ranges[-1][1] == len(data)
            parts = [data[lo:hi] for lo, hi in ranges]
            shards = [read_shard(str(path), i, n) for i in range(n)]
            assert parts == shards


@pytest.mark.parametrize("n_devices,cand", [(1, 1), (8, 2)])
def test_multi_chunk_batched_level_launch(n_devices, cand):
    """NB>1 in the batched level launch (several prefix chunks scanned
    inside one dispatch, models/apriori.py _count_level): tiny caps force
    many chunks per level — stacking, the device-side scan, the pow-2
    block padding, and the per-block collect indexing must all stay
    bit-exact vs the oracle on 1-D and 2-D meshes."""
    from conftest import random_dataset, tokenized
    from fastapriori_tpu import oracle
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.models.apriori import FastApriori

    lines = tokenized(random_dataset(29, n_txns=180, n_items=16, max_len=8))
    expected, _, _ = oracle.mine(lines, 0.05)
    got, _, _ = FastApriori(
        config=MinerConfig(
            min_support=0.05, engine="level", level_prefix_cap=4,
            min_prefix_bucket=1, level_cand_cap=8,
            num_devices=n_devices, cand_devices=cand,
        )
    ).run(lines)
    assert dict(got) == dict(expected)


@pytest.mark.parametrize("cand", [1, 2])
@pytest.mark.parametrize("dups", [128, 300, 16500])
def test_level_engine_heavy_weight_split(dups, cand):
    """Multiplicities >= 128 route through the single-low-digit weight
    split (main kernels count w % 128; the remainder rides the tiny
    heavy-row int32 correction — ops/count.py heavy_*_correction).
    16500 crosses the old 2-digit bound, proving the remainder path has
    no digit limit.  Must match the oracle exactly.  cand=2 exercises
    the _heavy_gate shard-0 gating under a 2-D (txn x cand) mesh, where
    the one-hot varies over the cand axis (ADVICE r3)."""
    lines = tokenized(
        ["1 2 3"] * dups + ["1 2 4"] * 60 + ["2 3 4 5"] * 9 + ["5 6"] * 3
    )
    expected, _, _ = oracle.mine(lines, 2.0 / len(lines))
    got, _, _ = FastApriori(
        config=MinerConfig(
            min_support=2.0 / len(lines), engine="level", num_devices=8,
            cand_devices=cand,
        )
    ).run(lines)
    assert dict(got) == dict(expected)


def test_level_engine_heavy_split_cap_fallback():
    """More heavy rows than HEAVY_SPLIT_CAP falls back to the legacy
    multi-digit path — same results either way."""
    lines = tokenized(
        [f"{i} {i + 1}" for i in range(40) for _ in range(130)]
    )
    ms = 2.0 / len(lines)
    expected, _, _ = oracle.mine(lines, ms)
    miner = FastApriori(
        config=MinerConfig(min_support=ms, engine="level", num_devices=1)
    )
    miner.HEAVY_SPLIT_CAP = 8  # force the fallback (40 heavy rows)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)


def test_pair_cap_overflow_regather_and_hint():
    """A pair_cap below the survivor count must re-extract over the
    resident count matrix (exact result, no Gram re-run) and record the
    grown budget so the second run needs no regather at all."""
    lines = tokenized(random_dataset(3, n_txns=200, max_len=8))
    expected, _, _ = oracle.mine(lines, 0.02)
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.02, engine="level", num_devices=1, pair_cap=8,
            log_metrics=True,
        )
    )
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    # The grown budget was recorded against this profile...
    assert miner.context._pair_caps, "grown pair cap not remembered"
    # ...and a repeat run is still exact (single dispatch path).
    got2, _, _ = miner.run(lines)
    assert dict(got2) == dict(expected)


# ---------------------------------------------------------------------------
# r6 latency-hiding pipeline: dispatch budget, level-3 fold, threaded ingest


def _mine_loop_dispatches(records):
    """The dispatch-accounting trace's mining-loop total — the same
    aggregation bench.py's _phase_summary reports as ``dispatches``
    (per-event counts; ingest-overlapped level 2/3 fetches carry 0)."""
    return sum(
        int(r.get("dispatches", 1))
        for r in records
        if r.get("event")
        in ("level", "tail_fuse", "fused_mine", "pair_prepass",
            "counts_drain")
    )


@pytest.mark.skipif(
    not native_available(), reason="native extension not built"
)
def test_webdocs_shaped_mine_dispatch_budget(tmp_path):
    """Regression pin for the r6 dispatch fold (ISSUE 3 acceptance): a
    webdocs-shaped mine — deep lattice, pipelined capture ingest,
    shallow-tail fold — runs in <= 5 mining-loop device dispatches.
    Levels 2 AND 3 ride the ONE ingest-overlapped dispatch (their level
    events are pure fetches, dispatches=0), the tail fold absorbs the
    deep levels, and the output stays byte-identical vs the oracle."""
    from conftest import tokenized
    from fastapriori_tpu.parallel.mesh import DeviceContext

    deep = ["0 1 2 3 4 5 6 7 8 9 10 11"] * 40  # 12-deep closed lattice
    noise = random_dataset(17, n_txns=300, n_items=30, max_len=6)
    d_raw = deep + noise
    path = tmp_path / "D.dat"
    path.write_text("".join(l + "\n" for l in d_raw))
    ms = 30.0 / len(d_raw)

    cfg = MinerConfig(
        min_support=ms, engine="level", ingest_pipeline_blocks=4,
        ingest_threads=2, tail_fuse_rows=65536,
        # Row-budget floor so the fold's pow2 budget covers this
        # lattice's mid-peak (924 rows at k=6) from the k=3 seed — the
        # webdocs-shaped analog of folding near the peak.
        min_prefix_bucket=2048,
    )
    miner = FastApriori(config=cfg, context=DeviceContext(num_devices=1))
    lv, d = miner.run_file_raw(str(path))
    assert len(lv) >= 8, "not webdocs-shaped: lattice too shallow"

    lev = {
        r.get("k"): r
        for r in miner.metrics.records
        if r.get("event") == "level"
    }
    assert lev[2].get("overlapped") and lev[2].get("dispatches") == 0
    assert lev[3].get("overlapped") and lev[3].get("dispatches") == 0
    disp = _mine_loop_dispatches(miner.metrics.records)
    assert disp <= 5, f"mining loop used {disp} dispatches (budget 5)"

    expected, _, _ = oracle.mine(tokenized(d_raw), ms)
    got = miner._decode_levels(lv, d)
    assert dict(got) == dict(expected)
    assert len(got) == len(expected)


@pytest.mark.skipif(
    not native_available(), reason="native extension not built"
)
def test_pair_l3_overflow_falls_back_and_records_budget(tmp_path):
    """A pair_l3 budget below the true level-3 survivor count must fall
    back to the classic level-3 dispatch (exact results) and record the
    grown budgets so a repeat run folds."""
    d_raw = random_dataset(23, n_txns=400, n_items=16, max_len=10)
    path = tmp_path / "D.dat"
    path.write_text("".join(l + "\n" for l in d_raw))

    from fastapriori_tpu.parallel.mesh import DeviceContext

    ctx = DeviceContext(num_devices=1)
    cfg = MinerConfig(
        min_support=0.03, engine="level", ingest_pipeline_blocks=2,
        ingest_threads=1, pair_l3_cap=4,  # far below the survivor count
    )
    miner = FastApriori(config=cfg, context=ctx)
    lv, d = miner.run_file_raw(str(path))
    lev3 = [
        r for r in miner.metrics.records
        if r.get("event") == "level" and r.get("k") == 3
    ]
    assert lev3 and not lev3[0].get("overlapped")  # classic dispatch ran

    lv2, _ = FastApriori(
        config=MinerConfig(
            min_support=0.03, engine="level", ingest_pipeline_blocks=1
        ),
        context=DeviceContext(num_devices=1),
    ).run_file_raw(str(path))
    assert len(lv) == len(lv2)
    for (a, ca), (b, cb) in zip(lv, lv2):
        assert (a == b).all() and (ca == cb).all()

    # The grown cap3 was recorded: the repeat run folds level 3.
    miner2 = FastApriori(config=cfg, context=ctx)
    lv3_run, _ = miner2.run_file_raw(str(path))
    lev3b = [
        r for r in miner2.metrics.records
        if r.get("event") == "level" and r.get("k") == 3
    ]
    assert lev3b and lev3b[0].get("overlapped")
    for (a, ca), (b, cb) in zip(lv3_run, lv2):
        assert (a == b).all() and (ca == cb).all()


@pytest.mark.skipif(
    not native_available(), reason="native extension not built"
)
@pytest.mark.parametrize("n_threads", [2, 3])
def test_capture_ingest_threaded_matches_serial(tmp_path, n_threads):
    """The parallel segmented pass-1 capture + threaded pass-2 replay
    (native/preprocess.cc, VERDICT r5 next #3) must mine byte-identically
    to the serial capture path: same global tables, same levels (weighted
    counts are block-structure-invariant)."""
    d_raw = (
        ["4 7 9 11"] * 140  # heavy rows cross the w>=128 split
        + random_dataset(37, n_txns=500, n_items=20, max_len=9)
        + ["", "  "] * 10  # edge lines
    )
    path = tmp_path / "D.dat"
    path.write_text("".join(l + "\n" for l in d_raw))

    from fastapriori_tpu.parallel.mesh import DeviceContext

    def mine(threads):
        cfg = MinerConfig(
            min_support=0.03, engine="level", ingest_pipeline_blocks=4,
            ingest_threads=threads,
        )
        m = FastApriori(config=cfg, context=DeviceContext(num_devices=1))
        return m.run_file_raw(str(path)), m

    (lv1, d1), _ = mine(1)
    (lvN, dN), miner = mine(n_threads)
    pre = [
        r for r in miner.metrics.records if r.get("event") == "preprocess"
    ]
    assert pre and pre[0].get("threads") == n_threads
    assert d1.n_raw == dN.n_raw and d1.min_count == dN.min_count
    assert d1.freq_items == dN.freq_items
    assert (d1.item_counts == dN.item_counts).all()
    assert d1.weights.sum() == dN.weights.sum()
    assert len(lv1) == len(lvN)
    for (a, ca), (b, cb) in zip(lv1, lvN):
        assert (a == b).all() and (ca == cb).all()


def test_ingest_threads_env_override(monkeypatch):
    """FA_INGEST_THREADS overrides the config; typos are InputError
    (strict parse, like FA_NO_PALLAS)."""
    from fastapriori_tpu.errors import InputError
    from fastapriori_tpu.preprocess import ingest_thread_count

    monkeypatch.delenv("FA_INGEST_THREADS", raising=False)
    assert ingest_thread_count(3) == 3
    assert ingest_thread_count(None) >= 1
    monkeypatch.setenv("FA_INGEST_THREADS", "5")
    assert ingest_thread_count(3) == 5
    for bad in ("zero", "0", "-2", "1.5"):
        monkeypatch.setenv("FA_INGEST_THREADS", bad)
        with pytest.raises(InputError, match="FA_INGEST_THREADS"):
            ingest_thread_count(None)


def test_tail_fold_carries_counts_resolve():
    """ISSUE 4 satellite (ROADMAP pipeline follow-up): when the tail
    fold finishes a mine with deferred counts pending, the end-of-mine
    counts_resolve gather rides the SAME dispatch — the resolve event
    still reports its own (now zero) dispatch count, and the output is
    bit-exact vs the unfolded path."""
    from conftest import random_dataset, tokenized

    lines = tokenized(
        ["1 2 3 4 5 6"] * 50
        + ["1 2 3 4 5"] * 30
        + ["2 3 4 5 6"] * 20
        + random_dataset(5, n_txns=60, max_len=6)
    )
    folded = FastApriori(
        config=MinerConfig(
            min_support=0.05, engine="level", num_devices=1,
            tail_fuse_rows=64,
        )
    )
    got = folded.run(lines)[0]
    tails = [
        r for r in folded.metrics.records if r.get("event") == "tail_fuse"
    ]
    assert tails and tails[0].get("resolve_folded") is True
    res = [
        r
        for r in folded.metrics.records
        if r.get("event") == "counts_resolve"
    ]
    assert res and res[0]["dispatches"] == 0 and res[0]["drains"] == 1
    plain = FastApriori(
        config=MinerConfig(
            min_support=0.05, engine="level", num_devices=1,
            tail_fuse_rows=0,
        )
    )
    assert sorted(got) == sorted(plain.run(lines)[0])


def test_tail_entry_near_peak_gate():
    """The lowered tail-fold entry (ISSUE 3): shrinking or near-peak
    (<= 20% growth) seeds enter; a still-doubling mid-lattice does not."""
    ok = FastApriori._tail_entry_ok
    assert ok(False, 50_000, None)  # explicit rows: always
    assert ok(True, 16_384, None)  # legacy small-seed bar
    assert not ok(True, 20_000, None)  # big seed, no evidence
    assert ok(True, 20_000, 25_000)  # shrinking
    assert ok(True, 24_000, 20_000)  # near-peak: +20%
    assert not ok(True, 30_000, 20_000)  # still growing fast
