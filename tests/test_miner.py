"""The full mining engine vs the oracle (C6-C9)."""

import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu import oracle
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("min_support", [0.05, 0.1, 0.2])
def test_miner_matches_oracle(seed, min_support):
    lines = tokenized(random_dataset(seed))
    expected, exp_rank, exp_items = oracle.mine(lines, min_support)

    miner = FastApriori(min_support, num_devices=1)
    got, item_to_rank, freq_items = miner.run(lines)

    assert freq_items == exp_items
    assert item_to_rank == exp_rank
    assert dict(got) == dict(expected)
    assert len(got) == len(expected)


def test_miner_dense_data_many_levels():
    # Highly correlated baskets force levels >= 4.
    lines = tokenized(
        ["1 2 3 4 5"] * 10
        + ["1 2 3 4"] * 5
        + ["2 3 4 5"] * 5
        + ["6 7"] * 3
        + ["1", "8 9"]
    )
    expected, _, _ = oracle.mine(lines, 0.2)
    miner = FastApriori(0.2, num_devices=1)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    assert max(len(s) for s, _ in got) >= 4


def test_miner_no_frequent_pairs():
    lines = tokenized(["1 2", "3 4", "5 6", "7 8"])
    miner = FastApriori(0.5, num_devices=1)
    got, _, freq_items = miner.run(lines)
    assert got == [] and freq_items == []


def test_miner_only_singletons():
    lines = tokenized(["1", "1", "2", "1 2"])
    expected, _, _ = oracle.mine(lines, 0.5)
    miner = FastApriori(0.5, num_devices=1)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    assert all(len(s) == 1 for s, _ in got)


def test_miner_repeated_baskets_weighting():
    # 60 identical baskets dedupe to one row with weight 60; exercises the
    # weighted counting path (weight < 128, single digit).
    lines = tokenized(["10 20 30"] * 60 + ["10 20"] * 5 + ["40"] * 3)
    expected, _, _ = oracle.mine(lines, 0.1)
    got, _, _ = FastApriori(0.1, num_devices=1).run(lines)
    assert dict(got) == dict(expected)


def test_miner_weight_overflow_digit():
    # >128 identical baskets forces a second base-128 digit.
    lines = tokenized(["1 2 3"] * 300 + ["4 5"] * 10)
    expected, _, _ = oracle.mine(lines, 0.05)
    got, _, _ = FastApriori(0.05, num_devices=1).run(lines)
    assert dict(got) == dict(expected)


def test_miner_small_prefix_bucket():
    # Tiny bucket forces multi-chunk level counting.
    lines = tokenized(random_dataset(7, n_items=10, n_txns=100))
    cfg = MinerConfig(min_support=0.05, min_prefix_bucket=2, num_devices=1)
    expected, _, _ = oracle.mine(lines, 0.05)
    got, _, _ = FastApriori(0.05, config=cfg).run(lines)
    assert dict(got) == dict(expected)


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("engine", ["fused", "level"])
def test_apriori_invariants(seed, engine):
    """SURVEY §4 property tests on the miner's own output:

    - downward closure: every (k-1)-subset of a frequent k-set (k >= 3)
      is itself in the result, and every 2-subset too;
    - count monotonicity: count(S) <= count(S - {i}).  For |S| = 2 the
      comparison is against the 1-itemsets' RAW occurrence counts
      (within-line duplicates and dropped size<=1 baskets included,
      FastApriori.scala:55 vs :70), which can only exceed the
      deduplicated basket support.
    """
    lines = tokenized(
        random_dataset(seed, n_txns=150, n_items=14, max_len=7)
    )
    itemsets, _, _ = FastApriori(
        config=MinerConfig(min_support=0.04, engine=engine, num_devices=8)
    ).run(lines)
    counts = dict(itemsets)
    assert itemsets, "degenerate dataset"
    for s, c in itemsets:
        assert c > 0
        if len(s) < 2:
            continue
        for item in s:
            sub = s - {item}
            assert sub in counts, (s, sub)
            assert c <= counts[sub], (s, c, sub, counts[sub])
