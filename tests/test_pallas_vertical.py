"""Pallas kernel tier for the vertical popcount and serving scan hot
loops (ISSUE 18): the VMEM-resident vertical kernel and the strided
first-match serving kernel must be BIT-EXACT in interpreter mode
against the XLA vertical path and the bitmap differential oracle on
every corpus shape x mesh size, their engine-selection/env tables
mirror the FA_NO_PALLAS contract, transient exhaustion walks the
``vertical_kernel`` cascade to the exact-by-construction XLA path,
and kill-and-resume stays byte-identical with the tier engaged.

CPU-only: the kernels are TPU-gated in production
(DeviceContext._vertical_pallas_plan / _serve_pallas_plan return None
off-TPU), so every test here monkeypatches the plan hook to force an
``interpret=True`` plan — the documented test seam.  Interpreter mode
proves VALUES, not VMEM behaviour; real-chip shape coverage is the
standing TPU-time item (ROADMAP)."""

import inspect

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.io import checkpoint as ckpt
from fastapriori_tpu.io import writer
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.parallel.mesh import DeviceContext
from fastapriori_tpu.reliability import failpoints, ledger, retry


@pytest.fixture(autouse=True)
def _clean_state():
    failpoints.disarm_all()
    ledger.reset()
    yield
    failpoints.disarm_all()
    ledger.reset()


def _mine(lines, min_support, **cfg):
    miner = FastApriori(
        config=MinerConfig(min_support=min_support, **cfg)
    )
    got, _, _ = miner.run(lines)
    return dict(got), miner


def _patch_vertical_pallas(monkeypatch, lane_tile=128):
    """Force the interpreter-mode vertical Pallas plan on CPU: the
    candidate tile walks a small ladder (test candidate counts are
    modest), the lane tile is the caller's, interpret=True."""
    from fastapriori_tpu.ops.pallas_level import pick_tile

    def plan(self, arena, ps, cs, n_planes, lt):
        if self._vertical_pallas_off:  # honor the sticky cascade switch
            return None
        return (
            pick_tile(cs.shape[1], (256, 128, 64, 32, 16, 8, 4, 2, 1)),
            lane_tile,
            True,
        )

    monkeypatch.setattr(DeviceContext, "_vertical_pallas_plan", plan)


# ---------------------------------------------------------------------------
# corpora (mirrors tests/test_vertical.py: the shapes the XLA engine is
# pinned on are exactly the shapes the Pallas tier must match)


def _t10i4_shaped(n_txns=1500):
    from fastapriori_tpu.utils.datagen import generate_transactions

    return [
        l.split()
        for l in generate_transactions(
            n_txns=n_txns, n_items=90, avg_txn_len=9, n_patterns=30,
            avg_pattern_len=4, corruption=0.35, seed=11,
        )
    ]


def _webdocs_shaped():
    return tokenized(
        random_dataset(23, n_txns=400, n_items=40, max_len=12)
    )


def _deep_lattice():
    return tokenized(
        random_dataset(13, n_txns=200, n_items=14, max_len=9)
    )


def _no_survivor_level():
    return tokenized(random_dataset(3, n_txns=120))


# ---------------------------------------------------------------------------
# differential matrix: Pallas (interpreter) vs XLA vertical vs bitmap


@pytest.mark.parametrize(
    "lines_fn, min_support",
    [
        (_t10i4_shaped, 0.03),
        (_webdocs_shaped, 0.04),
        (_deep_lattice, 0.05),
        (_no_survivor_level, 0.4),
    ],
    ids=["t10i4", "webdocs", "deep-lattice", "no-survivor"],
)
@pytest.mark.parametrize("n_devices", [1, 2, 4])
def test_pallas_vertical_bitexact_differential(
    monkeypatch, lines_fn, min_support, n_devices
):
    lines = lines_fn()
    exp, _ = _mine(
        lines, min_support, engine="level", num_devices=n_devices,
        mine_engine="bitmap",
    )
    xla, _ = _mine(
        lines, min_support, engine="level", num_devices=n_devices,
        mine_engine="vertical",
    )
    assert xla == exp
    _patch_vertical_pallas(monkeypatch)
    pal, miner = _mine(
        lines, min_support, engine="level", num_devices=n_devices,
        mine_engine="vertical",
    )
    assert pal == exp
    # The Pallas tier really ran (every vlevel dispatch got the forced
    # plan) — except on corpora whose lattice dies before k=3, where
    # there is no vlevel dispatch to run it (the no-survivor case).
    if any(len(items) >= 3 for items in pal):
        assert miner.context.vertical_pallas_active()


@pytest.mark.parametrize("lane_tile", [16, 32, 64])
def test_pallas_lane_tile_boundaries_bitexact(monkeypatch, lane_tile):
    """Lane counts that divide, under-fill, and straddle the tile (the
    kernel zero-pads the lane axis to a tile multiple; zero words add
    zero popcount, so every boundary case must stay exact)."""
    lines = _t10i4_shaped()
    exp, _ = _mine(lines, 0.03, engine="level", mine_engine="bitmap")
    _patch_vertical_pallas(monkeypatch, lane_tile=lane_tile)
    pal, _ = _mine(lines, 0.03, engine="level", mine_engine="vertical")
    assert pal == exp


# ---------------------------------------------------------------------------
# lane chunking (the ceiling lift) — XLA path and knob contracts


def test_xla_lane_tiling_past_ceiling_bitexact(monkeypatch):
    """FA_VERTICAL_LANE_TILE=128 on a ~6000-txn corpus forces multiple
    lane slabs through the scan (arena words >> tile) — the same slab
    code path a real >50K-lane (>1.6M-txn) arena takes with the default
    8192 tile, shrunk to tier-1 scale.  Must stay bit-exact vs bitmap,
    which never tiles lanes."""
    monkeypatch.setenv("FA_VERTICAL_LANE_TILE", "128")
    lines = _t10i4_shaped(n_txns=6000)
    exp, _ = _mine(lines, 0.03, engine="level", mine_engine="bitmap")
    got, miner = _mine(
        lines, 0.03, engine="level", mine_engine="vertical"
    )
    assert got == exp
    assert miner._vertical_lane_tile() == 128
    # The corpus really overflows the tile (else this test is vacuous).
    assert -(-len(lines) // 32) > 128


def test_pallas_with_lane_tiling_bitexact(monkeypatch):
    """Both tiers tile lanes under the same knob: the Pallas plan's
    lane tile and the XLA slab scan must agree with the oracle."""
    monkeypatch.setenv("FA_VERTICAL_LANE_TILE", "128")
    lines = _t10i4_shaped(n_txns=6000)
    exp, _ = _mine(lines, 0.03, engine="level", mine_engine="bitmap")
    _patch_vertical_pallas(monkeypatch, lane_tile=128)
    pal, _ = _mine(lines, 0.03, engine="level", mine_engine="vertical")
    assert pal == exp


def test_lane_tile_pow2_bucketed_with_floor():
    """G011: one compiled program per pow2 bucket, 128 floor."""
    def tile(**kw):
        return FastApriori(
            config=MinerConfig(min_support=0.1, **kw)
        )._vertical_lane_tile()

    assert tile() == 1 << 13  # the documented default
    assert tile(vertical_lane_tile=100) == 128  # floor
    assert tile(vertical_lane_tile=5000) == 8192  # next pow2
    assert tile(vertical_lane_tile=4096) == 4096  # exact pow2 kept


def test_env_lane_tile_overrides_and_strict(monkeypatch):
    monkeypatch.setenv("FA_VERTICAL_LANE_TILE", "300")
    m = FastApriori(config=MinerConfig(min_support=0.1))
    assert m._vertical_lane_tile() == 512
    monkeypatch.setenv("FA_VERTICAL_LANE_TILE", "4k")  # the typo class
    with pytest.raises(InputError, match="FA_VERTICAL_LANE_TILE"):
        FastApriori(
            config=MinerConfig(min_support=0.1)
        )._vertical_lane_tile()


# ---------------------------------------------------------------------------
# engine-selection / env-strictness table (the FA_NO_PALLAS contract)


def test_no_pallas_typo_fails_loudly_on_cpu():
    """The strict parse runs at vertical dispatch on EVERY backend — a
    typo'd kill switch must not silently no-op just because this host
    has no TPU."""
    import os

    os.environ["FA_NO_PALLAS"] = "maybe"
    try:
        with pytest.raises(InputError, match="FA_NO_PALLAS"):
            _mine(
                _deep_lattice(), 0.05, engine="level",
                mine_engine="vertical",
            )
    finally:
        del os.environ["FA_NO_PALLAS"]


def _gate_args():
    """Dummy shape-carrying args for the plan hook (it reads shapes
    only): 256 candidates divide the production cand-tile ladder."""
    arena = np.zeros((257, 64), np.uint32)
    prefix = np.zeros((256, 4), np.int32)
    cand = np.zeros((1, 256), np.int32)
    return arena, prefix, cand


def test_vertical_gate_table(monkeypatch, capsys):
    ctx = DeviceContext(num_devices=1)
    arena, prefix, cand = _gate_args()
    # CPU: never a candidate, regardless of the env value.
    assert ctx._vertical_pallas_plan(arena, prefix, cand, 1, 8192) is None
    monkeypatch.setenv("FA_NO_PALLAS", "1")
    assert ctx._vertical_pallas_plan(arena, prefix, cand, 1, 8192) is None
    monkeypatch.delenv("FA_NO_PALLAS")
    # TPU platform (faked): the gate engages, with a non-interpret plan.
    monkeypatch.setattr(
        DeviceContext, "platform", property(lambda self: "tpu")
    )
    plan = ctx._vertical_pallas_plan(arena, prefix, cand, 1, 8192)
    assert plan is not None and plan[-1] is False
    ct, lt, _interp = plan
    assert 256 % ct == 0 and lt >= 128
    # Falsy spellings keep it on.
    for v in ("0", "false", "no", ""):
        monkeypatch.setenv("FA_NO_PALLAS", v)
        assert (
            ctx._vertical_pallas_plan(arena, prefix, cand, 1, 8192)
            is not None
        )
    # Kill switch: off, every dispatch ledger-recorded, but the
    # operator warning printed ONCE (the once_key="env" contract).
    monkeypatch.setenv("FA_NO_PALLAS", "on")
    assert ctx._vertical_pallas_plan(arena, prefix, cand, 1, 8192) is None
    assert ctx._vertical_pallas_plan(arena, prefix, cand, 1, 8192) is None
    evs = [
        e for e in ledger.snapshot() if e["kind"] == "pallas_disabled"
    ]
    assert len(evs) == 2
    assert all(e["reason"] == "FA_NO_PALLAS" for e in evs)
    assert capsys.readouterr().err.count("pallas_disabled") == 1
    monkeypatch.delenv("FA_NO_PALLAS")
    # Sticky local disable (the cascade walk's switch): forward-only.
    ctx.disable_vertical_pallas()
    assert ctx._vertical_pallas_plan(arena, prefix, cand, 1, 8192) is None


def test_serve_gate_table(monkeypatch):
    ctx = DeviceContext(num_devices=1)
    assert ctx._serve_pallas_plan(512) is None  # CPU
    monkeypatch.setattr(
        DeviceContext, "platform", property(lambda self: "tpu")
    )
    assert ctx._serve_pallas_plan(512) == (512, False)
    monkeypatch.setenv("FA_NO_PALLAS", "yes")
    assert ctx._serve_pallas_plan(512) is None
    monkeypatch.delenv("FA_NO_PALLAS")
    ctx.disable_serve_pallas()
    assert ctx._serve_pallas_plan(512) is None


def test_cpu_runs_never_select_pallas():
    """The acceptance line: TPU-only execution runtime-gates cleanly on
    CPU — a plain vertical mine neither crashes nor engages the tier."""
    lines = _deep_lattice()
    got, miner = _mine(
        lines, 0.05, engine="level", mine_engine="vertical"
    )
    exp, _ = _mine(lines, 0.05, engine="level", mine_engine="bitmap")
    assert got == exp
    assert miner.context.vertical_pallas_active() is False


# ---------------------------------------------------------------------------
# cascade: transient exhaustion walks vertical_kernel pallas -> xla


def test_vertical_kernel_cascade_walks_to_xla(monkeypatch):
    """Unlimited oom at the vlevel fetch with the Pallas tier active:
    the FIRST exhaustion walks vertical_kernel pallas->xla (sticky,
    ledger-recorded); the still-armed fetch then exhausts the XLA
    retier too and the engine chain finishes on bitmap — the full
    forward-only walk, bit-exact at the end."""
    monkeypatch.setenv("FA_RETRY_MAX", "2")
    monkeypatch.setenv("FA_RETRY_BACKOFF_MS", "0")
    retry.reload_policy_from_env()
    try:
        lines = _deep_lattice()
        exp, _ = _mine(lines, 0.05, engine="level", mine_engine="bitmap")
        ledger.reset()
        _patch_vertical_pallas(monkeypatch)
        failpoints.arm("fetch.vlevel_bits", "oom")  # every attempt
        got, miner = _mine(
            lines, 0.05, engine="level", mine_engine="vertical"
        )
        failpoints.disarm_all()
        assert got == exp
        casc = [
            e for e in ledger.snapshot() if e["kind"] == "cascade"
        ]
        assert any(
            e["chain"] == "vertical_kernel"
            and e["frm"] == "pallas"
            and e["to"] == "xla"
            and e["reason"] == "transient_exhausted"
            for e in casc
        )
        assert any(
            e["chain"] == "mine_engine"
            and e["frm"] == "vertical"
            and e["to"] == "bitmap"
            for e in casc
        )
        # Sticky: the tier stays off for the rest of the process run.
        assert miner.context.vertical_pallas_active() is False
    finally:
        retry.reload_policy_from_env()


# ---------------------------------------------------------------------------
# kill-and-resume stays byte-identical with the tier engaged


def test_pallas_kill_resume_round_trip_bit_exact(tmp_path, monkeypatch):
    lines = _deep_lattice()
    prefix = str(tmp_path) + "/"
    cfg = dict(min_support=0.05, engine="level")
    clean_sets, _, clean_items = FastApriori(
        config=MinerConfig(**cfg)
    ).run(lines)
    _patch_vertical_pallas(monkeypatch)
    failpoints.arm("level.3", "abort")  # die right after level 3 commits
    miner = FastApriori(
        config=MinerConfig(
            mine_engine="vertical", checkpoint_prefix=prefix, **cfg
        )
    )
    with pytest.raises(failpoints.InjectedAbort):
        miner.run(lines)
    failpoints.disarm_all()
    levels, meta = ckpt.load_checkpoint(prefix)
    assert levels[-1][0].shape[1] == 3
    resumed = FastApriori(
        config=MinerConfig(mine_engine="vertical", **cfg)
    )
    resumed.set_resume_levels(levels, meta, label=prefix)
    got_sets, _, got_items = resumed.run(lines)
    assert got_items == clean_items
    out_a, out_b = str(tmp_path / "a_"), str(tmp_path / "b_")
    writer.save_freq_itemsets(out_a, clean_sets, clean_items)
    writer.save_freq_itemsets(out_b, got_sets, got_items)
    assert (
        open(out_a + "freqItemset", "rb").read()
        == open(out_b + "freqItemset", "rb").read()
    )


# ---------------------------------------------------------------------------
# satellite: ops/pallas_level.py design-note constants stay pinned


def test_pallas_level_design_constants_pinned():
    from fastapriori_tpu.ops import pallas_level, pallas_vertical

    # The measured production tiles from the module's design note: t
    # generous ([tt, F] int8 B tiles are cheap), m bounded so the VMEM
    # [mt, tt] membership tile stays <= 16 MB.
    assert pallas_level.T_TILE == 4096
    assert pallas_level.M_TILE == 1024
    # pick_tile: largest ladder entry evenly dividing n, 0 = no fit.
    assert pallas_level.pick_tile(8192) == 4096
    assert pallas_level.pick_tile(768) == 256
    assert pallas_level.pick_tile(4224) == 0
    assert pallas_level.pick_tile(512, (512, 128)) == 512
    # The vertical kernel shares the SAME helper (one tile-planning
    # idiom across kernel modules, not a drifting copy).
    assert pallas_vertical.pick_tile is pallas_level.pick_tile


def test_level_gate_wb_single_digit_contract_pinned():
    """The level kernel takes ONE unscaled w (.) B digit; the mesh gate
    must keep routing multi-digit weight profiles to the XLA path."""
    src = inspect.getsource(DeviceContext.level_gather_batch)
    assert 'tuple(scales) == (1,)' in src
