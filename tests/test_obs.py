"""Observability substrate (ISSUE 11): span tracer determinism +
schema, the serving metrics registry's bucket exactness, the flight
recorder's ring semantics and failure dumps, the MetricsLogger
retention bound, and phase_timer routing."""

import json
import os
import threading
import time

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.obs import flight, metrics, trace
from fastapriori_tpu.obs.flight import FlightRecorder
from fastapriori_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from fastapriori_tpu.obs.trace import (
    FETCH_SITE_SPANS,
    TRACER,
    Tracer,
    validate_chrome_trace,
)
from fastapriori_tpu.preprocess import preprocess
from fastapriori_tpu.reliability import failpoints, ledger, retry, watchdog
from fastapriori_tpu.utils.logging import MetricsLogger, phase_timer


@pytest.fixture(autouse=True)
def _clean_state():
    failpoints.disarm_all()
    ledger.reset()
    TRACER.disable()
    TRACER.reset()
    flight.RECORDER.reset()
    yield
    failpoints.disarm_all()
    ledger.reset()
    TRACER.disable()
    TRACER.reset()
    flight.RECORDER.set_dump_prefix(None)
    flight.RECORDER.reset()


D_LINES = tokenized(random_dataset(31, n_txns=220, max_len=7))


def _mine_traced():
    TRACER.enable()
    data = preprocess(D_LINES, 0.05)
    cfg = MinerConfig(min_support=0.05, engine="level")
    FastApriori(config=cfg).mine_levels_raw(data)
    return TRACER.span_tree()


# ---------------------------------------------------------------------------
# tracer


def test_tracer_deterministic_span_tree_across_identical_runs():
    """Two identical seeded mines produce IDENTICAL span trees (ids,
    names, parentage) — timestamps are the only run-to-run variance."""
    t1 = _mine_traced()
    t2 = _mine_traced()
    assert t1, "traced mine recorded no spans"
    assert t1 == t2


def test_tracer_ids_count_per_parent_occurrence():
    tr = Tracer()
    tr.enable()
    with tr.span("run"):
        for _ in range(2):
            with tr.span("level"):
                with tr.span("fetch.x"):
                    pass
    tree = tr.span_tree()
    sids = [s for s, _, _ in tree]
    assert "main:run#0/level#0" in sids
    assert "main:run#0/level#1" in sids
    assert "main:run#0/level#0/fetch.x#0" in sids
    # The second level's child restarts ITS OWN occurrence counter.
    assert "main:run#0/level#1/fetch.x#0" in sids


def test_tracer_thread_roots_are_thread_named():
    tr = Tracer()
    tr.enable()

    def work():
        with tr.span("batch"):
            pass

    t = threading.Thread(target=work, name="fa-serve-dispatch")
    t.start()
    t.join()
    (sid, name, parent) = tr.span_tree()[0]
    assert sid == "fa-serve-dispatch:batch#0"
    assert parent is None


def test_chrome_trace_schema_validates():
    _mine_traced()
    obj = TRACER.chrome_trace()
    assert validate_chrome_trace(obj) == []
    # Round-trips through JSON (the export form).
    obj2 = json.loads(json.dumps(obj))
    assert validate_chrome_trace(obj2) == []
    phs = {e["ph"] for e in obj["traceEvents"]}
    assert "X" in phs and "M" in phs


def test_chrome_trace_schema_catches_malformed():
    assert validate_chrome_trace({"traceEvents": []})
    assert validate_chrome_trace({"notTraceEvents": 1})
    bad = {"traceEvents": [{"ph": "X", "name": "a", "pid": 1, "tid": 1,
                            "ts": -1, "dur": 1, "args": {"sid": "s"}}]}
    assert any("ts" in p for p in validate_chrome_trace(bad))


def test_tracer_export_is_committed_and_loadable(tmp_path):
    _mine_traced()
    path = TRACER.export(str(tmp_path / "out.trace.json"))
    with open(path) as fh:
        assert validate_chrome_trace(json.load(fh)) == []


def test_tracer_disabled_records_nothing_and_is_cheap():
    assert not TRACER.enabled
    with trace.span("x", k=1):
        trace.instant("y")
        trace.counter("z", v=1)
        trace.annotate(a=2)
    assert TRACER.events() == []
    t0 = time.perf_counter()
    for _ in range(50_000):
        with trace.span("x"):
            pass
    assert (time.perf_counter() - t0) / 50_000 < 10e-6


def test_tracer_event_cap_counts_drops():
    tr = Tracer(max_events=3)
    tr.enable()
    for _ in range(5):
        with tr.span("s"):
            pass
    assert len(tr.events()) == 3
    assert tr.dropped == 2
    assert tr.chrome_trace()["otherData"]["dropped_events"] == 2


def test_tracer_event_cap_knob(monkeypatch):
    """FA_TRACE_EVENTS (ISSUE 12 satellite / ROADMAP obs residue):
    raises the 200K default ceiling for a deliberate webdocs-scale
    capture — strictly parsed, default and counted-drop behavior
    unchanged."""
    from fastapriori_tpu.errors import InputError

    assert trace.max_events_from_env() == trace.DEFAULT_MAX_EVENTS
    monkeypatch.setenv("FA_TRACE_EVENTS", "5")
    trace.reload_from_env()
    tr = Tracer()
    assert tr.max_events == 5
    tr.enable()
    for _ in range(7):
        with tr.span("s"):
            pass
    assert len(tr.events()) == 5 and tr.dropped == 2
    # The process-wide singleton follows the reload too.
    assert TRACER.max_events == 5
    monkeypatch.setenv("FA_TRACE_EVENTS", "many")
    with pytest.raises(InputError, match="FA_TRACE_EVENTS"):
        trace.reload_from_env()
    monkeypatch.setenv("FA_TRACE_EVENTS", "0")
    with pytest.raises(InputError, match="out of range"):
        Tracer()
    monkeypatch.delenv("FA_TRACE_EVENTS")
    trace.reload_from_env()
    assert TRACER.max_events == trace.DEFAULT_MAX_EVENTS


def test_fetch_spans_cover_declared_sites():
    """An audited fetch produces a span named fetch.<site>, and the
    G014 census declaration stays truthful: every declared name has the
    fetch. prefix shape the tracer emits."""
    TRACER.enable()
    arr = np.arange(4)
    retry.fetch(lambda: np.asarray(arr), "serve_match")
    names = {name for _, name, _ in TRACER.span_tree()}
    assert "fetch.serve_match" in names
    assert all(s.startswith("fetch.") for s in FETCH_SITE_SPANS)
    assert "fetch.serve_match" in FETCH_SITE_SPANS


def test_retry_annotations_land_on_fetch_span():
    TRACER.enable()
    failpoints.arm("fetch.serve_match", "oom*1")
    arr = np.arange(4)
    retry.fetch(lambda: np.asarray(arr), "serve_match")
    spans = [e for e in TRACER.events() if e["ph"] == "X"]
    (fetch_span,) = [e for e in spans if e["name"] == "fetch.serve_match"]
    assert fetch_span["args"]["retries"] == 1
    # The ledger's retry event also landed as an instant on the stream.
    instants = [e for e in TRACER.events() if e["ph"] == "i"]
    assert any(e["name"] == "degraded" for e in instants)


# ---------------------------------------------------------------------------
# metrics registry


def test_histogram_bucket_exactness():
    h = Histogram("h", (1.0, 2.0, 5.0))
    for v in (0.5, 1.0, 1.5, 2.0, 4.9, 5.0, 5.1, 100.0):
        h.observe(v)
    # le-semantics: a value equal to a bound lands IN that bound.
    assert h.counts == [2, 2, 2, 2]
    assert h.total == 8
    assert h.sum == pytest.approx(120.0)
    text = "\n".join(h.render())
    assert 'h_bucket{le="1"} 2' in text
    assert 'h_bucket{le="2"} 4' in text      # cumulative
    assert 'h_bucket{le="5"} 6' in text
    assert 'h_bucket{le="+Inf"} 8' in text
    assert "h_count 8" in text


def test_histogram_rejects_unsorted_bounds():
    with pytest.raises(ValueError):
        Histogram("h", (2.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("h", (1.0, 1.0))


def test_counter_gauge_and_registry_snapshot():
    reg = MetricsRegistry()
    c = reg.counter("c_total")
    g = reg.gauge("g")
    c.inc()
    c.inc(3)
    g.set(7)
    g.set(2)
    assert isinstance(c, Counter) and isinstance(g, Gauge)
    snap = reg.snapshot()
    assert snap["c_total"] == 4
    assert snap["g"] == {"value": 2, "max": 7}
    # get-or-create is idempotent: the hot path's reference IS the
    # registry's instrument.
    assert reg.counter("c_total") is c
    text = reg.render()
    assert "c_total 4" in text and "g_max 7" in text


def test_labeled_fetch_latency_histogram():
    metrics.GLOBAL.reset()
    metrics.fetch_latency_observe("serve_match", 3.0)
    metrics.fetch_latency_observe("serve_match", 700.0)
    metrics.fetch_latency_observe("level_bits", 1.0)
    text = metrics.GLOBAL.render()
    assert 'fa_fetch_latency_ms_count{site="serve_match"} 2' in text
    assert 'fa_fetch_latency_ms_count{site="level_bits"} 1' in text
    snap = metrics.GLOBAL.snapshot()["fa_fetch_latency_ms"]
    assert snap["serve_match"]["count"] == 2


def test_server_registry_counts_and_mid_run_scrape():
    from fastapriori_tpu.serve import RecommendServer, ServingState

    data = preprocess(D_LINES, 0.05)
    cfg = MinerConfig(min_support=0.05, engine="level")
    miner = FastApriori(config=cfg)
    levels = miner.mine_levels_raw(data)
    st = ServingState(
        levels, data.item_counts, data.freq_items, data.item_to_rank,
        config=cfg, context=miner.context, engine="host",
    )
    server = RecommendServer(
        st, batch_rows=32, linger_ms=0.5, queue_depth=64
    ).start()
    reqs = [server.submit(l) for l in D_LINES[:50]]
    mid = server.metrics_text()  # mid-run scrape must not crash
    assert "fa_serve_submitted_total 50" in mid
    assert server.wait_for(reqs, timeout_s=30.0)
    snap = server.metrics_snapshot()["server"]
    assert (
        snap["fa_serve_served_total"] + snap["fa_serve_shed_total"] == 50
    )
    assert snap["fa_serve_batch_fill"]["count"] >= 1
    assert server.stop(drain=True)
    # The no-obs control flavor records nothing.
    server2 = RecommendServer(
        st, batch_rows=32, metrics=False, queue_depth=64
    ).start()
    r2 = [server2.submit(l) for l in D_LINES[:10]]
    server2.wait_for(r2, timeout_s=30.0)
    assert (
        server2.metrics_snapshot()["server"]["fa_serve_submitted_total"]
        == 0
    )
    assert server2.stop(drain=True)


def test_metrics_dump_knob_strictness(monkeypatch):
    monkeypatch.setenv("FA_METRICS_DUMP_S", "nope")
    metrics.reload_from_env()
    with pytest.raises(InputError):
        metrics.dump_interval_s()
    monkeypatch.setenv("FA_METRICS_DUMP_S", "0.5")
    metrics.reload_from_env()
    assert metrics.dump_interval_s() == 0.5
    monkeypatch.delenv("FA_METRICS_DUMP_S")
    metrics.reload_from_env()


# ---------------------------------------------------------------------------
# flight recorder


def test_flight_ring_overwrite_order():
    rec = FlightRecorder(cap=4)
    for i in range(7):
        rec.note("ledger", i=i)
    snap = rec.snapshot()
    assert [e["i"] for e in snap] == [3, 4, 5, 6]  # oldest dropped first
    assert [e["seq"] for e in snap] == [4, 5, 6, 7]  # monotone seqs
    assert snap[0]["kind"] == "ledger"


def test_flight_ring_size_knob(monkeypatch):
    monkeypatch.setenv("FA_FLIGHT_RECORDER_N", "2")
    rec = FlightRecorder()
    assert rec.cap == 2
    monkeypatch.setenv("FA_FLIGHT_RECORDER_N", "0")
    rec = FlightRecorder()
    rec.note("ledger", x=1)
    assert rec.snapshot() == []  # disabled
    monkeypatch.setenv("FA_FLIGHT_RECORDER_N", "junk")
    with pytest.raises(InputError):
        FlightRecorder()


def test_ledger_events_enter_flight_ring():
    ledger.record("retry", site="fetch.x", attempt=1)
    events = flight.snapshot()
    assert any(
        e["kind"] == "ledger" and e.get("event") == "retry"
        for e in events
    )


def test_flight_dump_on_injected_watchdog_timeout(tmp_path):
    """The ISSUE 11 satellite case: an injected watchdog timeout lands
    in the ring, and the dump is a manifest-committed artifact naming
    it."""
    prefix = str(tmp_path) + "/"
    with pytest.raises(watchdog.DispatchTimeout):
        watchdog.guard(
            lambda: time.sleep(2.0), "fetch.slow", timeout_s=0.05
        )
    path = flight.dump(prefix, "test: injected watchdog_timeout")
    with open(path) as fh:
        body = json.load(fh)
    assert body["reason"].startswith("test:")
    assert any(
        e.get("event") == "watchdog_timeout"
        and e.get("site") == "fetch.slow"
        for e in body["events"]
    )
    # Manifest-committed: resume-side validation accepts the artifact.
    from fastapriori_tpu.io.resume import validate_artifact_bytes

    with open(path, "rb") as fh:
        validate_artifact_bytes(prefix, "flight.json", fh.read())


def test_flight_auto_dump_requires_prefix(tmp_path):
    assert flight.auto_dump("no prefix registered") is None
    flight.set_dump_prefix(str(tmp_path) + "/")
    ledger.record("retry", site="fetch.x", attempt=1)
    path = flight.auto_dump("now registered")
    assert path is not None
    with open(path) as fh:
        assert json.load(fh)["reason"] == "now registered"


def test_abandoned_thread_cap_dumps_flight(tmp_path, monkeypatch):
    monkeypatch.setenv("FA_DISPATCH_MAX_ABANDONED", "1")
    watchdog.reload_from_env()
    watchdog.reset_abandoned()
    flight.set_dump_prefix(str(tmp_path) + "/")
    release = threading.Event()
    try:
        with pytest.raises(watchdog.DispatchTimeout):
            watchdog.guard(
                lambda: release.wait(30.0), "fetch.wedge", timeout_s=0.05
            )
        with pytest.raises(watchdog.AbandonedThreadCap):
            watchdog.guard(
                lambda: release.wait(30.0), "fetch.wedge", timeout_s=0.05
            )
    finally:
        release.set()
        watchdog.reset_abandoned()
        monkeypatch.delenv("FA_DISPATCH_MAX_ABANDONED")
        watchdog.reload_from_env()
    with open(str(tmp_path) + "/flight.json") as fh:
        body = json.load(fh)
    assert body["reason"] == "abandoned_thread_cap"
    assert body["context"]["site"] == "fetch.wedge"


# ---------------------------------------------------------------------------
# MetricsLogger bound + phase_timer routing (satellites)


def test_metrics_logger_records_are_bounded():
    log = MetricsLogger(enabled=False, records_cap=5)
    for i in range(8):
        log.emit("e", i=i)
    assert len(log.records) == 5
    assert log.records_dropped == 3
    assert [r["i"] for r in log.records] == [0, 1, 2, 3, 4]


def test_metrics_logger_timed_respects_bound():
    log = MetricsLogger(enabled=False, records_cap=1)
    with log.timed("a"):
        pass
    with log.timed("b"):
        pass
    assert len(log.records) == 1 and log.records_dropped == 1


def test_phase_timer_routes_through_tracer_and_logger(capsys):
    TRACER.enable()
    log = MetricsLogger(enabled=False)
    with phase_timer("get freqItemsets", enabled=True, metrics=log):
        pass
    err = capsys.readouterr().err
    assert "==== Use Time get freqItemsets" in err
    assert log.records and log.records[-1]["event"] == "phase"
    assert log.records[-1]["label"] == "get freqItemsets"
    names = {name for _, name, _ in TRACER.span_tree()}
    assert "phase" in names


def test_phase_timer_uses_active_logger():
    from fastapriori_tpu.utils import logging as fa_logging

    log = MetricsLogger(enabled=True, stream=open(os.devnull, "w"))
    assert fa_logging.active_logger() is log
    with phase_timer("p", enabled=False):
        pass
    assert log.records[-1]["event"] == "phase"


def test_timed_sections_become_spans():
    TRACER.enable()
    log = MetricsLogger(enabled=False)
    with log.timed("level", k=4) as m:
        m.update(frequent=10, psum_bytes=128, gather_bytes=64)
    spans = [e for e in TRACER.events() if e["ph"] == "X"]
    assert spans[0]["name"] == "level"
    assert spans[0]["args"]["frequent"] == 10
    counters = [e for e in TRACER.events() if e["ph"] == "C"]
    assert counters and counters[0]["args"] == {"psum": 128, "gather": 64}
    # The JSON record kept the same fields (one event source, two views).
    assert log.records[-1]["psum_bytes"] == 128
