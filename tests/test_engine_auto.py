"""Engine auto-selection (config.engine="auto", the default): the
zero-flag path must BE the fast path (VERDICT r3 task 1).  The choice is
made from the level-2 pair pre-pass — survivor count with 2x headroom AND
the level-3 candidate census (ops/count.py _pair_triangles) against the
memory-derived fused row-budget ceiling — so webdocs-class mid-lattice
blowup goes straight to the level engine while small lattices get the
one-dispatch fused program.  The reference has exactly one driver path
(Main.scala:16-38); auto keeps ours one-path from the user's view."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu import oracle
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori


def _write_dat(tmp_path, lines):
    p = tmp_path / "D.dat"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _events(miner, name):
    return [r for r in miner.metrics.records if r["event"] == name]


def _decoded(levels, data):
    out = {}
    for mat, cnts in levels:
        for row, c in zip(mat.tolist(), cnts.tolist()):
            out[frozenset(row)] = int(c)
    return out


def _native_pipelined_available():
    from fastapriori_tpu.preprocess import _use_native

    return _use_native(None, 1 << 62)


needs_native = pytest.mark.skipif(
    not _native_pipelined_available(),
    reason="pipelined ingest needs the native preprocessor",
)


@needs_native
def test_auto_picks_fused_on_small_lattice(tmp_path):
    """Quest-style sparse data: auto must choose fused (engine_auto
    choice event) and match the forced level engine bit-exactly."""
    lines = random_dataset(11, n_items=60, n_txns=400, max_len=8)
    d_path = _write_dat(tmp_path, lines)
    auto = FastApriori(
        config=MinerConfig(
            min_support=0.02, engine="auto", num_devices=1, log_metrics=True
        )
    )
    lv_a, data_a = auto.run_file_raw(d_path)
    choices = _events(auto, "engine_auto")
    assert choices and choices[0]["choice"] == "fused", choices
    assert _events(auto, "fused_mine"), "fused engine did not run"

    level = FastApriori(
        config=MinerConfig(min_support=0.02, engine="level", num_devices=1)
    )
    lv_l, data_l = level.run_file_raw(d_path)
    assert _decoded(lv_a, data_a) == _decoded(lv_l, data_l)


@needs_native
def test_auto_picks_level_on_lattice_blowup(tmp_path):
    """A webdocs-shaped profile (level-3 census over the row-budget
    ceiling — here the ceiling is pinned tiny) must go straight to the
    level engine with NO fused attempt, and stay exact."""
    lines = random_dataset(5, n_items=40, n_txns=300, max_len=10)
    d_path = _write_dat(tmp_path, lines)
    auto = FastApriori(
        config=MinerConfig(
            min_support=0.02,
            engine="auto",
            num_devices=1,
            log_metrics=True,
            # Pin the ceiling below this dataset's pair survivors so the
            # auto rule must reject fused (webdocs-in-miniature).
            fused_m_cap_max=32,
        )
    )
    lv, data = auto.run_file_raw(d_path)
    choices = _events(auto, "engine_auto")
    assert choices and choices[0]["choice"] == "level", choices
    assert not _events(auto, "fused_mine"), "doomed fused attempt ran"
    expected, _, _ = oracle.mine(tokenized(lines), 0.02)
    got = dict(auto._decode_levels(lv, data))
    got.update(
        (frozenset((r,)), int(c)) for r, c in enumerate(data.item_counts)
    )
    assert got == dict(expected)


@needs_native
def test_auto_warm_run_uses_memo(tmp_path):
    """Second run of the same profile must skip the decision pre-pass:
    fused-able data goes straight to ONE fused dispatch (no level-2
    gather), level-bound data reuses the recorded choice."""
    lines = random_dataset(11, n_items=60, n_txns=400, max_len=8)
    d_path = _write_dat(tmp_path, lines)
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.02, engine="auto", num_devices=1, log_metrics=True
        )
    )
    miner.run_file_raw(d_path)
    n_before = len(miner.metrics.records)
    lv2, data2 = miner.run_file_raw(d_path)
    warm = miner.metrics.records[n_before:]
    assert [r for r in warm if r["event"] == "fused_mine"], warm
    assert not [
        r for r in warm if r["event"] == "level" and r.get("k") == 2
    ], "warm fused run paid the pair gather"

    # Level-bound profile: the memoized choice skips the fused machinery.
    bound = FastApriori(
        config=MinerConfig(
            min_support=0.02,
            engine="auto",
            num_devices=1,
            log_metrics=True,
            fused_m_cap_max=32,
        )
    )
    bound.run_file_raw(d_path)
    n_before = len(bound.metrics.records)
    bound.run_file_raw(d_path)
    warm = bound.metrics.records[n_before:]
    memo = [r for r in warm if r["event"] == "engine_auto"]
    assert memo and memo[0].get("memo"), warm
    assert not [r for r in warm if r["event"] == "fused_mine"]


def test_auto_nonpipelined_prepass_bail():
    """The in-memory (non-pipelined) path: auto with an over-tight
    ceiling bails at the pair pre-pass — no fused_mine attempt — and the
    level fallback stays exact (mine_levels_raw route)."""
    lines = tokenized(random_dataset(5, n_txns=200, max_len=8))
    expected, _, _ = oracle.mine(lines, 0.03)
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.03,
            engine="auto",
            num_devices=1,
            log_metrics=True,
            fused_m_cap_max=32,
        )
    )
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    choices = _events(miner, "engine_auto")
    assert choices and choices[0]["choice"] == "level"
    assert not _events(miner, "fused_mine")


def test_auto_nonpipelined_picks_fused():
    """The in-memory path with a small lattice: auto runs fused."""
    lines = tokenized(random_dataset(2, n_txns=150))
    expected, _, _ = oracle.mine(lines, 0.05)
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.05, engine="auto", num_devices=1, log_metrics=True
        )
    )
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    choices = _events(miner, "engine_auto")
    assert choices and choices[0]["choice"] == "fused"
    assert _events(miner, "fused_mine")


def test_pair_triangle_census_matches_candidate_gen():
    """The in-kernel level-3 census (cand3 on the k=2 level event) must
    equal the actual k=3 candidate count the generator produces (the
    census IS the post-prune candidate space: triangles of the pair
    graph)."""
    lines = tokenized(random_dataset(9, n_items=30, n_txns=250, max_len=9))
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.02, engine="level", num_devices=1, log_metrics=True
        )
    )
    miner.run(lines)
    k2 = [
        r
        for r in miner.metrics.records
        if r["event"] == "level" and r.get("k") == 2
    ]
    k3 = [
        r
        for r in miner.metrics.records
        if r["event"] == "level" and r.get("k") == 3
    ]
    assert k2 and k3
    assert k2[0]["cand3"] == k3[0]["candidates"], (k2, k3)


def test_auto_salvage_on_midlattice_overflow(tmp_path):
    """When the census under-predicts (forced here by pinning the
    headroom ceiling between n2 and the true peak), the fused overflow
    salvage must hand complete levels to the level engine and the result
    stays exact — auto never sacrifices correctness."""
    # Deep identical baskets: n2 small, mid-lattice huge (C(10,5)=252).
    lines = tokenized(
        ["1 2 3 4 5 6 7 8 9 10"] * 30 + ["11 12"] * 5 + ["13"]
    )
    expected, _, _ = oracle.mine(lines, 0.2)
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.2,
            engine="auto",
            num_devices=1,
            log_metrics=True,
            fused_m_cap_max=128,
        )
    )
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)


def test_salvage_then_tail_fold_compose():
    """The three execution mechanisms compose in one run: a fused
    attempt overflows mid-lattice (tiny cap), its complete levels
    salvage-resume the level engine, and the level engine then folds
    the remaining tail into one seeded dispatch — result stays exact
    through all three hand-offs."""
    lines = tokenized(
        ["1 2 3 4 5 6 7 8 9"] * 40 + ["1 2 3"] * 5 + ["10 11"] * 4
        + ["12"]
    )
    expected, _, _ = oracle.mine(lines, 0.15)
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.15,
            engine="fused",  # force the attempt so overflow salvages
            num_devices=1,
            log_metrics=True,
            fused_m_cap_max=32,  # overflows at C(9,k) peak levels
            fused_m_cap=8,
            tail_fuse_rows=1 << 20,  # tail fold force-enabled on cpu
        )
    )
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    events = [r["event"] for r in miner.metrics.records]
    assert "fused_mine" in events
    assert "level_resume" in events or "fused_fallback" in events
    assert "tail_fuse" in events, events
