"""Validate the pure-Python oracle against independent brute-force
implementations on small random datasets (SURVEY.md §4: framework output
must equal naive O(2^F) enumeration).  The oracle is then trusted as the
golden model for the framework tests."""

import itertools
import math
from collections import Counter

import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu import oracle


def brute_force_itemsets(lines, min_support):
    """Independent: enumerate ALL subsets of frequent items, count support
    by direct containment over raw transactions."""
    n = len(lines)
    min_count = math.ceil(min_support * n)
    occ = Counter()
    for t in lines:
        occ.update(t)
    freq = sorted(
        [i for i, c in occ.items() if c >= min_count],
        key=lambda i: (-occ[i], int(i) if i.isdigit() else i),
    )
    rank = {i: r for r, i in enumerate(freq)}
    filtered = [frozenset(rank[i] for i in t if i in rank) for t in lines]

    expected = {}
    for r, item in enumerate(freq):
        expected[frozenset((r,))] = occ[item]
    for size in range(2, len(freq) + 1):
        found_any = False
        for combo in itertools.combinations(range(len(freq)), size):
            s = frozenset(combo)
            support = sum(1 for t in filtered if s <= t)
            if support >= min_count:
                expected[s] = support
                found_any = True
        if not found_any:
            break
    return expected, freq, rank


def brute_force_rules(freq_itemsets):
    """Independent recursive formulation of the dominance prune: a rule
    survives iff every (antecedent-minus-one -> same consequent) rule
    survives with strictly lower confidence."""
    support = dict(freq_itemsets)
    raw = {}
    for s, c in freq_itemsets:
        if len(s) < 2:
            continue
        for i in s:
            raw[(s - {i}, i)] = c / support[s - {i}]
    if not raw:
        return []
    min_len = min(len(a) for a, _ in raw)
    memo = {}

    def survives(ant, cons):
        key = (ant, cons)
        if key in memo:
            return memo[key]
        if len(ant) == min_len:
            memo[key] = True
            return True
        conf = raw[key]
        ok = all(
            (ant - {e}, cons) in raw
            and survives(ant - {e}, cons)
            and raw[(ant - {e}, cons)] < conf
            for e in ant
        )
        memo[key] = ok
        return ok

    return [(a, c, conf) for (a, c), conf in raw.items() if survives(a, c)]


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("min_support", [0.05, 0.1, 0.2])
def test_oracle_mine_matches_brute_force(seed, min_support):
    lines = tokenized(random_dataset(seed))
    expected, freq, rank = brute_force_itemsets(lines, min_support)

    itemsets, item_to_rank, freq_items = oracle.mine(lines, min_support)
    got = {s: c for s, c in itemsets}
    assert len(got) == len(itemsets), "duplicate itemsets in oracle output"
    assert freq_items == freq
    assert item_to_rank == rank
    assert got == expected


@pytest.mark.parametrize("seed", range(6))
def test_oracle_rules_match_brute_force(seed):
    lines = tokenized(random_dataset(seed, n_txns=60))
    itemsets, _, _ = oracle.mine(lines, 0.08)
    got = oracle.gen_rules(itemsets)
    expected = brute_force_rules(itemsets)
    assert sorted(got, key=repr) == sorted(expected, key=repr)


@pytest.mark.parametrize("seed", range(4))
def test_oracle_recommend_first_match(seed):
    lines = tokenized(random_dataset(seed))
    u_lines = tokenized(random_dataset(seed + 100, n_txns=30))
    itemsets, item_to_rank, freq_items = oracle.mine(lines, 0.08)
    rules = oracle.gen_rules(itemsets)
    recs = oracle.recommend(u_lines, rules, freq_items, item_to_rank)

    # Independent check: direct scan per user over independently sorted rules.
    sorted_rules = sorted(
        rules, key=lambda r: (-r[2], int(freq_items[r[1]]))
    )
    assert [i for i, _ in recs] == list(range(len(u_lines)))
    for idx, item in recs:
        basket = frozenset(
            item_to_rank[i] for i in u_lines[idx] if i in item_to_rank
        )
        expected = "0"
        for ant, cons, _ in sorted_rules:
            if (
                basket
                and len(ant) <= len(basket)
                and cons not in basket
                and ant <= basket
            ):
                expected = freq_items[cons]
                break
        assert item == expected


def test_oracle_known_tiny_case():
    # 8 txns, minSupport 0.25 -> minCount 2.
    lines = tokenized(["1 2", "1 2", "1 3", "2 3", "1 2 3", "4", "4", "1"])
    itemsets, item_to_rank, freq_items = oracle.mine(lines, 0.25)
    got = dict(itemsets)
    # occurrence counts: 1->5, 2->4, 3->3, 4->2
    assert freq_items == ["1", "2", "3", "4"]
    r = item_to_rank
    assert got[frozenset((r["1"],))] == 5
    assert got[frozenset((r["4"],))] == 2
    assert got[frozenset((r["1"], r["2"]))] == 3
    assert got[frozenset((r["1"], r["3"]))] == 2
    assert got[frozenset((r["2"], r["3"]))] == 2
    # {1,2,3} appears once only -> not frequent.
    assert frozenset((r["1"], r["2"], r["3"])) not in got


def test_tokenize_matches_java_semantics():
    assert oracle.tokenize("") == [""]
    assert oracle.tokenize("   ") == [""]
    assert oracle.tokenize(" 1  2\t3 ") == ["1", "2", "3"]
