"""Fused whole-loop engine (ops/fused.py) vs the per-level engine and the
oracle, including overflow retry and multi-device equality."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu import oracle
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.ops.fused import pack_bitmap


def _mine(lines, min_support, **cfg_kwargs):
    cfg = MinerConfig(min_support=min_support, **cfg_kwargs)
    got, _, _ = FastApriori(config=cfg).run(lines)
    return got


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("min_support", [0.05, 0.15])
def test_fused_matches_level_engine(seed, min_support):
    lines = tokenized(random_dataset(seed, n_txns=120))
    fused = _mine(lines, min_support, engine="fused", num_devices=1)
    level = _mine(lines, min_support, engine="level", num_devices=1)
    assert dict(fused) == dict(level)
    assert len(fused) == len(level)


@pytest.mark.parametrize("n_devices", [1, 8])
def test_fused_matches_oracle_deep_levels(n_devices):
    lines = tokenized(
        ["1 2 3 4 5 6"] * 12
        + ["1 2 3 4 5"] * 6
        + ["2 3 4 5 6"] * 6
        + ["7 8"] * 4
        + ["9", "1 7"]
    )
    expected, _, _ = oracle.mine(lines, 0.15)
    got = _mine(lines, 0.15, engine="fused", num_devices=n_devices)
    assert dict(got) == dict(expected)
    assert max(len(s) for s, _ in got) >= 5


def test_fused_overflow_retries_then_succeeds():
    # Tiny m_cap forces the doubling retry path; result must still be exact.
    lines = tokenized(random_dataset(3, n_txns=100))
    expected, _, _ = oracle.mine(lines, 0.05)
    got = _mine(
        lines, 0.05, engine="fused", num_devices=1,
        fused_m_cap=4, fused_m_cap_max=8192,
    )
    assert dict(got) == dict(expected)


def test_fused_falls_back_to_level_engine():
    # m_cap capped too low for the data -> must fall back and stay exact.
    lines = tokenized(random_dataset(3, n_txns=100))
    expected, _, _ = oracle.mine(lines, 0.05)
    got = _mine(
        lines, 0.05, engine="fused", num_devices=1,
        fused_m_cap=4, fused_m_cap_max=4,
    )
    assert dict(got) == dict(expected)


def test_fused_overflow_jumps_to_needed_budget():
    # One basket of 14 items makes every C(14,k) level frequent: n2=91
    # sizes the starting budget at 256, then levels 3..6 (364, 1001,
    # 2002, 3003 rows) overflow in turn and the meta row's TRUE survivor
    # counts size each retry exactly: 256→512→1024→2048→4096, completing
    # at 4096 (level 7 peaks at 3432).  On smooth binomial growth the
    # sized jump coincides with doubling — what this test pins is the
    # meta-slot wiring: a mis-read overflow flag would break to the
    # fallback after one attempt, and a garbage n_lvl would derail the
    # deterministic budget sequence.
    lines = tokenized([" ".join(map(str, range(1, 15)))] * 20)
    expected, _, _ = oracle.mine(lines, 0.5)
    cfg = MinerConfig(
        min_support=0.5, engine="fused", num_devices=1,
        fused_m_cap=4, min_prefix_bucket=1, fused_m_cap_max=8192,
        log_metrics=False,
    )
    miner = FastApriori(config=cfg)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    attempts = [
        r for r in miner.metrics.records if r["event"] == "fused_mine"
    ]
    assert [a["m_cap"] for a in attempts] == [256, 512, 1024, 2048, 4096]
    assert attempts[0]["overflow"] and attempts[0]["incomplete"]
    assert not attempts[-1]["overflow"] and not attempts[-1]["incomplete"]


def test_fused_l_max_exceeded_falls_back():
    # 6-deep itemset lattice with l_max=3 -> incomplete (not overflow) ->
    # a larger row budget can't help, so exactly ONE fused attempt, then
    # the level engine RESUMES from the fused attempt's complete levels
    # (2..4) instead of recounting them — and exact output either way.
    lines = tokenized(["1 2 3 4 5 6 7"] * 10 + ["8 9"] * 2)
    expected, _, _ = oracle.mine(lines, 0.5)
    cfg = MinerConfig(
        min_support=0.5, engine="fused", num_devices=1,
        fused_l_max=3, fused_m_cap_max=8192, log_metrics=False,
    )
    miner = FastApriori(config=cfg)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    records = miner.metrics.records
    attempts = [r for r in records if r["event"] == "fused_mine"]
    assert len(attempts) == 1, attempts
    assert any(r["event"] == "fused_fallback" for r in records)
    resume = [r for r in records if r["event"] == "level_resume"]
    assert resume and resume[0]["from_k"] == 5, records
    recounted = [r["k"] for r in records if r["event"] == "level"]
    assert min(recounted) == 5, recounted


@pytest.mark.parametrize("n_devices", [1, 8])
def test_fused_txn_chunked_scan(n_devices):
    # Tiny chunk target forces the multi-chunk scan path on every device.
    lines = tokenized(random_dataset(11, n_txns=200))
    expected, _, _ = oracle.mine(lines, 0.05)
    got = _mine(
        lines, 0.05, engine="fused", num_devices=n_devices,
        fused_txn_chunk=8,
    )
    assert dict(got) == dict(expected)


def test_pack_bitmap_roundtrip():
    rng = np.random.default_rng(0)
    b = (rng.random((16, 256)) < 0.3).astype(np.int8)
    packed = pack_bitmap(b)
    assert packed.shape == (16, 32)
    assert (np.unpackbits(packed, axis=1) == b).all()


def test_fused_weighted_digits():
    # >128 duplicate baskets exercise the on-device two-digit path.
    lines = tokenized(["1 2 3"] * 300 + ["4 5"] * 10 + ["1 2"] * 50)
    expected, _, _ = oracle.mine(lines, 0.05)
    got = _mine(lines, 0.05, engine="fused", num_devices=1)
    assert dict(got) == dict(expected)


def test_fused_m_cap_memory_clamp_and_salvage():
    """A tiny injected HBM budget must clamp the row-budget ceiling BELOW
    the configured fused_m_cap_max (so the oversized program is never
    compiled), and a dataset whose levels outgrow that ceiling must
    salvage-resume through the level engine bit-exactly (VERDICT weak #5:
    no compile-then-OOM path reachable)."""
    lines = tokenized(
        [" ".join(str(i) for i in range(1, 15))] * 10 + ["20 21"]
    )
    expected, _, _ = oracle.mine(lines, 0.5)
    cfg = MinerConfig(
        min_support=0.5, engine="fused", num_devices=1,
        fused_m_cap=4, min_prefix_bucket=1, fused_m_cap_max=32768,
        fused_hbm_budget_bytes=space_budget_for_m(256),
    )
    miner = FastApriori(config=cfg)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    events = {r["event"] for r in miner.metrics.records}
    assert "fused_m_cap_clamp" in events
    clamp = next(
        r for r in miner.metrics.records if r["event"] == "fused_m_cap_clamp"
    )
    assert clamp["memory_limit"] < 32768
    attempts = [
        r["m_cap"]
        for r in miner.metrics.records
        if r["event"] == "fused_mine"
    ]
    # No attempt ever exceeded the memory-derived ceiling, and the run
    # ended in a level-engine salvage (levels 5-6 need >256 rows).
    assert attempts and max(attempts) <= clamp["memory_limit"]
    assert "fused_fallback" in events


def space_budget_for_m(m_target):
    """HBM budget that admits ~m_target rows under the engine's byte
    model (keeps the test decoupled from the model's exact constants)."""
    # From _fused_m_cap_memory_limit's bytes_at with small t_c/f_pad the
    # quadratic 8*m^2 term dominates; give 2x headroom over it.
    return 16 * m_target * m_target


@pytest.mark.parametrize("n_devices", [1, 8])
def test_tail_fold_matches_oracle(n_devices):
    """Shallow-tail fold (ops/fused.py _tail_mine_local): forcing the
    fold threshold makes the level engine hand the whole tail to one
    seeded device program; results must stay oracle-exact."""
    lines = tokenized(
        random_dataset(2, n_txns=150, max_len=8)
        + ["1 2 3 4 5 6 7"] * 20
    )
    expected, _, _ = oracle.mine(lines, 0.04)
    cfg = MinerConfig(
        min_support=0.04, engine="level", num_devices=n_devices,
        tail_fuse_rows=1 << 20, log_metrics=True,
    )
    miner = FastApriori(config=cfg)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    tails = [
        r for r in miner.metrics.records if r["event"] == "tail_fuse"
    ]
    assert tails and tails[0]["levels"] >= 2, tails


def test_tail_fold_p_cap_overflow_falls_back():
    """A candidate-prefix count above p_cap marks the level invalid; the
    engine must resume per-level counting from the last complete level
    and stay exact."""
    lines = tokenized(
        random_dataset(2, n_txns=150, max_len=8)
        + ["1 2 3 4 5 6 7"] * 20
    )
    expected, _, _ = oracle.mine(lines, 0.04)
    cfg = MinerConfig(
        min_support=0.04, engine="level", num_devices=1,
        tail_fuse_rows=1 << 20, tail_fuse_p_cap=2, log_metrics=True,
    )
    miner = FastApriori(config=cfg)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    tails = [
        r for r in miner.metrics.records if r["event"] == "tail_fuse"
    ]
    assert tails and tails[0]["incomplete"], tails
    # The per-level engine finished the job after the failed fold.
    assert [
        r for r in miner.metrics.records
        if r["event"] == "level" and r.get("k", 0) >= 4
    ]


def test_tail_fold_depth_bound_falls_back():
    """More remaining levels than tail_fuse_l_max: fold what fits, then
    resume per-level (and possibly fold again is NOT allowed — one fold
    per run); exactness holds."""
    lines = tokenized(["1 2 3 4 5 6 7 8 9 10 11 12"] * 30 + ["13 14"] * 3)
    expected, _, _ = oracle.mine(lines, 0.2)
    cfg = MinerConfig(
        min_support=0.2, engine="level", num_devices=1,
        tail_fuse_rows=1 << 20, tail_fuse_l_max=3, log_metrics=True,
    )
    miner = FastApriori(config=cfg)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)


def test_tail_fold_with_heavy_weight_split():
    """Tail counting must include the heavy-row int32 correction (rows
    with multiplicity >= 128 under the single-low-digit split)."""
    lines = tokenized(
        ["1 2 3 4 5"] * 200 + ["1 2 3 4"] * 40 + ["2 3 4 5 6"] * 9
        + ["6 7"] * 3
    )
    ms = 8.0 / len(lines)
    expected, _, _ = oracle.mine(lines, ms)
    cfg = MinerConfig(
        min_support=ms, engine="level", num_devices=1,
        tail_fuse_rows=1 << 20, log_metrics=True,
    )
    miner = FastApriori(config=cfg)
    got, _, _ = miner.run(lines)
    assert dict(got) == dict(expected)
    assert [
        r for r in miner.metrics.records if r["event"] == "tail_fuse"
    ]


def test_fused_on_2d_mesh_matches_oracle():
    """Single-host fused engine on a (txn x cand) 2-D mesh: rows shard
    over txn, cand replicas compute identically (psum over txn only) —
    bit-exact with the oracle (VERDICT r3 task 8)."""
    lines = tokenized(random_dataset(2, n_txns=150))
    expected, _, _ = oracle.mine(lines, 0.05)
    got = _mine(
        lines, 0.05, engine="fused", num_devices=8, cand_devices=2
    )
    assert dict(got) == dict(expected)
