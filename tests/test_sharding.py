"""Multi-chip-without-a-cluster (SURVEY.md §4): the real Mesh/shard_map
path on 8 fake CPU devices must produce bit-identical results to the
1-device path — counting is int32-exact so equality is strict."""

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu import oracle
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.models.recommender import AssociationRules
from fastapriori_tpu.parallel.mesh import DeviceContext


def test_eight_fake_devices_present():
    import jax

    assert len(jax.devices()) == 8


@pytest.mark.parametrize("seed", range(3))
def test_mining_single_vs_multi_device(seed):
    lines = tokenized(random_dataset(seed, n_txns=120))
    one, _, _ = FastApriori(0.06, num_devices=1).run(lines)
    eight, _, _ = FastApriori(0.06, num_devices=8).run(lines)
    assert dict(one) == dict(eight)
    assert len(one) == len(eight)


@pytest.mark.parametrize("seed", range(2))
def test_mining_multi_device_matches_oracle(seed):
    lines = tokenized(random_dataset(seed, n_txns=150))
    expected, _, _ = oracle.mine(lines, 0.05)
    got, _, _ = FastApriori(0.05, num_devices=8).run(lines)
    assert dict(got) == dict(expected)


def test_recommender_single_vs_multi_device():
    d_lines = tokenized(random_dataset(5))
    u_lines = tokenized(random_dataset(55, n_txns=60))
    itemsets, item_to_rank, freq_items = oracle.mine(d_lines, 0.08)

    rec1 = AssociationRules(
        itemsets, freq_items, item_to_rank,
        context=DeviceContext(num_devices=1),
    ).run(u_lines, use_device=True)
    rec8 = AssociationRules(
        itemsets, freq_items, item_to_rank,
        context=DeviceContext(num_devices=8),
    ).run(u_lines, use_device=True)
    assert sorted(rec1) == sorted(rec8)


def test_bitmap_sharding_layout():
    """The bitmap must actually be row-sharded across the mesh (each device
    holds T'/n rows), not replicated — the inversion of the reference's
    broadcast-everything layout (FastApriori.scala:100)."""
    ctx = DeviceContext(num_devices=8)
    b = np.ones((64, 128), dtype=np.int8)
    sharded = ctx.shard_bitmap(b)
    shard_shapes = {s.data.shape for s in sharded.addressable_shards}
    assert shard_shapes == {(8, 128)}
    assert len(sharded.addressable_shards) == 8


@pytest.mark.parametrize("cand", [2, 4])
def test_level_engine_2d_mesh_matches_single_device(cand):
    """2-D (txn x cand) mesh: candidate-prefix rows sharded over the cand
    axis (SURVEY.md §7 optional 2-D mesh) must count bit-exactly like the
    1-device run.  Deep levels force multiple per-shard prefix blocks."""
    from fastapriori_tpu.config import MinerConfig
    lines = tokenized(
        random_dataset(13, n_txns=200, n_items=14, max_len=9)
    )
    expected, _, _ = FastApriori(
        config=MinerConfig(min_support=0.05, engine="level", num_devices=1)
    ).run(lines)
    got, _, _ = FastApriori(
        config=MinerConfig(
            min_support=0.05, engine="level",
            num_devices=8, cand_devices=cand,
        )
    ).run(lines)
    assert dict(got) == dict(expected)


def test_2d_mesh_full_pipeline_with_fused_engine():
    """The fused engine and recommender run 1-D-style on a 2-D mesh
    (replicated over cand) — the whole pipeline must still be exact."""
    from fastapriori_tpu.config import MinerConfig
    d_lines = tokenized(random_dataset(7))
    u_lines = tokenized(random_dataset(77, n_txns=40))
    exp_sets, item_to_rank, freq_items = oracle.mine(d_lines, 0.08)
    exp_rules = oracle.sort_rules(oracle.gen_rules(exp_sets), freq_items)
    exp_rec = oracle.recommend(u_lines, exp_rules, freq_items, item_to_rank)

    cfg = MinerConfig(min_support=0.08, num_devices=8, cand_devices=2)
    ctx = DeviceContext(num_devices=8, cand_devices=2)
    got, i2r, fi = FastApriori(config=cfg, context=ctx).run(d_lines)
    assert dict(got) == dict(exp_sets)
    rec = AssociationRules(got, fi, i2r, config=cfg, context=ctx).run(u_lines, use_device=True)
    assert sorted(rec) == sorted(exp_rec)


def _level_byte_series(miner):
    """k -> (psum_bytes, gather_bytes) per level event of the last run."""
    return {
        r.get("k"): (r.get("psum_bytes"), r.get("gather_bytes", 0))
        for r in miner.metrics.records
        if r.get("event") == "level"
    }


def test_psum_bytes_invariant_across_device_counts():
    """DENSE-engine contract: per-level psum bytes must be CONSTANT
    across 1/2/4/8 virtual devices (VERDICT r5 next #7): the collective
    reduces the gathered candidate array, whose size is set by the
    candidate space — a psum payload that grew with the mesh would mean
    the kernels were resharding data instead of reducing partial sums.
    (The sparse engine's payload legitimately moves with the mesh — its
    contract is the strictly-below-dense test following this one.)"""
    from fastapriori_tpu.config import MinerConfig

    lines = tokenized(random_dataset(11, n_txns=240, n_items=14, max_len=8))
    series = {}
    for n in (1, 2, 4, 8):
        miner = FastApriori(
            config=MinerConfig(
                min_support=0.05, engine="level", num_devices=n,
                count_reduce="dense",
            )
        )
        miner.run(lines)
        series[n] = {
            k: p for k, (p, _g) in _level_byte_series(miner).items()
        }
    assert series[1] and all(v is not None for v in series[1].values())
    for n in (2, 4, 8):
        assert series[n] == series[1], (
            f"per-level psum bytes moved with device count "
            f"(1 dev: {series[1]}, {n} dev: {series[n]})"
        )


def test_sparse_collective_bytes_below_dense():
    """SPARSE-engine contract (ROADMAP item 2, ISSUE 6): on a power-law
    corpus at >= 2 devices the sparse exchange's total collective bytes
    (mask gather + compact psum) must be strictly below the dense psum
    payload — and <= 25% of it on the 4-device mesh, where the r6
    acceptance bar sits — while staying bit-exact."""
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.utils.datagen import generate_transactions

    # IBM-Quest-style power-law corpus: a core of planted patterns plus
    # a long infrequent tail, so most mid-level candidates die at the
    # threshold (the regime the sparse exchange exists for).
    lines = [
        l.split()
        for l in generate_transactions(
            n_txns=3000, n_items=200, avg_txn_len=8, n_patterns=60,
            avg_pattern_len=5, corruption=0.4, seed=5,
        )
    ]
    dense = FastApriori(
        config=MinerConfig(
            min_support=0.02, engine="level", num_devices=4,
            count_reduce="dense",
        )
    )
    exp, _, _ = dense.run(lines)
    dense_bytes = {
        k: p + g for k, (p, g) in _level_byte_series(dense).items()
    }
    for n in (2, 4, 8):
        sparse = FastApriori(
            config=MinerConfig(
                min_support=0.02, engine="level", num_devices=n,
                count_reduce="sparse", count_sparse_min=1,
            )
        )
        got, _, _ = sparse.run(lines)
        assert dict(got) == dict(exp)  # bit-exact vs the dense oracle
        sparse_bytes = {
            k: p + g for k, (p, g) in _level_byte_series(sparse).items()
        }
        assert sum(sparse_bytes.values()) < sum(dense_bytes.values()), (
            n, sparse_bytes, dense_bytes,
        )
        if n == 4:
            assert sum(sparse_bytes.values()) <= 0.25 * sum(
                dense_bytes.values()
            ), (sparse_bytes, dense_bytes)
