"""graftlint rule tests: for every rule a minimal must-flag snippet, a
must-pass sibling, and a waived variant — plus CLI smoke tests proving
the shipped tree is clean under the shipped baseline and that injecting
any must-flag fixture trips the gate.

Deliberately jax-free: the linter is pure stdlib and these tests must
run on boxes with no accelerator runtime.
"""

import json
import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:  # tier-1 runs `python -m pytest tests/`
    sys.path.insert(0, REPO_ROOT)

from tools.lint import cli, engine  # noqa: E402
from tools.lint.engine import FileContext, PackageContext  # noqa: E402
from tools.lint.rules import ALL_RULES, RULES_BY_ID  # noqa: E402

BASELINE = os.path.join("tools", "lint", "baseline.json")

# A Mesh declaration so G002 has a declared-axis universe to check
# against; rides along as an auxiliary file in every case.
MESH_DECL = ("pkg/meshdef.py", 'from jax.sharding import Mesh\n'
             'AXIS = "txn"\n'
             'mesh = Mesh(devices, (AXIS, "cand"))\n')

# (rule, case-name, path, source) triples.  ``flag`` must yield >= 1
# finding of the rule; ``pass`` and ``waived`` must yield none.
CASES = [
    # -- G001: host sync in traced code / unaudited mesh-layer fetch ----
    ("G001", "flag", "pkg/mod.py",
     "import jax\n"
     "@jax.jit\n"
     "def f(x):\n"
     "    return x.item()\n"),
    ("G001", "flag", "pkg/mod.py",
     "import numpy as np\n"
     "from jax.experimental.shard_map import shard_map\n"
     "@shard_map\n"
     "def f(x):\n"
     "    return np.asarray(x)\n"),
    ("G001", "flag", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    return np.asarray(arr)\n"),
    ("G001", "flag", "pkg/models/apriori.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    return np.asarray(arr)\n"),  # engine layer is audited too
    ("G001", "pass", "pkg/mod.py",
     "def g(x):\n"
     "    return x.item()\n"),  # not traced, not the mesh layer
    ("G001", "pass", "pkg/models/recommender.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    return np.asarray(arr)\n"),  # engine audit covers apriori only
    ("G001", "pass", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def host_table():\n"
     "    return np.array([1, 2, 3])\n"),  # literal arg: host data
    ("G001", "pass", "pkg/parallel/m.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability import retry\n"
     "def pull(arr):\n"
     "    return retry.fetch(lambda: np.asarray(arr), 'pair')\n"),
    # the audited helper IS the audit: no inline waiver needed
    ("G001", "pass", "pkg/models/apriori.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability.retry import fetch_async\n"
     "def pull(arr):\n"
     "    return fetch_async(np.asarray(arr), 'level_bits')\n"),
    ("G001", "flag", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def pull(arr, fetch):\n"
     "    return fetch(np.asarray(arr))\n"),  # no site label: not audited
    ("G001", "waived", "pkg/mod.py",
     "import jax\n"
     "@jax.jit\n"
     "def f(x):\n"
     "    return x.item()  # lint: fetch-site -- test waiver\n"),
    ("G001", "waived", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    # lint: fetch-site -- audited test fetch\n"
     "    return np.asarray(arr)\n"),
    # -- G002: collective axis names tie back to a Mesh declaration ----
    ("G002", "flag", "pkg/mod.py",
     "from jax import lax\n"
     "def f(x):\n"
     "    return lax.psum(x, 'tn')\n"),  # typo'd axis
    ("G002", "flag", "pkg/mod.py",
     "from jax import lax\n"
     "def f(x, a):\n"
     "    return lax.all_gather(x, a)\n"),  # unverifiable, not axis-named
    ("G002", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "def f(x):\n"
     "    return lax.psum(x, 'txn')\n"),
    ("G002", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "from pkg.meshdef import AXIS\n"
     "def f(x):\n"
     "    return lax.psum(x, AXIS)\n"),  # package-wide constant
    ("G002", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "def f(x, axis_name=None):\n"
     "    return lax.psum(x, axis_name) if axis_name else x\n"),
    ("G002", "waived", "pkg/mod.py",
     "from jax import lax\n"
     "def f(x):\n"
     "    return lax.psum(x, 'tn')  # lint: waive G002 -- test waiver\n"),
    # -- G003: recompile hazards ---------------------------------------
    ("G003", "flag", "pkg/mod.py",
     "import jax\n"
     "g = jax.jit(lambda x: x, static_argnums=[0])\n"),
    ("G003", "flag", "pkg/mod.py",
     "import jax\n"
     "def run(fs, xs):\n"
     "    for f in fs:\n"
     "        xs = jax.jit(f)(xs)\n"
     "    return xs\n"),
    ("G003", "pass", "pkg/mod.py",
     "import jax\n"
     "g = jax.jit(lambda x: x, static_argnums=(0,))\n"),
    ("G003", "pass", "pkg/mod.py",
     "import jax\n"
     "def run(fs, xs):\n"
     "    jitted = [jax.jit(f) for f in fs]\n"
     "    return jitted\n"),  # comprehension, not a loop-body rebuild
    ("G003", "waived", "pkg/mod.py",
     "import jax\n"
     "def run(fs, xs):\n"
     "    for f in fs:\n"
     "        # lint: waive G003 -- test waiver\n"
     "        xs = jax.jit(f)(xs)\n"
     "    return xs\n"),
    # -- G004: dtype discipline ----------------------------------------
    ("G004", "flag", "pkg/mod.py",
     "import jax.numpy as jnp\n"
     "def f():\n"
     "    return jnp.zeros(3, jnp.int64)\n"),
    ("G004", "flag", "pkg/mod.py",
     "import jax.numpy as jnp\n"
     "def f():\n"
     "    return jnp.arange(3, dtype='float64')\n"),
    ("G004", "flag", "pkg/mod.py",
     "from jax import lax\n"
     "import jax.numpy as jnp\n"
     "def count(a, b):\n"
     "    '''Exact weighted count.'''\n"
     "    return lax.dot_general(a, b, (((1,), (0,)), ((), ())),\n"
     "                           preferred_element_type=jnp.float32)\n"),
    ("G004", "pass", "pkg/utils/order.py",
     "import jax.numpy as jnp\n"
     "def pack():\n"
     "    return jnp.zeros(3, jnp.int64)\n"),  # key-packing module
    ("G004", "pass", "pkg/mod.py",
     "import numpy as np\n"
     "def f():\n"
     "    return np.zeros(3, np.int64)\n"),  # host-side numpy is fine
    ("G004", "waived", "pkg/mod.py",
     "from jax import lax\n"
     "import jax.numpy as jnp\n"
     "def count(a, b):\n"
     "    '''Exact weighted count.'''\n"
     "    # lint: f32-gate -- counts < 2^24 in this test\n"
     "    return lax.dot_general(a, b, (((1,), (0,)), ((), ())),\n"
     "                           preferred_element_type=jnp.float32)\n"),
    # -- G005: Pallas constraints --------------------------------------
    ("G005", "flag", "pkg/mod.py",
     "from jax.experimental import pallas as pl\n"
     "spec = pl.BlockSpec((16, 100), lambda i: (i, 0))\n"),
    ("G005", "flag", "pkg/mod.py",
     "from jax.experimental import pallas as pl\n"
     "spec = pl.BlockSpec((13, 128), lambda i: (i, 0))\n"),
    ("G005", "flag", "pkg/mod.py",
     "from jax.experimental import pallas as pl\n"
     "def add_kernel(a_ref, o_ref):\n"
     "    if a_ref[0] > 0:\n"
     "        o_ref[0] = a_ref[0]\n"),
    ("G005", "pass", "pkg/mod.py",
     "from jax.experimental import pallas as pl\n"
     "T = 4096\n"
     "spec = pl.BlockSpec((T, 128), lambda i: (i, 0))\n"),
    ("G005", "pass", "pkg/mod.py",
     "spec = BlockSpec((16, 100), None)\n"),  # no pallas import: not ours
    ("G005", "waived", "pkg/mod.py",
     "from jax.experimental import pallas as pl\n"
     "# lint: tile-ok -- test waiver\n"
     "spec = pl.BlockSpec((16, 100), lambda i: (i, 0))\n"),
    # -- G006: silent broad except -------------------------------------
    ("G006", "flag", "pkg/mod.py",
     "def f():\n"
     "    try:\n"
     "        work()\n"
     "    except Exception:\n"
     "        pass\n"),
    ("G006", "flag", "pkg/mod.py",
     "def f():\n"
     "    try:\n"
     "        work()\n"
     "    except:\n"
     "        return None\n"),
    ("G006", "pass", "pkg/mod.py",
     "def f():\n"
     "    try:\n"
     "        work()\n"
     "    except Exception as e:\n"
     "        raise InputError(str(e))\n"),
    ("G006", "pass", "pkg/mod.py",
     "def f():\n"
     "    try:\n"
     "        work()\n"
     "    except ValueError:\n"
     "        pass\n"),  # narrow catch is allowed
    ("G006", "waived", "pkg/mod.py",
     "def f():\n"
     "    try:\n"
     "        work()\n"
     "    # lint: waive G006 -- best-effort in this test\n"
     "    except Exception:\n"
     "        pass\n"),
    # -- G007: mutable defaults / import-time device work --------------
    ("G007", "flag", "pkg/mod.py",
     "def f(acc=[]):\n"
     "    return acc\n"),
    ("G007", "flag", "pkg/mod.py",
     "import jax.numpy as jnp\n"
     "ZERO = jnp.zeros(8)\n"),
    ("G007", "pass", "pkg/mod.py",
     "import jax.numpy as jnp\n"
     "def f(acc=None):\n"
     "    return acc or jnp.zeros(8)\n"),
    ("G007", "waived", "pkg/mod.py",
     "import jax.numpy as jnp\n"
     "# lint: import-time-ok -- test waiver\n"
     "ZERO = jnp.zeros(8)\n"),
    # -- G008: TODO/FIXME need an issue reference ----------------------
    ("G008", "flag", "pkg/mod.py",
     "# TODO make this faster\n"
     "X = 1\n"),
    ("G008", "pass", "pkg/mod.py",
     "# TODO(#123) make this faster\n"
     "# FIXME tracked in ROADMAP.md open items\n"
     "X = 1\n"),
    ("G008", "waived", "pkg/mod.py",
     "# TODO make this faster  lint: waive G008\n"
     "X = 1\n"),
    # -- G009: artifact writes must use the atomic writer --------------
    ("G009", "flag", "pkg/io/w.py",
     "def save(path, lines):\n"
     "    with open(path, 'w') as f:\n"
     "        f.writelines(lines)\n"),
    ("G009", "flag", "pkg/io/w.py",
     "def save(path, lines):\n"
     "    with open(path, mode='wb') as f:\n"
     "        f.write(lines)\n"),
    ("G009", "flag", "pkg/io/w.py",
     "from fastapriori_tpu.io.writer import open_write\n"
     "def save(path, lines):\n"
     "    with open_write(path) as f:\n"
     "        f.writelines(lines)\n"),
    ("G009", "pass", "pkg/io/w.py",
     "def load(path):\n"
     "    with open(path, 'rb') as f:\n"
     "        return f.read()\n"),
    ("G009", "pass", "pkg/io/w.py",
     "def load(path):\n"
     "    with open(path) as f:\n"
     "        return f.read()\n"),
    ("G009", "pass", "tests/test_w.py",
     "def fixture(path):\n"
     "    with open(path, 'w') as f:\n"
     "        f.write('1 2 3')\n"),  # test fixtures are exempt
    ("G009", "waived", "pkg/io/w.py",
     "def save(path, lines):\n"
     "    # lint: waive G009 -- test waiver\n"
     "    with open(path, 'w') as f:\n"
     "        f.writelines(lines)\n"),
    # -- G002 satellite: shard_map kwarg + P(...) spec axis sources ----
    ("G002", "pass", "pkg/mod.py",
     "import jax\n"
     "from jax import lax\n"
     "from jax.experimental.shard_map import shard_map\n"
     "def build(f, mesh):\n"
     "    return shard_map(f, mesh=mesh, axis_names=('tx2',))\n"
     "def g(x):\n"
     "    return lax.psum(x, 'tx2')\n"),  # axis declared by the kwarg
    ("G002", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "from jax.sharding import PartitionSpec as P\n"
     "SPEC = P('tx3', None)\n"
     "def g(x):\n"
     "    return lax.psum(x, 'tx3')\n"),  # axis declared by the spec
    ("G002", "flag", "pkg/mod.py",
     "from jax import lax\n"
     "from jax.experimental.shard_map import shard_map\n"
     "def build(f, mesh):\n"
     "    return shard_map(f, mesh=mesh, axis_name='tx4')\n"
     "def g(x):\n"
     "    return lax.psum(x, 'tx9')\n"),  # tx4 declared, tx9 is a typo
    # -- G010: donated buffer referenced after the jitted call ---------
    ("G010", "flag", "pkg/mod.py",
     "import jax\n"
     "def run(x):\n"
     "    f = jax.jit(lambda a: a * 2, donate_argnums=0)\n"
     "    y = f(x)\n"
     "    return y + x\n"),  # x's buffer was freed at dispatch
    ("G010", "flag", "pkg/mod.py",
     "import jax\n"
     "def run(x):\n"
     "    y = jax.jit(lambda a: a, donate_argnums=(0,))(x)\n"
     "    z = x.sum()\n"
     "    return y, z\n"),  # direct-call donation form
    ("G010", "flag", "pkg/mod.py",
     "import jax\n"
     "_inner = jax.jit(lambda a: a, donate_argnums=0)\n"
     "def consume(buf):\n"
     "    return _inner(buf)\n"
     "def run(x):\n"
     "    y = consume(x)\n"
     "    return y + x\n"),  # one-level cross-function propagation
    ("G010", "pass", "pkg/mod.py",
     "import jax\n"
     "def run(x):\n"
     "    f = jax.jit(lambda a: a * 2, donate_argnums=0)\n"
     "    y = f(x)\n"
     "    return y\n"),  # no use after donation
    ("G010", "pass", "pkg/mod.py",
     "import jax\n"
     "def run(x):\n"
     "    f = jax.jit(lambda a: a * 2, donate_argnums=0)\n"
     "    x = f(x)\n"
     "    return x + 1\n"),  # rebinding makes the name live again
    ("G010", "waived", "pkg/mod.py",
     "import jax\n"
     "def run(x):\n"
     "    f = jax.jit(lambda a: a * 2, donate_argnums=0)\n"
     "    y = f(x)\n"
     "    return y + x  # lint: donate-ok -- test waiver\n"),
    # -- G011: dynamic ints must bucket before becoming shapes ---------
    ("G011", "flag", "pkg/parallel/m.py",
     "import jax.numpy as jnp\n"
     "def up(xs):\n"
     "    n = len(xs)\n"
     "    return jnp.zeros((n, 8), jnp.int32)\n"),
    ("G011", "flag", "pkg/parallel/m.py",
     "import jax\n"
     "import jax.numpy as jnp\n"
     "def sig(x):\n"
     "    m = x.shape[0] * 2\n"
     "    return jax.ShapeDtypeStruct((m, 8), jnp.int32)\n"),
    ("G011", "pass", "pkg/parallel/m.py",
     "import jax.numpy as jnp\n"
     "from fastapriori_tpu.ops.bitmap import next_pow2\n"
     "def up(xs):\n"
     "    n = next_pow2(len(xs))\n"
     "    return jnp.zeros((n, 8), jnp.int32)\n"),  # bucketed: fine
    ("G011", "pass", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def host_scratch(xs):\n"
     "    return np.zeros((len(xs), 8), np.int32)\n"),  # host numpy: free
    ("G011", "pass", "pkg/ops/m.py",
     "import jax.numpy as jnp\n"
     "def up(xs):\n"
     "    return jnp.zeros((len(xs), 8), jnp.int32)\n"),  # out of scope
    ("G011", "waived", "pkg/parallel/m.py",
     "import jax.numpy as jnp\n"
     "def up(xs):\n"
     "    n = len(xs)\n"
     "    # lint: bucket-ok -- test waiver\n"
     "    return jnp.zeros((n, 8), jnp.int32)\n"),
    # -- G012: FA_* env knobs are a strict, registered contract --------
    ("G012", "flag", "pkg/mod.py",
     "import os\n"
     "def knob():\n"
     "    return os.environ.get('FA_TEST_KNOB', '') == '1'\n"),
    ("G012", "flag", "pkg/mod.py",
     "import os\n"
     "FLAG = os.environ.get('FA_TEST_KNOB', '')\n"),  # module level
    ("G012", "pass", "pkg/mod.py",
     "import os\n"
     "from fastapriori_tpu.errors import InputError\n"
     "def knob():\n"
     "    raw = os.environ.get('FA_TEST_KNOB', '')\n"
     "    if raw not in ('', '0', '1'):\n"
     "        raise InputError(f'unrecognized FA_TEST_KNOB {raw!r}')\n"
     "    return raw == '1'\n"),
    ("G012", "pass", "pkg/mod.py",
     "import os\n"
     "from fastapriori_tpu.errors import InputError\n"
     "def parse(raw):\n"
     "    if raw not in ('', '0', '1'):\n"
     "        raise InputError(f'bad value {raw!r}')\n"
     "    return raw == '1'\n"
     "def knob():\n"
     "    return parse(os.environ.get('FA_TEST_KNOB', ''))\n"),
    # the read routes through a strict parser: one-level propagation
    ("G012", "waived", "pkg/mod.py",
     "import os\n"
     "def knob():\n"
     "    # lint: env-ok -- free-form test knob\n"
     "    return os.environ.get('FA_TEST_KNOB', '')\n"),
    # -- G013: audited-site census (uniqueness + failpoint coverage) ---
    ("G013", "flag", "pkg/mod.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability import retry\n"
     "COVER = ('fetch.a', 'fetch.b')\n"
     "def pull(x, y):\n"
     "    u = retry.fetch(lambda: np.asarray(x), 'a')\n"
     "    v = retry.fetch(lambda: np.asarray(y), 'a')\n"
     "    return u, v\n"),  # duplicated label 'a'
    ("G013", "flag", "pkg/mod.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability import retry\n"
     "def pull(x):\n"
     "    return retry.fetch(lambda: np.asarray(x), 'uncovered')\n"),
    # no literal 'fetch.uncovered' armed anywhere: no coverage
    ("G013", "pass", "pkg/mod.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability import retry\n"
     "COVERAGE_SITES = ('fetch.a', 'fetch.b')\n"
     "def pull(x, y):\n"
     "    u = retry.fetch(lambda: np.asarray(x), 'a')\n"
     "    v = retry.fetch(lambda: np.asarray(y), 'b')\n"
     "    return u, v\n"),  # unique labels, both covered
    ("G013", "waived", "pkg/mod.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability import retry\n"
     "def pull(x, y):\n"
     "    u = retry.fetch(lambda: np.asarray(x), 'a')"
     "  # lint: waive G013 -- test waiver\n"
     "    v = retry.fetch(lambda: np.asarray(y), 'a')"
     "  # lint: waive G013 -- test waiver\n"
     "    return u, v\n"),
    # -- G014: span-scope census (fetch labels <-> tracer declaration) --
    ("G014", "flag", "pkg/mod.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability import retry\n"
     "FETCH_SITE_SPANS = ('fetch.a',)\n"
     "COVERAGE = ('fetch.a', 'fetch.unspanned')\n"
     "def pull(x, y):\n"
     "    u = retry.fetch(lambda: np.asarray(x), 'a')\n"
     "    v = retry.fetch(lambda: np.asarray(y), 'unspanned')\n"
     "    return u, v\n"),  # 'unspanned' not declared a span scope
    ("G014", "flag", "pkg/mod.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability import retry\n"
     "FETCH_SITE_SPANS = ('fetch.a', 'fetch.gone')\n"
     "COVERAGE = ('fetch.a',)\n"
     "def pull(x):\n"
     "    return retry.fetch(lambda: np.asarray(x), 'a')\n"),
    # ^ stale declaration: no fetch site 'gone' remains
    ("G014", "pass", "pkg/mod.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability import retry\n"
     "FETCH_SITE_SPANS = ('fetch.a', 'fetch.b')\n"
     "COVERAGE = ('fetch.a', 'fetch.b')\n"
     "def pull(x, y):\n"
     "    u = retry.fetch(lambda: np.asarray(x), 'a')\n"
     "    v = retry.fetch(lambda: np.asarray(y), 'b')\n"
     "    return u, v\n"),  # census and declaration agree both ways
    ("G014", "waived", "pkg/mod.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability import retry\n"
     "FETCH_SITE_SPANS = ('fetch.a',)\n"
     "COVERAGE = ('fetch.a', 'fetch.b')\n"
     "def pull(x, y):\n"
     "    u = retry.fetch(lambda: np.asarray(x), 'a')\n"
     "    v = retry.fetch(lambda: np.asarray(y), 'b')"
     "  # lint: waive G014 -- test waiver\n"
     "    return u, v\n"),
    # -- G015: rank-divergent values must not steer collectives --------
    ("G015", "flag", "pkg/mod.py",
     "import os\n"
     "from jax import lax\n"
     "def count(x):\n"
     "    if os.environ.get('FA_FAST', '') == '1':\n"
     "        return lax.psum(x, 'txn')\n"
     "    return x\n"),  # unguarded env branch changes collective count
    ("G015", "flag", "pkg/mod.py",
     "from jax import lax\n"
     "def count(x):\n"
     "    try:\n"
     "        y = quick(x)\n"
     "    except Exception as e:\n"
     "        y = lax.psum(x, 'txn')\n"
     "    return y\n"),  # only the failing rank takes the psum path
    ("G015", "pass", "pkg/mod.py",
     "import os\n"
     "from jax import lax\n"
     "from fastapriori_tpu.reliability import quorum\n"
     "def count(x):\n"
     "    fast = os.environ.get('FA_FAST', '') == '1'\n"
     "    if fast and quorum.stage_allowed('count_reduce', 'sparse'):\n"
     "        return lax.psum(x, 'txn')\n"
     "    return x\n"),  # the consensus floor sanitizes the decision
    ("G015", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "def count(x, enabled):\n"
     "    if enabled:\n"
     "        return lax.psum(x, 'txn')\n"
     "    return x\n"),  # uniform parameter: peers branch identically
    ("G015", "waived", "pkg/mod.py",
     "import os\n"
     "from jax import lax\n"
     "def count(x):\n"
     "    # lint: waive G015 -- test waiver (single-process-only path)\n"
     "    if os.environ.get('FA_FAST', '') == '1':\n"
     "        return lax.psum(x, 'txn')\n"
     "    return x\n"),
    # -- G016: collective-shaping chains must be consensus-registered --
    ("G016", "flag", "pkg/mod.py",
     "from jax import lax\n"
     "CHAINS = {'myengine': ('fast', 'slow'),\n"
     "          'logchain': ('a', 'b')}\n"
     "CONSENSUS_CHAINS = ('logchain',)\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def run(x, bad):\n"
     "    if bad:\n"
     "        downgrade('myengine', 'fast', 'slow')\n"
     "        downgrade('logchain', 'a', 'b')\n"
     "    return lax.psum(x, 'txn')\n"),
    # ^ myengine walks in a collective-bearing fn, unregistered
    ("G016", "flag", "pkg/mod.py",
     "CHAINS = {'myengine': ('fast', 'slow')}\n"
     "CONSENSUS_CHAINS = ('ghost',)\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"),  # registered chain that does not exist in CHAINS
    ("G016", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "CHAINS = {'myengine': ('fast', 'slow')}\n"
     "CONSENSUS_CHAINS = ('myengine',)\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def run(x, bad):\n"
     "    if bad:\n"
     "        downgrade('myengine', 'fast', 'slow')\n"
     "    return lax.psum(x, 'txn')\n"),  # registered and walked
    ("G016", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "CHAINS = {'myengine': ('fast', 'slow')}\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def run(x, bad):\n"
     "    if bad:\n"
     "        downgrade('myengine', 'fast', 'slow')\n"
     "    return lax.psum(x, 'txn')\n"),
    # ^ no CONSENSUS_CHAINS declared: pre-quorum tree, no registry to check
    ("G016", "waived", "pkg/mod.py",
     "from jax import lax\n"
     "CHAINS = {\n"
     "    # lint: waive G016 -- host-local test chain: never crosses the mesh\n"
     "    'myengine': ('fast', 'slow'),\n"
     "    'otherchain': ('a', 'b'),\n"
     "}\n"
     "CONSENSUS_CHAINS = ('otherchain',)\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def run(x, bad):\n"
     "    if bad:\n"
     "        downgrade('myengine', 'fast', 'slow')\n"
     "        downgrade('otherchain', 'a', 'b')\n"
     "    return lax.psum(x, 'txn')\n"),
    # -- G017: mid-loop re-clamps must be exchange-dominated -----------
    ("G017", "flag", "pkg/mod.py",
     "from jax import lax\n"
     "from fastapriori_tpu.reliability import quorum\n"
     "def mine(levels, x):\n"
     "    for k in levels:\n"
     "        if not quorum.stage_allowed('count_reduce', 'sparse'):\n"
     "            x = x + 1\n"
     "        x = lax.psum(x, 'txn')\n"
     "    return x\n"),  # loop never exchanges: the floor cannot move
    ("G017", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "from fastapriori_tpu.reliability import quorum\n"
     "def mine(levels, x):\n"
     "    for k in levels:\n"
     "        quorum.sync('level')\n"
     "        if not quorum.stage_allowed('count_reduce', 'sparse'):\n"
     "            x = x + 1\n"
     "        x = lax.psum(x, 'txn')\n"
     "    return x\n"),  # boundary exchange dominates the re-clamp
    ("G017", "pass", "pkg/mod.py",
     "from fastapriori_tpu.reliability import quorum\n"
     "def pick():\n"
     "    return quorum.stage_allowed('count_reduce', 'sparse')\n"),
    # ^ start-of-phase clamp outside any loop: rendezvous-covered
    ("G017", "waived", "pkg/mod.py",
     "from jax import lax\n"
     "from fastapriori_tpu.reliability import quorum\n"
     "def mine(levels, x):\n"
     "    for k in levels:\n"
     "        # lint: waive G017 -- test waiver (lockstep argument)\n"
     "        if not quorum.stage_allowed('count_reduce', 'sparse'):\n"
     "            x = x + 1\n"
     "        x = lax.psum(x, 'txn')\n"
     "    return x\n"),
    # -- v4 path-sensitivity: branch-local taint states (ISSUE 16) -----
    ("G015", "flag", "pkg/mod.py",
     "import os\n"
     "from jax import lax\n"
     "def decide(mode):\n"
     "    flaky = os.environ.get('FA_FAST', '') == '1'\n"
     "    if mode:\n"
     "        flaky = False\n"
     "    return flaky\n"
     "def count(x, mode):\n"
     "    if decide(mode):\n"
     "        return lax.psum(x, 'txn')\n"
     "    return x\n"),
    # ^ cleared in ONE arm only: the divergent fall-through arm must
    #   survive the join (v3's suite-shared env let the body assignment
    #   overwrite the taint — a false negative, now caught)
    ("G015", "pass", "pkg/mod.py",
     "import os\n"
     "from jax import lax\n"
     "def decide(mode):\n"
     "    flaky = os.environ.get('FA_FAST', '') == '1'\n"
     "    if mode:\n"
     "        flaky = True\n"
     "    else:\n"
     "        flaky = False\n"
     "    return flaky\n"
     "def count(x, mode):\n"
     "    if decide(mode):\n"
     "        return lax.psum(x, 'txn')\n"
     "    return x\n"),  # BOTH arms overwrite: uniform after the join
    ("G015", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "from fastapriori_tpu.reliability import quorum\n"
     "def count(x, mode):\n"
     "    if mode:\n"
     "        ok = quorum.stage_allowed('count_reduce', 'sparse')\n"
     "    else:\n"
     "        ok = quorum.stage_allowed('count_reduce', 'exact')\n"
     "    if ok:\n"
     "        return lax.psum(x, 'txn')\n"
     "    return x\n"),  # sanitized in BOTH arms: uniform after the join
    ("G015", "pass", "pkg/mod.py",
     "import os\n"
     "from jax import lax\n"
     "from fastapriori_tpu.reliability import quorum\n"
     "def count(x):\n"
     "    fast = os.environ.get('FA_FAST', '') == '1'\n"
     "    if fast and quorum.current_fence() == 0:\n"
     "        return lax.psum(x, 'txn')\n"
     "    return x\n"),  # epoch-guard compare sanitizes (v4 lattice)
    # -- G018: boundary raises must be classified ----------------------
    ("G018", "flag", "pkg/parallel/m.py",
     "def shard(n, k):\n"
     "    if n % k:\n"
     "        raise ValueError('devices must divide rows')\n"
     "    return n // k\n"),
    ("G018", "flag", "pkg/parallel/m.py",
     "class LocalOops(Exception):\n"
     "    pass\n"
     "def run(args):\n"
     "    raise LocalOops('unclassified local type')\n"),
    ("G018", "pass", "pkg/obs/mod.py",
     "def load(path):\n"
     "    raise ValueError('not a boundary surface')\n"),
    ("G018", "pass", "pkg/io/errors.py",
     "class DataError(Exception):\n"
     "    pass\n"
     "def load(path):\n"
     "    raise DataError('classified: defined by the errors module')\n"),
    ("G018", "pass", "pkg/io/mod.py",
     "from fastapriori_tpu.errors import InputError\n"
     "def load(path):\n"
     "    try:\n"
     "        raise ValueError('probe')\n"
     "    except ValueError:\n"
     "        raise InputError('wrapped locally: ' + path) from None\n"),
    ("G018", "pass", "pkg/serve/mod.py",
     "from fastapriori_tpu.reliability import ledger\n"
     "def answer(q):\n"
     "    ledger.record('serve.degraded', q=q)\n"
     "    raise RuntimeError('after the recorded degrade')\n"),
    ("G018", "waived", "pkg/io/mod.py",
     "def load(path):\n"
     "    # lint: waive G018 -- test waiver\n"
     "    raise ValueError('bad input')\n"),
    ("G018", "waived", "pkg/io/mod.py",
     "def load(path):\n"
     "    raise ValueError('bad')  # lint: raise-ok -- test alias\n"),
    # -- G019: downgrade walks vs the live CHAINS literal --------------
    ("G019", "flag", "pkg/mod.py",
     "CHAINS = {'eng': ('fast', 'exact')}\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def trip():\n"
     "    downgrade('ghost', 'fast', 'exact')\n"),  # unregistered chain
    ("G019", "flag", "pkg/mod.py",
     "CHAINS = {'eng': ('fast', 'exact')}\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def trip():\n"
     "    downgrade('eng', 'fast', 'slow')\n"),  # stage drifted
    ("G019", "flag", "pkg/mod.py",
     "CHAINS = {'eng': ('fast', 'exact')}\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def trip():\n"
     "    downgrade('eng', 'exact', 'fast')\n"),  # backward walk
    ("G019", "flag", "pkg/mod.py",
     "CHAINS = {'eng': ('fast', 'mid', 'exact')}\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def step():\n"
     "    downgrade('eng', 'fast', 'mid')\n"),  # terminus unreachable
    ("G019", "pass", "pkg/mod.py",
     "CHAINS = {'eng': ('fast', 'mid', 'exact')}\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def step():\n"
     "    downgrade('eng', 'fast', 'mid')\n"
     "def fall():\n"
     "    downgrade('eng', 'mid', 'exact')\n"),  # full literal path
    ("G019", "pass", "pkg/mod.py",
     "CHAINS = {'eng': ('fast', 'mid', 'exact')}\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def adopt(pos):\n"
     "    downgrade('eng', pos, 'exact')\n"),  # dynamic frm: from-anywhere
    ("G019", "waived", "pkg/mod.py",
     "CHAINS = {'eng': ('fast', 'exact')}\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def trip():\n"
     "    # lint: waive G019 -- test waiver\n"
     "    downgrade('ghost', 'fast', 'exact')\n"),
    ("G019", "waived", "pkg/mod.py",
     "CHAINS = {'eng': ('fast', 'exact')}\n"
     "def downgrade(chain, frm, to):\n"
     "    pass\n"
     "def trip():\n"
     "    downgrade('eng', 'exact', 'fast')  # lint: cascade-ok -- alias\n"),
    # -- G020: fenced checkpoints, checked not trusted -----------------
    ("G020", "flag", "pkg/io/mod.py",
     "from fastapriori_tpu.io.writer import write_manifest\n"
     "def save(prefix, manifest):\n"
     "    write_manifest(prefix, manifest)\n"),  # fence-less commit
    ("G020", "flag", "pkg/io/mod.py",
     "from fastapriori_tpu.io.resume import load_manifest\n"
     "def resume(prefix):\n"
     "    return load_manifest(prefix)\n"),  # validate-less resume read
    ("G020", "pass", "pkg/io/mod.py",
     "from fastapriori_tpu.io.writer import write_manifest\n"
     "from fastapriori_tpu.reliability import quorum\n"
     "def save(prefix, manifest):\n"
     "    write_manifest(prefix, manifest,\n"
     "                   fence=quorum.checkpoint_fence() or None)\n"),
    ("G020", "pass", "pkg/io/mod.py",
     "from fastapriori_tpu.io.resume import load_manifest, manifest_fence\n"
     "from fastapriori_tpu.reliability import quorum\n"
     "def resume(prefix):\n"
     "    quorum.validate_resume_fence(manifest_fence(prefix))\n"
     "    return load_manifest(prefix)\n"),
    ("G020", "waived", "pkg/io/mod.py",
     "from fastapriori_tpu.io.writer import write_manifest\n"
     "def dump(prefix, manifest):\n"
     "    # lint: waive G020 -- test waiver (crash-path dump)\n"
     "    write_manifest(prefix, manifest)\n"),
    ("G020", "waived", "pkg/io/mod.py",
     "from fastapriori_tpu.io.resume import load_manifest\n"
     "def probe(prefix):\n"
     "    return load_manifest(prefix)  # lint: fence-ok -- test alias\n"),
    # -- G021: bounded-wait (v5 concurrency layer) ---------------------
    ("G021", "flag", "pkg/serve/worker.py",
     "import threading\n"
     "def pump(ev):\n"
     "    ev.wait()\n"),
    # An inescapable poll loop: constant-true, sleeps, never exits.
    ("G021", "flag", "pkg/serve/worker.py",
     "import time\n"
     "def spin():\n"
     "    while True:\n"
     "        time.sleep(0.01)\n"),
    ("G021", "pass", "pkg/serve/worker.py",
     "import threading\n"
     "def pump(ev):\n"
     "    ev.wait(0.05)\n"),
    # Unbounded queue.get escapes via a censused shutdown sentinel:
    # module-level object(), checked with `is` in the consumer, and
    # DELIVERED on a finally path in the same file.
    ("G021", "pass", "pkg/serve/worker.py",
     "_STOP = object()\n"
     "def pump(q):\n"
     "    while True:\n"
     "        item = q.get()\n"
     "        if item is _STOP:\n"
     "            return\n"
     "def feed(q):\n"
     "    try:\n"
     "        pass\n"
     "    finally:\n"
     "        q.append(_STOP)\n"),
    ("G021", "waived", "pkg/serve/worker.py",
     "import threading\n"
     "def pump(ev):\n"
     "    ev.wait()  # lint: waive G021 -- test waiver\n"),
    # -- G022: cross-thread shared state needs the class lock ----------
    ("G022", "flag", "pkg/serve/srv.py",
     "import threading\n"
     "class Srv:\n"
     "    def __init__(self):\n"
     "        self._lock = threading.Lock()\n"
     "        self._stats = {}\n"
     "    def start(self):\n"
     "        threading.Thread(target=self._loop, daemon=True).start()\n"
     "    def _loop(self):\n"
     "        self._stats = {'n': 1}\n"
     "    def stats(self):\n"
     "        return dict(self._stats)\n"),
    ("G022", "pass", "pkg/serve/srv.py",
     "import threading\n"
     "class Srv:\n"
     "    def __init__(self):\n"
     "        self._lock = threading.Lock()\n"
     "        self._stats = {}\n"
     "    def start(self):\n"
     "        threading.Thread(target=self._loop, daemon=True).start()\n"
     "    def _loop(self):\n"
     "        with self._lock:\n"
     "            self._stats = {'n': 1}\n"
     "    def stats(self):\n"
     "        return dict(self._stats)\n"),
    ("G022", "waived", "pkg/serve/srv.py",
     "import threading\n"
     "class Srv:\n"
     "    def __init__(self):\n"
     "        self._lock = threading.Lock()\n"
     "        self._stats = {}\n"
     "    def start(self):\n"
     "        threading.Thread(target=self._loop, daemon=True).start()\n"
     "    def _loop(self):\n"
     "        self._stats = {'n': 1}  # lint: waive G022 -- test waiver\n"
     "    def stats(self):\n"
     "        return dict(self._stats)\n"),
    # -- G023: served table installed only via the barrier path --------
    ("G023", "flag", "pkg/serve/srv.py",
     "import threading\n"
     "class Srv:\n"
     "    def __init__(self, state):\n"
     "        self._cond = threading.Condition()\n"
     "        self._state = state\n"
     "    def start(self):\n"
     "        threading.Thread(target=self._loop, daemon=True).start()\n"
     "    def _loop(self):\n"
     "        x = self._state\n"
     "    def install(self, table):\n"
     "        with self._cond:\n"
     "            self._state = table\n"),
    ("G023", "pass", "pkg/serve/srv.py",
     "import threading\n"
     "class Srv:\n"
     "    def __init__(self, state):\n"
     "        self._cond = threading.Condition()\n"
     "        self._state = state\n"
     "    def start(self):\n"
     "        threading.Thread(target=self._loop, daemon=True).start()\n"
     "    def _loop(self):\n"
     "        x = self._state\n"
     "    def _commit_swap(self, marker):\n"
     "        with self._cond:\n"
     "            self._state = marker.state\n"),
    ("G023", "waived", "pkg/serve/srv.py",
     "import threading\n"
     "class Srv:\n"
     "    def __init__(self, state):\n"
     "        self._cond = threading.Condition()\n"
     "        self._state = state\n"
     "    def start(self):\n"
     "        threading.Thread(target=self._loop, daemon=True).start()\n"
     "    def _loop(self):\n"
     "        x = self._state\n"
     "    def install(self, table):\n"
     "        with self._cond:\n"
     "            self._state = table  # lint: waive G023 -- test waiver\n"),
    # -- G024: marker/payload names carry the epoch namespace ----------
    ("G024", "flag", "pkg/reliability/quorum.py",
     "def announce(t, doc):\n"
     "    t.post_marker('barrier', doc)\n"),
    # Payload file name without the sequence interpolated (part B).
    ("G024", "flag", "pkg/serve/router.py",
     "import os\n"
     "def respond(d, name):\n"
     "    return os.path.join(d, f'rsp-{name}.json')\n"),
    ("G024", "pass", "pkg/reliability/quorum.py",
     "class Dom:\n"
     "    def __init__(self):\n"
     "        self.mesh_epoch = 0\n"
     "    def _esite(self, site):\n"
     "        return 'e%d.%s' % (self.mesh_epoch, site)\n"
     "    def announce(self, t, doc):\n"
     "        t.post_marker(self._esite('barrier'), doc)\n"),
    ("G024", "pass", "pkg/serve/router.py",
     "import os\n"
     "def respond(d, seq):\n"
     "    return os.path.join(d, f'rsp-{seq:08d}.json')\n"),
    ("G024", "waived", "pkg/reliability/quorum.py",
     "def announce(t, doc):\n"
     "    t.post_marker('barrier', doc)  # lint: waive G024 -- test waiver\n"),
    # -- waiver-grammar edge cases (engine, pinned by ISSUE 5) ---------
    # (a) a waiver above a decorator attaches to the decorated line
    ("G003", "waived", "pkg/mod.py",
     "import jax\n"
     "def run(fs, xs):\n"
     "    for f in fs:\n"
     "        # lint: waive G003 -- test waiver above the decorator\n"
     "        @jax.jit\n"
     "        def step(x):\n"
     "            return x\n"
     "        xs = step(xs)\n"
     "    return xs\n"),
    ("G003", "flag", "pkg/mod.py",
     "import jax\n"
     "def run(fs, xs):\n"
     "    # lint: waive G003 -- too far: not adjacent to the decorator\n"
     "    fs = list(fs)\n"
     "    for f in fs:\n"
     "        @jax.jit\n"
     "        def step(x):\n"
     "            return x\n"
     "        xs = step(xs)\n"
     "    return xs\n"),
    # (b) stacked waivers in one comment must all match
    ("G001", "waived", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    return np.asarray(arr)"
     "  # lint: waive G008 -- unrelated; lint: fetch-site -- stacked\n"),
    ("G001", "flag", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    return np.asarray(arr)"
     "  # lint: waive G008 -- wrong; lint: waive G006 -- also wrong\n"),
    # (c) a waiver inside a multi-line call binds to the call's span
    ("G001", "waived", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    return np.asarray(\n"
     "        arr,  # lint: fetch-site -- inner line binds to the span\n"
     "    )\n"),
    ("G001", "flag", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    return np.asarray(\n"
     "        arr,\n"
     "    )\n"
     "    # lint: fetch-site -- below the span: does not bind\n"),
]


def _ids():
    counts = {}
    out = []
    for rule, kind, _, _ in CASES:
        n = counts.get((rule, kind), 0)
        counts[(rule, kind)] = n + 1
        out.append(f"{rule}-{kind}{n}")
    return out


@pytest.mark.parametrize("rule,kind,path,src", CASES, ids=_ids())
def test_rule_case(rule, kind, path, src):
    result = engine.lint_sources([MESH_DECL, (path, src)])
    hits = [f for f in result.findings if f.rule == rule]
    assert not result.parse_errors, result.parse_errors
    if kind == "flag":
        assert hits, f"{rule} should have flagged:\n{src}"
    else:
        assert not hits, f"{rule} unexpectedly flagged {kind} case: {hits}"


def test_every_rule_has_all_three_case_kinds():
    covered = {(r, k) for r, k, _, _ in CASES}
    for rule in RULES_BY_ID:
        for kind in ("flag", "pass", "waived"):
            assert (rule, kind) in covered, f"missing {kind} case for {rule}"


def test_all_rules_registered_and_distinct():
    ids = [r.id for r in ALL_RULES]
    assert len(ids) == len(set(ids)) == 24
    assert all(hasattr(r, "name") and r.name for r in ALL_RULES)


def test_repo_mesh_axes_are_discovered():
    """Guards G002 against silently never checking: the real mesh module
    must contribute its axis declarations to the package context."""
    path = os.path.join(REPO_ROOT, "fastapriori_tpu", "parallel", "mesh.py")
    with open(path, "r", encoding="utf-8") as fh:
        ctx = FileContext("fastapriori_tpu/parallel/mesh.py", fh.read())
    pkg = PackageContext([ctx])
    assert {"txn", "cand"} <= pkg.declared_axes


def test_baseline_roundtrip(tmp_path):
    findings = engine.lint_sources(
        [("pkg/mod.py", "def f(acc=[]):\n    return acc\n")]
    ).findings
    assert findings
    data = engine.make_baseline(findings)
    assert engine.subtract_baseline(findings, data) == []
    # One MORE identical finding than the baseline froze still trips.
    assert engine.subtract_baseline(findings + findings[:1], data)


def test_cli_repo_is_clean_under_shipped_baseline():
    # The FULL v2 surface: package, tests, bench driver, entry script,
    # and the tooling (the linter lints itself) — under the EMPTY
    # baseline.
    rc = cli.main(
        cli.DEFAULT_PATHS
        + [
            "--baseline",
            os.path.join(REPO_ROOT, BASELINE),
            "--root",
            REPO_ROOT,
        ]
    )
    assert rc == 0


@pytest.mark.parametrize(
    "rule,src",
    [(r, s) for r, k, _, s in CASES if k == "flag"],
    ids=[f"{r}-{i}" for i, (r, k, _, s) in enumerate(CASES) if k == "flag"],
)
def test_cli_fails_when_must_flag_fixture_is_injected(tmp_path, rule, src):
    # The injected tree inherits the shipped baseline — a baselined repo
    # must still fail on any NEW instance of a must-flag pattern.
    pkg = tmp_path / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "meshdef.py").write_text(MESH_DECL[1])
    # Inject the fixture at its DECLARED path: several rules are
    # path-sensitive (G018 boundary dirs, G024 proto-file basenames),
    # so flattening to pkg/injected.py would mask the pattern.
    target = tmp_path / "pkg" / "injected.py"
    for r, k, p, s in CASES:
        if s == src and k == "flag":
            target = tmp_path / p
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(src)
    rc = cli.main(
        [
            "pkg",
            "--baseline",
            os.path.join(REPO_ROOT, BASELINE),
            "--root",
            str(tmp_path),
        ]
    )
    assert rc == 1


def test_cli_write_baseline_freezes_findings(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("def f(acc=[]):\n    return acc\n")
    bl = tmp_path / "bl.json"
    assert (
        cli.main(
            ["pkg", "--root", str(tmp_path), "--baseline", str(bl),
             "--write-baseline"]
        )
        == 0
    )
    frozen = json.loads(bl.read_text())
    assert frozen["fingerprints"]
    assert (
        cli.main(["pkg", "--root", str(tmp_path), "--baseline", str(bl)])
        == 0
    )


def test_cli_select_and_json_format(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "# TODO untracked\n" "def f(acc=[]):\n    return acc\n"
    )
    rc = cli.main(
        ["pkg", "--root", str(tmp_path), "--select", "G008",
         "--format", "json"]
    )
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [f["rule"] for f in out["findings"]] == ["G008"]


def test_waiver_justification_words_never_become_tokens():
    """A unicode-dash (or missing) separator must not let justification
    words waive other rules: only well-formed tokens count."""
    from tools.lint.engine import _parse_waiver_tokens

    assert _parse_waiver_tokens("# lint: waive G006 — version probe") == {
        "G006"
    }
    # No separator at all: prose words are dropped unless they happen to
    # be well-formed tokens — a justification mentioning another rule's
    # ALIAS shape must use `--` to be safe, so spell that requirement:
    assert "G003" in _parse_waiver_tokens("# lint: waive G003 -- fetch-site")
    assert "fetch-site" not in _parse_waiver_tokens(
        "# lint: waive G003 -- fetch-site"
    )


def test_g003_nested_loops_yield_one_finding():
    src = (
        "import jax\n"
        "def run(fs, xs):\n"
        "    for a in fs:\n"
        "        for b in a:\n"
        "            xs = jax.jit(b)(xs)\n"
        "    return xs\n"
    )
    result = engine.lint_sources([("pkg/mod.py", src)])
    assert len([f for f in result.findings if f.rule == "G003"]) == 1


def test_cli_write_baseline_rejects_select(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("X = 1\n")
    bl = tmp_path / "bl.json"
    rc = cli.main(
        ["pkg", "--root", str(tmp_path), "--baseline", str(bl),
         "--write-baseline", "--select", "G001"]
    )
    assert rc == 2
    assert not bl.exists()


def test_syntax_error_is_reported_not_crashed(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("def f(:\n")
    rc = cli.main(["pkg", "--root", str(tmp_path)])
    assert rc == 1


# ---------------------------------------------------------------------------
# v2 dataflow layer (tools/lint/graph.py + flow.py)


def test_call_graph_resolves_renamed_imports():
    from tools.lint.graph import PackageGraph

    a = FileContext("pkg/a.py", "def helper(x):\n    return x\n")
    b = FileContext(
        "pkg/b.py",
        "from pkg.a import helper as h\n"
        "def run(x):\n"
        "    return h(x)\n",
    )
    g = PackageGraph([a, b])
    run_fn = g.modules["pkg.b"].functions["run"]
    assert "pkg.a.helper" in g.callees(b, run_fn)


def test_call_graph_resolves_dotted_module_imports():
    from tools.lint.graph import PackageGraph

    a = FileContext("pkg/sub/a.py", "def helper(x):\n    return x\n")
    b = FileContext(
        "pkg/b.py",
        "import pkg.sub.a as mod\n"
        "def run(x):\n"
        "    return mod.helper(x)\n",
    )
    g = PackageGraph([a, b])
    run_fn = g.modules["pkg.b"].functions["run"]
    assert "pkg.sub.a.helper" in g.callees(b, run_fn)


def test_cross_file_constant_resolution_through_rename():
    """`from pkg.meshdef import AXIS as A` must resolve to the literal
    (v1's flat constant table is keyed by the ORIGINAL name and misses
    the rename) — shrinking G002's waiver pressure."""
    src = (
        "from jax import lax\n"
        "from pkg.meshdef import AXIS as A\n"
        "def f(x):\n"
        "    return lax.psum(x, A)\n"
    )
    result = engine.lint_sources([MESH_DECL, ("pkg/mod.py", src)])
    assert not [f for f in result.findings if f.rule == "G002"]


def test_shape_flow_summary_propagates_one_level():
    """A helper returning `x.shape[0]` makes its CALLERS' shape args
    dynamic — one level of cross-function propagation."""
    helper = ("pkg/parallel/h.py", "def rows(x):\n    return x.shape[0]\n")
    user = (
        "pkg/parallel/m.py",
        "import jax.numpy as jnp\n"
        "from pkg.parallel.h import rows\n"
        "def up(x):\n"
        "    return jnp.zeros((rows(x), 8), jnp.int32)\n",
    )
    result = engine.lint_sources([MESH_DECL, helper, user])
    hits = [f for f in result.findings if f.rule == "G011"]
    assert hits and hits[0].path == "pkg/parallel/m.py"


def test_shape_flow_summary_fixpoint_catches_two_hop_launder():
    """ISSUE 6 satellite (ROADMAP graftlint residue): a dynamic int
    laundered through TWO helpers — ``rows`` returns ``x.shape[0]``,
    ``padded`` forwards it — must still flag at the sink; the depth-1
    summary judged the forwarding helper CLEAN and the launder escaped.
    The fixpoint also must NOT over-taint: a two-hop chain whose inner
    helper sanitizes through next_pow2 stays BUCKETED and clean."""
    inner = ("pkg/parallel/h.py", "def rows(x):\n    return x.shape[0]\n")
    fwd = (
        "pkg/parallel/g.py",
        "from pkg.parallel.h import rows\n"
        "def padded(x):\n"
        "    return rows(x) + 8\n",
    )
    user = (
        "pkg/parallel/m.py",
        "import jax.numpy as jnp\n"
        "from pkg.parallel.g import padded\n"
        "def up(x):\n"
        "    return jnp.zeros((padded(x), 8), jnp.int32)\n",
    )
    result = engine.lint_sources([MESH_DECL, inner, fwd, user])
    hits = [f for f in result.findings if f.rule == "G011"]
    assert hits and hits[0].path == "pkg/parallel/m.py"
    # The positive twin: the same two-hop chain sanitized at the root
    # (the sparse-cap helper idiom — a compaction-size helper calling
    # next_pow2 indirectly) must stay clean.
    inner_ok = (
        "pkg/parallel/h.py",
        "from fastapriori_tpu.ops.bitmap import next_pow2\n"
        "def rows(x):\n"
        "    return next_pow2(x.shape[0])\n",
    )
    clean = engine.lint_sources([MESH_DECL, inner_ok, fwd, user])
    assert not [f for f in clean.findings if f.rule == "G011"]


def test_return_summaries_fixpoint_converges_monotonically():
    """The summary iteration must reach a stable fixpoint (not oscillate)
    and report the whole chain DYNAMIC."""
    from tools.lint import flow
    from tools.lint.engine import FileContext
    from tools.lint.graph import PackageGraph

    files = [
        FileContext("pkg/a.py", "def f(x):\n    return len(x)\n"),
        FileContext(
            "pkg/b.py",
            "from pkg.a import f\n"
            "def g(x):\n"
            "    return f(x)\n",
        ),
        FileContext(
            "pkg/c.py",
            "from pkg.b import g\n"
            "def h(x):\n"
            "    return g(x) * 2\n",
        ),
    ]
    graph = PackageGraph(files)
    summaries = flow.return_summaries(files, graph)
    assert summaries["pkg.a.f"] == flow.DYNAMIC
    assert summaries["pkg.b.g"] == flow.DYNAMIC
    assert summaries["pkg.c.h"] == flow.DYNAMIC


def test_g012_registry_membership_and_staleness():
    registry = {"vars": {"FA_KNOWN": {"description": "d", "readers": []},
                         "FA_STALE": {"description": "d", "readers": []}}}
    src = (
        "import os\n"
        "from fastapriori_tpu.errors import InputError\n"
        "def knob():\n"
        "    raw = os.environ.get('FA_SURPRISE', '')\n"
        "    if raw:\n"
        "        raise InputError('bad')\n"
        "    return raw\n"
    )
    result = engine.lint_sources(
        [("pkg/mod.py", src)], env_registry=registry
    )
    msgs = [f.message for f in result.findings if f.rule == "G012"]
    assert any("FA_SURPRISE" in m and "env_registry" in m for m in msgs)
    assert any("FA_STALE" in m and "no remaining" in m for m in msgs)
    # FA_KNOWN is... also unreferenced — stale too.  A referenced knob
    # is not:
    src2 = src.replace("FA_SURPRISE", "FA_KNOWN")
    result2 = engine.lint_sources(
        [("pkg/mod.py", src2)], env_registry=registry
    )
    msgs2 = [f.message for f in result2.findings if f.rule == "G012"]
    assert not any("FA_KNOWN" in m and "no remaining" in m for m in msgs2)
    assert not any("env_registry.json —" in m and "FA_KNOWN" in m
                   for m in msgs2)


def test_fixture_package_flow_rules_json_findings(tmp_path, capsys):
    """Every G010-G013 must-flag snippet, driven through the real CLI
    (`python -m tools.lint --format json` equivalent) as one fixture
    package — the dataflow layer works end-to-end, not just in-memory."""
    pkg = tmp_path / "pkg"
    parallel = pkg / "parallel"
    parallel.mkdir(parents=True)
    (pkg / "meshdef.py").write_text(MESH_DECL[1])
    for i, (rule, kind, path, src) in enumerate(CASES):
        if kind != "flag" or rule not in ("G010", "G011", "G012", "G013"):
            continue
        target = parallel if "parallel" in path else pkg
        (target / f"injected_{rule.lower()}_{i}.py").write_text(src)
    rc = cli.main(["pkg", "--root", str(tmp_path), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    flagged = {f["rule"] for f in out["findings"]}
    assert {"G010", "G011", "G012", "G013"} <= flagged


# ---------------------------------------------------------------------------
# contract inventory + drift check


def test_inventory_is_deterministic_and_censuses_sites():
    src = (
        "import numpy as np\n"
        "from fastapriori_tpu.reliability import retry, failpoints\n"
        "COVERAGE = ('fetch.a',)\n"
        "def pull(x):\n"
        "    failpoints.fire('pull.start')\n"
        "    # lint: waive G008 -- census me\n"
        "    return retry.fetch(lambda: np.asarray(x), 'a')\n"
    )
    r1 = engine.lint_sources([("pkg/mod.py", src)])
    r2 = engine.lint_sources([("pkg/mod.py", src)])
    assert r1.inventory == r2.inventory
    inv = r1.inventory
    assert {"label": "a", "path": "pkg/mod.py", "count": 1} in (
        inv["fetch_sites"]
    )
    assert {"site": "pull.start", "path": "pkg/mod.py", "count": 1} in (
        inv["failpoint_sites"]
    )
    assert any(w["justification"] == "census me" for w in inv["waivers"])


def test_inventory_excludes_test_files_from_site_census():
    src = (
        "import numpy as np\n"
        "from fastapriori_tpu.reliability import retry\n"
        "def pull(x):\n"
        "    return retry.fetch(lambda: np.asarray(x), 'a')\n"
    )
    inv = engine.lint_sources([("tests/test_x.py", src)]).inventory
    assert inv["fetch_sites"] == []


def test_env_registry_regeneration_preserves_descriptions():
    src = (
        "import os\n"
        "from fastapriori_tpu.errors import InputError\n"
        "def knob():\n"
        "    raw = os.environ.get('FA_NEW_KNOB', '')\n"
        "    if raw not in ('', '1'):\n"
        "        raise InputError('bad')\n"
        "    return raw\n"
    )
    result = engine.lint_sources([("pkg/mod.py", src)])
    old = {"vars": {"FA_NEW_KNOB": {"description": "kept!", "readers": []}}}
    reg = engine.regenerate_env_registry(result.pkg, old)
    assert reg["vars"]["FA_NEW_KNOB"]["description"] == "kept!"
    assert reg["vars"]["FA_NEW_KNOB"]["readers"] == ["pkg/mod.py"]
    # Unreferenced entries drop out on regeneration.
    old["vars"]["FA_GONE"] = {"description": "x", "readers": []}
    reg2 = engine.regenerate_env_registry(result.pkg, old)
    assert "FA_GONE" not in reg2["vars"]


def test_cli_inventory_write_then_check_roundtrip(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (tmp_path / "tools" / "lint").mkdir(parents=True)
    (pkg / "mod.py").write_text(
        "import numpy as np\n"
        "from fastapriori_tpu.reliability import retry\n"
        "COVERAGE = ('fetch.a',)\n"
        "def pull(x):\n"
        "    return retry.fetch(lambda: np.asarray(x), 'a')\n"
    )
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--write-inventory"]) == 0
    capsys.readouterr()
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--check-inventory"]) == 0
    # Any census churn now fails the drift check until regenerated.
    (pkg / "mod.py").write_text(
        "import numpy as np\n"
        "from fastapriori_tpu.reliability import retry\n"
        "COVERAGE = ('fetch.b',)\n"
        "def pull(x):\n"
        "    return retry.fetch(lambda: np.asarray(x), 'b')\n"
    )
    capsys.readouterr()
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--check-inventory"]) == 1
    err = capsys.readouterr().err
    assert "drift" in err
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--write-inventory"]) == 0
    capsys.readouterr()
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--check-inventory"]) == 0


def test_shipped_inventory_matches_tree():
    """The committed inventory/registry/README table must match what
    the tree regenerates (the same check tools/ci.sh runs)."""
    rc = cli.main(
        cli.DEFAULT_PATHS
        + [
            "--baseline",
            os.path.join(REPO_ROOT, BASELINE),
            "--root",
            REPO_ROOT,
            "--check-inventory",
        ]
    )
    assert rc == 0


def test_waiver_above_decorator_attaches_to_def_line():
    src = (
        "import jax\n"
        "def run(fs, xs):\n"
        "    for f in fs:\n"
        "        # lint: waive G003 -- hoisting tracked in ROADMAP\n"
        "        @jax.jit\n"
        "        def step(x):\n"
        "            return x\n"
        "        xs = step(xs)\n"
        "    return xs\n"
    )
    result = engine.lint_sources([("pkg/mod.py", src)])
    assert not [f for f in result.findings if f.rule == "G003"]


def test_g011_sanitizer_inside_branch_is_respected():
    """A sink in a branch SUITE must be judged with the suite's own
    env — `n = next_pow2(n)` just before the sink is a sanitize, even
    when both live inside an `if` (regression: the pre-scan used the
    stale pre-branch env and flagged correctly bucketed code)."""
    src = (
        "import jax.numpy as jnp\n"
        "from fastapriori_tpu.ops.bitmap import next_pow2\n"
        "def up(xs, cond):\n"
        "    n = len(xs)\n"
        "    if cond:\n"
        "        n = next_pow2(n)\n"
        "        return jnp.zeros((n, 8), jnp.int32)\n"
        "    return None\n"
    )
    result = engine.lint_sources([("pkg/parallel/m.py", src)])
    assert not [f for f in result.findings if f.rule == "G011"]
    # ...while an UNsanitized sink in a branch suite still flags.
    flagged = engine.lint_sources(
        [("pkg/parallel/m.py", src.replace("n = next_pow2(n)", "pass"))]
    )
    assert [f for f in flagged.findings if f.rule == "G011"]


def test_inventory_modes_refuse_partial_paths(capsys):
    """Regenerating (or drift-checking) the committed inventory from a
    partial path set would silently truncate the census."""
    rc = cli.main(
        ["fastapriori_tpu", "--root", REPO_ROOT, "--check-inventory"]
    )
    assert rc == 2
    assert "full default paths" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# v3: collective census + rank-divergence taint (tools/lint/collective.py)


def test_collective_census_captures_axis_engine_and_guards():
    src = (
        "from jax import lax\n"
        "def f(x, flag):\n"
        "    if flag:\n"
        "        return lax.psum(x, 'txn')\n"
        "    return x\n"
    )
    r1 = engine.lint_sources([MESH_DECL, ("pkg/mod.py", src)])
    r2 = engine.lint_sources([MESH_DECL, ("pkg/mod.py", src)])
    assert r1.inventory == r2.inventory  # deterministic
    sites = r1.inventory["collective_sites"]
    assert {
        "collective": "psum",
        "axis": "txn",
        "engine": "pkg.mod:f",
        "guards": "flag",
        "path": "pkg/mod.py",
        "count": 1,
    } in sites


def test_collective_census_multi_operand_sort_only():
    src = (
        "from jax import lax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def join_kernel(a, b, idx):\n"
        "    srt = lax.sort((a, b, idx), num_keys=2)\n"
        "    one = lax.sort(a)\n"
        "    return srt, one\n"
    )
    inv = engine.lint_sources([MESH_DECL, ("pkg/mod.py", src)]).inventory
    sorts = [
        s for s in inv["collective_sites"] if s["collective"] == "sort"
    ]
    assert len(sorts) == 1  # the single-operand local sort is free


def test_collective_census_excludes_test_files():
    src = (
        "from jax import lax\n"
        "def f(x):\n"
        "    return lax.psum(x, 'txn')\n"
    )
    inv = engine.lint_sources(
        [MESH_DECL, ("tests/test_x.py", src)]
    ).inventory
    assert inv["collective_sites"] == []


def test_collective_census_drift_trips_check_inventory(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (tmp_path / "tools" / "lint").mkdir(parents=True)
    (pkg / "meshdef.py").write_text(MESH_DECL[1])
    (pkg / "mod.py").write_text(
        "from jax import lax\n"
        "def f(x, flag):\n"
        "    if flag:\n"
        "        return lax.psum(x, 'txn')\n"
        "    return x\n"
    )
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--write-inventory"]) == 0
    capsys.readouterr()
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--check-inventory"]) == 0
    # Re-guarding the collective is census churn: the drift gate trips
    # until the inventory is regenerated.
    (pkg / "mod.py").write_text(
        "from jax import lax\n"
        "def f(x, flag, extra):\n"
        "    if flag and extra:\n"
        "        return lax.psum(x, 'txn')\n"
        "    return x\n"
    )
    capsys.readouterr()
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--check-inventory"]) == 1
    assert "drift" in capsys.readouterr().err


def test_rank_taint_lattice_sources_and_sanitizers():
    """Taint-lattice unit table: divergence sources taint, consensus
    primitives sanitize, the fixpoint propagates across call hops."""
    from tools.lint import flow
    from tools.lint.graph import PackageGraph

    files = [
        FileContext(
            "pkg/a.py",
            "import os\n"
            "def knob():\n"
            "    return os.environ.get('FA_X', '')\n",
        ),
        FileContext(
            "pkg/b.py",
            "from pkg.a import knob\n"
            "def fwd():\n"
            "    return knob() == '1'\n",
        ),
        FileContext(
            "pkg/c.py",
            "from fastapriori_tpu.reliability import quorum\n"
            "from pkg.a import knob\n"
            "def clamped():\n"
            "    want = knob() == '1'\n"
            "    return want and quorum.stage_allowed('engine', 'fused')\n",
        ),
        FileContext(
            "pkg/d.py",
            "import time\n"
            "def now():\n"
            "    return time.monotonic()\n",
        ),
        FileContext(
            "pkg/e.py",
            "def pure(x):\n"
            "    return x + 1\n",
        ),
    ]
    graph = PackageGraph(files)
    summaries, clamped = flow.rank_summaries(files, graph, None)
    assert summaries["pkg.a.knob"] == flow.RANK_DIVERGENT
    assert summaries["pkg.b.fwd"] == flow.RANK_DIVERGENT  # one hop
    assert summaries["pkg.c.clamped"] == flow.RANK_UNIFORM
    assert "pkg.c.clamped" in clamped
    assert summaries["pkg.d.now"] == flow.RANK_DIVERGENT
    assert summaries["pkg.e.pure"] == flow.RANK_UNIFORM
    assert "pkg.e.pure" not in clamped


def test_rank_taint_caught_exception_is_divergent():
    from tools.lint import flow

    ctx = FileContext(
        "pkg/mod.py",
        "def f(x):\n"
        "    try:\n"
        "        y = g(x)\n"
        "    except ValueError as exc:\n"
        "        y = exc\n"
        "    return y\n",
    )
    rf = flow.RankFlow(ctx)
    env = {}
    fn = ctx.tree.body[0]
    rf.run(fn.body, env)
    assert env["exc"] == flow.RANK_DIVERGENT
    assert env["y"] == flow.RANK_DIVERGENT


def test_g015_divergence_through_helper_chain_still_flags():
    """A divergent value laundered through a helper in another file
    must still flag at the branch (the rank fixpoint mirrors G011's)."""
    helper = (
        "pkg/h.py",
        "import os\n"
        "def knob():\n"
        "    return os.environ.get('FA_X', '') == '1'\n",
    )
    user = (
        "pkg/mod.py",
        "from jax import lax\n"
        "from pkg.h import knob\n"
        "def count(x):\n"
        "    if knob():\n"
        "        return lax.psum(x, 'txn')\n"
        "    return x\n",
    )
    result = engine.lint_sources([MESH_DECL, helper, user])
    hits = [f for f in result.findings if f.rule == "G015"]
    assert hits and hits[0].path == "pkg/mod.py"


def test_g015_reaches_collective_through_bearing_callee():
    """The branch suite need not spell the collective itself: a call
    into a collective-bearing function counts."""
    ops = (
        "pkg/ops.py",
        "from jax import lax\n"
        "def reduce_counts(x):\n"
        "    return lax.psum(x, 'txn')\n",
    )
    user = (
        "pkg/mod.py",
        "import os\n"
        "from pkg.ops import reduce_counts\n"
        "def count(x):\n"
        "    if os.environ.get('FA_X', '') == '1':\n"
        "        return reduce_counts(x)\n"
        "    return x\n",
    )
    result = engine.lint_sources([MESH_DECL, ops, user])
    hits = [f for f in result.findings if f.rule == "G015"]
    assert hits and hits[0].path == "pkg/mod.py"


def test_g015_sync_clamped_callee_is_a_barrier():
    """A callee that runs the rendezvous exchange itself re-uniforms
    the mesh before its collectives: branches above it are exempt."""
    mine = (
        "pkg/mine.py",
        "from jax import lax\n"
        "from fastapriori_tpu.reliability import quorum\n"
        "def fit(x):\n"
        "    quorum.sync('mine.start')\n"
        "    return lax.psum(x, 'txn')\n",
    )
    user = (
        "pkg/mod.py",
        "import os\n"
        "from pkg.mine import fit\n"
        "def main(x):\n"
        "    if os.environ.get('FA_X', '') == '1':\n"
        "        return fit(x)\n"
        "    return x\n",
    )
    result = engine.lint_sources([MESH_DECL, mine, user])
    assert not [f for f in result.findings if f.rule == "G015"]


def test_g015_unrelated_sync_call_is_not_a_sanitizer():
    """`mm.sync()` (mmap flush) must not clamp the function: only a
    quorum-resolved sync is a rendezvous (review regression)."""
    src = (
        "import os\n"
        "from jax import lax\n"
        "def count(x, mm):\n"
        "    mm.sync()\n"
        "    if os.environ.get('FA_FAST', '') == '1':\n"
        "        return lax.psum(x, 'txn')\n"
        "    return x\n"
    )
    result = engine.lint_sources([MESH_DECL, ("pkg/mod.py", src)])
    assert [f for f in result.findings if f.rule == "G015"]
    # ...while the quorum spelling still sanitizes/clamps.
    ok = src.replace("mm.sync()", "quorum.sync('level')").replace(
        "import os\n",
        "import os\nfrom fastapriori_tpu.reliability import quorum\n",
    )
    clean = engine.lint_sources([MESH_DECL, ("pkg/mod.py", ok)])
    assert not [f for f in clean.findings if f.rule == "G015"]


def test_g013_kwonly_label_default_resolves():
    """A keyword-only label parameter's default is a compile-time
    constant too (review regression: kw_defaults were skipped)."""
    src = (
        "import numpy as np\n"
        "from fastapriori_tpu.reliability import retry\n"
        "COVERAGE = ('fetch.counts',)\n"
        "def gather(arr, *, site='counts'):\n"
        "    return retry.fetch_async(np.asarray(arr), site)\n"
    )
    result = engine.lint_sources([("pkg/mod.py", src)])
    labels = {e["label"] for e in result.inventory["fetch_sites"]}
    assert "counts" in labels
    assert not [
        f for f in result.findings
        if f.rule == "G013" and "not statically resolvable" in f.message
    ]


def test_analysis_cache_subset_run_keeps_other_entries(tmp_path):
    """A targeted single-file run must not evict the rest of the warm
    cache (review regression)."""
    from tools.lint import cache

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (tmp_path / "tools" / "lint").mkdir(parents=True)
    (pkg / "a.py").write_text("A = 'one'\n")
    (pkg / "b.py").write_text("B = 'two'\n")
    engine.lint_paths(["pkg"], root=str(tmp_path))
    assert set(cache.load(str(tmp_path))) == {"pkg/a.py", "pkg/b.py"}
    engine.lint_paths(["pkg/a.py"], root=str(tmp_path))
    assert set(cache.load(str(tmp_path))) == {"pkg/a.py", "pkg/b.py"}


def test_g013_label_resolution_closes_the_residue():
    """f-strings/``+``/``.format`` over compile-time constants census;
    a genuinely dynamic label flags as a blind spot."""
    src = (
        "import numpy as np\n"
        "from fastapriori_tpu.reliability import retry, failpoints\n"
        "PREFIX = 'pair'\n"
        "COVERAGE = ('fetch.pair_sparse', 'fetch.pair_x')\n"
        "def pull(x, k):\n"
        "    u = retry.fetch(lambda: np.asarray(x), f'{PREFIX}_sparse')\n"
        "    v = retry.fetch(lambda: np.asarray(x), PREFIX + '_x')\n"
        "    failpoints.fire('lvl.{}'.format(k))\n"
        "    return u, v\n"
    )
    result = engine.lint_sources([("pkg/mod.py", src)])
    inv = result.inventory
    labels = {e["label"] for e in inv["fetch_sites"]}
    assert {"pair_sparse", "pair_x"} <= labels
    blind = [
        f for f in result.findings
        if f.rule == "G013" and "not statically resolvable" in f.message
    ]
    assert len(blind) == 1 and blind[0].line == 8  # the dynamic fire


def test_g013_param_flow_censuses_per_inflowing_label():
    """A label parameter censuses once per compile-time value flowing
    into it package-wide (the gather_level_counts_start pattern)."""
    helper = (
        "pkg/mesh.py",
        "import numpy as np\n"
        "from fastapriori_tpu.reliability import retry\n"
        "COVERAGE = ('fetch.counts', 'fetch.counts_drain')\n"
        "def gather_start(arr, site='counts'):\n"
        "    return retry.fetch_async(np.asarray(arr), site)\n",
    )
    caller = (
        "pkg/mod.py",
        "from pkg.mesh import gather_start\n"
        "def drain(arr):\n"
        "    return gather_start(arr, site='counts_drain')\n",
    )
    result = engine.lint_sources([helper, caller])
    labels = {e["label"] for e in result.inventory["fetch_sites"]}
    assert {"counts", "counts_drain"} <= labels
    assert not [
        f for f in result.findings
        if f.rule == "G013" and "not statically resolvable" in f.message
    ]


def test_analysis_cache_roundtrip_is_bit_identical(tmp_path, capsys):
    """Warm (cached) and cold runs must produce identical findings and
    inventories; a touched file invalidates its fragment."""
    from tools.lint import cache

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (tmp_path / "tools" / "lint").mkdir(parents=True)
    (pkg / "mod.py").write_text(
        "# lint: waive G008 -- census me\n"
        "X = 'const'\n"
        "def f(acc=[]):\n"
        "    return acc\n"
    )
    r_cold = engine.lint_paths(["pkg"], root=str(tmp_path))
    assert (tmp_path / cache.CACHE_PATH).exists()
    r_warm = engine.lint_paths(["pkg"], root=str(tmp_path))
    as_dicts = lambda r: [f.to_dict() for f in r.findings]  # noqa: E731
    assert as_dicts(r_cold) == as_dicts(r_warm)
    assert r_cold.inventory == r_warm.inventory
    assert any(
        w["justification"] == "census me"
        for w in r_warm.inventory["waivers"]
    )
    # Edit the file (same size, different bytes => force mtime bump).
    mod = pkg / "mod.py"
    mod.write_text(mod.read_text().replace("G008", "G007"))
    os.utime(mod, (1, 1))
    os.utime(mod)  # fresh mtime
    r_edit = engine.lint_paths(["pkg"], root=str(tmp_path))
    assert any(
        "G007" in w["tokens"] for w in r_edit.inventory["waivers"]
    )
    # A corrupted cache file is a miss, never an error.
    (tmp_path / cache.CACHE_PATH).write_text("{not json")
    r_bad = engine.lint_paths(["pkg"], root=str(tmp_path))
    assert as_dicts(r_bad) == as_dicts(r_edit)


def test_analysis_cache_drops_on_lint_source_change(tmp_path):
    from tools.lint import cache

    files = {"pkg/mod.py": {"mtime_ns": 1, "size": 2}}
    (tmp_path / "tools" / "lint").mkdir(parents=True, exist_ok=True)
    cache.save(str(tmp_path), files)
    # No tools/lint/*.py under this root: fingerprint is stable, loads.
    assert cache.load(str(tmp_path)) == files
    # A linter source appearing (or changing) drops the cache.
    (tmp_path / "tools" / "lint" / "x.py").write_text("X = 1\n")
    assert cache.load(str(tmp_path)) == {}


def test_stacked_waiver_segments_parse_independently():
    from tools.lint.engine import _parse_waiver_segments

    segs = _parse_waiver_segments(
        "# lint: fetch-site -- why one; lint: waive G013 -- why two"
    )
    assert [t for t, _ in segs] == [{"fetch-site"}, {"G013"}]
    assert [j for _, j in segs] == ["why one", "why two"]


# ---------------------------------------------------------------------------
# v4: path-sensitive taint environments (ISSUE 16 tentpole a)


def test_join_worst_takes_the_worst_state_per_variable():
    from tools.lint import flow

    env = {"keep": flow.RANK_DIVERGENT}
    flow.join_worst(env, [
        {"a": flow.RANK_DIVERGENT, "b": flow.RANK_UNIFORM},
        {"a": flow.RANK_UNIFORM, "c": flow.RANK_DIVERGENT},
    ])
    assert env["a"] == flow.RANK_DIVERGENT
    assert env["b"] == flow.RANK_UNIFORM
    assert env["c"] == flow.RANK_DIVERGENT  # introduced in one branch
    assert env["keep"] == flow.RANK_DIVERGENT  # untouched by the join


def test_rank_flow_branch_environments_are_isolated():
    """A sanitizing assignment inside one arm must not clear the taint
    on the fall-through path (v3's suite-shared env let the body
    assignment overwrite it — a false negative, fixed by the per-suite
    copies + worst-state join)."""
    from tools.lint import flow

    ctx = FileContext(
        "pkg/mod.py",
        "import os\n"
        "from fastapriori_tpu.reliability import quorum\n"
        "def f(x, mode):\n"
        "    flaky = os.environ.get('FA_X', '') == '1'\n"
        "    if mode:\n"
        "        flaky = quorum.stage_allowed('engine', 'fused')\n"
        "    return flaky\n",
    )
    rf = flow.RankFlow(ctx)
    env = {}
    rf.run(ctx.tree.body[2].body, env)
    assert env["flaky"] == flow.RANK_DIVERGENT


def test_rank_flow_both_arms_sanitized_joins_uniform():
    from tools.lint import flow

    ctx = FileContext(
        "pkg/mod.py",
        "import os\n"
        "from fastapriori_tpu.reliability import quorum\n"
        "def f(x, mode):\n"
        "    flaky = os.environ.get('FA_X', '') == '1'\n"
        "    if mode:\n"
        "        flaky = quorum.stage_allowed('engine', 'fused')\n"
        "    else:\n"
        "        flaky = quorum.stage_allowed('engine', 'level')\n"
        "    return flaky\n",
    )
    rf = flow.RankFlow(ctx)
    env = {}
    rf.run(ctx.tree.body[2].body, env)
    assert env["flaky"] == flow.RANK_UNIFORM


def test_epoch_guard_sanitizer_clears_rank_taint():
    """The v4 lattice addition: quorum epoch reads (checkpoint_fence /
    current_fence / validate_resume_fence) answer from the domain's
    authoritative FENCE, so they evaluate uniform and consensus-clamp
    the function that consults them — exactly like stage_allowed."""
    import ast as ast_mod

    from tools.lint import flow

    call = ast_mod.parse("quorum.current_fence()").body[0].value
    assert flow._rank_call_kind(call) == "sanitizer"
    ctx = FileContext(
        "pkg/mod.py",
        "from fastapriori_tpu.reliability import quorum\n"
        "def f():\n"
        "    fence = quorum.checkpoint_fence()\n"
        "    return fence\n",
    )
    rf = flow.RankFlow(ctx)
    env = {}
    fn = ctx.tree.body[1]
    rf.run(fn.body, env)
    assert env["fence"] == flow.RANK_UNIFORM
    assert rf.contains_sanitizer(fn)  # rank_summaries clamps f


def test_g016_chain_walk_in_non_bearing_helper_is_clean():
    """v4 function-granular attribution (the watchdog rule_scan shape,
    waived under v3's module-granularity fallback): a chain walked only
    by a non-collective helper in a module that ALSO has collective-
    bearing functions must not flag — the serving-tier walk never
    shapes the mesh's collective sequence."""
    src = (
        "from jax import lax\n"
        "CHAINS = {'local': ('device', 'host'),\n"
        "          'global': ('hier', 'flat')}\n"
        "CONSENSUS_CHAINS = ('global',)\n"
        "def downgrade(chain, frm, to):\n"
        "    pass\n"
        "def exchange(x):\n"
        "    downgrade('global', 'hier', 'flat')\n"
        "    return lax.psum(x, 'txn')\n"
        "def scan(rows):\n"
        "    downgrade('local', 'device', 'host')\n"
        "    return rows\n"
    )
    result = engine.lint_sources([MESH_DECL, ("pkg/mod.py", src)])
    assert not [f for f in result.findings if f.rule == "G016"], (
        "non-bearing helper walk must not be attributed to the "
        "collective path"
    )


# ---------------------------------------------------------------------------
# v4: protocol censuses (ISSUE 16 tentpole b)


def test_raise_and_ledger_censuses_are_deterministic():
    src = (
        "from fastapriori_tpu.reliability import ledger\n"
        "KIND = 'mesh.degraded'\n"
        "def f(path):\n"
        "    ledger.record(KIND, path=path)\n"
        "    raise ValueError('boom')\n"
        "def g():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        raise\n"
    )
    r1 = engine.lint_sources([("pkg/obs/mod.py", src)])
    r2 = engine.lint_sources([("pkg/obs/mod.py", src)])
    assert r1.inventory == r2.inventory
    inv = r1.inventory
    assert {"exception": "ValueError", "path": "pkg/obs/mod.py",
            "count": 1} in inv["raise_sites"]
    assert {"exception": "<reraise>", "path": "pkg/obs/mod.py",
            "count": 1} in inv["raise_sites"]
    assert {"kind": "mesh.degraded", "path": "pkg/obs/mod.py",
            "count": 1} in inv["ledger_events"]


def test_chain_walk_census_is_function_granular():
    src = (
        "CHAINS = {'eng': ('fast', 'exact')}\n"
        "def downgrade(chain, frm, to):\n"
        "    pass\n"
        "def helper():\n"
        "    downgrade('eng', 'fast', 'exact')\n"
        "downgrade('eng', 'fast', 'exact')\n"
    )
    inv = engine.lint_sources([("pkg/mod.py", src)]).inventory
    walkers = {(w["chain"], w["walker"]) for w in inv["chain_walks"]}
    assert ("eng", "pkg.mod.helper") in walkers
    assert ("eng", "<module>") in walkers


def test_protocol_censuses_exclude_test_files():
    src = (
        "from fastapriori_tpu.reliability import ledger\n"
        "def f():\n"
        "    ledger.record('x.y')\n"
        "    raise ValueError('x')\n"
    )
    inv = engine.lint_sources([("tests/test_x.py", src)]).inventory
    assert inv["raise_sites"] == []
    assert inv["ledger_events"] == []


def test_raise_census_drift_trips_check_inventory(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (tmp_path / "tools" / "lint").mkdir(parents=True)
    (pkg / "mod.py").write_text(
        "def f(path):\n"
        "    raise ValueError('bad: ' + path)\n"
    )
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--write-inventory"]) == 0
    capsys.readouterr()
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--check-inventory"]) == 0
    # Reclassifying the raise is census churn: the drift gate trips
    # until the inventory is regenerated.
    (pkg / "mod.py").write_text(
        "def f(path):\n"
        "    raise RuntimeError('bad: ' + path)\n"
    )
    capsys.readouterr()
    assert cli.main(["pkg", "--root", str(tmp_path),
                     "--check-inventory"]) == 1
    assert "drift" in capsys.readouterr().err


def test_analysis_cache_carries_protocol_facts(tmp_path):
    """The v4 fragment fields: per-file raise/ledger facts round-trip
    through the cache with bit-identical censuses."""
    from tools.lint import cache

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (tmp_path / "tools" / "lint").mkdir(parents=True)
    (pkg / "mod.py").write_text(
        "from fastapriori_tpu.reliability import ledger\n"
        "def f(path):\n"
        "    ledger.record('io.fallback', path=path)\n"
        "    raise ValueError('bad: ' + path)\n"
    )
    r_cold = engine.lint_paths(["pkg"], root=str(tmp_path))
    frag = cache.load(str(tmp_path))["pkg/mod.py"]
    assert frag["raises"] == [["ValueError", 4]]
    assert frag["ledger"] == [["io.fallback", 3]]
    r_warm = engine.lint_paths(["pkg"], root=str(tmp_path))
    assert (
        r_cold.inventory["raise_sites"] == r_warm.inventory["raise_sites"]
    )
    assert (
        r_cold.inventory["ledger_events"]
        == r_warm.inventory["ledger_events"]
    )
    assert {"kind": "io.fallback", "path": "pkg/mod.py", "count": 1} in (
        r_warm.inventory["ledger_events"]
    )


# -- v5: k-bounded call-graph walks (protocol layer) -------------------


def test_g018_classified_helper_resolves_through_two_indirections():
    """`raise mk1(...)` where mk1 delegates once before constructing a
    classified type: the k-bounded walk (K_HOPS = 3) must see through
    the delegation instead of flagging the raise."""
    errs = ("pkg/io/errors.py", "class MeshFault(Exception):\n    pass\n")
    helpers = (
        "pkg/h.py",
        "from pkg.io.errors import MeshFault\n"
        "def mk2(n):\n"
        "    return MeshFault('n=%d' % n)\n"
        "def mk1(n):\n"
        "    return mk2(n)\n",
    )
    user = (
        "pkg/parallel/m.py",
        "from pkg.h import mk1\n"
        "def run(n):\n"
        "    raise mk1(n)\n",
    )
    result = engine.lint_sources([errs, helpers, user])
    assert not [f for f in result.findings if f.rule == "G018"]


def test_g018_classified_helper_resolves_through_three_indirections():
    """Three helper layers (mk1 -> mk2 -> mk3 constructs) sit exactly
    at the K_HOPS bound and must still resolve."""
    errs = ("pkg/io/errors.py", "class MeshFault(Exception):\n    pass\n")
    helpers = (
        "pkg/h.py",
        "from pkg.io.errors import MeshFault\n"
        "def mk3(n):\n"
        "    return MeshFault('n=%d' % n)\n"
        "def mk2(n):\n"
        "    return mk3(n)\n"
        "def mk1(n):\n"
        "    return mk2(n)\n",
    )
    user = (
        "pkg/parallel/m.py",
        "from pkg.h import mk1\n"
        "def run(n):\n"
        "    raise mk1(n)\n",
    )
    result = engine.lint_sources([errs, helpers, user])
    assert not [f for f in result.findings if f.rule == "G018"]


def test_g018_four_indirection_delegation_still_flags():
    """One layer past the bound (mk1 -> mk2 -> mk3 -> mk4 constructs)
    is deliberately NOT credited: the walk is k-bounded, not a full
    interprocedural analysis, and the bound is pinned here."""
    errs = ("pkg/io/errors.py", "class MeshFault(Exception):\n    pass\n")
    helpers = (
        "pkg/h.py",
        "from pkg.io.errors import MeshFault\n"
        "def mk4(n):\n"
        "    return MeshFault('n=%d' % n)\n"
        "def mk3(n):\n"
        "    return mk4(n)\n"
        "def mk2(n):\n"
        "    return mk3(n)\n"
        "def mk1(n):\n"
        "    return mk2(n)\n",
    )
    user = (
        "pkg/parallel/m.py",
        "from pkg.h import mk1\n"
        "def run(n):\n"
        "    raise mk1(n)\n",
    )
    result = engine.lint_sources([errs, helpers, user])
    assert [f for f in result.findings if f.rule == "G018"]


def test_g020_fence_validation_through_wrapper_chain_resolves():
    """A resume path whose fence check lives three calls down
    (resume -> check0 -> check1 -> check2 validates) resolves under
    the k-bounded reachability walk."""
    helpers = (
        "pkg/io/checks.py",
        "from fastapriori_tpu.reliability import quorum\n"
        "def check2(prefix):\n"
        "    quorum.validate_resume_fence(prefix)\n"
        "def check1(prefix):\n"
        "    check2(prefix)\n"
        "def check0(prefix):\n"
        "    check1(prefix)\n",
    )
    user = (
        "pkg/io/mod.py",
        "from fastapriori_tpu.io.resume import load_manifest\n"
        "from pkg.io.checks import check0\n"
        "def resume(prefix):\n"
        "    check0(prefix)\n"
        "    return load_manifest(prefix)\n",
    )
    result = engine.lint_sources([helpers, user])
    assert not [f for f in result.findings if f.rule == "G020"]


def test_g020_fence_four_hops_down_still_flags():
    """Four wrapper layers put the validator past K_HOPS: the resume
    site flags, pinning the bound for the fence walk too."""
    helpers = (
        "pkg/io/checks.py",
        "from fastapriori_tpu.reliability import quorum\n"
        "def check3(prefix):\n"
        "    quorum.validate_resume_fence(prefix)\n"
        "def check2(prefix):\n"
        "    check3(prefix)\n"
        "def check1(prefix):\n"
        "    check2(prefix)\n"
        "def check0(prefix):\n"
        "    check1(prefix)\n",
    )
    user = (
        "pkg/io/mod.py",
        "from fastapriori_tpu.io.resume import load_manifest\n"
        "from pkg.io.checks import check0\n"
        "def resume(prefix):\n"
        "    check0(prefix)\n"
        "    return load_manifest(prefix)\n",
    )
    result = engine.lint_sources([helpers, user])
    assert [f for f in result.findings if f.rule == "G020"]


# -- v5: G019 value-range tracking for dynamic targets -----------------

_G019_PRELUDE = (
    "CHAINS = {'eng': ('fast', 'mid', 'exact')}\n"
    "def downgrade(chain, frm, to):\n"
    "    pass\n"
)


def test_g019_computed_target_resolves_by_value_range():
    """A `to` computed from branch-dependent literals is verified per
    VALUE: the bad rung flags, the good rung counts as a real edge
    (so the chain still reaches its terminus)."""
    src = _G019_PRELUDE + (
        "def trip(deep):\n"
        "    to = 'exact' if deep else 'ghost'\n"
        "    downgrade('eng', 'fast', to)\n"
    )
    hits = [
        f
        for f in engine.lint_sources([("pkg/mod.py", src)]).findings
        if f.rule == "G019"
    ]
    assert len(hits) == 1
    assert "'ghost'" in hits[0].message


def test_g019_single_literal_local_resolves():
    """One local literal assignment is the smallest value range; a
    backward value must flag exactly as a literal walk would."""
    src = _G019_PRELUDE + (
        "def trip():\n"
        "    to = 'fast'\n"
        "    downgrade('eng', 'mid', to)\n"
    )
    hits = [
        f
        for f in engine.lint_sources([("pkg/mod.py", src)]).findings
        if f.rule == "G019"
    ]
    assert hits and "backward" in hits[0].message


def test_g019_multi_rung_jump_in_range_is_verified():
    """Resolved values that jump several rungs forward are REAL edges
    (the v4 fallback under-modeled them as next-stage-down): both
    values here are forward, so the site is clean and the terminus is
    reachable through the fast -> exact jump."""
    src = _G019_PRELUDE + (
        "def trip(deep):\n"
        "    to = 'exact' if deep else 'mid'\n"
        "    downgrade('eng', 'fast', to)\n"
    )
    result = engine.lint_sources([("pkg/mod.py", src)])
    assert not [f for f in result.findings if f.rule == "G019"]


def test_g019_unresolvable_target_keeps_next_stage_fallback():
    """A `to` no assignment can resolve (a parameter) still falls back
    to the weakest edge — one step down — so exhaustiveness keeps its
    v4 behavior: 'eng' cannot reach 'exact' through fast -> mid."""
    src = _G019_PRELUDE + (
        "def trip(to):\n"
        "    downgrade('eng', 'fast', to)\n"
    )
    hits = [
        f
        for f in engine.lint_sources([("pkg/mod.py", src)]).findings
        if f.rule == "G019"
    ]
    assert len(hits) == 1
    assert "cannot reach its exact-fallback terminus" in hits[0].message
    assert not any("resolves to" in f.message for f in hits)


# -- v5: concurrency facts in the analysis cache (schema 3) ------------


def test_analysis_cache_carries_concurrency_facts(tmp_path):
    """The v5 fragment field: per-file spawn/lock facts round-trip
    through the cache with bit-identical censuses."""
    from tools.lint import cache

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (tmp_path / "tools" / "lint").mkdir(parents=True)
    (pkg / "mod.py").write_text(
        "import threading\n"
        "class Srv:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def start(self):\n"
        "        threading.Thread(\n"
        "            target=self._loop, daemon=True\n"
        "        ).start()\n"
        "    def _loop(self):\n"
        "        with self._lock:\n"
        "            pass\n"
    )
    r_cold = engine.lint_paths(["pkg"], root=str(tmp_path))
    frag = cache.load(str(tmp_path))["pkg/mod.py"]
    assert [t for t, _ln in frag["concurrency"]["spawns"]] == ["_loop"]
    assert [n for n, _ln in frag["concurrency"]["locks"]] == ["_lock"]
    r_warm = engine.lint_paths(["pkg"], root=str(tmp_path))
    for census in ("thread_spawns", "lock_sites", "blocking_sites"):
        assert r_cold.inventory[census] == r_warm.inventory[census]
    assert {"path": "pkg/mod.py", "target": "_loop", "count": 1} in (
        r_warm.inventory["thread_spawns"]
    )


def test_analysis_cache_old_schema_is_a_miss(tmp_path):
    """A schema-2 (v4) cache file must load as EMPTY, not as stale
    fragments missing the concurrency facts."""
    from tools.lint import cache

    (tmp_path / "tools" / "lint").mkdir(parents=True)
    files = {"pkg/a.py": {"mtime_ns": 1, "size": 2}}
    cache.save(str(tmp_path), files)
    assert cache.load(str(tmp_path)) == files
    path = tmp_path / cache.CACHE_PATH
    doc = json.loads(path.read_text())
    doc["schema"] = 2
    path.write_text(json.dumps(doc))
    assert cache.load(str(tmp_path)) == {}


# -- v5: the router race this release fixed, pinned statically ---------


def test_g022_pins_the_router_swap_registry_race():
    """The exact pre-v5 ProcHost shape: the flusher registering the
    barrier event in the swap registry OUTSIDE the lock that the main
    thread holds while iterating it — and the shipped fix (the
    registration rides the seq-allocation critical section)."""
    src = (
        "import threading\n"
        "class Host:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Condition()\n"
        "        self._swap_events = {}\n"
        "        self._next_seq = 0\n"
        "    def start(self):\n"
        "        threading.Thread(\n"
        "            target=self._flush_loop, daemon=True\n"
        "        ).start()\n"
        "    def _flush_loop(self):\n"
        "        with self._lock:\n"
        "            seq = self._next_seq\n"
        "            self._next_seq += 1\n"
        "        self._swap_events[seq] = object()\n"
        "    def fail_outstanding(self):\n"
        "        with self._lock:\n"
        "            return list(self._swap_events.values())\n"
    )
    result = engine.lint_sources([("pkg/serve/router.py", src)])
    hits = [f for f in result.findings if f.rule == "G022"]
    assert hits and "_swap_events" in hits[0].message
    fixed = src.replace(
        "            self._next_seq += 1\n"
        "        self._swap_events[seq] = object()\n",
        "            self._next_seq += 1\n"
        "            self._swap_events[seq] = object()\n",
    )
    clean = engine.lint_sources([("pkg/serve/router.py", fixed)])
    assert not [f for f in clean.findings if f.rule == "G022"]
