"""graftlint rule tests: for every rule a minimal must-flag snippet, a
must-pass sibling, and a waived variant — plus CLI smoke tests proving
the shipped tree is clean under the shipped baseline and that injecting
any must-flag fixture trips the gate.

Deliberately jax-free: the linter is pure stdlib and these tests must
run on boxes with no accelerator runtime.
"""

import json
import os
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:  # tier-1 runs `python -m pytest tests/`
    sys.path.insert(0, REPO_ROOT)

from tools.lint import cli, engine  # noqa: E402
from tools.lint.engine import FileContext, PackageContext  # noqa: E402
from tools.lint.rules import ALL_RULES, RULES_BY_ID  # noqa: E402

BASELINE = os.path.join("tools", "lint", "baseline.json")

# A Mesh declaration so G002 has a declared-axis universe to check
# against; rides along as an auxiliary file in every case.
MESH_DECL = ("pkg/meshdef.py", 'from jax.sharding import Mesh\n'
             'AXIS = "txn"\n'
             'mesh = Mesh(devices, (AXIS, "cand"))\n')

# (rule, case-name, path, source) triples.  ``flag`` must yield >= 1
# finding of the rule; ``pass`` and ``waived`` must yield none.
CASES = [
    # -- G001: host sync in traced code / unaudited mesh-layer fetch ----
    ("G001", "flag", "pkg/mod.py",
     "import jax\n"
     "@jax.jit\n"
     "def f(x):\n"
     "    return x.item()\n"),
    ("G001", "flag", "pkg/mod.py",
     "import numpy as np\n"
     "from jax.experimental.shard_map import shard_map\n"
     "@shard_map\n"
     "def f(x):\n"
     "    return np.asarray(x)\n"),
    ("G001", "flag", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    return np.asarray(arr)\n"),
    ("G001", "flag", "pkg/models/apriori.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    return np.asarray(arr)\n"),  # engine layer is audited too
    ("G001", "pass", "pkg/mod.py",
     "def g(x):\n"
     "    return x.item()\n"),  # not traced, not the mesh layer
    ("G001", "pass", "pkg/models/recommender.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    return np.asarray(arr)\n"),  # engine audit covers apriori only
    ("G001", "pass", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def host_table():\n"
     "    return np.array([1, 2, 3])\n"),  # literal arg: host data
    ("G001", "pass", "pkg/parallel/m.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability import retry\n"
     "def pull(arr):\n"
     "    return retry.fetch(lambda: np.asarray(arr), 'pair')\n"),
    # the audited helper IS the audit: no inline waiver needed
    ("G001", "pass", "pkg/models/apriori.py",
     "import numpy as np\n"
     "from fastapriori_tpu.reliability.retry import fetch_async\n"
     "def pull(arr):\n"
     "    return fetch_async(np.asarray(arr), 'level_bits')\n"),
    ("G001", "flag", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def pull(arr, fetch):\n"
     "    return fetch(np.asarray(arr))\n"),  # no site label: not audited
    ("G001", "waived", "pkg/mod.py",
     "import jax\n"
     "@jax.jit\n"
     "def f(x):\n"
     "    return x.item()  # lint: fetch-site -- test waiver\n"),
    ("G001", "waived", "pkg/parallel/m.py",
     "import numpy as np\n"
     "def fetch(arr):\n"
     "    # lint: fetch-site -- audited test fetch\n"
     "    return np.asarray(arr)\n"),
    # -- G002: collective axis names tie back to a Mesh declaration ----
    ("G002", "flag", "pkg/mod.py",
     "from jax import lax\n"
     "def f(x):\n"
     "    return lax.psum(x, 'tn')\n"),  # typo'd axis
    ("G002", "flag", "pkg/mod.py",
     "from jax import lax\n"
     "def f(x, a):\n"
     "    return lax.all_gather(x, a)\n"),  # unverifiable, not axis-named
    ("G002", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "def f(x):\n"
     "    return lax.psum(x, 'txn')\n"),
    ("G002", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "from pkg.meshdef import AXIS\n"
     "def f(x):\n"
     "    return lax.psum(x, AXIS)\n"),  # package-wide constant
    ("G002", "pass", "pkg/mod.py",
     "from jax import lax\n"
     "def f(x, axis_name=None):\n"
     "    return lax.psum(x, axis_name) if axis_name else x\n"),
    ("G002", "waived", "pkg/mod.py",
     "from jax import lax\n"
     "def f(x):\n"
     "    return lax.psum(x, 'tn')  # lint: waive G002 -- test waiver\n"),
    # -- G003: recompile hazards ---------------------------------------
    ("G003", "flag", "pkg/mod.py",
     "import jax\n"
     "g = jax.jit(lambda x: x, static_argnums=[0])\n"),
    ("G003", "flag", "pkg/mod.py",
     "import jax\n"
     "def run(fs, xs):\n"
     "    for f in fs:\n"
     "        xs = jax.jit(f)(xs)\n"
     "    return xs\n"),
    ("G003", "pass", "pkg/mod.py",
     "import jax\n"
     "g = jax.jit(lambda x: x, static_argnums=(0,))\n"),
    ("G003", "pass", "pkg/mod.py",
     "import jax\n"
     "def run(fs, xs):\n"
     "    jitted = [jax.jit(f) for f in fs]\n"
     "    return jitted\n"),  # comprehension, not a loop-body rebuild
    ("G003", "waived", "pkg/mod.py",
     "import jax\n"
     "def run(fs, xs):\n"
     "    for f in fs:\n"
     "        # lint: waive G003 -- test waiver\n"
     "        xs = jax.jit(f)(xs)\n"
     "    return xs\n"),
    # -- G004: dtype discipline ----------------------------------------
    ("G004", "flag", "pkg/mod.py",
     "import jax.numpy as jnp\n"
     "def f():\n"
     "    return jnp.zeros(3, jnp.int64)\n"),
    ("G004", "flag", "pkg/mod.py",
     "import jax.numpy as jnp\n"
     "def f():\n"
     "    return jnp.arange(3, dtype='float64')\n"),
    ("G004", "flag", "pkg/mod.py",
     "from jax import lax\n"
     "import jax.numpy as jnp\n"
     "def count(a, b):\n"
     "    '''Exact weighted count.'''\n"
     "    return lax.dot_general(a, b, (((1,), (0,)), ((), ())),\n"
     "                           preferred_element_type=jnp.float32)\n"),
    ("G004", "pass", "pkg/utils/order.py",
     "import jax.numpy as jnp\n"
     "def pack():\n"
     "    return jnp.zeros(3, jnp.int64)\n"),  # key-packing module
    ("G004", "pass", "pkg/mod.py",
     "import numpy as np\n"
     "def f():\n"
     "    return np.zeros(3, np.int64)\n"),  # host-side numpy is fine
    ("G004", "waived", "pkg/mod.py",
     "from jax import lax\n"
     "import jax.numpy as jnp\n"
     "def count(a, b):\n"
     "    '''Exact weighted count.'''\n"
     "    # lint: f32-gate -- counts < 2^24 in this test\n"
     "    return lax.dot_general(a, b, (((1,), (0,)), ((), ())),\n"
     "                           preferred_element_type=jnp.float32)\n"),
    # -- G005: Pallas constraints --------------------------------------
    ("G005", "flag", "pkg/mod.py",
     "from jax.experimental import pallas as pl\n"
     "spec = pl.BlockSpec((16, 100), lambda i: (i, 0))\n"),
    ("G005", "flag", "pkg/mod.py",
     "from jax.experimental import pallas as pl\n"
     "spec = pl.BlockSpec((13, 128), lambda i: (i, 0))\n"),
    ("G005", "flag", "pkg/mod.py",
     "from jax.experimental import pallas as pl\n"
     "def add_kernel(a_ref, o_ref):\n"
     "    if a_ref[0] > 0:\n"
     "        o_ref[0] = a_ref[0]\n"),
    ("G005", "pass", "pkg/mod.py",
     "from jax.experimental import pallas as pl\n"
     "T = 4096\n"
     "spec = pl.BlockSpec((T, 128), lambda i: (i, 0))\n"),
    ("G005", "pass", "pkg/mod.py",
     "spec = BlockSpec((16, 100), None)\n"),  # no pallas import: not ours
    ("G005", "waived", "pkg/mod.py",
     "from jax.experimental import pallas as pl\n"
     "# lint: tile-ok -- test waiver\n"
     "spec = pl.BlockSpec((16, 100), lambda i: (i, 0))\n"),
    # -- G006: silent broad except -------------------------------------
    ("G006", "flag", "pkg/mod.py",
     "def f():\n"
     "    try:\n"
     "        work()\n"
     "    except Exception:\n"
     "        pass\n"),
    ("G006", "flag", "pkg/mod.py",
     "def f():\n"
     "    try:\n"
     "        work()\n"
     "    except:\n"
     "        return None\n"),
    ("G006", "pass", "pkg/mod.py",
     "def f():\n"
     "    try:\n"
     "        work()\n"
     "    except Exception as e:\n"
     "        raise InputError(str(e))\n"),
    ("G006", "pass", "pkg/mod.py",
     "def f():\n"
     "    try:\n"
     "        work()\n"
     "    except ValueError:\n"
     "        pass\n"),  # narrow catch is allowed
    ("G006", "waived", "pkg/mod.py",
     "def f():\n"
     "    try:\n"
     "        work()\n"
     "    # lint: waive G006 -- best-effort in this test\n"
     "    except Exception:\n"
     "        pass\n"),
    # -- G007: mutable defaults / import-time device work --------------
    ("G007", "flag", "pkg/mod.py",
     "def f(acc=[]):\n"
     "    return acc\n"),
    ("G007", "flag", "pkg/mod.py",
     "import jax.numpy as jnp\n"
     "ZERO = jnp.zeros(8)\n"),
    ("G007", "pass", "pkg/mod.py",
     "import jax.numpy as jnp\n"
     "def f(acc=None):\n"
     "    return acc or jnp.zeros(8)\n"),
    ("G007", "waived", "pkg/mod.py",
     "import jax.numpy as jnp\n"
     "# lint: import-time-ok -- test waiver\n"
     "ZERO = jnp.zeros(8)\n"),
    # -- G008: TODO/FIXME need an issue reference ----------------------
    ("G008", "flag", "pkg/mod.py",
     "# TODO make this faster\n"
     "X = 1\n"),
    ("G008", "pass", "pkg/mod.py",
     "# TODO(#123) make this faster\n"
     "# FIXME tracked in ROADMAP.md open items\n"
     "X = 1\n"),
    ("G008", "waived", "pkg/mod.py",
     "# TODO make this faster  lint: waive G008\n"
     "X = 1\n"),
    # -- G009: artifact writes must use the atomic writer --------------
    ("G009", "flag", "pkg/io/w.py",
     "def save(path, lines):\n"
     "    with open(path, 'w') as f:\n"
     "        f.writelines(lines)\n"),
    ("G009", "flag", "pkg/io/w.py",
     "def save(path, lines):\n"
     "    with open(path, mode='wb') as f:\n"
     "        f.write(lines)\n"),
    ("G009", "flag", "pkg/io/w.py",
     "from fastapriori_tpu.io.writer import open_write\n"
     "def save(path, lines):\n"
     "    with open_write(path) as f:\n"
     "        f.writelines(lines)\n"),
    ("G009", "pass", "pkg/io/w.py",
     "def load(path):\n"
     "    with open(path, 'rb') as f:\n"
     "        return f.read()\n"),
    ("G009", "pass", "pkg/io/w.py",
     "def load(path):\n"
     "    with open(path) as f:\n"
     "        return f.read()\n"),
    ("G009", "pass", "tests/test_w.py",
     "def fixture(path):\n"
     "    with open(path, 'w') as f:\n"
     "        f.write('1 2 3')\n"),  # test fixtures are exempt
    ("G009", "waived", "pkg/io/w.py",
     "def save(path, lines):\n"
     "    # lint: waive G009 -- test waiver\n"
     "    with open(path, 'w') as f:\n"
     "        f.writelines(lines)\n"),
]


def _ids():
    counts = {}
    out = []
    for rule, kind, _, _ in CASES:
        n = counts.get((rule, kind), 0)
        counts[(rule, kind)] = n + 1
        out.append(f"{rule}-{kind}{n}")
    return out


@pytest.mark.parametrize("rule,kind,path,src", CASES, ids=_ids())
def test_rule_case(rule, kind, path, src):
    result = engine.lint_sources([MESH_DECL, (path, src)])
    hits = [f for f in result.findings if f.rule == rule]
    assert not result.parse_errors, result.parse_errors
    if kind == "flag":
        assert hits, f"{rule} should have flagged:\n{src}"
    else:
        assert not hits, f"{rule} unexpectedly flagged {kind} case: {hits}"


def test_every_rule_has_all_three_case_kinds():
    covered = {(r, k) for r, k, _, _ in CASES}
    for rule in RULES_BY_ID:
        for kind in ("flag", "pass", "waived"):
            assert (rule, kind) in covered, f"missing {kind} case for {rule}"


def test_all_rules_registered_and_distinct():
    ids = [r.id for r in ALL_RULES]
    assert len(ids) == len(set(ids)) == 9
    assert all(hasattr(r, "name") and r.name for r in ALL_RULES)


def test_repo_mesh_axes_are_discovered():
    """Guards G002 against silently never checking: the real mesh module
    must contribute its axis declarations to the package context."""
    path = os.path.join(REPO_ROOT, "fastapriori_tpu", "parallel", "mesh.py")
    with open(path, "r", encoding="utf-8") as fh:
        ctx = FileContext("fastapriori_tpu/parallel/mesh.py", fh.read())
    pkg = PackageContext([ctx])
    assert {"txn", "cand"} <= pkg.declared_axes


def test_baseline_roundtrip(tmp_path):
    findings = engine.lint_sources(
        [("pkg/mod.py", "def f(acc=[]):\n    return acc\n")]
    ).findings
    assert findings
    data = engine.make_baseline(findings)
    assert engine.subtract_baseline(findings, data) == []
    # One MORE identical finding than the baseline froze still trips.
    assert engine.subtract_baseline(findings + findings[:1], data)


def test_cli_repo_is_clean_under_shipped_baseline():
    rc = cli.main(
        [
            "fastapriori_tpu",
            "tests",
            "--baseline",
            os.path.join(REPO_ROOT, BASELINE),
            "--root",
            REPO_ROOT,
        ]
    )
    assert rc == 0


@pytest.mark.parametrize(
    "rule,src",
    [(r, s) for r, k, _, s in CASES if k == "flag"],
    ids=[f"{r}-{i}" for i, (r, k, _, s) in enumerate(CASES) if k == "flag"],
)
def test_cli_fails_when_must_flag_fixture_is_injected(tmp_path, rule, src):
    # The injected tree inherits the shipped baseline — a baselined repo
    # must still fail on any NEW instance of a must-flag pattern.
    pkg = tmp_path / "pkg"
    parallel = pkg / "parallel"
    parallel.mkdir(parents=True)
    (pkg / "meshdef.py").write_text(MESH_DECL[1])
    # Preserve the fixture's path expectations (parallel/ vs pkg/).
    (tmp_path / "pkg" / "parallel" / "__init__.py").write_text("")
    target = tmp_path / "pkg" / "injected.py"
    for r, k, p, s in CASES:
        if s == src and "parallel" in p:
            target = parallel / "injected.py"
    target.write_text(src)
    rc = cli.main(
        [
            "pkg",
            "--baseline",
            os.path.join(REPO_ROOT, BASELINE),
            "--root",
            str(tmp_path),
        ]
    )
    assert rc == 1


def test_cli_write_baseline_freezes_findings(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("def f(acc=[]):\n    return acc\n")
    bl = tmp_path / "bl.json"
    assert (
        cli.main(
            ["pkg", "--root", str(tmp_path), "--baseline", str(bl),
             "--write-baseline"]
        )
        == 0
    )
    frozen = json.loads(bl.read_text())
    assert frozen["fingerprints"]
    assert (
        cli.main(["pkg", "--root", str(tmp_path), "--baseline", str(bl)])
        == 0
    )


def test_cli_select_and_json_format(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "# TODO untracked\n" "def f(acc=[]):\n    return acc\n"
    )
    rc = cli.main(
        ["pkg", "--root", str(tmp_path), "--select", "G008",
         "--format", "json"]
    )
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [f["rule"] for f in out["findings"]] == ["G008"]


def test_waiver_justification_words_never_become_tokens():
    """A unicode-dash (or missing) separator must not let justification
    words waive other rules: only well-formed tokens count."""
    from tools.lint.engine import _parse_waiver_tokens

    assert _parse_waiver_tokens("# lint: waive G006 — version probe") == {
        "G006"
    }
    # No separator at all: prose words are dropped unless they happen to
    # be well-formed tokens — a justification mentioning another rule's
    # ALIAS shape must use `--` to be safe, so spell that requirement:
    assert "G003" in _parse_waiver_tokens("# lint: waive G003 -- fetch-site")
    assert "fetch-site" not in _parse_waiver_tokens(
        "# lint: waive G003 -- fetch-site"
    )


def test_g003_nested_loops_yield_one_finding():
    src = (
        "import jax\n"
        "def run(fs, xs):\n"
        "    for a in fs:\n"
        "        for b in a:\n"
        "            xs = jax.jit(b)(xs)\n"
        "    return xs\n"
    )
    result = engine.lint_sources([("pkg/mod.py", src)])
    assert len([f for f in result.findings if f.rule == "G003"]) == 1


def test_cli_write_baseline_rejects_select(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("X = 1\n")
    bl = tmp_path / "bl.json"
    rc = cli.main(
        ["pkg", "--root", str(tmp_path), "--baseline", str(bl),
         "--write-baseline", "--select", "G001"]
    )
    assert rc == 2
    assert not bl.exists()


def test_syntax_error_is_reported_not_crashed(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("def f(:\n")
    rc = cli.main(["pkg", "--root", str(tmp_path)])
    assert rc == 1
