"""Rule generation + dominance prune (C11) and ordering vs the oracle."""

import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu import oracle
from fastapriori_tpu.rules.gen import gen_rules, sort_rules


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("min_support", [0.05, 0.1])
def test_rules_match_oracle(seed, min_support):
    lines = tokenized(random_dataset(seed))
    itemsets, _, freq_items = oracle.mine(lines, min_support)
    expected = oracle.gen_rules(itemsets)
    got = gen_rules(itemsets)
    assert sorted(got, key=repr) == sorted(expected, key=repr)

    exp_sorted = oracle.sort_rules(expected, freq_items)
    got_sorted = sort_rules(got, freq_items)
    # Priority keys must agree pairwise (ties beyond the key are
    # output-equivalent: same consequent).
    assert [(r[2], freq_items[r[1]]) for r in got_sorted] == [
        (r[2], freq_items[r[1]]) for r in exp_sorted
    ]


def test_rules_strictly_increasing_chain_semantics():
    # Hand-built table: {0},{1},{2} singletons; pairs; one triple.
    # Confidence chain checks the strict < requirement.
    itemsets = [
        (frozenset((0,)), 10),
        (frozenset((1,)), 10),
        (frozenset((2,)), 8),
        (frozenset((0, 1)), 6),
        (frozenset((0, 2)), 6),
        (frozenset((1, 2)), 6),
        (frozenset((0, 1, 2)), 5),
    ]
    rules = gen_rules(itemsets)
    as_set = {(a, c): conf for a, c, conf in rules}
    # level-1 rules all kept
    assert as_set[(frozenset((0,)), 1)] == 6 / 10
    # rule {0,1}->2: subsets {0}->2 (6/10) and {1}->2 (6/10); conf 5/6.
    # 5/6 > 6/10 strictly for both -> survives.
    assert (frozenset((0, 1)), 2) in as_set
    # rule {0,2}->1: conf 5/6 vs {0}->1 (6/10), {2}->1 (6/8)=0.75 < 5/6 -> ok
    assert (frozenset((0, 2)), 1) in as_set


def test_rules_prune_kills_non_increasing():
    # {0,1}->2 with confidence equal to {0}->2 must be pruned (>= kills).
    itemsets = [
        (frozenset((0,)), 10),
        (frozenset((1,)), 10),
        (frozenset((2,)), 10),
        (frozenset((0, 1)), 10),
        (frozenset((0, 2)), 6),
        (frozenset((1, 2)), 6),
        (frozenset((0, 1, 2)), 6),
    ]
    rules = gen_rules(itemsets)
    as_set = {(a, c) for a, c, _ in rules}
    # {0,1}->2 conf 6/10; {0}->2 conf 6/10 -> equal -> pruned.
    assert (frozenset((0, 1)), 2) not in as_set


def test_rules_empty_when_no_pairs():
    assert gen_rules([(frozenset((0,)), 5)]) == []
    assert gen_rules([]) == []


def test_rule_arrays_pipeline_matches_object_pipeline():
    """The matrix-form rule pipeline (gen_rule_arrays_levels +
    sort_rule_arrays + rule_objects_from_arrays) must produce the SAME
    rules in the SAME priority order as the object pipeline — including
    stable tie order, which the device table's first-match semantics
    depend on."""
    from conftest import random_dataset, tokenized
    from fastapriori_tpu.config import MinerConfig
    from fastapriori_tpu.models.apriori import FastApriori
    from fastapriori_tpu.rules.gen import (
        gen_rule_arrays_levels,
        gen_rules_levels,
        rule_objects_from_arrays,
        sort_rule_arrays,
        sort_rules,
    )

    from fastapriori_tpu.preprocess import preprocess

    lines = tokenized(random_dataset(4, n_txns=250, max_len=8))
    miner = FastApriori(
        config=MinerConfig(min_support=0.02, engine="level", num_devices=1)
    )
    d = preprocess(lines, 0.02)
    levels = miner.mine_levels_raw(d)
    objs = sort_rules(gen_rules_levels(levels, d.item_counts), d.freq_items)
    arrs = sort_rule_arrays(
        gen_rule_arrays_levels(levels, d.item_counts), d.freq_items
    )
    from_arrays = rule_objects_from_arrays(*arrs)
    assert len(objs) == len(from_arrays)
    for (a1, c1, f1), (a2, c2, f2) in zip(objs, from_arrays):
        assert a1 == a2 and c1 == c2 and f1 == f2


@pytest.mark.parametrize(
    "f,k,seed",
    [(200, 3, 0), (200, 8, 1), (60000, 4, 2), (9, 9, 3), (70000, 4, 4)],
)
def test_deleted_row_keys_match_repacked(f, k, seed):
    """The incremental per-deleted-column keys must equal the repacked
    _row_keys of np.delete for every column (the raw rule-generation
    hot path relies on this equivalence)."""
    import numpy as np

    from fastapriori_tpu.rules.gen import _deleted_row_keys, _row_keys

    rng = np.random.default_rng(seed)
    m = np.sort(
        rng.choice(f, size=(50, k), replace=True).astype(np.int32), axis=1
    )
    dk = _deleted_row_keys(m, f)
    bits = 8 if f <= 256 else (16 if f <= 65536 else 32)
    if (k - 1) * bits > 64:
        assert dk is None
        return
    assert dk is not None
    for e in range(k):
        want = _row_keys(np.delete(m, e, axis=1), f)
        assert (dk[:, e] == want).all(), e
