"""Real multi-process execution: 2 CPU processes under
``jax.distributed.initialize`` build one global mesh, mine the same
dataset SPMD, and must agree bit-for-bit with the single-process result
(VERDICT missing #3 — the reference demonstrably ran multi-node,
/root/reference/README.md:22-35; this is the jax.distributed analog of
that contract, runnable without a cluster).

The child processes deliberately bypass tests/conftest (fresh
interpreters) so ``jax.distributed`` owns backend initialization.
"""

import json
import os
import socket
import subprocess
import sys

import jax
import pytest

from conftest import random_dataset
from fastapriori_tpu import oracle

# jax 0.4.x's CPU backend rejects multiprocess computations outright
# ("Multiprocess computations aren't implemented on the CPU backend"),
# so the two-process contract is only provable on >= 0.5 (or real
# chips).  Skip, don't fail: an environmental impossibility must stay
# distinguishable from a regression in the CI gate.
_JAX_VERSION = tuple(int(p) for p in jax.__version__.split(".")[:2])
pytestmark = pytest.mark.skipif(
    _JAX_VERSION < (0, 5),
    reason="multiprocess-on-CPU needs jax >= 0.5",
)

_CHILD = r"""
import json, sys
import jax

coordinator, n_proc, pid, d_path, out_path, engine = sys.argv[1:7]
jax.config.update("jax_platforms", "cpu")
from fastapriori_tpu.parallel.mesh import initialize_distributed

initialize_distributed(
    coordinator_address=coordinator,
    num_processes=int(n_proc),
    process_id=int(pid),
)
assert jax.device_count() == int(n_proc), jax.devices()
assert jax.local_device_count() == 1

from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori

cfg = MinerConfig(min_support=0.05, engine=engine)
miner = FastApriori(config=cfg)
assert miner.context.n_devices == int(n_proc)
itemsets, item_to_rank, freq_items = miner.run_file(d_path)
if int(pid) == 0:
    with open(out_path, "w") as f:
        json.dump(
            sorted([sorted(s), int(c)] for s, c in itemsets), f
        )
"""


_CHILD_SHARDED = r"""
import json, sys
import jax

coordinator, n_proc, pid, d_path, out_path, engine, remote = sys.argv[1:8]
jax.config.update("jax_platforms", "cpu")
from fastapriori_tpu.parallel.mesh import initialize_distributed

initialize_distributed(
    coordinator_address=coordinator,
    num_processes=int(n_proc),
    process_id=int(pid),
)
assert jax.process_count() == int(n_proc)

if remote == "1":
    # Remote-URL ingest: stage the bytes into THIS process's in-memory
    # filesystem and point the sharded reader at the URL — exercises the
    # fsspec ranged-read path (fs.size + seek) under real multi-process.
    import fsspec

    with open(d_path, "rb") as f:
        raw = f.read()
    with fsspec.open("memory://dist_in/D.dat", "wb") as f:
        f.write(raw)
    d_path = "memory://dist_in/D.dat"

from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori

miner = FastApriori(config=MinerConfig(min_support=0.05, engine=engine))
levels, data = miner.run_file_sharded(d_path)
# This process really only preprocessed its shard...
assert data.shard is not None
assert data.shard.num_processes == int(n_proc)
assert data.total_count < data.shard.global_count
if engine == "fused":
    # The fused whole-loop program must have run to completion (a hint
    # is recorded only on success), not silently fallen back.
    assert miner.context._fused_hints, "fused engine fell back"
    assert not miner.context._fused_fails
# ...yet the mined result is global and replicated.
if int(pid) == 0:
    out = []
    for mat, cnts in levels:
        out.extend(
            [sorted(r), int(c)] for r, c in zip(mat.tolist(), cnts.tolist())
        )
    out.extend(
        [[r], int(c)] for r, c in enumerate(data.item_counts)
    )
    with open(out_path, "w") as f:
        json.dump(sorted(out), f)
"""


_CHILD_CLI = r"""
import sys
import jax

coordinator, n_proc, pid, inp, outp = sys.argv[1:6]
jax.config.update("jax_platforms", "cpu")
from fastapriori_tpu.parallel.mesh import initialize_distributed

initialize_distributed(
    coordinator_address=coordinator,
    num_processes=int(n_proc),
    process_id=int(pid),
)
from fastapriori_tpu.cli import main

rc = main([inp, outp, "--min-support", "0.05", "--distributed"])
sys.exit(rc)
"""


_CHILD_RECOMMEND = r"""
import json, sys
import jax

coordinator, n_proc, pid, d_path, u_path, out_path = sys.argv[1:7]
jax.config.update("jax_platforms", "cpu")
from fastapriori_tpu.parallel.mesh import initialize_distributed

initialize_distributed(
    coordinator_address=coordinator,
    num_processes=int(n_proc),
    process_id=int(pid),
)

from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.io.reader import read_dat
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.models.recommender import AssociationRules

cfg = MinerConfig(min_support=0.05)
miner = FastApriori(config=cfg)
levels, data = miner.run_file_raw(d_path)
rec = AssociationRules(
    [], data.freq_items, data.item_to_rank, config=cfg,
    levels=levels, item_counts=data.item_counts,
)
# Force the containment-matmul path: the small test data would
# auto-select the host scan, leaving the n_proc>1 device branch
# (local row slices, per-process lagged early exit, final
# process_allgather) unexercised — where SPMD hangs live.
out = rec.run(read_dat(u_path), use_device=True)
if int(pid) == 0:
    with open(out_path, "w") as f:
        json.dump(sorted([int(i), s] for i, s in out), f)
"""


_CHILD_CKPT = r"""
import json, sys
import jax

coordinator, n_proc, pid, d_path, ckpt_dir, out_path, phase = sys.argv[1:8]
jax.config.update("jax_platforms", "cpu")
from fastapriori_tpu.parallel.mesh import initialize_distributed

initialize_distributed(
    coordinator_address=coordinator,
    num_processes=int(n_proc),
    process_id=int(pid),
)

from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.reliability import failpoints

prefix = ckpt_dir.rstrip("/") + "/"
cfg = MinerConfig(
    min_support=0.05, engine="level", checkpoint_prefix=prefix
)
if phase == "kill":
    # Both processes die right after level 3 commits; only process 0
    # may have written the checkpoint (the process-0-writes contract).
    failpoints.arm("level.3", "abort")
    miner = FastApriori(config=cfg)
    try:
        miner.run_file_sharded(d_path)
    except failpoints.InjectedAbort:
        sys.exit(0)
    sys.exit(3)  # the abort failpoint did not fire

# Resume phase: EVERY process validates the checkpoint (manifest
# cross-check + structural lattice check) and seeds its own mine from
# it — the real multi-host resume path the ROADMAP called untested.
from fastapriori_tpu.io.checkpoint import (
    load_checkpoint,
    validate_checkpoint,
)

meta_v = validate_checkpoint(prefix)
levels, meta = load_checkpoint(prefix)
assert meta == meta_v
assert levels[-1][0].shape[1] == 3, "deepest completed level"
miner = FastApriori(config=cfg)
miner.set_resume_levels(levels, meta, label=prefix)
levels_out, data = miner.run_file_sharded(d_path)
if int(pid) == 0:
    out = []
    for mat, cnts in levels_out:
        out.extend(
            [sorted(r), int(c)]
            for r, c in zip(mat.tolist(), cnts.tolist())
        )
    out.extend([[r], int(c)] for r, c in enumerate(data.item_counts))
    with open(out_path, "w") as f:
        json.dump(sorted(out), f)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cli_end_to_end(tmp_path):
    """The full CLI under --distributed with 2 processes: sharded ingest
    for mining, host first-match for recommendation, process 0 writing
    byte-exact output files."""
    d_raw = ["1 2 3"] * 40 + random_dataset(4, n_txns=120, n_items=20)
    u_raw = random_dataset(14, n_txns=25, n_items=20)
    (tmp_path / "in").mkdir()
    (tmp_path / "out").mkdir()
    (tmp_path / "in" / "D.dat").write_text(
        "".join(l + "\n" for l in d_raw)
    )
    (tmp_path / "in" / "U.dat").write_text(
        "".join(l + "\n" for l in u_raw)
    )
    inp = str(tmp_path / "in") + "/"
    outp = str(tmp_path / "out") + "/"

    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_CPU_DEVICES")
    }
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-c", _CHILD_CLI,
                f"127.0.0.1:{port}", "2", str(pid), inp, outp,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process jax.distributed run timed out (ports/env)")
    for rc, out, err in outs:
        assert rc == 0, err.decode()[-3000:]

    d_lines = [l.split() for l in d_raw]
    u_lines = [l.split() for l in u_raw]
    exp_freq, exp_rec = oracle.run_pipeline(d_lines, u_lines, 0.05)
    assert (tmp_path / "out" / "freqItemset").read_text() == exp_freq
    assert (tmp_path / "out" / "recommends").read_text() == exp_rec


@pytest.mark.parametrize(
    "engine,remote",
    [("level", False), ("fused", False), ("auto", False), ("level", True)],
)
def test_two_process_sharded_ingest_matches_oracle(tmp_path, engine, remote):
    """Sharded ingest: each process preprocesses only its byte range of
    D.dat (global tables merged via allgather_bytes, basket shards stay
    process-local), and mining over the global mesh must be bit-exact vs
    the oracle.  The dataset repeats baskets ACROSS the shard boundary so
    the no-cross-shard-dedup path (identical baskets as separate weighted
    rows) is exercised, and one basket repeats 130x so the globally
    uniform digit count (max weight in one shard only) matters.  Both
    engines run: the fused whole-loop program assembles the global bitmap
    from process-local rows exactly like the level engine."""
    d_raw = (
        ["1 2 3"] * 130
        + random_dataset(9, n_txns=150, n_items=25, max_len=10)
        + ["1 2 3"] * 5  # same basket, other end of the file
        + ["7 8 9 10"] * 3
    )
    d_path = tmp_path / "D.dat"
    d_path.write_text("".join(l + "\n" for l in d_raw))
    out_path = tmp_path / "result.json"

    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_CPU_DEVICES")
    }
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                "-c",
                _CHILD_SHARDED,
                f"127.0.0.1:{port}",
                "2",
                str(pid),
                str(d_path),
                str(out_path),
                engine,
                "1" if remote else "0",
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process jax.distributed run timed out (ports/env)")
    for rc, out, err in outs:
        assert rc == 0, err.decode()[-3000:]

    got = {
        frozenset(s): c for s, c in json.loads(out_path.read_text())
    }
    lines = [l.split() for l in d_raw]
    expected, _, _ = oracle.mine(lines, 0.05)
    assert got == {frozenset(s): c for s, c in expected}


def test_two_process_device_recommender_matches_oracle(tmp_path):
    """The multi-process DEVICE recommender (VERDICT missing #2): 2
    processes run the containment-matmul path with use_device=True
    forced, each scanning only its own row slice with its own lagged
    early exit, reassembled by one process_allgather — result must be
    byte-exact vs the oracle's recommendation semantics."""
    d_raw = ["1 2 3"] * 40 + random_dataset(17, n_txns=160, n_items=18)
    u_raw = random_dataset(27, n_txns=60, n_items=18)
    d_path = tmp_path / "D.dat"
    u_path = tmp_path / "U.dat"
    d_path.write_text("".join(l + "\n" for l in d_raw))
    u_path.write_text("".join(l + "\n" for l in u_raw))
    out_path = tmp_path / "rec.json"

    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_CPU_DEVICES")
    }
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-c", _CHILD_RECOMMEND,
                f"127.0.0.1:{port}", "2", str(pid),
                str(d_path), str(u_path), str(out_path),
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process jax.distributed run timed out (ports/env)")
    for rc, out, err in outs:
        assert rc == 0, err.decode()[-3000:]

    got = json.loads(out_path.read_text())
    d_lines = [l.split() for l in d_raw]
    u_lines = [l.split() for l in u_raw]
    _, exp_rec = oracle.run_pipeline(d_lines, u_lines, 0.05)
    exp = [
        [i, s] for i, s in enumerate(exp_rec.splitlines())
    ]
    assert got == exp


def test_two_process_checkpoint_kill_resume_matches_oracle(tmp_path):
    """The multi-host checkpoint path (ISSUE 9 satellite — ROADMAP
    residue: process-0-writes was wired but untested): a 2-process
    sharded mine is killed after level 3 commits, exactly ONE
    checkpoint (process 0's, manifest-validated) must exist, and a
    fresh 2-process run resuming from it must be bit-exact vs the
    oracle."""
    d_raw = (
        ["1 2 3"] * 60
        + random_dataset(9, n_txns=150, n_items=25, max_len=10)
    )
    d_path = tmp_path / "D.dat"
    d_path.write_text("".join(l + "\n" for l in d_raw))
    ckpt_dir = tmp_path / "ckpt"
    ckpt_dir.mkdir()
    out_path = tmp_path / "result.json"

    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_CPU_DEVICES")
    }

    def run_phase(phase):
        port = _free_port()
        procs = [
            subprocess.Popen(
                [
                    sys.executable, "-c", _CHILD_CKPT,
                    f"127.0.0.1:{port}", "2", str(pid),
                    str(d_path), str(ckpt_dir), str(out_path), phase,
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
            for pid in (0, 1)
        ]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=300)
                outs.append((p.returncode, out, err))
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.skip(
                "2-process jax.distributed run timed out (ports/env)"
            )
        for rc, out, err in outs:
            assert rc == 0, err.decode()[-3000:]

    run_phase("kill")
    prefix = str(ckpt_dir) + "/"
    assert os.path.exists(prefix + "checkpoint.npz")
    # Manifest cross-check on the test side too: the committed bytes
    # match what process 0's manifest recorded.
    from fastapriori_tpu.io import resume as resume_io

    manifest = resume_io.load_manifest(prefix)
    with open(prefix + "checkpoint.npz", "rb") as f:
        resume_io.validate_artifact_bytes(
            prefix, "checkpoint.npz", f.read(), manifest
        )
    run_phase("resume")

    got = {
        frozenset(s): c for s, c in json.loads(out_path.read_text())
    }
    lines = [l.split() for l in d_raw]
    expected, _, _ = oracle.mine(lines, 0.05)
    assert got == {frozenset(s): c for s, c in expected}


@pytest.mark.parametrize("engine", ["level", "fused"])
def test_two_process_distributed_mining_matches_oracle(tmp_path, engine):
    d_raw = random_dataset(7, n_txns=200, n_items=25, max_len=10)
    d_path = tmp_path / "D.dat"
    d_path.write_text("".join(l + "\n" for l in d_raw))
    out_path = tmp_path / "result.json"

    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        # Children own their backend: scrub the parent suite's forced
        # platform/device-count flags.
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_CPU_DEVICES")
    }
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                "-c",
                _CHILD,
                f"127.0.0.1:{port}",
                "2",
                str(pid),
                str(d_path),
                str(out_path),
                engine,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process jax.distributed run timed out (ports/env)")
    for rc, out, err in outs:
        assert rc == 0, err.decode()[-3000:]

    got = {
        frozenset(s): c
        for s, c in json.loads(out_path.read_text())
    }
    # Oracle mines item *ranks*; map back through the oracle's own
    # preprocessing to compare as rank-sets with counts.
    lines = [l.split() for l in d_raw]
    expected, _, _ = oracle.mine(lines, 0.05)
    assert got == {frozenset(s): c for s, c in expected}


# ---------------------------------------------------------------------------
# multi-process fault domain over the REAL jax.distributed transport
# (ISSUE 12): the quorum layer's JaxTransport exchanges the consensus
# vector through process_allgather at the CLI's rendezvous points, each
# call bounded by the dispatch watchdog — a dead peer surfaces as a
# classified PeerLost (exit 3) instead of an indefinite collective
# hang.  The lockstep-recovery granularity of the file transport
# (mid-mine adoption) is NOT claimed here: an already-issued mismatched
# collective on a real mesh is only BOUNDED, not repaired (ROADMAP
# residue).  Version-gated like the rest of this file.

_CHILD_QUORUM = r"""
import sys
import jax

coordinator, n_proc, pid, inp, outp, phase = sys.argv[1:7]
jax.config.update("jax_platforms", "cpu")
from fastapriori_tpu.parallel.mesh import initialize_distributed

initialize_distributed(
    coordinator_address=coordinator,
    num_processes=int(n_proc),
    process_id=int(pid),
)
from fastapriori_tpu.reliability import failpoints
from fastapriori_tpu.cli import main

if phase == "kill" and int(pid) == 1:
    # Rank 1 dies right after level 2 commits; rank 0's next quorum
    # rendezvous must classify the loss within the bound.
    failpoints.arm("level.2", "abort")
try:
    rc = main([inp, outp, "--min-support", "0.05", "--distributed"])
except failpoints.InjectedAbort:
    sys.exit(9)  # the injected death (expected for rank 1 / kill)
sys.exit(rc)
"""


@pytest.mark.parametrize("phase", ["clean", "kill"])
def test_two_process_quorum_domain_real_transport(tmp_path, phase):
    """Clean: the rendezvous exchanges are transparent (byte-exact
    output, rc 0 on both ranks).  Kill: the killed rank exits on its
    injected abort and the SURVIVOR exits classified (PeerLost, rc 3)
    within the quorum bound — never a hang."""
    d_raw = ["1 2 3"] * 40 + random_dataset(21, n_txns=120, n_items=20)
    u_raw = random_dataset(22, n_txns=20, n_items=20)
    (tmp_path / "in").mkdir()
    (tmp_path / "out").mkdir()
    (tmp_path / "in" / "D.dat").write_text(
        "".join(l + "\n" for l in d_raw)
    )
    (tmp_path / "in" / "U.dat").write_text(
        "".join(l + "\n" for l in u_raw)
    )
    inp = str(tmp_path / "in") + "/"
    outp = str(tmp_path / "out") + "/"

    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_CPU_DEVICES")
    }
    # Bounded everything: the survivor's rendezvous allgather abandons
    # at this bound (watchdog) and classifies after the retry budget.
    env["FA_QUORUM_TIMEOUT_S"] = "15"
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-c", _CHILD_QUORUM,
                f"127.0.0.1:{port}", "2", str(pid), inp, outp, phase,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("2-process jax.distributed run timed out (ports/env)")
    if phase == "clean":
        for rc, out, err in outs:
            assert rc == 0, err.decode()[-3000:]
        d_lines = [l.split() for l in d_raw]
        u_lines = [l.split() for l in u_raw]
        exp_freq, exp_rec = oracle.run_pipeline(d_lines, u_lines, 0.05)
        assert (tmp_path / "out" / "freqItemset").read_text() == exp_freq
        assert (tmp_path / "out" / "recommends").read_text() == exp_rec
    else:
        rc0, _, err0 = outs[0]
        rc1, _, err1 = outs[1]
        assert rc1 == 9, err1.decode()[-2000:]  # the injected death
        # The survivor: classified PeerLost (rc 3), naming the loss —
        # or rc 0 if it raced to completion before needing the peer.
        assert rc0 in (0, 3), err0.decode()[-3000:]
        if rc0 == 3:
            assert b"quorum peer" in err0 or b"UNAVAILABLE" in err0
