"""Hierarchical two-level exchange (ISSUE 15, ROADMAP direction 3):
the ``(groups, per_group)`` staging of every linear-in-S collective —
the sparse count reduction's mask-union gather + compact psum
(parallel/hier.py via ops/count.py local_sparse_psum) and the sharded
rule join's tiled reassembly (ops/contain.py) — must be BIT-EXACT
against the flat single-level exchange on every counting path and at
every admissible group shape, the topology knob must be strict
(FA_EXCHANGE_GROUPS / config.exchange_groups), the multi-process
engine gates must stop forcing dense/bitmap once the jax process
world spans the ingest world (the mine.start W_s rendezvous supplies
the cross-host thresholds), and the hier→flat cascade must compose
with the quorum consensus like every other collective-shaping
chain."""

import threading

import numpy as np
import pytest

from conftest import random_dataset, tokenized
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.errors import InputError
from fastapriori_tpu.models.apriori import FastApriori
from fastapriori_tpu.parallel import hier
from fastapriori_tpu.reliability import failpoints, ledger, quorum, watchdog


@pytest.fixture(autouse=True)
def _clean_state():
    failpoints.disarm_all()
    ledger.reset()
    quorum.set_domain(None)
    yield
    failpoints.disarm_all()
    ledger.reset()
    quorum.set_domain(None)


def _mine(lines, min_support, **cfg):
    miner = FastApriori(
        config=MinerConfig(min_support=min_support, **cfg)
    )
    got, _, _ = miner.run(lines)
    return dict(got), miner


def _t10i4_shaped():
    from fastapriori_tpu.utils.datagen import generate_transactions

    return [
        l.split()
        for l in generate_transactions(
            n_txns=1200, n_items=80, avg_txn_len=9, n_patterns=25,
            avg_pattern_len=4, corruption=0.35, seed=11,
        )
    ]


def _deep_lattice():
    return tokenized(
        random_dataset(13, n_txns=200, n_items=14, max_len=9)
    )


# ---------------------------------------------------------------------------
# topology resolution: auto policy + strictness table


def test_auto_group_count_policy():
    # Single-process virtual meshes: divisor nearest √S from below,
    # flat wherever the hierarchy cannot strictly win (per+G < S).
    assert hier.auto_group_count(8) == 2
    assert hier.auto_group_count(16) == 4
    assert hier.auto_group_count(32) == 4
    assert hier.auto_group_count(64) == 8
    assert hier.auto_group_count(4) == 1  # 2+2 == 4: no strict win
    assert hier.auto_group_count(2) == 1
    assert hier.auto_group_count(1) == 1
    assert hier.auto_group_count(7) == 1  # prime: no admissible split
    # Real multi-host meshes: groups ARE the process boundaries.
    assert hier.auto_group_count(16, n_procs=2) == 2
    assert hier.auto_group_count(16, n_procs=4) == 4
    assert hier.auto_group_count(4, n_procs=2) == 2
    # Processes that do not divide the axis fall back to √ grouping.
    assert hier.auto_group_count(16, n_procs=3) == 4


def test_resolve_spec_strictness_table():
    assert hier.resolve_spec(8, 0) == (2, 4)
    assert hier.resolve_spec(8, 2) == (2, 4)
    assert hier.resolve_spec(8, 4) == (4, 2)
    assert hier.resolve_spec(8, 1) is None  # explicit flat
    assert hier.resolve_spec(8, 8) is None  # per_group 1 degenerates
    assert hier.resolve_spec(4, 0) is None  # auto: no strict win
    assert hier.resolve_spec(1, 0) is None
    with pytest.raises(InputError, match="does not divide"):
        hier.resolve_spec(8, 3)
    with pytest.raises(InputError, match=">= 0"):
        hier.resolve_spec(8, -2)


def test_index_groups_partition_the_axis():
    intra, inter = hier.index_groups((2, 4))
    assert intra == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert inter == [[0, 4], [1, 5], [2, 6], [3, 7]]
    # Both stagings partition every rank exactly once.
    for grouping in (intra, inter):
        flat = sorted(r for g in grouping for r in g)
        assert flat == list(range(8))


def test_env_knob_strictly_parsed(monkeypatch):
    monkeypatch.setenv("FA_EXCHANGE_GROUPS", "2")
    assert hier.resolve_active_spec(8, MinerConfig()) == (2, 4)
    monkeypatch.setenv("FA_EXCHANGE_GROUPS", "1")
    assert hier.resolve_active_spec(8, MinerConfig()) is None
    monkeypatch.setenv("FA_EXCHANGE_GROUPS", "3")
    with pytest.raises(InputError, match="does not divide"):
        hier.resolve_active_spec(8, MinerConfig())
    monkeypatch.setenv("FA_EXCHANGE_GROUPS", "nope")
    with pytest.raises(InputError, match="FA_EXCHANGE_GROUPS"):
        hier.resolve_active_spec(8, MinerConfig())
    monkeypatch.setenv("FA_EXCHANGE_GROUPS", "-1")
    with pytest.raises(InputError, match="out of range"):
        hier.resolve_active_spec(8, MinerConfig())
    monkeypatch.delenv("FA_EXCHANGE_GROUPS")
    # Unset: the config knob rules (and is validated identically).
    assert hier.resolve_active_spec(
        8, MinerConfig(exchange_groups=4)
    ) == (4, 2)
    with pytest.raises(InputError, match="does not divide"):
        hier.resolve_active_spec(8, MinerConfig(exchange_groups=5))


def test_stage_byte_models():
    # Reduction exchange: hier moves (per+G)·b vs flat S·b.
    assert hier.union_stage_bytes(100, 8, None) == (0, 800)
    assert hier.union_stage_bytes(100, 8, (2, 4)) == (400, 200)
    # Concatenation reassembly: received total is invariant (S·b); the
    # hierarchy restages it as per·b intra + G·(per·b) inter.
    assert hier.gather_stage_bytes(100, 8, None) == (0, 800)
    assert hier.gather_stage_bytes(100, 8, (2, 4)) == (400, 800)
    from fastapriori_tpu.ops.count import (
        sparse_psum_bytes,
        sparse_stage_bytes,
    )

    g_f, p_f = sparse_psum_bytes(4096, 256, 8)
    g_h, p_h = sparse_psum_bytes(4096, 256, 8, (2, 4))
    assert p_h == p_f  # the compact psum payload is topology-invariant
    assert g_h == 6 * 512 and g_f == 8 * 512  # (per+G)/S of the mask
    i_b, e_b = sparse_stage_bytes(4096, 256, 8, (2, 4))
    assert (i_b, e_b) == (4 * 512, 2 * 512 + p_f)


# ---------------------------------------------------------------------------
# primitive differential: local_sparse_psum hier vs flat vs numpy, at
# every admissible (S, groups) shape on the 8-device conftest mesh


@pytest.mark.parametrize(
    "n_dev, groups",
    [(2, 2), (4, 2), (4, 4), (8, 2), (8, 4), (8, 8)],
)
def test_local_sparse_psum_hier_bitexact(n_dev, groups):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from fastapriori_tpu import compat
    from fastapriori_tpu.ops.count import local_sparse_psum
    from fastapriori_tpu.parallel.mesh import AXIS, DeviceContext

    ctx = DeviceContext(num_devices=n_dev)
    rng = np.random.default_rng(7 + n_dev + groups)
    n = 512
    local = rng.integers(0, 40, size=(n_dev, n), dtype=np.int32)
    # Make the distribution power-law-ish: most candidates tiny.
    local[:, rng.random(n) < 0.7] //= 8
    thr = np.full(n_dev, 9, dtype=np.int32)
    expected = local.sum(axis=0)
    expected[~(local >= 9).any(axis=0)] = 0  # provably-infrequent -> 0

    def run(spec):
        def _local(x, t):
            out, nu = local_sparse_psum(
                x, t[lax.axis_index(AXIS)], 512, AXIS, groups=spec
            )
            return out, nu

        from jax.sharding import PartitionSpec as P

        fn = jax.jit(
            compat.shard_map(
                _local,
                mesh=ctx.mesh,
                in_specs=(P(AXIS, None), P(None)),
                out_specs=(P(AXIS, None), P()),
            )
        )
        out, nu = fn(
            local.reshape(-1, n), jnp.asarray(thr, dtype=jnp.int32)
        )
        # Every shard computed the identical reduction; read shard 0.
        return np.asarray(out)[:1].reshape(-1), int(nu)

    flat, nu_flat = run(None)
    # Degenerate shapes (per_group == 1) are legal and still bit-exact
    # (the intra stage is the identity) — the knob layer resolves them
    # to flat for performance, not correctness.
    hi, nu_hier = run((groups, n_dev // groups))
    np.testing.assert_array_equal(flat, expected)
    np.testing.assert_array_equal(hi, flat)
    assert nu_flat == nu_hier


# ---------------------------------------------------------------------------
# end-to-end differential: all four counting paths, hier vs flat vs
# dense, at 8 devices x group shapes


_DENSE_EXPECTED = {}


@pytest.mark.parametrize("groups", [2, 4])
@pytest.mark.parametrize(
    "path_cfg",
    [
        {"engine": "level"},
        {"engine": "fused"},
        {"engine": "level", "tail_fuse_rows": 8192},
        {"engine": "level", "mine_engine": "vertical"},
    ],
    ids=["level", "fused", "tail", "vertical"],
)
def test_mine_bitexact_hier_vs_flat(path_cfg, groups):
    lines = _t10i4_shaped()
    key = tuple(sorted(path_cfg.items()))
    if key not in _DENSE_EXPECTED:
        # One dense oracle mine per counting path (flat-vs-dense is
        # PR 6's suite; this one pins hier-vs-dense per group shape).
        _DENSE_EXPECTED[key], _ = _mine(
            lines, 0.03, num_devices=8, count_reduce="dense",
            **path_cfg
        )
    exp = _DENSE_EXPECTED[key]
    got, miner = _mine(
        lines, 0.03, num_devices=8, count_reduce="sparse",
        count_sparse_min=1, exchange_groups=groups, **path_cfg
    )
    assert got == exp
    ev = [
        e for e in ledger.snapshot() if e["kind"] == "exchange_engine"
    ]
    assert any(e.get("engine") == "hier" for e in ev)


def test_deep_lattice_hier_bitexact():
    lines = _deep_lattice()
    exp, _ = _mine(lines, 0.05, num_devices=8, count_reduce="dense")
    got, _ = _mine(
        lines, 0.05, num_devices=8, count_reduce="sparse",
        count_sparse_min=1, exchange_groups=2,
    )
    assert got == exp


def test_hier_gather_bytes_strictly_below_flat():
    """The ISSUE-15 byte claim at the unit level: on the 8-device mesh
    the hierarchical mask gather moves (per+G)/S = 6/8 of the flat
    bytes per sparse level, and the per-stage fields decompose it."""
    lines = _t10i4_shaped()

    def levels_of(miner):
        return {
            r["k"]: r
            for r in miner.metrics.records
            if r.get("event") == "level" and r.get("reduce") == "sparse"
        }

    _, m_flat = _mine(
        lines, 0.03, num_devices=8, engine="level",
        count_reduce="sparse", count_sparse_min=1, exchange_groups=1,
    )
    _, m_hier = _mine(
        lines, 0.03, num_devices=8, engine="level",
        count_reduce="sparse", count_sparse_min=1, exchange_groups=2,
    )
    lf, lh = levels_of(m_flat), levels_of(m_hier)
    assert lf and set(lh) == set(lf)
    for k, rf in lf.items():
        rh = lh[k]
        assert rh["exchange"] == "hier" and rf["exchange"] == "flat"
        assert rh["gather_bytes"] < rf["gather_bytes"]
        assert rh["gather_bytes"] * 8 == rf["gather_bytes"] * 6
        # Stage decomposition: intra+inter == gather + compact psum.
        assert (
            rh["intra_bytes"] + rh["inter_bytes"]
            == rh["gather_bytes"] + rh["psum_bytes"]
        )


# ---------------------------------------------------------------------------
# sharded rule join: hier reassembly vs flat, bit-exact recommendations


@pytest.mark.parametrize(
    "n_dev, groups", [(4, 2), (8, 2), (8, 4)]
)
def test_rule_join_hier_bitexact(n_dev, groups):
    from fastapriori_tpu.models.recommender import AssociationRules
    from fastapriori_tpu.preprocess import preprocess

    d_lines = tokenized(random_dataset(6, n_txns=250, max_len=8))
    u_lines = tokenized(random_dataset(60, n_txns=150))
    data = preprocess(d_lines, 0.05)

    def recommend(xgroups):
        cfg = MinerConfig(
            min_support=0.05, engine="level", num_devices=n_dev,
            rule_engine="device", exchange_groups=xgroups,
        )
        miner = FastApriori(config=cfg)
        levels = miner.mine_levels_raw(data)
        rec = AssociationRules(
            [], data.freq_items, data.item_to_rank, config=cfg,
            context=miner.context, levels=levels,
            item_counts=data.item_counts,
        )
        out = rec.run(u_lines, use_device=True)
        gen = [
            r for r in rec.metrics.records
            if r.get("event") == "rule_gen_device"
        ]
        host = rec.run(u_lines, use_device=False)
        assert out == host  # the host oracle agrees either way
        return out, gen[-1] if gen else {}

    out_flat, ev_flat = recommend(1)
    out_hier, ev_hier = recommend(groups)
    assert out_flat == out_hier
    assert ev_flat.get("exchange") == "flat"
    assert ev_hier.get("exchange") == "hier"
    # The reassembly total is topology-invariant; the slow tier's
    # message count is the staging win.
    cf, ch = ev_flat["comms"][0], ev_hier["comms"][0]
    assert ch["gather_bytes"] == cf["gather_bytes"]
    assert ch["inter_msgs"] < cf["inter_msgs"]


# ---------------------------------------------------------------------------
# multi-process activation: the W_s exchange unblocks sparse + vertical


def _sharded_data_2proc(tmp_path, rank=0):
    """A 2-process sharded CompressedData for ``rank`` with the
    allgather simulated (the test_native pattern, compacted)."""
    import pickle

    from fastapriori_tpu.native.loader import (
        compress_with_ranks,
        count_buffer,
    )
    from fastapriori_tpu.preprocess import (
        preprocess_file,
        preprocess_file_sharded,
        read_shard,
    )

    d_raw = (
        ["1 2 3"] * 40
        + random_dataset(21, n_txns=160, n_items=24, max_len=8)
        + ["1 2 3"] * 7
    )
    path = tmp_path / "D.dat"
    path.write_text("".join(l + "\n" for l in d_raw))
    plain = preprocess_file(str(path), 0.05)
    p1 = [
        pickle.dumps(count_buffer(read_shard(str(path), i, 2)), 4)
        for i in range(2)
    ]
    calls = {"n": 0}

    def ag(blob):
        calls["n"] += 1
        if calls["n"] == 1:
            return p1
        out = []
        for j in range(2):
            if j == rank:
                out.append(blob)
            else:
                dj = read_shard(str(path), j, 2)
                _, _, _, wj = compress_with_ranks(dj, plain.freq_items)
                out.append(
                    pickle.dumps(
                        (len(wj), int(wj.max()) if len(wj) else 1), 4
                    )
                )
        return out

    return (
        preprocess_file_sharded(
            str(path), 0.05, process_id=rank, num_processes=2,
            allgather=ag,
        ),
        str(path),
    )


def _domain_pair(root):
    d0 = quorum.QuorumDomain(quorum.FileTransport(root, 0, 2), 0, 2)
    d1 = quorum.QuorumDomain(quorum.FileTransport(root, 1, 2), 1, 2)
    return d0, d1


def test_sparse_activates_on_sharded_data_with_domain(
    tmp_path, monkeypatch
):
    """The PR-6 residue closed: a sharded (multi-process) ingest with a
    quorum transport spanning its world resolves count_reduce=sparse —
    no multi-process dense fallback event — and the W_s thresholds
    come from the mine.start exchange, matching the weighted
    pigeonhole over the concatenated per-rank totals exactly."""
    import jax

    monkeypatch.setenv("FA_QUORUM_TIMEOUT_S", "5.0")
    monkeypatch.setenv("FA_HEARTBEAT_MS", "40")
    data0, _ = _sharded_data_2proc(tmp_path, rank=0)
    data1, _ = _sharded_data_2proc(tmp_path, rank=1)
    d0, d1 = _domain_pair(str(tmp_path / "q"))
    quorum.set_domain(d0)
    try:
        miner = FastApriori(
            config=MinerConfig(min_support=0.05, num_devices=2)
        )
        miner.context  # build the mesh before faking the world size
        # The simulated 2-process world (the PR-9 monkeypatch pattern):
        # the gate requires the MESH to span the ingest processes; the
        # quorum domain is the W_s transport, not the unlock.
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        engine, _req = miner._count_reduce_engine(data0)
        assert engine == "sparse"
        assert not [
            e for e in ledger.snapshot()
            if e["kind"] == "count_reduce_fallback"
        ]
        # The exchange itself: rank 1 posts its totals on a thread (the
        # peer's half of the rendezvous), rank 0 runs the real
        # threshold computation.
        s = miner.context.txn_shards
        t_pad = 256 * 2  # per-process pad (generously above shard rows)
        w1 = np.zeros(256, dtype=np.int64)
        w1[: data1.total_count] = data1.weights
        peer_payload = [int(w1.sum())]
        t = threading.Thread(
            target=lambda: d1.exchange("mine.wstotals", peer_payload)
        )
        t.start()
        thr = miner._sparse_thresholds(data0, t_pad, heavy=False)
        t.join()
        assert thr.shape == (s,)
        w0 = np.zeros(256, dtype=np.int64)
        w0[: data0.total_count] = data0.weights
        per = np.array([int(w0.sum()), peer_payload[0]], dtype=np.int64)
        want = np.maximum(
            1, -(-(int(data0.min_count) * per) // int(per.sum()))
        ).astype(np.int32)
        np.testing.assert_array_equal(thr, want)
        assert [
            e for e in ledger.snapshot()
            if e["kind"] == "wstotals_exchange"
        ]
    finally:
        d0.close()
        d1.close()


def test_sharded_data_without_transport_still_falls_back(tmp_path):
    data0, _ = _sharded_data_2proc(tmp_path, rank=0)
    miner = FastApriori(
        config=MinerConfig(
            min_support=0.05, num_devices=2, count_reduce="sparse"
        )
    )
    engine, _ = miner._count_reduce_engine(data0)
    assert engine == "dense"
    ev = [
        e for e in ledger.snapshot()
        if e["kind"] == "count_reduce_fallback"
    ]
    assert ev and ev[0]["reason"] == "no_wstotals_transport"


def test_vertical_activates_on_sharded_data_with_domain(
    tmp_path, monkeypatch
):
    """The PR-7 residue closed at the gate: a sharded CSR-bearing
    ingest with a spanning transport no longer forces the bitmap
    layout — no mine_engine_fallback event under a forced vertical."""
    import jax

    monkeypatch.setenv("FA_QUORUM_TIMEOUT_S", "5.0")
    data0, _ = _sharded_data_2proc(tmp_path, rank=0)
    d0, d1 = _domain_pair(str(tmp_path / "q"))
    quorum.set_domain(d0)
    try:
        miner = FastApriori(
            config=MinerConfig(
                min_support=0.05, num_devices=2,
                mine_engine="vertical",
            )
        )
        miner.context
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        engine, _ = miner._mine_engine(data0)
        assert engine == "vertical"
        assert not [
            e for e in ledger.snapshot()
            if e["kind"] == "mine_engine_fallback"
        ]
    finally:
        d0.close()
        d1.close()


def test_vertical_sharded_single_process_mines_bitexact(tmp_path):
    """The full vertical lane path over a ShardInfo-bearing ingest
    (num_processes == 1: the local rows ARE the world): must equal the
    bitmap engine's output bit for bit."""
    import pickle

    from fastapriori_tpu.preprocess import preprocess_file_sharded

    d_raw = random_dataset(31, n_txns=220, n_items=40, max_len=9)
    path = tmp_path / "D.dat"
    path.write_text("".join(l + "\n" for l in d_raw))
    data = preprocess_file_sharded(
        str(path), 0.04, process_id=0, num_processes=1,
        allgather=lambda b: [b],
    )
    assert data.shard is not None

    def mine(engine):
        miner = FastApriori(
            config=MinerConfig(
                min_support=0.04, num_devices=8, mine_engine=engine,
            )
        )
        return miner.mine_levels_raw(data)

    bm = mine("bitmap")
    vt = mine("vertical")
    assert len(bm) == len(vt)
    for (ma, ca), (mb, cb) in zip(bm, vt):
        np.testing.assert_array_equal(ma, mb)
        np.testing.assert_array_equal(ca, cb)


# ---------------------------------------------------------------------------
# cascade + consensus composition


def test_hier_transient_walks_to_flat_then_dense():
    """Transient exhaustion on a sparse counting fetch under the
    hierarchical exchange walks BOTH chains — exchange hier→flat, then
    count_reduce sparse→dense for the recount — and the mine stays
    exact."""
    lines = _deep_lattice()
    exp, _ = _mine(lines, 0.05, num_devices=8, count_reduce="dense")
    failpoints.arm("fetch.level_bits_sparse", "oom*3")
    got, _ = _mine(
        lines, 0.05, num_devices=8, engine="level",
        count_reduce="sparse", count_sparse_min=1, exchange_groups=2,
    )
    assert got == exp
    casc = [e for e in ledger.snapshot() if e["kind"] == "cascade"]
    chains = [(e["chain"], e["frm"], e["to"]) for e in casc]
    assert ("exchange", "hier", "flat") in chains
    assert ("count_reduce", "sparse", "dense") in chains


def test_pair_sparse_transient_walks_and_redoes_dense():
    """The pair phase's sparse fetch gains the same cascade catch the
    level path has (found via the chaos divergence schedule): transient
    exhaustion walks exchange hier→flat then count_reduce sparse→dense
    at site=pair and the ONE dense redo keeps the mine exact."""
    lines = _t10i4_shaped()
    exp, _ = _mine(lines, 0.03, num_devices=8, count_reduce="dense")
    failpoints.arm("fetch.pair_sparse", "oom*3")
    got, _ = _mine(
        lines, 0.03, num_devices=8, engine="level",
        count_reduce="sparse", exchange_groups=2,
    )
    assert got == exp
    casc = [e for e in ledger.snapshot() if e["kind"] == "cascade"]
    chains = [
        (e["chain"], e["frm"], e["to"], e.get("site")) for e in casc
    ]
    assert ("exchange", "hier", "flat", "pair") in chains
    assert ("count_reduce", "sparse", "dense", "pair") in chains


def test_exchange_chain_is_consensus_registered():
    assert "exchange" in quorum.CONSENSUS_CHAINS
    assert watchdog.CHAINS["exchange"] == ("hier", "flat")
    # A local hier→flat walk proposes; a fresh domain adopting it
    # clamps resolve_active_spec to flat.
    dom = quorum.QuorumDomain(
        quorum.FileTransport("/tmp/_fa_hier_dom_test", 0, 1), 0, 1
    )
    quorum.set_domain(dom)
    try:
        assert hier.resolve_active_spec(8, MinerConfig()) == (2, 4)
        watchdog.downgrade(
            "exchange", "hier", "flat", reason="transient_exhausted"
        )
        assert dom.floor_stage("exchange") == "flat"
        assert hier.resolve_active_spec(8, MinerConfig()) is None
    finally:
        dom.close()


def test_wstotals_rendezvous_transient_absorbed(tmp_path, monkeypatch):
    """An armed transient on the W_s rendezvous site is absorbed by the
    standard bounded retry — the exchange completes and the thresholds
    are unchanged."""
    monkeypatch.setenv("FA_QUORUM_TIMEOUT_S", "5.0")
    data0, _ = _sharded_data_2proc(tmp_path, rank=0)
    d0, d1 = _domain_pair(str(tmp_path / "q"))
    quorum.set_domain(d0)
    # oom = transient-classified (the chaos divergence menu's kind):
    # whichever side's attempt consumes the single shot retries and
    # the rendezvous still completes.
    failpoints.arm("quorum.mine.wstotals", "oom*1")
    try:
        miner = FastApriori(
            config=MinerConfig(min_support=0.05, num_devices=2)
        )
        t = threading.Thread(
            target=lambda: d1.exchange("mine.wstotals", [123])
        )
        t.start()
        thr = miner._sparse_thresholds(data0, 512, heavy=False)
        t.join()
        assert thr.shape == (2,)
        retries = [
            e for e in ledger.snapshot() if e["kind"] == "retry"
        ]
        assert any(
            "wstotals" in str(e.get("site", "")) for e in retries
        )
    finally:
        d0.close()
        d1.close()


def test_wstotals_divergence_classified(tmp_path, monkeypatch):
    """Full-replica domains (the chaos --procs shape): ranks deriving
    DIFFERENT W_s totals must fail classified at the rendezvous, never
    silently issue mismatched sparse collectives."""
    monkeypatch.setenv("FA_QUORUM_TIMEOUT_S", "5.0")
    d0, d1 = _domain_pair(str(tmp_path / "q"))
    quorum.set_domain(d0)
    try:
        miner = FastApriori(
            config=MinerConfig(min_support=0.05, num_devices=2)
        )

        class _Data:
            shard = None
            total_count = 2
            weights = np.array([5, 5], dtype=np.int64)

        t = threading.Thread(
            target=lambda: d1.exchange("mine.wstotals", [999, 1])
        )
        t.start()
        with pytest.raises(quorum.MeshDivergence, match="wstotals"):
            miner._verify_wstotals(_Data(), 4)
        t.join()
    finally:
        d0.close()
        d1.close()


def test_hier_kill_and_resume_bit_exact(tmp_path):
    """Kill-and-resume under the hierarchical exchange: interrupt after
    a completed level, resume from the checkpoint with hier still
    selected — output byte-equal to the uninterrupted dense run."""
    from fastapriori_tpu.io import checkpoint as ckpt
    from fastapriori_tpu.io import writer

    lines = _deep_lattice()
    prefix = str(tmp_path) + "/"

    def cfg(**kw):
        return MinerConfig(
            min_support=0.05, num_devices=8, engine="level",
            count_reduce="sparse", count_sparse_min=1,
            exchange_groups=2, **kw
        )

    clean_sets, _, clean_items = FastApriori(
        config=MinerConfig(min_support=0.05, num_devices=8)
    ).run(lines)
    failpoints.arm("level.3", "abort")
    miner = FastApriori(config=cfg(checkpoint_prefix=prefix))
    with pytest.raises(failpoints.InjectedAbort):
        miner.run(lines)
    failpoints.disarm_all()
    levels, meta = ckpt.load_checkpoint(prefix)
    resumed = FastApriori(config=cfg())
    resumed.set_resume_levels(levels, meta, label=prefix)
    got_sets, _, got_items = resumed.run(lines)
    assert got_items == clean_items
    out_a, out_b = str(tmp_path / "a_"), str(tmp_path / "b_")
    writer.save_freq_itemsets(out_a, clean_sets, clean_items)
    writer.save_freq_itemsets(out_b, got_sets, got_items)
    assert (
        open(out_a + "freqItemset", "rb").read()
        == open(out_b + "freqItemset", "rb").read()
    )


# ---------------------------------------------------------------------------
# 16/32-shard differential (subprocess: the in-process mesh is 8-wide)


@pytest.mark.slow
@pytest.mark.parametrize("n_dev", [16, 32])
def test_hier_bitexact_at_pod_scale(n_dev, tmp_path):
    import json
    import subprocess
    import sys

    from fastapriori_tpu.utils.datagen import generate_transactions

    path = tmp_path / "D.dat"
    path.write_text(
        "\n".join(
            generate_transactions(n_txns=4000, n_items=80, seed=5)
        )
        + "\n"
    )
    child = r"""
import json, os, sys
n = int(sys.argv[2])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    f"--xla_force_host_platform_device_count={n}"
)
from fastapriori_tpu.config import MinerConfig
from fastapriori_tpu.models.apriori import FastApriori
outs = []
for groups in (1, 0):
    cfg = MinerConfig(min_support=0.02, num_devices=n, engine="level",
                      count_reduce="sparse", count_sparse_min=1,
                      exchange_groups=groups)
    m = FastApriori(config=cfg)
    levels, _ = m.run_file_raw(sys.argv[1])
    outs.append([
        [lv.tolist(), c.tolist()] for lv, c in levels
    ])
    ex = [r for r in m.metrics.records if r.get("event") == "level"
          and r.get("exchange")]
    outs.append(ex[0]["exchange"] if ex else "none")
print(json.dumps({"equal": outs[0] == outs[2],
                  "flat": outs[1], "hier": outs[3]}))
"""
    proc = subprocess.run(
        [sys.executable, "-c", child, str(path), str(n_dev)],
        capture_output=True,
        timeout=1500,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-800:]
    line = next(
        l for l in proc.stdout.decode().splitlines()
        if l.startswith("{")
    )
    rec = json.loads(line)
    assert rec["equal"], rec
    assert rec["flat"] == "flat" and rec["hier"] == "hier"
