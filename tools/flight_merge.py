"""Merge per-rank flight-recorder dumps into ONE ordered post-mortem
(ISSUE 12 satellite).

A multi-process fault domain dumps one ``<prefix>rank<r>.flight.json``
per process (obs/flight.py; the rank suffix keeps them from clobbering)
— but the failure narrative ("rank 1 degraded at epoch 7, rank 0
adopted at epoch 8, rank 1's heartbeat stopped, rank 0 raised
PeerLost") spans processes.  Each dump carries its recorder's
wall-clock anchor (``t0_unix_s``); this tool rebases every event to
absolute time, interleaves the rings, and writes (or prints) one
chronological stream with each event tagged by its source file.

Usage::

    python tools/flight_merge.py out/rank0.flight.json out/rank1.flight.json
    python tools/flight_merge.py --prefix out/        # globs *flight.json
    python tools/flight_merge.py --prefix out/ -o merged.json

Stdlib-only; no jax import.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional


def _label(path: str) -> str:
    """Source tag for one dump: the rank when the filename carries one
    (``...rank<r>.flight.json``), else the basename."""
    m = re.search(r"rank(\d+)\.flight\.json$", os.path.basename(path))
    return f"rank{m.group(1)}" if m else os.path.basename(path)


def merge_flights(paths: List[str]) -> Dict:
    """The merged document: every ring's events rebased to absolute
    unix time (``t_abs_s``), tagged with ``src``, sorted
    chronologically (ties broken by (src, seq) so the order is
    deterministic).  Per-source drop accounting is preserved — a
    wrapped ring (first_seq > 1) means the merged stream is missing
    that source's oldest events, and the summary says so."""
    sources = []
    events: List[Dict] = []
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        src = _label(path)
        t0 = float(doc.get("t0_unix_s") or 0.0)
        first = doc.get("first_seq")
        sources.append(
            {
                "src": src,
                "path": path,
                "reason": doc.get("reason"),
                "total_events": doc.get("total_events"),
                "ring_capacity": doc.get("ring_capacity"),
                "dropped_before_ring": (first - 1) if first else 0,
                "t0_unix_s": t0 or None,
            }
        )
        for e in doc.get("events", []):
            ev = dict(e)
            ev["src"] = src
            ev["t_abs_s"] = (
                round(t0 + float(e.get("t_s", 0.0)), 6) if t0 else None
            )
            events.append(ev)
    # Dumps without an anchor (pre-ISSUE-12 recorders) sort after
    # anchored ones, in their own relative order — merged best-effort
    # rather than rejected.
    events.sort(
        key=lambda e: (
            e["t_abs_s"] is None,
            e["t_abs_s"] or e.get("t_s", 0.0),
            e["src"],
            e.get("seq", 0),
        )
    )
    return {
        "version": 1,
        "sources": sources,
        # Elastic-mesh transitions (ISSUE 17): the mesh-epoch timeline
        # — who aborted, why, which survivors re-rendezvoused, and
        # what each epoch's level loop re-seeded from — pulled out of
        # the interleaved stream so a continued run's post-mortem
        # shows which epoch produced which levels at a glance.
        "mesh_epochs": _mesh_epoch_timeline(events),
        "events": events,
    }


def _mesh_epoch_timeline(events: List[Dict]) -> List[Dict]:
    """The chronological mesh-epoch transitions in ``events``: the
    quorum layer's ``mesh_epoch`` notes (abort reason + dead ranks +
    survivor set, one per rank per transition), the ledger's copy of
    the same (kind ``ledger``, event ``mesh_epoch``), and the level
    loop's ``mesh_epoch_reseed`` notes (resume level + respec
    summary).  ``events`` must already be sorted."""
    out: List[Dict] = []
    for e in events:
        kind = e.get("kind")
        if kind == "ledger" and e.get("event") == "mesh_epoch":
            kind = "mesh_epoch"
        elif kind not in ("mesh_epoch", "mesh_epoch_reseed"):
            continue
        keep = {
            k: v
            for k, v in e.items()
            if k
            in (
                "src",
                "t_abs_s",
                "seq",
                "mesh_epoch",
                "epoch",
                "from_epoch",
                "dead",
                "members",
                "reason",
                "resume_from_k",
                "levels_kept",
                "respec",
            )
        }
        keep["kind"] = kind
        out.append(keep)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "files", nargs="*", help="flight.json dumps to merge"
    )
    ap.add_argument(
        "--prefix",
        default=None,
        help="glob <prefix>*flight.json instead of naming files",
    )
    ap.add_argument(
        "-o", "--output", default=None,
        help="write merged JSON here (default: stdout)",
    )
    args = ap.parse_args(argv)
    paths = list(args.files)
    if args.prefix:
        paths.extend(sorted(glob.glob(args.prefix + "*flight.json")))
    paths = sorted(set(paths))
    if not paths:
        print(
            "flight_merge: no flight.json inputs (name files or pass "
            "--prefix)",
            file=sys.stderr,
        )
        return 2
    merged = merge_flights(paths)
    body = json.dumps(merged, indent=1) + "\n"
    if args.output:
        # lint: waive G009 -- offline post-mortem tool output, not a run artifact (no manifest to join)
        with open(args.output, "w") as f:
            f.write(body)
        print(
            f"flight_merge: {len(merged['events'])} events from "
            f"{len(paths)} dump(s) -> {args.output}",
            file=sys.stderr,
        )
    else:
        sys.stdout.write(body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
