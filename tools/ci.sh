#!/bin/sh
# Tier-1 gate: graftlint first (fast, no JAX import), then the test
# suite.  Usage: tools/ci.sh [extra pytest args].
set -e
cd "$(dirname "$0")/.."

python -m tools.lint fastapriori_tpu tests --baseline tools/lint/baseline.json

exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider "$@"
