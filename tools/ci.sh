#!/bin/sh
# Tier-1 gate: graftlint first (fast, no JAX import), then the test
# suite, then the failpoint smoke pass (injected transient fetch /
# kill-resume / truncated artifact against the full CLI pipeline).
# Usage: tools/ci.sh [extra pytest args].
set -e
cd "$(dirname "$0")/.."

python -m tools.lint fastapriori_tpu tests --baseline tools/lint/baseline.json

env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider "$@"

# Device-vs-host rule-generation differential suite (ISSUE 4): explicit
# gate on the bit-exactness contract even when callers trim the pytest
# args above.
env JAX_PLATFORMS=cpu python -m pytest tests/test_rules_device.py -q \
    -p no:cacheprovider

env JAX_PLATFORMS=cpu python tools/failpoint_smoke.py
