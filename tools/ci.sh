#!/bin/sh
# Tier-1 gate: graftlint first (fast, no JAX import) including the
# contract-inventory drift check, then the test suite, then the
# failpoint smoke pass (injected transient fetch / kill-resume /
# truncated artifact against the full CLI pipeline).
# Usage: tools/ci.sh [extra pytest args].
set -e
cd "$(dirname "$0")/.."

# Full linted surface (package + tests + bench driver + entry script +
# tooling) under the EMPTY baseline, plus the inventory drift check:
# tools/lint/inventory.json, env_registry.json and the README knob
# table must match what the tree regenerates (including the v5
# concurrency censuses) — inventory churn rides the PR that causes
# it.  Both the COLD wall (cache deleted first — what a fresh CI box
# pays, and what the v5 concurrency + k-hop passes actually cost) and
# the WARM wall (second run over the schema-3 analysis cache) are
# logged; the 15 s budget gates the cold run, the expensive one.
rm -f tools/lint/.cache.json
lint_t0=$(python -c 'import time; print(time.time())')
python -m tools.lint --baseline tools/lint/baseline.json --check-inventory
lint_t1=$(python -c 'import time; print(time.time())')
python -m tools.lint --baseline tools/lint/baseline.json --check-inventory
python - "$lint_t0" "$lint_t1" <<'EOF'
import sys, time
t0, t1 = float(sys.argv[1]), float(sys.argv[2])
cold = t1 - t0
warm = time.time() - t1
print(f"lint+inventory wall time: cold {cold:.2f}s, warm {warm:.2f}s "
      "(cold budget 15s)")
sys.exit(1 if cold > 15.0 else 0)
EOF

env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider "$@"

# Device-vs-host rule-generation differential suite (ISSUE 4): explicit
# gate on the bit-exactness contract even when callers trim the pytest
# args above.
env JAX_PLATFORMS=cpu python -m pytest tests/test_rules_device.py -q \
    -p no:cacheprovider

# Vertical-vs-bitmap mining-engine differential suite (ISSUE 7): the
# tid-lane engine must stay bit-exact against the bitmap oracle on
# every corpus/mesh shape.
env JAX_PLATFORMS=cpu python -m pytest tests/test_vertical.py -q \
    -p no:cacheprovider

# Pallas kernel-tier differential suite (ISSUE 18): the VMEM-resident
# vertical kernel and the strided first-match serving kernel, in
# interpreter mode, must stay bit-exact against the XLA vertical path
# and the bitmap oracle on every corpus/mesh shape, the FA_NO_PALLAS
# gate table strict, the vertical_kernel/serve_scan cascades walked,
# and kill-resume byte-identical with the tier engaged.  Wall-budgeted
# like the serving smoke (the suite is the slowest differential block:
# three engines per corpus cell).
pallas_t0=$(python -c 'import time; print(time.time())')
env JAX_PLATFORMS=cpu python -m pytest tests/test_pallas_vertical.py -q \
    -p no:cacheprovider
python - "$pallas_t0" <<'EOF'
import sys, time
elapsed = time.time() - float(sys.argv[1])
print(f"pallas differential wall time: {elapsed:.2f}s (budget 240s)")
sys.exit(1 if elapsed > 240.0 else 0)
EOF

# Sharded rule generation + device-resident priority scan differential
# suite (ISSUE 8): the sharded join engine and the rank-strided
# resident scan must stay bit-exact against the host oracle at
# 1/2/4/8 virtual devices.
env JAX_PLATFORMS=cpu python -m pytest tests/test_rules_shard.py -q \
    -p no:cacheprovider

# Hierarchical-exchange differential suite (ISSUE 15): the two-level
# (groups, per_group) staging of the sparse count reduction and the
# sharded rule-join reassembly must stay bit-exact against the flat
# exchange on every counting path and group shape, the topology knob
# strict, and the hier→flat cascade consensus-registered.
env JAX_PLATFORMS=cpu python -m pytest tests/test_hier_exchange.py -q \
    -m 'not slow' -p no:cacheprovider

env JAX_PLATFORMS=cpu python tools/failpoint_smoke.py

# Serving-tier smoke (ISSUE 10): resident server on the CI corpus —
# build + warm-restart byte-identical, a seeded open-loop burst, an
# overload spike that must degrade to recorded sheds (bounded queue,
# recovery after), and a transient-absorb pass on the serving fetch.
# Wall-budgeted and logged like lint/chaos (soft signal: the smoke
# itself bounds every wait; the gate catches a pathological slowdown).
serve_t0=$(python -c 'import time; print(time.time())')
env JAX_PLATFORMS=cpu python tools/serve_smoke.py
python - "$serve_t0" <<'EOF'
import sys, time
elapsed = time.time() - float(sys.argv[1])
print(f"serve smoke wall time: {elapsed:.2f}s (budget 90s)")
sys.exit(1 if elapsed > 90.0 else 0)
EOF

# Observability smoke (ISSUE 11): mine+serve under --trace (artifact
# schema-validated as Perfetto-loadable, span hierarchy + counter
# tracks present), metrics dump parseable, a mid-burst registry
# scrape, and the tracing-off ≈-zero-overhead pin.  Wall-budgeted and
# logged like the serve smoke.
obs_t0=$(python -c 'import time; print(time.time())')
env JAX_PLATFORMS=cpu python tools/obs_smoke.py
python - "$obs_t0" <<'EOF'
import sys, time
elapsed = time.time() - float(sys.argv[1])
print(f"obs smoke wall time: {elapsed:.2f}s (budget 60s)")
sys.exit(1 if elapsed > 60.0 else 0)
EOF

# Seeded chaos soak (ISSUE 9): deterministic failpoint schedules over
# the lint-censused site inventory against the full CLI pipeline —
# byte-identical, classified, or ledger-degraded; never a hang, silent
# corruption, or unclassified crash.  Fixed seed set, wall-budgeted and
# logged like lint's wall budget.
chaos_t0=$(python -c 'import time; print(time.time())')
env JAX_PLATFORMS=cpu python tools/chaos.py \
    --seeds 0,4,6,9 --scenarios 3 --budget-s 120
# Hard gate = soft budget (120 s, stops NEW scenarios) + the
# per-scenario hang bound (90 s, the worst legitimate overshoot for a
# scenario started just inside the budget) + slack.
python - "$chaos_t0" <<'EOF'
import sys, time
elapsed = time.time() - float(sys.argv[1])
print(f"chaos soak wall time: {elapsed:.2f}s (hard gate 215s)")
sys.exit(1 if elapsed > 215.0 else 0)
EOF

# Multi-process fault-domain soak (ISSUE 12): 2 real subprocess ranks
# per scenario over the file-transport quorum — seeded kill-mid-level /
# divergence-injection / coordinator-flap / heartbeat-delay /
# elastic-mesh (ISSUE 17: continuation, rendezvous kill, retry-budget
# exhaustion) schedules under the EXTENDED invariant: all surviving
# ranks byte-identical, or
# all failing ranks classified naming a rank/site; never a hang, never
# a mixed-epoch checkpoint.  Hard gate derived like the single-process
# soak's: soft budget (120 s) + one scenario hang bound (90 s) + slack.
chaos_mp_t0=$(python -c 'import time; print(time.time())')
env JAX_PLATFORMS=cpu python tools/chaos.py --procs 2 \
    --seeds 0,2,5 --scenarios 3 --budget-s 120
python - "$chaos_mp_t0" <<'PYEOF'
import sys, time
elapsed = time.time() - float(sys.argv[1])
print(f"chaos-mp soak wall time: {elapsed:.2f}s (hard gate 240s)")
sys.exit(1 if elapsed > 240.0 else 0)
PYEOF
