"""Failpoint smoke pass for CI (tools/ci.sh / `make ci`).

Drives the full CLI pipeline through the three headline reliability
scenarios on a tiny synthetic dataset, entirely on CPU:

1. **transient fetch**: an injected one-shot RESOURCE_EXHAUSTED on the
   pair fetch is retried, the run succeeds, and the retry is recorded in
   the degradation ledger;
2. **kill → resume**: a run with --checkpoint-every-level is aborted
   right after a completed level, then --resume-from restarts it
   mid-mine and the outputs are byte-identical to an uninterrupted run;
3. **truncated artifact**: an injected truncation of the freqItems
   resume artifact is rejected by MANIFEST.json validation with exit
   code 2 naming the file.

Exits non-zero on the first violated expectation.  Deliberately a plain
script (no pytest): this is the "does the shipped wiring actually hold
under injected failure" gate, one process, ~seconds.
"""

from __future__ import annotations

import contextlib
import io
import os
import random
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python tools/failpoint_smoke.py`
    sys.path.insert(0, _REPO_ROOT)

from fastapriori_tpu.cli import main  # noqa: E402
from fastapriori_tpu.reliability import failpoints, ledger  # noqa: E402


def die(msg: str) -> None:
    print(f"failpoint_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def make_inputs(root: str) -> str:
    rng = random.Random(11)
    items = [str(i) for i in range(1, 13)]
    weights = [1.0 / (i + 1) for i in range(12)]
    lines = [
        " ".join(rng.choices(items, weights=weights, k=rng.randint(1, 6)))
        for _ in range(150)
    ]
    inp = os.path.join(root, "in") + os.sep
    os.makedirs(inp)
    # lint: waive G009 -- smoke-test INPUT fixtures in a fresh temp dir, not run artifacts
    with open(os.path.join(inp, "D.dat"), "w") as f:
        f.writelines(l + "\n" for l in lines)
    # lint: waive G009 -- smoke-test INPUT fixtures in a fresh temp dir, not run artifacts
    with open(os.path.join(inp, "U.dat"), "w") as f:
        f.writelines(l + "\n" for l in lines[:25])
    return inp


def run(argv: list) -> int:
    return main(argv)


def read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def main_smoke() -> None:
    root = tempfile.mkdtemp(prefix="fa_failpoint_smoke_")
    try:
        inp = make_inputs(root)
        out_clean = os.path.join(root, "clean") + os.sep
        os.makedirs(out_clean)
        base = [inp, "--min-support", "0.08"]
        if run([inp, out_clean] + base[1:]) != 0:
            die("clean run failed")

        # 1. transient fetch failure: retried, run succeeds, recorded.
        out_flaky = os.path.join(root, "flaky") + os.sep
        os.makedirs(out_flaky)
        ledger.reset()
        # The pair fetch's site depends on the ingest flavor: the
        # pipelined capture ingest (native, any thread count since r6)
        # fetches the overlapped pair program at fetch.pair_pre; the
        # classic flow at fetch.pair.  Arm both — exactly one fires.
        failpoints.arm("fetch.pair", "oom*1")
        failpoints.arm("fetch.pair_pre", "oom*1")
        failpoints.arm("fetch.counts", "delay@5")
        if run([inp, out_flaky, "--min-support", "0.08",
                "--engine", "level"]) != 0:
            die("run with injected transient fetch failure did not succeed")
        failpoints.disarm_all()
        if not any(e["kind"] == "retry" for e in ledger.snapshot()):
            die("injected transient fetch failure was not recorded as a retry")
        if read(out_flaky + "freqItemset") != read(out_clean + "freqItemset"):
            die("flaky-fetch run output differs from clean run")

        # 2. kill -> resume: abort after a completed level, resume, compare.
        out_ckpt = os.path.join(root, "ckpt") + os.sep
        os.makedirs(out_ckpt)
        failpoints.arm("level.3", "abort")
        aborted = False
        try:
            run([inp, out_ckpt, "--min-support", "0.08",
                 "--checkpoint-every-level"])
        except failpoints.InjectedAbort:
            aborted = True
        failpoints.disarm_all()
        if not aborted:
            die("level.3 abort failpoint did not interrupt the mine")
        if os.path.exists(out_ckpt + "freqItemset"):
            die("aborted run left a final artifact behind")
        if not os.path.exists(out_ckpt + "checkpoint.npz"):
            die("aborted run left no checkpoint")
        if run([inp, out_ckpt, "--min-support", "0.08",
                "--resume-from", out_ckpt]) != 0:
            die("mid-mine resume failed")
        for name in ("freqItemset", "recommends"):
            if read(out_ckpt + name) != read(out_clean + name):
                die(f"resumed run {name} differs from uninterrupted run")

        # 3. truncated artifact: rejected by manifest validation.
        out_trunc = os.path.join(root, "trunc") + os.sep
        os.makedirs(out_trunc)
        failpoints.arm("write.freqItems", "truncate@30")
        if run([inp, out_trunc, "--min-support", "0.08",
                "--save-counts"]) != 0:
            die("truncating writer run failed outright")
        failpoints.disarm_all()
        err = io.StringIO()
        with contextlib.redirect_stderr(err):
            rc = run([inp, out_trunc, "--min-support", "0.08",
                      "--resume-from", out_trunc])
        if rc != 2:
            die(f"truncated artifact resume returned {rc}, expected 2")
        if "freqItems" not in err.getvalue():
            die("truncated-artifact error does not name the file")

        print("failpoint_smoke: OK (transient-retry, kill-resume, "
              "truncated-artifact)")
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main_smoke()
