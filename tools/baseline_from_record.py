"""Render BASELINE.md's results table from a bench record, mechanically.

Usage:
    python tools/baseline_from_record.py BENCH_r05.json
    python tools/baseline_from_record.py bench_logs/r5_final.json

Accepts either the driver capture shape ({"parsed": {...}}) or the raw
single-line record.  The output is the markdown table + phase breakdown
BASELINE.md embeds — the record-keeping rule (VERDICT r3 weak #1 /
r4 weak #1) is that the table IS the parsed record, field for field;
this script is how that equality is produced and re-checked (run it
against the driver's BENCH_r*.json and diff against BASELINE.md)."""

from __future__ import annotations

import json
import sys


def fmt_band(b):
    return f"[{b[0]}, {b[1]}, {b[2]}]" if b else "—"


def render(parsed: dict) -> str:
    out = []
    cfgs = parsed.get("configs", {})
    rows = [
        (
            "1 t10i4d100k", "0.01",
            parsed.get("value"), parsed.get("vs_baseline"),
            parsed.get("warm_wall_s"), parsed.get("warm_band_s"),
        ),
    ]
    r = cfgs.get("retail", {})
    rows.append(
        ("2 retail", "0.005", r.get("value"), r.get("vs_baseline"),
         r.get("warm_wall_s"), r.get("warm_band_s"))
    )
    k = cfgs.get("kosarak", {})
    rows.append(
        ("3 kosarak", "0.002", k.get("value"), k.get("vs_baseline"),
         k.get("warm_wall_s"), k.get("warm_band_s"))
    )
    rows.append(
        ("4 webdocs (north star)", "0.1",
         parsed.get("webdocs_txns_per_sec"), None,
         parsed.get("webdocs_warm_wall_s"),
         parsed.get("webdocs_warm_band_s"))
    )
    m = cfgs.get("movielens_recommend", {})
    rows.append(
        ("5 movielens + recommend", "0.1", m.get("value"),
         m.get("vs_baseline"), m.get("warm_wall_s"),
         m.get("warm_band_s"))
    )
    out.append(
        "| config | minSupport | value | vs_baseline | warm wall s "
        "[min, median, max] |"
    )
    out.append("|---|---|---|---|---|")
    for name, ms, val, vsb, wall, band in rows:
        unit = "users/sec" if "recommend" in name else "txns/sec"
        vs = "—" if not vsb else f"{vsb}x"
        out.append(
            f"| {name} | {ms} | **{val}** {unit} | {vs} | "
            f"{wall} {fmt_band(band)} |"
        )
    srv = (cfgs.get("movielens_serve") or {}).get("serve") or {}
    sus = srv.get("sustained") or {}
    if sus.get("achieved_rps") is not None:
        over = srv.get("overload") or {}
        model = srv.get("model") or {}
        out.append(
            f"| serving tier (movielens, open-loop) | 0.1 | "
            f"**{sus.get('achieved_rps')}** users/sec sustained "
            f"(offered {sus.get('offered_rps')}, closed-batch capacity "
            f"{srv.get('batch_users_per_s')}) | — | p50/p95/p99 "
            f"{sus.get('p50_ms')}/{sus.get('p95_ms')}/{sus.get('p99_ms')}"
            f" ms, shed {sus.get('shed')}; overload shed "
            f"{over.get('shed')}/{over.get('n_requests')} (queue bound "
            f"{over.get('queue_depth')}), engine {model.get('engine')}"
            f"{' resident' if model.get('resident_table') else ''}, "
            f"rule-table host bytes {srv.get('rule_table_host_bytes')} |"
        )
        # ISSUE 11: the per-scenario registry snapshot (hot-path
        # instruments vs loadgen cross-check), the no-obs control, and
        # the trace artifact, when the record carries them.
        sreg = sus.get("registry") or {}
        ctrl = srv.get("no_obs_control") or {}
        if sreg or ctrl or srv.get("trace"):
            bits = []
            if sreg:
                bits.append(
                    f"registry sustained: shed {sreg.get('shed_total')}, "
                    f"queue peak {sreg.get('queue_peak')}, batch fill "
                    f"{sreg.get('batch_fill_avg')} "
                    f"({'agrees' if sreg.get('agrees_loadgen') else 'DISAGREES'}"
                    " with loadgen)"
                )
            oreg = (srv.get("overload") or {}).get("registry") or {}
            if oreg:
                bits.append(
                    f"overload: shed {oreg.get('shed_total')}, queue "
                    f"peak {oreg.get('queue_peak')} "
                    f"({'agrees' if oreg.get('agrees_loadgen') else 'DISAGREES'})"
                )
            if ctrl:
                bits.append(
                    f"obs overhead {ctrl.get('obs_overhead_pct')}% vs "
                    f"no-obs control {ctrl.get('achieved_rps')} users/sec"
                )
            if srv.get("trace"):
                bits.append(f"trace: `{srv['trace']}`")
            out.append("")
            out.append("Serving observability: " + "; ".join(bits) + ".")
    rf = parsed.get("rules_full_scale") or {}
    if rf.get("value") is not None:
        eng = (
            f", engine {rf['engine']}" if rf.get("engine") else ""
        )
        split = (
            f" = join {rf.get('join_s')} + sort {rf.get('sort_s')}"
            if rf.get("join_s") is not None
            else ""
        )
        out.append(
            f"| phase 2 full scale (webdocs @ 0.092) | 0.092 | "
            f"**{rf.get('value')}** rules/sec ({rf.get('n_rules')} rules "
            f"from {rf.get('n_itemsets')} itemsets{eng}) | — | "
            f"gen_rules {rf.get('gen_rules_s')} s{split} "
            f"(mine {rf.get('mine_s')} s) |"
        )
    rsc = rf.get("scaling") or {}
    if rsc.get("devices"):
        out.append("")
        out.append(
            f"Rule engines per device count ({rsc.get('n_txns')} txns, "
            f"{rsc.get('n_users')} users, {rsc.get('platform')} — "
            "virtual devices share the host cores, so join_vs_1dev is "
            "sharding overhead, flat = ideal):"
        )
        out.append("")
        for n, d in sorted(
            rsc["devices"].items(), key=lambda kv: int(kv[0])
        ):
            out.append(
                f"- n={n} (shards {d.get('shards')}): join "
                f"{d.get('join_s')} s (vs 1dev {d.get('join_vs_1dev')}), "
                f"sort {d.get('sort_s')} s, scan_dispatches "
                f"{d.get('scan_dispatches')}, join gather/psum "
                f"{d.get('join_gather_bytes')}/{d.get('join_psum_bytes')} "
                f"B, rule-table host bytes "
                f"{d.get('rule_table_host_bytes')}, "
                f"**{d.get('users_per_s')}** users/sec"
            )
    ph = parsed.get("webdocs_phases")
    if ph:
        out.append("")
        out.append("Webdocs per-phase warm medians (the attributable record):")
        out.append("")
        keys = (
            ("preprocess_s", "ingest total"),
            ("pass1_s", "— pass 1 (AVX-512 tokenize+count)"),
            ("pass2_s", "— pass 2 (rank replay + dedup + callbacks)"),
            ("pack_s", "— per-block bitmap packing"),
            ("bitmap_build_s", "bitmap assembly (pair overlapped inside)"),
            ("pair_ms", "pair fetch (level 2; Gram rode the ingest)"),
            ("levels_total_ms", "levels 3+ total"),
            ("tail_fuse_ms", "tail fold"),
            ("counts_resolve_ms", "end-of-mine count resolve"),
            ("drain_ms", "mid-mine pending drains"),
            ("cold_s", "cold (compile cache state disclosed in record)"),
            ("dispatches", "mining-loop device dispatches"),
            ("ingest_dispatches", "ingest-overlapped dispatches (pair+L3)"),
            ("threads", "ingest threads"),
        )
        for key, label in keys:
            if key in ph:
                out.append(f"- {label}: **{ph[key]}**")
        if "levels_ms" in ph:
            lv = ", ".join(
                f"k={k}: {v}" for k, v in sorted(
                    ph["levels_ms"].items(), key=lambda kv: int(kv[0])
                )
            )
            out.append(f"- per-level ms: {lv}")
    ec = parsed.get("engine_compare") or {}
    if ec.get("vertical_vs_bitmap_wall") is not None:
        out.append("")
        line = (
            f"Mining engines ({ec.get('config', 'clickstream-sparse')}, "
            f"{ec.get('n_txns')} txns @ {ec.get('min_support')}): "
            f"vertical {ec['vertical_vs_bitmap_wall']}x faster than "
            f"bitmap wall-clock"
        )
        if ec.get("vertical_vs_bitmap_k_le3") is not None:
            line += f", {ec['vertical_vs_bitmap_k_le3']}x at k<=3"
        for n, row in sorted((ec.get("devices") or {}).items()):
            b = (row.get("bitmap") or {}).get("wall_s")
            v = (row.get("vertical") or {}).get("wall_s")
            if b is not None and v is not None:
                line += f"; {n}-dev {b}s vs {v}s"
        out.append(line + ".")
        pal = ec.get("pallas") or {}
        if pal.get("expected_speedup") is not None:
            # ISSUE 18: the Pallas tier row is MODELED on CPU hosts
            # (kernels are TPU-only); render it clearly labeled with
            # the HBM-traffic saving it models and the device-trace
            # artifact the attribution evidence lives at.
            pline = (
                f"Pallas vertical tier (modeled, HBM-traffic): "
                f"{pal['expected_speedup']}x expected over the XLA "
                f"vertical path ({pal.get('member_bytes_saved', 0):,} "
                f"prefix-intermediate bytes kept VMEM-resident)"
            )
            if pal.get("device_trace"):
                pline += f"; device trace: `{pal['device_trace']}`"
            out.append(pline + ".")
    cal = parsed.get("calibration")
    if cal:
        out.append("")
        out.append(
            "Calibration probes (host/link/device health bracketing the "
            "run — what makes cross-round drift attributable):"
        )
        for tag in ("start", "end"):
            c = cal.get(tag) or {}
            out.append(
                f"- {tag}: host_sort {c.get('host_sort_ms')} ms, "
                f"round-trip {c.get('device_roundtrip_ms')} ms, "
                f"down-link {c.get('link_down_mbyte_s')} MB/s, "
                f"int8 matmul {c.get('device_matmul_tops')} TOPS"
            )
    sc = parsed.get("scaling", {})
    if sc:
        ov = sc.get("sharding_overhead_8dev")
        out.append("")
        line = f"Scaling: 8-virtual-device sharding overhead {ov}"
        sp4 = ((sc.get("devices") or {}).get("4") or {}).get("sparse") or {}
        if sp4.get("collective_vs_dense") is not None:
            line += (
                "; sparse count-reduce collective bytes "
                f"{sp4['collective_vs_dense']}x dense at 4 devices "
                f"(engine {sp4.get('count_reduce')})"
            )
        # ISSUE 15: the hierarchical-exchange series — hier vs flat
        # collective bytes per device count, with the per-stage
        # (intra/inter) totals the two-level staging attributes.
        hier_rows = []
        for n in ("8", "16", "32"):
            hr = ((sc.get("devices") or {}).get(n) or {}).get("hier") or {}
            if hr.get("collective_vs_flat") is not None:
                hier_rows.append(
                    f"{n}dev {hr['collective_vs_flat']}x flat "
                    f"(intra {hr.get('intra_bytes')} / inter "
                    f"{hr.get('inter_bytes')} B)"
                )
        if hier_rows:
            line += (
                "; hierarchical exchange collective bytes: "
                + ", ".join(hier_rows)
            )
        for key, label in (
            ("two_process", "2-process"),
            ("four_process", "4-process"),
        ):
            mp = sc.get(key) or {}
            if not mp:
                continue
            ph = mp.get("phases") or {}
            phs = (
                f"; phases ingest {ph.get('ingest_s')} / pair "
                f"{ph.get('pair_s')} / levels {ph.get('levels_s')} / "
                f"fetch {ph.get('fetch_s')}"
                if ph
                else f" (ingest {mp.get('ingest_s')} s, "
                f"mine {mp.get('mine_s')} s)"
            )
            line += (
                f"; {label} jax.distributed wall {mp.get('wall_s')} s"
                f"{phs}"
            )
        line += " — all processes share this host's core(s)."
        out.append(line)
    return "\n".join(out)


def main() -> int:
    import os

    path = sys.argv[1]
    with open(path) as fh:
        rec = json.load(fh)
    parsed = rec.get("parsed") or rec
    # Since r6 the driver-parsed line is COMPACT and points at the full
    # record via record_file (relative to the repo root) — follow it so
    # `baseline_from_record.py BENCH_r06.json` still renders the full
    # table mechanically.
    rf = parsed.get("record_file")
    if rf:
        full = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, rf)
        if os.path.exists(full):
            with open(full) as fh:
                parsed = json.load(fh)
        else:
            print(
                f"note: record_file {rf!r} not found next to the repo; "
                "rendering the compact fields only",
                file=sys.stderr,
            )
    print(render(parsed))
    return 0


if __name__ == "__main__":
    sys.exit(main())
