"""graftlint v2 dataflow: intra-function def-use/taint walks.

Two analyses, both statement-ordered and PATH-SENSITIVE (v4): each
branch suite of an ``if``/``try``/loop walks its OWN copy of the taint
environment, and the copies worst-state merge only at the join point
(:func:`join_worst`).  A sanitize inside one arm therefore no longer
bleeds into its sibling arm (the v3 shared-environment approximation
that forced the G015 branch-suite waiver family), and a name the arms
bind to different states joins to the WORSE one — the old sequential
walk let the last suite win, which could hide a dynamic path behind a
clean sibling.  Bounds of the enumeration: suites are walked once (no
loop fixpoint — the body's join covers zero-or-more iterations), and a
``try`` handler joins from the pre-body and post-body states, not from
every intermediate statement.  Runtime re-ordering beyond that is out
of scope — a lint that guesses wrong asks for a waiver, it does not
stay silent:

- **Shape taint** (G011): a *dynamic int* — ``len()``, ``.shape[...]``,
  ``.size``, and arithmetic thereon — is DYNAMIC until it flows through
  one of the bucket helpers (``next_pow2`` / ``pad_axis`` /
  ``_pad_positions``), which make it BUCKETED.  A DYNAMIC value reaching
  a shape-forming argument compiles a fresh XLA program per distinct
  value (VERDICT r5 weak #6: 14 cache-miss compiles on a *primed*
  cache).  Arithmetic on a BUCKETED value stays BUCKETED: dividing a
  pow2 by a constant keeps the shape family finite, which is the whole
  point of the discipline.

- **Donation tracking** (G010): an argument passed at a
  ``donate_argnums``/``donate_argnames`` position of a jitted call has
  its buffer freed at dispatch; any later reference in the same scope
  reads freed memory (jax errors out at best).  One level of
  cross-function propagation: a function that forwards its own parameter
  to a donated position *donates that parameter*, and resolved callers
  inherit the contract.

Both analyses get one level of cross-function propagation through
tools/lint/graph.py summaries and no more — these are VALUE inferences
(shape families, buffer liveness), where depth-2 guesses about this
codebase start being wrong silently.  The v4 protocol layer's
REACHABILITY walks (does this helper construct a classified type, does
this resume path hit the fence validator) carry no values and so go
deeper safely: protocol.py k-bounds them at ``K_HOPS`` (= 3)
graph-resolvable call edges, with fixture tests pinning both the
3-hop resolve and the 4-hop flag.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.lint.graph import PackageGraph


def join_worst(
    env: Dict[str, int], branches: Sequence[Dict[str, int]]
) -> None:
    """Worst-state merge at a control-flow join: each branch walked its
    own copy of ``env``, so for every name any branch touched the joined
    state is the max across branches (an absent key is the lattice
    bottom, 0 — both taint lattices use 0 for their clean state).  The
    merge writes back into ``env`` in place."""
    keys: Set[str] = set()
    for b in branches:
        keys.update(b)
    for k in keys:
        env[k] = max(b.get(k, 0) for b in branches)


# -- shape taint ------------------------------------------------------------

CLEAN, BUCKETED, DYNAMIC = 0, 1, 2

# The bucket helpers (ops/bitmap.py next_pow2 / pad_axis and
# parallel/mesh.py _pad_positions) — matched by terminal name, same
# convention as every v1 rule.
SANITIZER_NAMES = ("next_pow2", "pad_axis", "_pad_positions")

# Terminal call names that introduce a dynamic int.
_DYNAMIC_CALLS = ("len",)

# Attribute reads that introduce a dynamic int.
_DYNAMIC_ATTRS = ("shape", "size", "nbytes")

# Calls that propagate their argument states unchanged.
_PASSTHROUGH_CALLS = ("int", "abs", "min", "max", "sum", "round")


class ShapeFlow:
    """Per-function shape-taint walk.

    ``summaries`` maps fully-qualified function names to the taint state
    of their return value (computed by :func:`return_summaries` — the
    one level of cross-function propagation).
    """

    def __init__(
        self,
        ctx,
        graph: Optional[PackageGraph] = None,
        summaries: Optional[Dict[str, int]] = None,
        check_sinks: bool = True,
    ):
        self.ctx = ctx
        self.graph = graph
        self.summaries = summaries or {}
        # The summary pass only needs the assignment walk + return
        # states; skipping sink evaluation there halves the package
        # pass (lint wall time is CI-budgeted).
        self.check_sinks = check_sinks

    # -- expression evaluation ------------------------------------------
    def eval(self, node: ast.AST, env: Dict[str, int]) -> int:
        from tools.lint.engine import terminal_name

        if isinstance(node, ast.Constant):
            return CLEAN
        if isinstance(node, ast.Name):
            return env.get(node.id, CLEAN)
        if isinstance(node, ast.Attribute):
            if node.attr in _DYNAMIC_ATTRS:
                return DYNAMIC
            return CLEAN
        if isinstance(node, ast.Subscript):
            return self.eval(node.value, env)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return max(
                (self.eval(e, env) for e in node.elts), default=CLEAN
            )
        if isinstance(node, ast.BinOp):
            return max(
                self.eval(node.left, env), self.eval(node.right, env)
            )
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand, env)
        if isinstance(node, ast.IfExp):
            return max(self.eval(node.body, env), self.eval(node.orelse, env))
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            # Comprehension targets are treated CLEAN; the element
            # expression's state is the collection's element state.
            return self.eval(node.elt, env)
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        if isinstance(node, ast.Call):
            t = terminal_name(node.func)
            arg_state = max(
                (self.eval(a, env) for a in node.args), default=CLEAN
            )
            if t in SANITIZER_NAMES:
                return BUCKETED
            if t in _DYNAMIC_CALLS:
                return DYNAMIC
            if t in _PASSTHROUGH_CALLS:
                return arg_state
            if self.graph is not None:
                fq = self.graph.resolve_call_fq(self.ctx, node)
                if fq is not None:
                    state = self.summaries.get(fq)
                    if state is not None:
                        return state
            return CLEAN
        return CLEAN

    # -- statement walk -------------------------------------------------
    def _assign(self, target: ast.AST, state: int, env: Dict[str, int]):
        if isinstance(target, ast.Name):
            env[target.id] = state
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._assign(el, state, env)

    def walk(
        self, body: Sequence[ast.stmt], env: Dict[str, int]
    ) -> Iterator[Tuple[ast.Call, str, int]]:
        """Yield ``(call, argument-description, state)`` for every
        shape-sink argument; the caller decides which states to flag."""
        compound = (
            ast.For,
            ast.While,
            ast.If,
            ast.With,
            ast.AsyncWith,
            ast.Try,
        )
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs run later, analyzed separately
            if self.check_sinks:
                # Sinks inside a compound statement's SUITES are checked
                # by the recursive walk below, AFTER the suite's own
                # assignments update the env — pre-scanning them here
                # would judge `n = next_pow2(n); jnp.zeros(n)` with the
                # stale pre-branch env.  Only header expressions (the
                # test / iterable / context managers) belong to this
                # statement's scan.
                if isinstance(stmt, compound):
                    headers: List[ast.AST] = []
                    if isinstance(stmt, (ast.If, ast.While)):
                        headers = [stmt.test]
                    elif isinstance(stmt, ast.For):
                        headers = [stmt.iter]
                    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                        headers = [i.context_expr for i in stmt.items]
                    for h in headers:
                        for node in ast.walk(h):
                            if isinstance(node, ast.Call):
                                yield from self._check_sink(node, env)
                else:
                    for node in ast.walk(stmt):
                        if isinstance(node, ast.Call):
                            yield from self._check_sink(node, env)
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                value = stmt.value
                if value is None:
                    continue
                state = self.eval(value, env)
                targets = (
                    stmt.targets
                    if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    self._assign(t, state, env)
            elif isinstance(stmt, ast.AugAssign):
                state = max(
                    self.eval(stmt.target, env), self.eval(stmt.value, env)
                )
                self._assign(stmt.target, state, env)
            elif isinstance(stmt, ast.For):
                self._assign(stmt.target, self.eval(stmt.iter, env), env)
                # The body may run zero times: walk it on a copy, join
                # with the fall-through state before the orelse (which
                # runs either way, sans break).
                body_env = dict(env)
                yield from self.walk(stmt.body, body_env)
                join_worst(env, [env, body_env])
                yield from self.walk(stmt.orelse, env)
            elif isinstance(stmt, ast.While):
                body_env = dict(env)
                yield from self.walk(stmt.body, body_env)
                join_worst(env, [env, body_env])
                yield from self.walk(stmt.orelse, env)
            elif isinstance(stmt, ast.If):
                # Per-suite environments: a sanitize in one arm must not
                # clean its sibling.  An absent orelse walks an empty
                # suite, so its copy IS the fall-through path.
                body_env = dict(env)
                orelse_env = dict(env)
                yield from self.walk(stmt.body, body_env)
                yield from self.walk(stmt.orelse, orelse_env)
                join_worst(env, [body_env, orelse_env])
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self.walk(stmt.body, env)
            elif isinstance(stmt, ast.Try):
                # Handlers see the worst of the pre-body and post-body
                # states (the exception may fire on any statement); the
                # orelse continues the success path only.
                body_env = dict(env)
                yield from self.walk(stmt.body, body_env)
                handler_env = dict(env)
                join_worst(handler_env, [env, body_env])
                handler_envs: List[Dict[str, int]] = []
                for h in stmt.handlers:
                    h_env = dict(handler_env)
                    yield from self.walk(h.body, h_env)
                    handler_envs.append(h_env)
                yield from self.walk(stmt.orelse, body_env)
                join_worst(env, [body_env] + handler_envs)
                yield from self.walk(stmt.finalbody, env)

    # Shape-forming sinks: terminal name -> selector of the shape
    # argument expressions in the call.  Only DEVICE shape-formers
    # count (jnp/lax roots, ShapeDtypeStruct): a host numpy scratch
    # buffer with a data-exact size compiles nothing — the discipline
    # binds at the point a size becomes a compiled shape.
    def _sink_args(self, call: ast.Call) -> List[Tuple[str, ast.AST]]:
        from tools.lint.engine import dotted_name, terminal_name

        t = terminal_name(call.func)
        out: List[Tuple[str, ast.AST]] = []

        def kw(name):
            for k in call.keywords:
                if k.arg == name:
                    return k.value
            return None

        def device_root() -> bool:
            if not isinstance(call.func, ast.Attribute):
                return False
            d = dotted_name(call.func.value)
            return d in ("jnp", "lax") or (
                d is not None
                and d.startswith(("jax.numpy", "jax.lax"))
            )

        if t in ("zeros", "ones", "full", "empty") and device_root():
            shape = kw("shape") or (call.args[0] if call.args else None)
            if shape is not None:
                out.append((f"{t}() shape", shape))
        elif t == "reshape":
            if isinstance(call.func, ast.Attribute) and not _is_module_root(
                call.func.value
            ):
                args = list(call.args)  # x.reshape(a, b)
            else:
                args = list(call.args[1:])  # jnp.reshape(x, shape)
            for a in args:
                out.append(("reshape() dim", a))
            nk = kw("newshape") or kw("shape")
            if nk is not None:
                out.append(("reshape() shape", nk))
        elif t == "broadcast_to" and device_root():
            shape = kw("shape") or (
                call.args[1] if len(call.args) > 1 else None
            )
            if shape is not None:
                out.append(("broadcast_to() shape", shape))
        elif t == "pad" and device_root():
            width = kw("pad_width") or (
                call.args[1] if len(call.args) > 1 else None
            )
            if width is not None:
                out.append(("pad() width", width))
        elif t in ("ShapeDtypeStruct", "shape_dtype_struct"):
            shape = kw("shape") or (call.args[0] if call.args else None)
            if shape is not None:
                out.append((f"{t} shape", shape))
        elif t == "dynamic_slice":
            sizes = kw("slice_sizes") or (
                call.args[2] if len(call.args) > 2 else None
            )
            if sizes is not None:
                out.append(("dynamic_slice() sizes", sizes))
        return out

    def _check_sink(
        self, call: ast.Call, env: Dict[str, int]
    ) -> Iterator[Tuple[ast.Call, str, int]]:
        for desc, expr in self._sink_args(call):
            yield call, desc, self.eval(expr, env)


def _is_module_root(node: ast.AST) -> bool:
    """``jnp.reshape`` vs ``x.reshape``: treat a bare lower-case Name
    that looks like a module alias (jnp/np/numpy/lax/jax chains) as a
    module root, so the first positional arg is the array, not a dim."""
    from tools.lint.engine import dotted_name

    d = dotted_name(node)
    return d in ("jnp", "np", "numpy", "jax", "lax") or (
        d is not None and d.startswith(("jax.", "numpy."))
    )


def return_summaries(
    files: Sequence, graph: PackageGraph, max_rounds: int = 5
) -> Dict[str, int]:
    """Taint state of each package function's return value — computed to
    a small FIXPOINT so a dynamic int laundered through a CHAIN of
    helpers is still caught (ROADMAP graftlint residue: the depth-1
    summary judged ``def a(x): return len(x)`` DYNAMIC but
    ``def b(x): return a(x)`` CLEAN, so a two-hop launder escaped
    G011).  Round 0 resolves only the sanitizer/dynamic primitives;
    each later round re-evaluates every return against the previous
    round's summaries, so taint propagates one extra call hop per
    round.  States move monotonically up the CLEAN < BUCKETED < DYNAMIC
    lattice (a call resolves to the callee's summary or to the max of
    its inputs, both monotone in the summary map), so the iteration
    converges; ``max_rounds`` bounds it for pathological chains — lint
    wall time is CI-budgeted — and real chains are 2-3 deep."""
    from tools.lint.engine import terminal_name

    out: Dict[str, int] = {}
    primitives = set(SANITIZER_NAMES) | set(_DYNAMIC_CALLS) | set(
        _PASSTHROUGH_CALLS
    )

    def compute(flow, fn) -> int:
        env: Dict[str, int] = {}
        # Run the assignment walk so `n = len(x); return n` works.
        for _ in flow.walk(fn.body, env):
            pass
        state = CLEAN
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                state = max(state, flow.eval(node.value, env))
        return state

    # Only functions with a call that graph resolution could rebind can
    # change after round 0 (primitive calls never consult summaries);
    # everything else keeps its round-0 state — the fixpoint's extra
    # rounds then touch a fraction of the package (lint wall time is
    # CI-budgeted at 10 s).
    fns = []  # (ctx, qualified name, fn node, may_resolve)
    for ctx in files:
        table = graph.by_path.get(ctx.path)
        if table is None:
            continue
        for local, fn in table.functions.items():
            may_resolve = any(
                isinstance(node, ast.Call)
                and terminal_name(node.func) not in primitives
                for node in ast.walk(fn)
            )
            out[f"{table.name}.{local}"] = CLEAN
            fns.append((ctx, f"{table.name}.{local}", fn, may_resolve))

    for _round in range(max_rounds):
        first = _round == 0
        changed = False
        flows: Dict[str, ShapeFlow] = {}
        for ctx, qual, fn, may_resolve in fns:
            if not first and not may_resolve:
                continue
            flow = flows.get(ctx.path)
            if flow is None:
                flow = flows[ctx.path] = ShapeFlow(
                    ctx,
                    graph=None if first else graph,
                    summaries=None if first else out,
                    check_sinks=False,
                )
            state = compute(flow, fn)
            if state != out[qual]:
                out[qual] = state
                changed = True
        if not changed:
            break
    return out


# -- rank-divergence taint (v3, ISSUE 13) -----------------------------------
#
# Values are RANK_UNIFORM or RANK_DIVERGENT.  A divergent value is one
# that can legitimately differ across the processes of a multi-process
# mesh: environment reads (PR 12's chaos harness arms failpoints
# per-rank through FA_FAILPOINTS), wall-clock and RNG reads, degradation
# ledger state (each rank walks its own cascade), caught exceptions
# (only the failing rank enters the handler), and per-rank identity
# (process_index, heartbeat ages).  The ONLY sanctioned ways back to
# uniformity are the consensus primitives (reliability/quorum.py):
# ``stage_allowed``/``floor_stage`` answer from the domain-agreed
# position vector, ``sync`` exchanges it, and a ``downgrade`` of a
# CONSENSUS_CHAINS-registered chain publishes an epoch-stamped proposal
# peers adopt before their next dispatch.  G015 walks this lattice to
# prove no unguarded divergent value can change which (or how many)
# collectives a rank issues.

RANK_UNIFORM, RANK_DIVERGENT = 0, 1

# Consensus primitives, matched by terminal name (the v1 convention:
# `quorum.stage_allowed` and an imported bare `stage_allowed` both
# count).  `sync` is NOT here: the terminal is far too common (mmap,
# file objects) to let any `.sync()` clamp a function — it must spell
# or resolve to the quorum module (see RankFlow._is_sanitizer).
# `downgrade` is conditional on the chain's registration.
RANK_SANITIZER_NAMES = ("stage_allowed", "floor_stage", "propose")

# Epoch-guard sanitizers (v4, the direction-5 enabler): the fenced
# checkpoint primitives answer from the domain's authoritative FENCE —
# `checkpoint_fence` validates the writer's acquired epoch against it
# at every commit, `validate_resume_fence` rejects a stale stamp on the
# resume side, and `acquire_fence`/`current_fence` are the transport
# reads both build on.  A value compared against (or stamped with) the
# fence epoch is domain-agreed by construction, so these clamp exactly
# like the consensus primitives: deliberate, epoch-guarded divergence
# is expressible in the lattice instead of waivable around it.
EPOCH_GUARD_SANITIZER_NAMES = (
    "checkpoint_fence",
    "validate_resume_fence",
    "acquire_fence",
    "current_fence",
)

# Call terminals that read a per-rank source.  env helper names are the
# strict parsers of utils/env.py; ledger snapshot/summary expose this
# rank's cascade history; process_index/heartbeat_age are rank identity.
_RANK_DIVERGENT_TERMINALS = {
    "getenv",
    "env_flag",
    "env_choice",
    "env_int",
    "env_float",
    "process_index",
    "heartbeat_age",
    "perf_counter",
    "perf_counter_ns",
}

# Dotted spellings (exact or suffix) that read a per-rank source.
_RANK_DIVERGENT_DOTTED_SUFFIXES = (
    "environ.get",
    "ledger.snapshot",
    "ledger.summary",
)
_RANK_DIVERGENT_DOTTED = {
    "os.getenv",
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.time_ns",
    "time.monotonic_ns",
}
_RANK_DIVERGENT_ROOTS = ("random.", "np.random.", "numpy.random.")


def _rank_call_kind(call: ast.Call) -> Optional[str]:
    """"divergent" / "sanitizer" / "downgrade" / None for a call, by
    terminal/dotted name (graph resolution refines this in eval)."""
    from tools.lint.engine import dotted_name, terminal_name

    t = terminal_name(call.func)
    if t == "downgrade":
        return "downgrade"
    if t in RANK_SANITIZER_NAMES or t in EPOCH_GUARD_SANITIZER_NAMES:
        return "sanitizer"
    if t in _RANK_DIVERGENT_TERMINALS:
        return "divergent"
    d = dotted_name(call.func)
    if d is not None:
        if d in _RANK_DIVERGENT_DOTTED or d.startswith(
            _RANK_DIVERGENT_ROOTS
        ):
            return "divergent"
        if d.endswith(_RANK_DIVERGENT_DOTTED_SUFFIXES):
            return "divergent"
    return None


class RankFlow:
    """Per-function rank-divergence walk (statement-ordered and
    path-sensitive, same contract as ShapeFlow: per-suite environment
    copies, worst-state merge at the join).

    ``summaries`` maps fully-qualified function names to the rank state
    of their return value; ``consensus_chains`` is the statically
    parsed ``quorum.CONSENSUS_CHAINS`` set (None = no registration
    declared in the linted tree, in which case every ``downgrade`` is
    accepted as a sanitizer — pre-quorum fixture trees have no
    registry to hold them to)."""

    def __init__(
        self,
        ctx,
        graph: Optional[PackageGraph] = None,
        summaries: Optional[Dict[str, int]] = None,
        consensus_chains: Optional[Set[str]] = None,
    ):
        self.ctx = ctx
        self.graph = graph
        self.summaries = summaries or {}
        self.consensus_chains = consensus_chains

    def _is_sanitizer(self, call: ast.Call) -> bool:
        """True when ``call`` is a consensus primitive: stage_allowed /
        floor_stage / propose, a quorum-resolved ``sync`` rendezvous,
        or a downgrade whose chain argument is consensus-registered."""
        kind = _rank_call_kind(call)
        if kind == "sanitizer":
            return True
        from tools.lint.engine import dotted_name, terminal_name

        if terminal_name(call.func) == "sync":
            # Must spell (or graph-resolve to) the quorum module — any
            # other `.sync()` (mmap, files) is unrelated host work and
            # must NOT clamp the enclosing function.
            d = dotted_name(call.func) or ""
            if d.endswith("quorum.sync"):
                return True
            if self.graph is not None:
                fq = self.graph.resolve_expr(self.ctx, call.func)
                if fq is not None and fq.endswith(
                    "reliability.quorum.sync"
                ):
                    return True
            return False
        if kind == "downgrade":
            if self.consensus_chains is None:
                return True
            from tools.lint.engine import resolve_str

            chain = None
            if call.args:
                chain = resolve_str(call.args[0], self.ctx, None)
            return chain is not None and chain in self.consensus_chains
        return False

    def contains_sanitizer(self, root: ast.AST) -> bool:
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and self._is_sanitizer(node):
                return True
        return False

    # -- expression evaluation ------------------------------------------
    def eval(self, node: ast.AST, env: Dict[str, int]) -> int:
        if isinstance(node, ast.Constant):
            return RANK_UNIFORM
        if isinstance(node, ast.Name):
            return env.get(node.id, RANK_UNIFORM)
        if isinstance(node, ast.Attribute):
            return self.eval(node.value, env)
        if isinstance(node, ast.Subscript):
            return self.eval(node.value, env)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return max(
                (self.eval(e, env) for e in node.elts),
                default=RANK_UNIFORM,
            )
        if isinstance(node, ast.Dict):
            return max(
                (
                    self.eval(e, env)
                    for e in list(node.keys) + list(node.values)
                    if e is not None
                ),
                default=RANK_UNIFORM,
            )
        if isinstance(node, ast.BinOp):
            return max(self.eval(node.left, env), self.eval(node.right, env))
        if isinstance(node, ast.BoolOp):
            return max(self.eval(v, env) for v in node.values)
        if isinstance(node, ast.Compare):
            return max(
                self.eval(node.left, env),
                max(self.eval(c, env) for c in node.comparators),
            )
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand, env)
        if isinstance(node, ast.IfExp):
            return max(
                self.eval(node.test, env),
                self.eval(node.body, env),
                self.eval(node.orelse, env),
            )
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self.eval(node.elt, env)
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        if isinstance(node, ast.JoinedStr):
            return max(
                (self.eval(v, env) for v in node.values),
                default=RANK_UNIFORM,
            )
        if isinstance(node, ast.FormattedValue):
            return self.eval(node.value, env)
        if isinstance(node, ast.Call):
            if self._is_sanitizer(node):
                return RANK_UNIFORM
            if _rank_call_kind(node) == "divergent":
                return RANK_DIVERGENT
            if self.graph is not None:
                fq = self.graph.resolve_call_fq(self.ctx, node)
                if fq is not None:
                    state = self.summaries.get(fq)
                    if state is not None:
                        return state
            # Unresolved calls propagate their argument states: parsing
            # or arithmetic on a divergent read stays divergent.
            return max(
                (
                    self.eval(a, env)
                    for a in list(node.args)
                    + [kw.value for kw in node.keywords]
                ),
                default=RANK_UNIFORM,
            )
        return RANK_UNIFORM

    # -- statement walk -------------------------------------------------
    def _assign(self, target: ast.AST, state: int, env: Dict[str, int]):
        if isinstance(target, ast.Name):
            env[target.id] = state
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._assign(el, state, env)

    def run(self, body: Sequence[ast.stmt], env: Dict[str, int]) -> None:
        """Statement-ordered assignment walk (no sinks — G015 interleaves
        its own branch checks; see rules.DivergentCollectiveRule)."""
        for stmt in body:
            self.step(stmt, env)

    def step(self, stmt: ast.stmt, env: Dict[str, int]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested scope, analyzed separately
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            if stmt.value is None:
                return
            state = self.eval(stmt.value, env)
            targets = (
                stmt.targets
                if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            for t in targets:
                self._assign(t, state, env)
        elif isinstance(stmt, ast.AugAssign):
            state = max(
                self.eval(stmt.target, env), self.eval(stmt.value, env)
            )
            self._assign(stmt.target, state, env)
        elif isinstance(stmt, ast.For):
            self._assign(stmt.target, self.eval(stmt.iter, env), env)
            body_env = dict(env)
            self.run(stmt.body, body_env)
            join_worst(env, [env, body_env])
            self.run(stmt.orelse, env)
        elif isinstance(stmt, ast.While):
            body_env = dict(env)
            self.run(stmt.body, body_env)
            join_worst(env, [env, body_env])
            self.run(stmt.orelse, env)
        elif isinstance(stmt, ast.If):
            body_env = dict(env)
            orelse_env = dict(env)
            self.run(stmt.body, body_env)
            self.run(stmt.orelse, orelse_env)
            join_worst(env, [body_env, orelse_env])
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self._assign(
                        item.optional_vars,
                        self.eval(item.context_expr, env),
                        env,
                    )
            self.run(stmt.body, env)
        elif isinstance(stmt, ast.Try):
            body_env = dict(env)
            self.run(stmt.body, body_env)
            handler_base = dict(env)
            join_worst(handler_base, [env, body_env])
            handler_envs: List[Dict[str, int]] = []
            for h in stmt.handlers:
                h_env = dict(handler_base)
                if h.name:
                    # Only the failing rank enters the handler: the
                    # caught exception is per-rank state.
                    h_env[h.name] = RANK_DIVERGENT
                self.run(h.body, h_env)
                handler_envs.append(h_env)
            self.run(stmt.orelse, body_env)
            join_worst(env, [body_env] + handler_envs)
            self.run(stmt.finalbody, env)


def rank_summaries(
    files: Sequence,
    graph: PackageGraph,
    consensus_chains: Optional[Set[str]] = None,
    max_rounds: int = 5,
) -> Tuple[Dict[str, int], Set[str]]:
    """``(summaries, clamped)``: the rank state of every package
    function's return value, iterated to the same bounded fixpoint as
    :func:`return_summaries`, plus the set of CONSENSUS-CLAMPED
    functions — those that call a consensus primitive anywhere in
    their body.  A clamped function's return value is RANK_UNIFORM by
    fiat (``_count_reduce_engine`` reads env AND consults
    ``stage_allowed``: whatever it answers, every peer adopts the
    agreed floor before the next dispatch), and G015 skips branches
    inside clamped functions — the consensus floor is consulted in
    that decision region, which is exactly the guard the rule
    demands."""
    out: Dict[str, int] = {}
    clamped: Set[str] = set()
    fns = []  # (ctx, qualified name, fn node, flow for round 0)
    for ctx in files:
        table = graph.by_path.get(ctx.path)
        if table is None:
            continue
        # The graph rides along so bare `sync` imported from quorum
        # resolves during the clamped-set scan.
        flow0 = RankFlow(ctx, graph=graph, consensus_chains=consensus_chains)
        for local, fn in table.functions.items():
            qual = f"{table.name}.{local}"
            out[qual] = RANK_UNIFORM
            if flow0.contains_sanitizer(fn):
                clamped.add(qual)
            fns.append((ctx, qual, fn))

    def compute(flow: RankFlow, fn) -> int:
        env: Dict[str, int] = {}
        flow.run(fn.body, env)
        state = RANK_UNIFORM
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                state = max(state, flow.eval(node.value, env))
        return state

    for _round in range(max_rounds):
        first = _round == 0
        changed = False
        flows: Dict[str, RankFlow] = {}
        for ctx, qual, fn in fns:
            if qual in clamped:
                continue  # stays RANK_UNIFORM by fiat
            flow = flows.get(ctx.path)
            if flow is None:
                flow = flows[ctx.path] = RankFlow(
                    ctx,
                    graph=None if first else graph,
                    summaries=None if first else out,
                    consensus_chains=consensus_chains,
                )
            state = compute(flow, fn)
            if state != out[qual]:
                out[qual] = state
                changed = True
        if not changed:
            break
    return out, clamped


# -- donation tracking ------------------------------------------------------


def _donation_spec(call: ast.Call) -> Optional[Tuple[Set[int], Set[str]]]:
    """``jit(..., donate_argnums=/donate_argnames=)`` -> (positions,
    kwarg names), or None when the call donates nothing."""
    from tools.lint.engine import terminal_name

    if terminal_name(call.func) not in ("jit", "pjit"):
        return None
    positions: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, int
                ):
                    positions.add(sub.value)
        elif kw.arg == "donate_argnames":
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    names.add(sub.value)
    if not positions and not names:
        return None
    return positions, names


class DonationUse:
    """One use-after-donation event."""

    __slots__ = ("use", "name", "donate_line")

    def __init__(self, use: ast.AST, name: str, donate_line: int):
        self.use = use
        self.name = name
        self.donate_line = donate_line


def _param_names(fn: ast.AST) -> List[str]:
    args = fn.args
    return [a.arg for a in list(args.posonlyargs) + list(args.args)]


def donating_functions(
    files: Sequence, graph: PackageGraph
) -> Dict[str, Tuple[Set[int], Set[str]]]:
    """Functions that forward a parameter to a donated position of a
    jit wrapper defined in their own scope — the one-level donation
    summary (``mesh.py _unpack_fn``'s inner ``unpack(arr)`` is the
    in-tree instance)."""
    out: Dict[str, Tuple[Set[int], Set[str]]] = {}
    for ctx in files:
        table = graph.by_path.get(ctx.path)
        if table is None:
            continue
        # A donating function necessarily spells donate_argnums/-names
        # somewhere in its own file; skip the walk everywhere else.
        if "donate_arg" not in ctx.source:
            continue
        module_donators = _scope_donators(ctx.tree.body)
        for local, fn in table.functions.items():
            params = _param_names(fn)
            if params and params[0] in ("self", "cls"):
                params = params[1:]
            donated_pos: Set[int] = set()
            donating = dict(module_donators)
            donating.update(_scope_donators(fn.body))
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                spec = _call_donation(node, donating)
                if spec is None:
                    continue
                positions, names = spec
                for i in positions:
                    if i < len(node.args) and isinstance(
                        node.args[i], ast.Name
                    ):
                        arg = node.args[i].id
                        if arg in params:
                            donated_pos.add(params.index(arg))
                for kw in node.keywords:
                    if kw.arg in names and isinstance(kw.value, ast.Name):
                        if kw.value.id in params:
                            donated_pos.add(params.index(kw.value.id))
            if donated_pos:
                out[f"{table.name}.{local}"] = (donated_pos, set())
    return out


def _scope_donators(body: Sequence[ast.stmt]) -> Dict[str, Tuple]:
    """Names bound (anywhere in this scope, including nested defs'
    enclosing scope via closures) to a donating jit call:
    ``inner = jax.jit(f, donate_argnums=0)``."""
    out: Dict[str, Tuple] = {}
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            spec = (
                _donation_spec(node.value)
                if isinstance(node.value, ast.Call)
                else None
            )
            if spec is not None:
                out[tgt.id] = spec + (node.lineno,)
    return out


def _call_donation(
    call: ast.Call, donating: Dict[str, Tuple]
) -> Optional[Tuple[Set[int], Set[str]]]:
    """Donation spec for a call site: direct ``jit(...)(x)``, or a call
    through a name bound to a donating wrapper."""
    if isinstance(call.func, ast.Call):
        spec = _donation_spec(call.func)
        if spec is not None:
            return spec
    if isinstance(call.func, ast.Name) and call.func.id in donating:
        positions, names, _line = donating[call.func.id]
        return positions, names
    return None


def donation_uses(
    ctx,
    graph: Optional[PackageGraph] = None,
    fn_summary: Optional[Dict[str, Tuple[Set[int], Set[str]]]] = None,
) -> Iterator[DonationUse]:
    """Walk every scope of ``ctx`` for donated-then-referenced buffers.

    Statement-ordered within a scope: a Store to the name between the
    donating call and the use clears the taint (the name was rebound to
    a live buffer)."""
    scopes: List[Tuple[Sequence[ast.stmt], ast.AST]] = [(ctx.tree.body, ctx.tree)]
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append((node.body, node))
    module_donators = _scope_donators(ctx.tree.body)
    for body, scope in scopes:
        donating = dict(module_donators)
        donating.update(_scope_donators(body))
        # (name -> line of the donating call that consumed it)
        pending: Dict[str, int] = {}
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested scope, walked separately
            # 1) uses of already-donated names anywhere in this stmt;
            # each donating call's spec is resolved ONCE and reused in
            # step 2 (graph resolution is the expensive part of this
            # CI-wall-time-budgeted pass).
            specs: Dict[int, Tuple[Set[int], Set[str]]] = {}
            calls_in_order: List[ast.Call] = []
            consumed_args: Set[int] = set()
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    spec = _call_donation(node, donating)
                    if spec is None and graph is not None and fn_summary:
                        hit = graph.resolve_call(ctx, node)
                        if hit is not None:
                            mod, target = hit
                            for local, cand in mod.functions.items():
                                if cand is target:
                                    spec = fn_summary.get(
                                        f"{mod.name}.{local}"
                                    )
                    if spec is not None:
                        specs[id(node)] = spec
                        calls_in_order.append(node)
                        positions, names = spec
                        # A FIRST donation consumes its argument quietly;
                        # donating an already-donated name is itself a
                        # use-after-donation, so leave it flaggable.
                        for i in positions:
                            if i < len(node.args) and isinstance(
                                node.args[i], ast.Name
                            ) and node.args[i].id not in pending:
                                consumed_args.add(id(node.args[i]))
                        for kw in node.keywords:
                            if kw.arg in names and isinstance(
                                kw.value, ast.Name
                            ) and kw.value.id not in pending:
                                consumed_args.add(id(kw.value))
            for node in ast.walk(stmt):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in pending
                    and id(node) not in consumed_args
                ):
                    yield DonationUse(node, node.id, pending[node.id])
            # 2) record this stmt's donations for later statements
            for call in calls_in_order:
                positions, names = specs[id(call)]
                for i in positions:
                    if i < len(call.args) and isinstance(
                        call.args[i], ast.Name
                    ):
                        pending[call.args[i].id] = call.lineno
                for kw in call.keywords:
                    if kw.arg in names and isinstance(kw.value, ast.Name):
                        pending[kw.value.id] = call.lineno
            # 3) stores rebind LAST (`x = f(x)` re-donates through a
            # fresh buffer: the RHS runs before the assignment lands,
            # so the store clears the taint the call just recorded)
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)
                ):
                    pending.pop(node.id, None)
