"""Per-file analysis cache for graftlint (ISSUE 13 satellite).

The lint gate re-tokenizes every file's comment/waiver map and re-walks
its constant tables on every run, even though both depend ONLY on that
file's bytes.  This cache keys those per-file facts by ``(mtime, size)``
so a warm run skips the tokenize pass and the symbol-table base for
every unchanged file — the census passes (waiver census, constant
resolution) read the cached fragments instead.

Two deliberate scope limits keep it correct:

- Only facts derivable from the file's OWN bytes are cached (comments,
  waiver segments, module-level string/int constants, the v4 protocol
  pass's per-file raise/ledger-event facts, and — v5, schema 3 — the
  concurrency pass's spawn/blocking/lock/hand-off/sentinel/marker
  facts, which feed the ``thread_spawns``/``blocking_sites``/...
  inventory censuses).  Anything
  resolved across files (fetch labels through cross-file constants,
  the collective census's axis resolution, the chain-walk census) is
  recomputed every run — an ``(mtime, size)`` key on one file cannot
  witness another file's edit.
- The cache key includes a fingerprint of ``tools/lint/*.py`` itself
  (name + mtime + size), so editing the linter invalidates everything:
  a stale analyzer must never answer for a new rule.

The cache file (``tools/lint/.cache.json``) is a pure wall-time
optimization: deleting it is always safe, results are bit-identical
either way (pinned by tests), and a torn write is re-read as a miss.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Set, Tuple

SCHEMA = 3  # v5: fragments carry the concurrency pass's per-file facts

CACHE_PATH = os.path.join("tools", "lint", ".cache.json")


def lint_fingerprint(root: str = ".") -> str:
    """Name+mtime+size over the linter's own sources: any edit to
    tools/lint/ drops the whole cache."""
    lint_dir = os.path.join(root, "tools", "lint")
    parts: List[str] = []
    try:
        names = sorted(os.listdir(lint_dir))
    except OSError:
        return "no-lint-dir"
    for name in names:
        if not name.endswith(".py"):
            continue
        full = os.path.join(lint_dir, name)
        try:
            st = os.stat(full)
        except OSError:
            continue
        parts.append(f"{name}:{st.st_mtime_ns}:{st.st_size}")
    return "|".join(parts)


def load(root: str = ".") -> Dict[str, dict]:
    """The per-file fragment map, or empty on any mismatch/corruption
    (a cache problem must never be a lint problem)."""
    path = os.path.join(root, CACHE_PATH)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return {}
    if not isinstance(data, dict) or data.get("schema") != SCHEMA:
        return {}
    if data.get("lint_fp") != lint_fingerprint(root):
        return {}
    files = data.get("files")
    return files if isinstance(files, dict) else {}


def save(root: str, files: Dict[str, dict]) -> None:
    """Best-effort atomic write (tmp + replace); failure is silent —
    the next run simply starts cold."""
    path = os.path.join(root, CACHE_PATH)
    doc = {
        "schema": SCHEMA,
        "comment": (
            "graftlint per-file analysis cache — safe to delete; "
            "regenerated on every run (tools/lint/cache.py)."
        ),
        "lint_fp": lint_fingerprint(root),
        "files": files,
    }
    tmp = path + ".tmp"
    try:
        # lint: waive G009 -- throwaway wall-time cache, not a run artifact: a torn write is re-read as a miss and regenerated
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def fragment_key(full_path: str) -> Optional[Tuple[int, int]]:
    try:
        st = os.stat(full_path)
    except OSError:
        return None
    return st.st_mtime_ns, st.st_size


def lookup(
    files: Dict[str, dict], rel_path: str, full_path: str
) -> Optional[dict]:
    """The cached fragment for ``rel_path`` when its (mtime, size)
    still match, else None."""
    entry = files.get(rel_path)
    if not isinstance(entry, dict):
        return None
    key = fragment_key(full_path)
    if key is None:
        return None
    if entry.get("mtime_ns") != key[0] or entry.get("size") != key[1]:
        return None
    return entry


def to_fragment(ctx, full_path: str) -> Optional[dict]:
    """Serialize a FileContext's own-bytes-only facts."""
    from tools.lint import concurrency as _concurrency
    from tools.lint import protocol as _protocol

    key = fragment_key(full_path)
    if key is None or ctx.tree is None:
        return None
    return {
        "mtime_ns": key[0],
        "size": key[1],
        "comments": {str(k): v for k, v in ctx.comments.items()},
        "waivers": {
            str(line): [[sorted(tokens), just] for tokens, just in segs]
            for line, segs in ctx.waiver_details.items()
        },
        "str_consts": dict(ctx.str_consts),
        "int_consts": dict(ctx.int_consts),
        "raises": [
            [s, ln] for s, ln in _protocol.file_raises(ctx)
        ],
        "ledger": [
            [k, ln] for k, ln in _protocol.file_ledger_events(ctx)
        ],
        "concurrency": _concurrency.file_facts(ctx),
    }


def apply_fragment(ctx, fragment: dict) -> None:
    """Install cached comment/waiver/constant facts on a FileContext
    BEFORE its own scan would run (engine.FileContext skips the
    tokenize + constant walks when these are pre-set)."""
    ctx.comments = {int(k): v for k, v in fragment["comments"].items()}
    waiver_details: Dict[int, List[Tuple[Set[str], str]]] = {}
    waivers: Dict[int, Set[str]] = {}
    for line, segs in fragment["waivers"].items():
        parsed = [(set(tokens), just) for tokens, just in segs]
        waiver_details[int(line)] = parsed
        waivers[int(line)] = set().union(*(t for t, _ in parsed))
    ctx.waiver_details = waiver_details
    ctx.waivers = waivers
    ctx.str_consts = dict(fragment["str_consts"])
    ctx.int_consts = {
        k: int(v) for k, v in fragment["int_consts"].items()
    }
    # v4 protocol facts: pre-installing them lets the raise/ledger
    # censuses skip their AST scans on warm runs (protocol.file_raises
    # / file_ledger_events consult these attributes first).
    if "raises" in fragment:
        ctx._protocol_raises = [
            (s, int(ln)) for s, ln in fragment["raises"]
        ]
    if "ledger" in fragment:
        ctx._protocol_ledger = [
            (k, int(ln)) for k, ln in fragment["ledger"]
        ]
    # v5 concurrency facts (schema 3): pre-installing them lets the
    # thread/blocking/lock/hand-off/sentinel/marker censuses skip
    # their AST scans on warm runs (concurrency.file_facts consults
    # this attribute first).
    if "concurrency" in fragment:
        ctx._concurrency_facts = fragment["concurrency"]
