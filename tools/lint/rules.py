"""graftlint rules G001-G008 — each encodes one invariant this repo's
performance tricks depend on (tools/lint/README.md documents the "why"
per rule; keep that file in sync when touching these).

Conventions shared by all rules:

- a rule yields Findings; the engine drops the waived ones (see
  engine.FileContext.is_waived for the waiver grammar);
- "terminal name" matching (``lax.psum`` and ``psum`` both match
  "psum") — this codebase imports both ways, and a linter that misses
  the aliased spelling teaches people to alias around it;
- name resolution is intentionally shallow (module-level constants,
  package-wide constants): anything deeper is a heuristic, and a lint
  heuristic that guesses wrong silently is worse than one that asks for
  a waiver.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Sequence, Set, Tuple

from tools.lint.engine import (
    FileContext,
    Finding,
    PackageContext,
    dotted_name,
    resolve_int,
    resolve_str,
    terminal_name,
)

_JIT_NAMES = {"jit", "pjit"}
_SHARD_NAMES = {"shard_map", "smap", "pmap"}
_NUMPY_ROOTS = {"np", "numpy"}


class Rule:
    id: str = "G000"
    name: str = ""
    aliases: Tuple[str, ...] = ()

    def check(
        self, ctx: FileContext, pkg: PackageContext
    ) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self, ctx: FileContext, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=self.id,
            path=ctx.path,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            snippet=ctx._line(line),
        )


def _is_jit_spelling(node: ast.AST) -> bool:
    """jit / jax.jit / pjit — as a bare reference (decorator or callee)."""
    t = terminal_name(node)
    return t in _JIT_NAMES


def _decorator_marks_device_fn(dec: ast.AST) -> bool:
    """True for @jit, @jax.jit, @shard_map, @partial(jax.jit, ...),
    @jax.jit(...)-style decorators."""
    t = terminal_name(dec)
    if t in _JIT_NAMES or t in _SHARD_NAMES:
        return True
    if isinstance(dec, ast.Call):
        ft = terminal_name(dec.func)
        if ft in _JIT_NAMES or ft in _SHARD_NAMES:
            return True
        if ft == "partial":
            for a in list(dec.args) + [kw.value for kw in dec.keywords]:
                at = terminal_name(a)
                if at in _JIT_NAMES or at in _SHARD_NAMES:
                    return True
    return False


def _device_functions(ctx: FileContext) -> List[ast.FunctionDef]:
    """Functions whose bodies are traced/compiled: @jit/@shard_map
    decorated, or ``*_kernel``-named (the Pallas kernel convention)."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name.endswith("_kernel") or node.name == "_kernel":
            out.append(node)
        elif any(_decorator_marks_device_fn(d) for d in node.decorator_list):
            out.append(node)
    return out


class HostSyncRule(Rule):
    """G001 — device→host synchronization.

    (a) Inside traced code (@jit/@shard_map/`*_kernel`), any host-sync
        call is a bug: it either fails at trace time or silently turns a
        compiled region into a round trip per dispatch.
    (b) In the device-mesh layer (``parallel/``), every ``np.asarray`` /
        ``jax.device_get`` / ``.item()`` / ``.block_until_ready()`` IS a
        device fetch crossing a link measured as low as 5 MB/s — each
        site must carry a ``# lint: fetch-site`` waiver naming why the
        fetch is necessary, so the audited-fetch-sites inventory lives
        in the code itself.
    """

    id = "G001"
    name = "host-sync"
    # fetch-site: audited device→host fetch.  host-data: the argument is
    # host-side data (e.g. a Python list of Device handles), not a device
    # array — a false-positive suppression, not a fetch audit.
    aliases = ("fetch-site", "host-data")

    # The reliability layer's audited fetch helpers
    # (fastapriori_tpu/reliability/retry.py): a sync call nested inside
    # their arguments IS the audited site — the helper failpoint-
    # instruments and retry-wraps it under the string label it takes —
    # so it needs no inline `# lint: fetch-site` waiver.  Recognized by
    # terminal name + a string site-label argument, so `retry.fetch`,
    # `fetch`, and `fetch_async` spellings all count while an unrelated
    # local `fetch()` without a label does not.
    _FETCH_HELPERS = {"fetch", "fetch_async"}
    # Path substrings where ALL host fetches need an audit waiver, not
    # just those inside traced functions: the mesh layer, the engine
    # layer's level loop (its np.asarray sites are the mining phase's
    # biggest link payloads — ROADMAP open item, extended from parallel/
    # in the reliability PR), and the rule generator since its device
    # engine landed (ISSUE 4: mask/denominator fetches must stay on the
    # audited retry.fetch_async / gather path).
    fetch_audit_dirs: Tuple[str, ...] = (
        "parallel/", "models/apriori", "rules/gen",
    )

    _SYNC_ATTRS = {"item", "block_until_ready", "tolist", "copy_to_host_async"}

    def _sync_call_reason(self, node: ast.Call) -> Optional[str]:
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in self._SYNC_ATTRS:
                return f".{node.func.attr}() forces a device sync"
            d = dotted_name(node.func)
            if d is not None:
                root, _, rest = d.partition(".")
                if root in _NUMPY_ROOTS and rest in ("asarray", "array"):
                    # A literal container argument is host data already —
                    # no device round trip to audit.
                    if node.args and isinstance(
                        node.args[0],
                        (ast.List, ast.Tuple, ast.Dict, ast.Set, ast.Constant),
                    ):
                        return None
                    return f"{d}() on a device array copies it to host"
                if rest == "device_get" or d.endswith("device_get"):
                    return f"{d}() copies to host"
        elif isinstance(node.func, ast.Name):
            if node.func.id == "device_get":
                return "device_get() copies to host"
        return None

    def check(self, ctx, pkg):
        device_fns = _device_functions(ctx)
        traced_lines: Set[int] = set()
        for fn in device_fns:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                reason = self._sync_call_reason(node)
                if reason is None and isinstance(node.func, ast.Name):
                    # int()/float()/bool() on a non-constant inside traced
                    # code concretizes a tracer (host sync or trace error).
                    if node.func.id in ("int", "float", "bool") and (
                        len(node.args) == 1
                        and not isinstance(node.args[0], ast.Constant)
                    ):
                        reason = (
                            f"{node.func.id}() on a traced value forces "
                            "concretization"
                        )
                if reason is not None:
                    traced_lines.add(node.lineno)
                    yield self.finding(
                        ctx,
                        node,
                        f"host sync inside traced function "
                        f"`{fn.name}`: {reason}",
                    )
        if not any(d in ctx.path for d in self.fetch_audit_dirs):
            return
        audited = self._helper_audited_calls(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if node.lineno in traced_lines:
                continue  # already reported above
            if id(node) in audited:
                continue  # inside retry.fetch/fetch_async: audited there
            reason = self._sync_call_reason(node)
            if reason is not None:
                yield self.finding(
                    ctx,
                    node,
                    f"device fetch in the mesh layer ({reason}); annotate "
                    "the audited site with `# lint: fetch-site -- why` or "
                    "route it through retry.fetch/fetch_async",
                )

    _RETRY_MODULE = "fastapriori_tpu.reliability.retry"

    def _retry_helper_names(self, ctx) -> Set[str]:
        """Spellings of the audited helpers that provably resolve to the
        reliability module IN THIS FILE: bare names imported from it
        (``from ...retry import fetch_async``) plus the dotted
        ``retry.fetch`` / ``retry.fetch_async`` forms when ``retry`` is
        imported from the reliability package.  An unrelated local
        ``fetch(...)`` (a cache API, a kwarg) must NOT exempt the device
        sync nested in its arguments."""
        names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module == self._RETRY_MODULE:
                    for a in node.names:
                        if a.name in self._FETCH_HELPERS:
                            names.add(a.asname or a.name)
                elif node.module == "fastapriori_tpu.reliability":
                    for a in node.names:
                        if a.name == "retry":
                            ref = a.asname or a.name
                            names.update(
                                f"{ref}.{h}" for h in self._FETCH_HELPERS
                            )
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == self._RETRY_MODULE:
                        ref = a.asname or a.name
                        names.update(
                            f"{ref}.{h}" for h in self._FETCH_HELPERS
                        )
        return names

    def _helper_audited_calls(self, ctx) -> Set[int]:
        """``id()``s of Call nodes nested inside an argument of an
        audited-fetch-helper call (``retry.fetch(lambda: np.asarray(x),
        "site")`` / ``retry.fetch_async(arr, "site")``) — helpers are
        matched by their RESOLVED reliability-module spelling
        (:meth:`_retry_helper_names`), with a string site label."""
        helper_names = self._retry_helper_names(ctx)
        if not helper_names:
            return set()
        out: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d not in helper_names:
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            if not any(
                isinstance(a, ast.Constant) and isinstance(a.value, str)
                for a in args
            ):
                continue  # no site label: not the audited helper shape
            for a in args:
                for sub in ast.walk(a):
                    if isinstance(sub, ast.Call):
                        out.add(id(sub))
        return out


class CollectiveAxisRule(Rule):
    """G002 — collective axis names must tie back to a Mesh declaration.

    A psum over a misspelled axis name fails only at trace time on a
    mesh-bearing path — i.e. in the multi-chip job, not in unit tests.
    Axis arguments must be string literals (or constants resolving to
    literals) found in some ``Mesh(...)`` declaration in the linted
    package, or flow through an ``axis``-named parameter (the
    ``axis_name=None`` plumbing idiom, checked at its literal source).
    """

    id = "G002"
    name = "collective-axis"
    aliases = ("axis-ok",)

    _COLLECTIVES = {
        "psum": 1,
        "pmean": 1,
        "pmax": 1,
        "pmin": 1,
        "all_gather": 1,
        "psum_scatter": 1,
        "all_to_all": 1,
        "ppermute": 1,
        "axis_index": 0,
        "axis_size": 0,
    }

    def _axis_arg(self, node: ast.Call, pos: int) -> Optional[ast.AST]:
        for kw in node.keywords:
            if kw.arg == "axis_name":
                return kw.value
        if len(node.args) > pos:
            return node.args[pos]
        return None

    def _check_axis_expr(
        self, expr: ast.AST, ctx: FileContext, pkg: PackageContext
    ) -> Optional[str]:
        """None = fine; str = complaint."""
        if isinstance(expr, (ast.Tuple, ast.List)):
            for el in expr.elts:
                bad = self._check_axis_expr(el, ctx, pkg)
                if bad:
                    return bad
            return None
        s = resolve_str(expr, ctx, pkg)
        if s is not None:
            if pkg.declared_axes and s not in pkg.declared_axes:
                return (
                    f"axis name {s!r} does not appear in any Mesh "
                    f"declaration (declared: {sorted(pkg.declared_axes)})"
                )
            return None
        if isinstance(expr, ast.Constant) and expr.value is None:
            return None  # the `axis_name or identity` guard idiom
        t = terminal_name(expr)
        if t is not None and "axis" in t.lower():
            return None  # axis_name plumbing parameter
        return (
            "collective axis is not a string literal, a resolvable "
            "constant, or an `axis`-named parameter"
        )

    def check(self, ctx, pkg):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            t = terminal_name(node.func)
            if t not in self._COLLECTIVES:
                continue
            expr = self._axis_arg(node, self._COLLECTIVES[t])
            if expr is None:
                continue
            complaint = self._check_axis_expr(expr, ctx, pkg)
            if complaint:
                yield self.finding(ctx, node, f"{t}: {complaint}")


class RecompileHazardRule(Rule):
    """G003 — recompile hazards.

    Each distinct static-argument value is a full XLA compile (seconds);
    unhashable statics are a TypeError at call time; a ``jax.jit`` call
    constructed inside a loop body builds a NEW cache entry per
    iteration and compiles every time.  The blessed pattern is the
    ``self._fns`` memo in parallel/mesh.py.
    """

    id = "G003"
    name = "recompile-hazard"
    aliases = ("compile-cache-ok",)

    def _jit_calls(self, root: ast.AST) -> Iterator[ast.Call]:
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and _is_jit_spelling(node.func):
                yield node

    def check(self, ctx, pkg):
        for node in self._jit_calls(ctx.tree):
            for kw in node.keywords:
                if kw.arg in ("static_argnums", "static_argnames") and (
                    isinstance(kw.value, (ast.List, ast.Set, ast.Dict))
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"{kw.arg} given a mutable {type(kw.value).__name__}"
                        " literal — unhashable; use a tuple",
                    )
        # jit constructed inside a loop body (direct call or decorator on
        # a nested def) — a fresh jit wrapper per iteration defeats the
        # compile cache.  One recursive pass carrying an in-loop flag:
        # ast.walk from every enclosing loop would report the same call
        # once per nesting level and over-freeze the baseline.
        findings: List[Finding] = []

        def visit(node: ast.AST, in_loop: bool) -> None:
            if isinstance(node, ast.Call) and _is_jit_spelling(node.func):
                if in_loop:
                    findings.append(
                        self.finding(
                            ctx,
                            node,
                            "jit() constructed inside a loop body — every "
                            "iteration makes a new wrapper and recompiles; "
                            "hoist it (or memoize like DeviceContext._fns)",
                        )
                    )
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                if in_loop and any(
                    _decorator_marks_device_fn(d)
                    for d in node.decorator_list
                ):
                    findings.append(
                        self.finding(
                            ctx,
                            node,
                            f"@jit function `{node.name}` defined inside "
                            "a loop body recompiles per iteration",
                        )
                    )
                in_loop = False  # a nested def's body runs per-call
            elif isinstance(node, (ast.For, ast.While)):
                for child in node.body + node.orelse:
                    visit(child, True)
                for child in ast.iter_child_nodes(node):
                    if child not in node.body and child not in node.orelse:
                        visit(child, in_loop)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, in_loop)

        visit(ctx.tree, False)
        yield from findings


class DtypeDisciplineRule(Rule):
    """G004 — dtype discipline.

    Counting is int32-exact by contract (ROADMAP); 64-bit device dtypes
    silently downcast while ``jax_enable_x64`` is off, so a ``jnp.int64``
    outside the audited key-packing modules is at best a no-op and at
    worst a wrong-answer generator.  Conversely a function that claims
    exactness in its name/docstring must not accumulate through float32
    without stating its gate (the ``< 2^24`` mantissa bound) in a waiver.
    """

    id = "G004"
    name = "dtype-discipline"
    aliases = ("f32-gate", "key-packing")

    # Modules allowed to talk 64-bit on purpose (key packing packs rule
    # rows into uint64 lanes; order.py is the historical home).
    allowed_path_parts: Tuple[str, ...] = ("utils/order", "rules/gen")

    _WIDE = {"int64", "float64", "uint64"}

    def _is_jnp_root(self, d: Optional[str]) -> bool:
        return d is not None and (
            d.startswith("jnp.") or d.startswith("jax.numpy.")
        )

    def check(self, ctx, pkg):
        allowed = any(p in ctx.path for p in self.allowed_path_parts)
        if not allowed:
            for node in ast.walk(ctx.tree):
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr in self._WIDE
                    and self._is_jnp_root(dotted_name(node))
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"{dotted_name(node)} outside the key-packing "
                        "modules: 64-bit is silently downcast while "
                        "jax_enable_x64 is off",
                    )
                elif isinstance(node, ast.Call):
                    d = dotted_name(node.func)
                    if self._is_jnp_root(d):
                        for kw in node.keywords:
                            if (
                                kw.arg == "dtype"
                                and isinstance(kw.value, ast.Constant)
                                and kw.value.value in self._WIDE
                            ):
                                yield self.finding(
                                    ctx,
                                    node,
                                    f"dtype={kw.value.value!r} string on a "
                                    "jnp call outside the key-packing "
                                    "modules",
                                )
        # Exactness claims vs f32 accumulation.
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            doc = ast.get_docstring(fn) or ""
            if "exact" not in fn.name.lower() and not re.search(
                r"\bexact", doc, re.IGNORECASE
            ):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if kw.arg != "preferred_element_type":
                        continue
                    d = dotted_name(kw.value)
                    if d in ("jnp.float32", "jax.numpy.float32"):
                        yield self.finding(
                            ctx,
                            node,
                            f"`{fn.name}` claims exactness but accumulates "
                            "in float32 — state the mantissa gate "
                            "(counts < 2^24) in a `# lint: f32-gate` "
                            "waiver or accumulate in int32",
                        )


class PallasConstraintRule(Rule):
    """G005 — Pallas/TPU kernel constraints.

    Mosaic tiles are (8, 128)-granular: a BlockSpec whose trailing dims
    are not multiples of (8, 128) either fails to lower or pads and
    silently wastes VMEM.  And a Python ``if`` on a ref value inside a
    kernel body is a trace-time error masked until the kernel is next
    recompiled — use ``pl.when`` / ``jnp.where``.
    """

    id = "G005"
    name = "pallas-constraint"
    aliases = ("tile-ok",)

    def _imports_pallas(self, ctx: FileContext) -> bool:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and (
                ("pallas" in (node.module or ""))
                or any("pallas" in a.name for a in node.names)
            ):
                return True
            if isinstance(node, ast.Import) and any(
                "pallas" in a.name for a in node.names
            ):
                return True
        return False

    def check(self, ctx, pkg):
        if not self._imports_pallas(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) != "BlockSpec":
                continue
            shape = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "block_shape":
                    shape = kw.value
            if not isinstance(shape, (ast.Tuple, ast.List)):
                continue
            dims = [resolve_int(e, ctx) for e in shape.elts]
            if len(dims) >= 1 and dims[-1] is not None and dims[-1] % 128:
                yield self.finding(
                    ctx,
                    node,
                    f"BlockSpec lane dim {dims[-1]} is not a multiple of "
                    "128 (Mosaic tile granularity)",
                )
            if len(dims) >= 2 and dims[-2] is not None and dims[-2] % 8:
                yield self.finding(
                    ctx,
                    node,
                    f"BlockSpec sublane dim {dims[-2]} is not a multiple "
                    "of 8 (Mosaic tile granularity)",
                )
        # Python `if` on ref values inside kernel bodies.
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            ref_params = {
                a.arg
                for a in list(fn.args.args) + list(fn.args.posonlyargs)
                if a.arg.endswith("_ref")
            }
            if not ref_params:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.IfExp)):
                    continue
                for sub in ast.walk(node.test):
                    if (
                        isinstance(sub, ast.Name)
                        and sub.id in ref_params
                    ):
                        yield self.finding(
                            ctx,
                            node,
                            f"Python `if` on ref `{sub.id}` in kernel "
                            f"`{fn.name}` — refs are traced; use pl.when "
                            "or jnp.where",
                        )
                        break


class SilentExceptRule(Rule):
    """G006 — swallowed exceptions.

    ``except Exception: <no raise>`` hid the conftest collection failure
    class of bug for five rounds; a broad handler must re-raise, convert
    to the typed ``InputError`` family, or carry a waiver saying why
    best-effort is correct (optional-dep probes, cache warming).
    """

    id = "G006"
    name = "silent-except"
    aliases = ("best-effort",)

    _BROAD = {"Exception", "BaseException"}

    def check(self, ctx, pkg):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or (
                terminal_name(node.type) in self._BROAD
            )
            if not broad:
                continue
            raises = any(
                isinstance(sub, ast.Raise) for sub in ast.walk(node)
            )
            converts = any(
                isinstance(sub, ast.Call)
                and (terminal_name(sub.func) or "").endswith("Error")
                for sub in ast.walk(node)
            )
            if raises or converts:
                continue
            what = (
                "bare except:"
                if node.type is None
                else f"except {terminal_name(node.type)}:"
            )
            yield self.finding(
                ctx,
                node,
                f"{what} swallows without re-raise or InputError "
                "conversion; narrow it, raise, or waive with the reason "
                "best-effort is safe here",
            )


class HazardousDefaultsRule(Rule):
    """G007 — mutable defaults and import-time device work.

    A mutable default is shared across calls (stale-state bugs that only
    repro on the second run); a module-level jnp array construction
    grabs a device and compiles at import time — which on a tunneled
    TPU turns `import fastapriori_tpu` into a multi-second stall and
    breaks JAX_PLATFORMS overrides applied after import.
    """

    id = "G007"
    name = "hazardous-defaults"
    aliases = ("import-time-ok",)

    _JNP_CONSTRUCTORS = {
        "array",
        "asarray",
        "zeros",
        "ones",
        "full",
        "arange",
        "linspace",
        "eye",
        "zeros_like",
        "ones_like",
    }

    def check(self, ctx, pkg):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for default in list(fn.args.defaults) + [
                d for d in fn.args.kw_defaults if d is not None
            ]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    yield self.finding(
                        ctx,
                        default,
                        f"mutable default argument in `{fn.name}` is "
                        "shared across calls; default to None",
                    )
        # Module/class level statements only — anything inside a def is
        # deferred and fine.
        def _toplevel(body):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, ast.ClassDef):
                    yield from _toplevel(stmt.body)
                    continue
                yield stmt

        for stmt in _toplevel(ctx.tree.body):
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                if d is None:
                    continue
                root, _, rest = d.partition(".")
                is_jnp = root == "jnp" or d.startswith("jax.numpy.")
                if (is_jnp and node.func.attr in self._JNP_CONSTRUCTORS) or d in (
                    "jax.device_put",
                    "jax.devices",
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"module-level {d}() grabs a device backend at "
                        "import time; construct lazily inside a function",
                    )


class TodoIssueRule(Rule):
    """G008 — TODO/FIXME must reference an issue.

    An unanchored TODO is a baseline-file entry nobody ever triages;
    forcing a reference (#123, GH-123, an ISSUE/ROADMAP pointer, or a
    URL) keeps the backlog in a place that gets read.
    """

    id = "G008"
    name = "todo-issue"
    aliases = ()

    _TODO = re.compile(r"\b(TODO|FIXME|XXX)\b", re.IGNORECASE)
    _REF = re.compile(
        r"(#\d+|\bGH-\d+\b|\bISSUE\b|\bROADMAP\b|https?://)", re.IGNORECASE
    )

    def check(self, ctx, pkg):
        for line_no, comment in sorted(ctx.comments.items()):
            if self._TODO.search(comment) and not self._REF.search(comment):
                yield Finding(
                    rule=self.id,
                    path=ctx.path,
                    line=line_no,
                    col=0,
                    message=(
                        "TODO/FIXME without an issue reference "
                        "(#N, GH-N, ISSUE/ROADMAP pointer, or URL)"
                    ),
                    snippet=ctx._line(line_no),
                )


class ArtifactWriteRule(Rule):
    """G009 — artifact writes must go through the atomic writer.

    ``io/writer.py write_artifact`` is the run's output committer: tmp +
    fsync + atomic rename, a manifest entry, and the ``write.<name>``
    failpoint.  A raw open-for-write anywhere in the package bypasses
    all three — a crash mid-write can leave a torn file under the final
    name that later *parses cleanly* (the bug class ``MANIFEST.json``
    exists to catch).  Flags ``open()``/``fsspec.open()`` with a writing
    mode and any ``open_write()`` call; the committer's own internals
    carry waivers, which is the point — every bypass is an audited
    decision.  Test code is exempt (fixtures write files legitimately).
    """

    id = "G009"
    name = "artifact-write"
    aliases = ("atomic-write",)

    _WRITE_CHARS = frozenset("wax+")

    def _mode_of(self, node: ast.Call) -> Optional[str]:
        mode: Optional[ast.AST] = None
        if len(node.args) >= 2:
            mode = node.args[1]
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return mode.value
        return None

    def check(self, ctx, pkg):
        parts = ctx.path.split("/")
        if "tests" in parts:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            t = terminal_name(node.func)
            if t == "open_write":
                yield self.finding(
                    ctx,
                    node,
                    "open_write() bypasses the atomic writer "
                    "(io/writer.py write_artifact): no tmp+fsync+rename, "
                    "no manifest entry, no write.<name> failpoint",
                )
            elif t == "open":
                mode = self._mode_of(node)
                if mode and (set(mode) & self._WRITE_CHARS):
                    yield self.finding(
                        ctx,
                        node,
                        f"open(..., {mode!r}) writes without the atomic "
                        "writer (io/writer.py write_artifact); route "
                        "artifacts through it, or waive stating why a "
                        "torn write is acceptable here",
                    )


ALL_RULES: Sequence[Rule] = (
    HostSyncRule(),
    CollectiveAxisRule(),
    RecompileHazardRule(),
    DtypeDisciplineRule(),
    PallasConstraintRule(),
    SilentExceptRule(),
    HazardousDefaultsRule(),
    TodoIssueRule(),
    ArtifactWriteRule(),
)

RULES_BY_ID = {r.id: r for r in ALL_RULES}
